"""Fault-injection harness for the BLS offload path.

The resilience layer's claims — fail-closed under every transport
fault, breaker-fast failover, sound degradation — are only as good as
the faults they were proven against. This module is the deterministic
seam that delivers those faults:

* `FaultInjector.wrap_transport` plugs into `BlsOffloadClient`'s
  `transport_wrapper` hook and intercepts every stub call the client
  dials: added latency, deadline blow-through, UNAVAILABLE /
  connection-reset, error frames, full partitions, and corrupt or
  verdict-flipped reply frames.
* `FaultInjector.wrap_backend` wraps a server-side verify backend with
  latency / exception faults (the server turns backend exceptions into
  error frames — the reply-path fault class).
* `partition(target)` / `heal(target)` toggle hard partitions at
  runtime, so an integration test can cut every offload endpoint
  mid-chain and watch the degradation chain keep block import alive.

Determinism: faults fire by per-(target, method) call index against
`FaultRule` windows; probabilistic rules draw from one seeded
`random.Random`, so a chaos soak replays exactly from its seed (under
concurrency the interleaving of coin draws can vary — schedule-window
rules stay exact regardless).

Replayable traces: `schedule()` exports the faults that actually fired
as exact (target, method, call_index) records, and
`FaultInjector.from_trace()` rebuilds an injector whose rules pin every
one of those records to its exact call index — a failed probabilistic
chaos run's fault schedule becomes a deterministic pinned regression
test, independent of RNG draw interleaving. (Payload-level corruption
bytes for CORRUPT_VERDICT still come from the replay injector's own
seeded RNG; the schedule — which fault, on which edge, at which call —
replays exactly.)

Virtual time: `sleep_fn` (default `time.sleep`) is the seam the fleet
harness points at `SimClock.sleep`, so injected latency advances the
simulation's virtual clock instead of stalling the test for real.

Verdict-flip scope: `FLIP_VERDICT` flips the verdict byte of a
well-formed reply IN FLIGHT — the digest check (`decode_verdict`)
catches it and the client fails closed. `LIE_VERDICT` is the byzantine
SERVER: it flips the verdict AND recomputes the digest over the lie,
producing a frame that is indistinguishable from an honest verdict at
the protocol layer — by construction NOTHING in the framing catches
it; only independent re-verification (the audit subsystem's 2G2T-style
random cross-checks, `offload/audit.py`) can, which is exactly the
property its tests prove.
"""

from __future__ import annotations

import enum
import random
import threading
import time
from dataclasses import dataclass, field

import grpc

from lodestar_tpu.offload import encode_verdict

__all__ = ["FaultKind", "FaultRule", "FaultInjector", "InjectedRpcError"]


class FaultKind(enum.Enum):
    LATENCY = "latency"  # sleep delay_s, then proceed (deadline honored)
    DEADLINE = "deadline"  # the RPC blows through its deadline
    UNAVAILABLE = "unavailable"  # transport refuses the call
    RESET = "reset"  # connection reset mid-call
    ERROR_FRAME = "error_frame"  # server answers with an error frame
    CORRUPT_VERDICT = "corrupt_verdict"  # seeded bit-flip/truncation of the reply
    FLIP_VERDICT = "flip_verdict"  # flip the verdict byte, leave the digest
    LIE_VERDICT = "lie_verdict"  # byzantine: flip the verdict AND re-sign the lie
    PARTITION = "partition"  # every call to the target fails instantly


#: kinds the backend wrapper understands (transport-only kinds are
#: rejected loudly rather than silently ignored)
_BACKEND_KINDS = frozenset(
    {FaultKind.LATENCY, FaultKind.DEADLINE, FaultKind.ERROR_FRAME}
)


class InjectedRpcError(grpc.RpcError):
    """A grpc.RpcError the client's `except grpc.RpcError` path accepts,
    carrying the injected status code."""

    def __init__(self, code: grpc.StatusCode, details: str):
        super().__init__()
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details

    def __str__(self) -> str:
        return f"InjectedRpcError({self._code}, {self._details!r})"


@dataclass(frozen=True)
class FaultRule:
    """One scheduled fault. Matches calls whose per-(target, method)
    index lies in [first_call, last_call] (inclusive; None = open-ended)
    against the given targets/methods (None = all), then fires with
    `probability` using the injector's seeded RNG."""

    kind: FaultKind
    first_call: int = 0
    last_call: int | None = None
    probability: float = 1.0
    delay_s: float = 0.0
    targets: frozenset[str] | None = None
    methods: frozenset[str] | None = None

    def matches(self, target: str, method: str, call_index: int) -> bool:
        if self.targets is not None and target not in self.targets:
            return False
        if self.methods is not None and method not in self.methods:
            return False
        if call_index < self.first_call:
            return False
        if self.last_call is not None and call_index > self.last_call:
            return False
        return True


@dataclass
class _CallRecord:
    target: str
    method: str
    call_index: int
    fault: FaultKind | None
    delay_s: float = 0.0


class FaultInjector:
    """Seeded, scheduled fault delivery through the offload seams."""

    def __init__(
        self,
        rules: tuple[FaultRule, ...] | list[FaultRule] = (),
        seed: int = 0,
        *,
        sleep_fn=None,
    ):
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, str], int] = {}
        self._partitioned: set[str] = set()
        self.calls: list[_CallRecord] = []
        self.injected: dict[FaultKind, int] = {k: 0 for k in FaultKind}
        # latency/deadline sleeps go through this seam so a virtual
        # clock (testing/clock.SimClock) can absorb them deterministically
        self._sleep = time.sleep if sleep_fn is None else sleep_fn

    @classmethod
    def from_trace(cls, trace: dict, *, sleep_fn=None) -> "FaultInjector":
        """Rebuild an injector from `export_trace()` output: every
        recorded fault becomes an exact-window rule (first_call ==
        last_call == its call index, pinned to its edge), so the replay
        fires the identical fault schedule with NO probabilistic draws —
        the pinned-regression constructor for a failed chaos run."""
        rules = [
            FaultRule(
                kind=FaultKind(ev["kind"]),
                first_call=int(ev["call_index"]),
                last_call=int(ev["call_index"]),
                delay_s=float(ev.get("delay_s", 0.0)),
                targets=frozenset({ev["target"]}),
                methods=frozenset({ev["method"]}),
            )
            for ev in trace.get("schedule", ())
        ]
        return cls(rules, seed=int(trace.get("seed", 0)), sleep_fn=sleep_fn)

    # -- runtime partition control --------------------------------------------

    def partition(self, target: str = "*") -> None:
        """Cut `target` (or every target) off: all calls fail instantly
        with UNAVAILABLE until heal()."""
        with self._lock:
            self._partitioned.add(target)

    def heal(self, target: str = "*") -> None:
        with self._lock:
            self._partitioned.discard(target)
            if target == "*":
                self._partitioned.clear()

    def is_partitioned(self, target: str) -> bool:
        with self._lock:
            return "*" in self._partitioned or target in self._partitioned

    # -- bookkeeping -----------------------------------------------------------

    def calls_to(self, target: str, method: str | None = None) -> int:
        with self._lock:
            return sum(
                1
                for c in self.calls
                if c.target == target and (method is None or c.method == method)
            )

    def _next_fault(self, target: str, method: str) -> tuple[FaultKind | None, FaultRule | None, int]:
        """Advance the per-(target, method) call counter and decide the
        fault (first matching rule wins) for this call."""
        with self._lock:
            key = (target, method)
            idx = self._counters.get(key, 0)
            self._counters[key] = idx + 1
            if "*" in self._partitioned or target in self._partitioned:
                self.calls.append(_CallRecord(target, method, idx, FaultKind.PARTITION))
                self.injected[FaultKind.PARTITION] += 1
                return FaultKind.PARTITION, None, idx
            for rule in self.rules:
                if rule.matches(target, method, idx) and (
                    rule.probability >= 1.0 or self._rng.random() < rule.probability
                ):
                    self.calls.append(
                        _CallRecord(target, method, idx, rule.kind, rule.delay_s)
                    )
                    self.injected[rule.kind] += 1
                    return rule.kind, rule, idx
            self.calls.append(_CallRecord(target, method, idx, None))
            return None, None, idx

    # -- trace export / replay -------------------------------------------------

    def schedule(self) -> list[dict]:
        """The faults that actually FIRED, in firing order, as exact
        (target, method, call_index) records — the SCHEDULE artifact a
        chaos ledger embeds and `from_trace()` replays. Pure data
        (JSON-able), stable field order, no RNG state."""
        with self._lock:
            return [
                {
                    "target": c.target,
                    "method": c.method,
                    "call_index": c.call_index,
                    "kind": c.fault.value,
                    "delay_s": c.delay_s,
                }
                for c in self.calls
                if c.fault is not None
            ]

    def export_trace(self) -> dict:
        """Self-contained replay artifact: the seed (for payload-level
        corruption draws) plus the exact fault schedule. Feed the dict —
        or its JSON round-trip — to `FaultInjector.from_trace()`."""
        return {"seed": self.seed, "schedule": self.schedule()}

    def _corrupt(self, data: bytes) -> bytes:
        """Seeded corruption: flip one bit, truncate, or extend."""
        with self._lock:
            mode = self._rng.randrange(3)
            if mode == 0 and data:  # bit flip
                i = self._rng.randrange(len(data))
                bit = 1 << self._rng.randrange(8)
                return data[:i] + bytes([data[i] ^ bit]) + data[i + 1 :]
            if mode == 1 and len(data) > 1:  # truncate
                return data[: self._rng.randrange(1, len(data))]
            return data + bytes([self._rng.randrange(256)])  # extend

    # -- transport seam --------------------------------------------------------

    def wrap_transport(self, target: str, method: str, fn):
        """`BlsOffloadClient(transport_wrapper=injector.wrap_transport)`
        — returns a callable supporting both `__call__` and `.with_call`
        (the shapes `grpc.UnaryUnaryMultiCallable` exposes that the
        client uses)."""
        return _FaultyCallable(self, target, method, fn)

    def _pre_call(
        self, target: str, method: str, timeout: float | None, request: bytes = b""
    ):
        """Faults decided before the wire: may sleep, may raise. Returns
        (response_override, response_mutator). `request` feeds the
        LIE_VERDICT mutator — a byzantine server signs its lie over the
        request it actually received."""
        kind, rule, _idx = self._next_fault(target, method)
        if kind is None:
            return None, None
        if kind is FaultKind.PARTITION:
            raise InjectedRpcError(grpc.StatusCode.UNAVAILABLE, f"injected partition of {target}")
        if kind is FaultKind.UNAVAILABLE:
            raise InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "injected UNAVAILABLE")
        if kind is FaultKind.RESET:
            raise InjectedRpcError(
                grpc.StatusCode.UNAVAILABLE, "injected connection reset"
            )
        if kind is FaultKind.DEADLINE:
            # simulated blow-through: the caller sees DEADLINE_EXCEEDED
            # after rule.delay_s of wall time (virtual when a SimClock
            # owns the sleep seam, real — and kept small — in tests)
            if rule is not None and rule.delay_s:
                self._sleep(rule.delay_s)
            raise InjectedRpcError(grpc.StatusCode.DEADLINE_EXCEEDED, "injected deadline")
        if kind is FaultKind.LATENCY:
            delay = rule.delay_s if rule is not None else 0.0
            if timeout is not None and delay >= timeout:
                self._sleep(timeout)
                raise InjectedRpcError(
                    grpc.StatusCode.DEADLINE_EXCEEDED, "injected latency past deadline"
                )
            self._sleep(delay)
            return None, None
        if kind is FaultKind.ERROR_FRAME:
            return encode_verdict(None, error="injected server error"), None
        if kind is FaultKind.CORRUPT_VERDICT:
            return None, self._corrupt
        if kind is FaultKind.FLIP_VERDICT:
            return None, _flip_verdict_byte
        if kind is FaultKind.LIE_VERDICT:
            return None, lambda data: _lie_verdict(data, request)
        return None, None

    # -- backend seam ----------------------------------------------------------

    def wrap_backend(self, verify_fn, target: str = "backend"):
        """Wrap a server-side verify backend (or a local pool's
        verify_fn). Backend faults become error frames at the server /
        rejected jobs at the pool — the fail-closed reply path."""
        for rule in self.rules:
            if (
                rule.methods is not None
                and "backend" in rule.methods
                and rule.kind not in _BACKEND_KINDS
            ):
                raise ValueError(
                    f"{rule.kind} is a transport fault; the backend seam supports "
                    f"{sorted(k.value for k in _BACKEND_KINDS)}"
                )

        def wrapped(sets):
            kind, rule, _idx = self._next_fault(target, "backend")
            if kind in (FaultKind.LATENCY, FaultKind.DEADLINE):
                self._sleep(rule.delay_s if rule is not None else 0.0)
                if kind is FaultKind.DEADLINE:
                    raise TimeoutError("injected backend deadline blow-through")
            elif kind is not None:
                raise RuntimeError(f"injected backend fault: {kind.value}")
            return verify_fn(sets)

        return wrapped


def _flip_verdict_byte(data: bytes) -> bytes:
    """Flip ok<->invalid on a well-formed verdict frame, leaving the
    rest (digest included) untouched — the fault the digest check must
    catch. Error frames pass through (already fail-closed)."""
    if data and data[0] in (0, 1):
        return bytes([1 - data[0]]) + data[1:]
    return data


def _lie_verdict(data: bytes, request: bytes) -> bytes:
    """The byzantine helper: flip the verdict and RE-SIGN the lie — the
    digest is recomputed over (request || lied_verdict), so the frame
    passes every protocol-layer check (`decode_verdict` accepts it).
    Distinct from FLIP_VERDICT, which framing catches: this fault is
    only detectable by independently re-verifying the signature sets
    (offload/audit.py). Legacy 1-byte verdicts just flip (no digest to
    forge); error frames pass through (already fail-closed)."""
    if not data or data[0] not in (0, 1):
        return data
    lied = 1 - data[0]
    if len(data) == 1:
        return bytes([lied])
    from lodestar_tpu.offload import encode_verdict

    return encode_verdict(bool(lied), request=request)


class _FaultyCallable:
    """Stub wrapper: fault gate in front of the real call, response
    mutation behind it."""

    def __init__(self, injector: FaultInjector, target: str, method: str, fn):
        self._injector = injector
        self._target = target
        self._method = method
        self._fn = fn

    def __call__(self, request: bytes, timeout: float | None = None, metadata=None):
        override, mutate = self._injector._pre_call(
            self._target, self._method, timeout, request
        )
        if override is not None:
            return override
        kwargs = {"timeout": timeout}
        if metadata is not None:
            kwargs["metadata"] = metadata
        resp = self._fn(request, **kwargs)
        return mutate(resp) if mutate is not None else resp

    def with_call(self, request: bytes, timeout: float | None = None, metadata=None):
        override, mutate = self._injector._pre_call(
            self._target, self._method, timeout, request
        )
        if override is not None:
            return override, _NullCall()
        kwargs = {"timeout": timeout}
        if metadata is not None:
            kwargs["metadata"] = metadata
        resp, call = self._fn.with_call(request, **kwargs)
        return (mutate(resp) if mutate is not None else resp), call


class _NullCall:
    """Stands in for grpc.Call when the injector short-circuited the
    wire: no trailing metadata came home."""

    def trailing_metadata(self):
        return ()
