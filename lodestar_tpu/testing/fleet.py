"""Deterministic fleet chaos harness: N beacon verification stacks
against M offload hosts, one seed, one replayable ledger.

Each simulated node is the REAL stack wired end to end — gossip
processor (`network/processor.py`) → priority scheduler
(`scheduler/core.py`) → degradation chain (`chain/bls/fallback.py`) →
`BlsOffloadClient` — and each simulated host is a real
`BlsOffloadServer` served over the in-process transport
(`offload/server.local_transports`), with a seeded `FaultInjector` on
every node→host edge and on every host's verify backend. No sockets, no
real BLS: signature sets carry a synthetic deterministic "signature"
(`make_set` / `oracle_verify`) so verdict correctness is checkable by
construction, at simulation speed.

Determinism contract: with `virtual_time=True` (the default) one
`SimClock` drives every clock seam — the SLO accountant's wall and
monotonic clocks, the scheduler queue's aging stamps, every breaker's
reset schedule (jitter pinned to 0), the local transports'
`time_remaining`, and every injector's latency sleeps — and the driver
runs each node's slot work sequentially. `run_fleet(cfg)` with the same
config therefore produces the byte-identical verdict ledger
(`FleetResult.ledger_lines`) and fault schedule
(`FleetResult.fault_schedule`, per-edge `FaultInjector.export_trace()`)
on every run; a failed run replays via `FaultInjector.from_trace`.
`virtual_time=False` trades byte-determinism for real concurrency —
the mode the true-hedge latency experiments use, where the hedge delay
must race a genuinely in-flight RPC.

Scenario matrix (`SCENARIOS` / `build_scenario`): smoke (tier-1 CI),
partition_storm, lying_helper, latency_ramp, chip_wedge, tenant_flood.
`check_invariants` encodes the properties every scenario must hold:
zero wrong verdicts (an invalid set NEVER resolves True, under any
fault class), block import alive within its slot deadline through a
full offload partition (the degradation chain's availability claim),
and every job's SLI counted exactly once (good + miss == total).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
import time
from dataclasses import dataclass, replace
from types import SimpleNamespace

from lodestar_tpu import slo
from lodestar_tpu.chain.bls.fallback import DegradingBlsVerifier
from lodestar_tpu.chain.bls.interface import IBlsVerifier, VerifySignatureOpts
from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.network.processor import NetworkProcessor
from lodestar_tpu.offload.audit import AuditSampler, OffloadAuditor
from lodestar_tpu.offload.client import BlsOffloadClient
from lodestar_tpu.offload.server import BlsOffloadServer, local_transports
from lodestar_tpu.scheduler import PriorityClass, PriorityWorkQueue

from .clock import SimClock
from .faults import FaultInjector, FaultKind, FaultRule

__all__ = [
    "FleetConfig",
    "FleetEvent",
    "FleetResult",
    "MetricsStub",
    "SCENARIOS",
    "SyntheticCpuVerifier",
    "build_scenario",
    "check_invariants",
    "make_set",
    "oracle_verify",
    "run_fleet",
]


# -- synthetic deterministic crypto -------------------------------------------


def _synthetic_signature(pubkey: bytes, message: bytes) -> bytes:
    """The harness's stand-in pairing: 96 'signature' bytes derived from
    (pubkey, message). Valid by construction iff untampered — verdict
    correctness is decidable without real BLS, at hash speed."""
    return hashlib.sha256(pubkey + message).digest() * 3


def make_set(rng: random.Random, valid: bool = True) -> SignatureSet:
    """One deterministic synthetic signature set from `rng`'s stream."""
    pubkey = rng.randbytes(48)
    message = rng.randbytes(32)
    sig = _synthetic_signature(pubkey, message)
    if not valid:
        sig = bytes([sig[0] ^ 0x01]) + sig[1:]
    return SignatureSet(pubkey=pubkey, message=message, signature=sig)


def oracle_verify(sets: list[SignatureSet]) -> bool:
    """Ground truth for synthetic sets (the harness's CPU oracle and the
    audit reference both bind to this)."""
    return all(
        s.signature == _synthetic_signature(s.pubkey, s.message) for s in sets
    )


class SyntheticCpuVerifier(IBlsVerifier):
    """The degradation chain's always-alive last layer: inline oracle
    verification, with an optional virtual-time cost per call so the
    fallback path is visibly slower than offload in the ledger."""

    def __init__(self, clock: SimClock | None = None, cost_s: float = 0.0) -> None:
        self._clock = clock
        self._cost_s = cost_s

    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        if self._clock is not None and self._cost_s:
            self._clock.advance(self._cost_s)
        return oracle_verify(list(sets))

    def can_accept_work(self) -> bool:
        return True

    async def close(self) -> None:
        return None


# -- duck-typed metrics capture ------------------------------------------------


class _Cell:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n

    def set(self, v: float) -> None:
        self.value = float(v)

    def observe(self, v: float) -> None:
        self.value += v


class _Metric:
    def __init__(self) -> None:
        self.cells: dict[tuple[str, ...], _Cell] = {}

    def labels(self, *labels) -> _Cell:
        return self.cells.setdefault(tuple(str(x) for x in labels), _Cell())

    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def total(self) -> float:
        return sum(c.value for c in self.cells.values())


class MetricsStub:
    """Autovivifying stand-in for any labeled-metrics family the client
    touches (`routed`, `hedges`, `hedge_wins`, `failovers`, `shed`,
    breaker gauges, ...) — records values instead of exporting them."""

    def __init__(self) -> None:
        object.__setattr__(self, "_metrics", {})

    def __getattr__(self, name: str) -> _Metric:
        if name.startswith("_"):
            raise AttributeError(name)
        return self._metrics.setdefault(name, _Metric())

    def total(self, name: str) -> float:
        m = self._metrics.get(name)
        return m.total() if m is not None else 0.0

    def snapshot(self) -> dict:
        return {
            name: {"|".join(k) or "_": c.value for k, c in m.cells.items()}
            for name, m in sorted(self._metrics.items())
        }


# -- config / events -----------------------------------------------------------


#: actions that START a degradation window
_DEGRADE_ACTIONS = {"partition": "partition", "latency": "latency",
                    "wedge": "wedge", "lie": "lie"}
#: actions that END one (mapped to the window kind they clear)
_HEAL_ACTIONS = {"heal": "partition", "clear_latency": "latency",
                 "heal_wedge": "wedge", "clear_lie": "lie"}


@dataclass(frozen=True)
class FleetEvent:
    """One scheduled fault-state change, applied at the start of `slot`.

    `node`/`host` select edges (None = every node / every host);
    `wedge`/`heal_wedge` are host-scoped (the backend seam).
    `latency` REPLACES any prior latency level on the selected edges
    (so a ramp is a sequence of latency events), `lie` adds a
    probabilistic byzantine LIE_VERDICT rule."""

    slot: int
    action: str  # partition|heal|latency|clear_latency|wedge|heal_wedge|lie|clear_lie
    node: int | None = None
    host: int | None = None
    delay_s: float = 0.0
    probability: float = 1.0


@dataclass
class FleetConfig:
    """One seeded simulation: fleet shape, workload scale, fault plan."""

    name: str = "custom"
    nodes: int = 2
    hosts: int = 1
    slots: int = 5
    validators: int = 512
    seed: int = 0
    seconds_per_slot: int = 12
    virtual_time: bool = True
    hedge_delay_ms: float | None = None
    audit_rate: float = 0.0
    invalid_rate: float = 0.0  # fraction of att/api packages made invalid
    api_burst: int = 0  # extra CONCURRENT api jobs per slot (tenant_flood)
    range_sync_every: int = 0  # bulk batch every N slots (0 = off)
    tenant_quota_depth: int | None = None  # host-side per-tenant shed depth
    backend_latency_s: float = 0.0  # per-launch backend hold time (real or virtual)
    cpu_cost_s: float = 0.050  # virtual cost of a fallback-layer verdict
    offload_cost_s: float = 0.002  # virtual cost of an offload verdict
    timeout_s: float = 10.0
    events: tuple[FleetEvent, ...] = ()

    def att_packages_per_slot(self) -> int:
        return max(1, min(8, self.validators // 256))


@dataclass
class FleetResult:
    config: FleetConfig
    ledger: list[dict]
    ledger_lines: list[str]  # JSON lines, byte-stable under virtual time
    fault_schedule: dict  # edge name -> FaultInjector.export_trace()
    summary: dict
    metrics: dict  # node index (str) -> MetricsStub.snapshot()
    sli: dict  # slo.wait_budget() at end of run
    endpoint_states: dict  # node index (str) -> client.endpoint_states()


# -- jobs ----------------------------------------------------------------------


@dataclass
class _Job:
    node: int
    slot: int
    jid: str
    cls: PriorityClass
    sets: list[SignatureSet]
    valid: bool
    js: object = None
    enqueued_at: float = 0.0


class _SimHost:
    """One offload host: real `BlsOffloadServer` on a fake target, its
    backend behind a seeded injector (the chip-wedge / backend-fault
    seam), chip table reflecting the wedge flag."""

    def __init__(self, index: int, cfg: FleetConfig, clock: SimClock | None) -> None:
        self.index = index
        self.target = f"sim-host-{index}:9"
        self.wedged = False
        rules = []
        if cfg.backend_latency_s > 0:
            # baseline per-launch hold time: with real time this is what
            # makes tenant service slots actually contended (quota sheds
            # need queue depth, and depth needs occupancy)
            rules.append(
                FaultRule(kind=FaultKind.LATENCY, delay_s=cfg.backend_latency_s)
            )
        self.backend_injector = FaultInjector(
            rules,
            seed=cfg.seed * 104729 + index,
            sleep_fn=clock.sleep if clock is not None else None,
        )
        backend = self.backend_injector.wrap_backend(
            oracle_verify, target=f"host{index}-backend"
        )
        kwargs = {}
        if cfg.tenant_quota_depth is not None:
            # cap EVERY class at the quota depth (reject == shed): the
            # flood scenario floods the API class, which per-tenant
            # grading only turns away at reject_depth
            kwargs["tenant_shed_depth"] = cfg.tenant_quota_depth
            kwargs["tenant_reject_depth"] = cfg.tenant_quota_depth
        self.server = BlsOffloadServer(
            backend, chip_status_fn=self._chip_table, **kwargs
        )

    def _chip_table(self):
        return [(self.server.occupancy.occupancy_permille(), self.wedged)]

    def set_wedged(self, wedged: bool) -> None:
        """Chip wedge: the backend errors every launch (ERROR_FRAME at
        the reply layer) and the Status mesh trailer advertises the
        wedged chip, so routing sees capacity drop within one probe."""
        self.wedged = wedged
        inj = self.backend_injector
        inj.rules = [r for r in inj.rules if r.kind is not FaultKind.ERROR_FRAME]
        if wedged:
            inj.rules.append(FaultRule(kind=FaultKind.ERROR_FRAME))


class _SimNode:
    """One beacon node's verification stack, wired end to end."""

    def __init__(
        self,
        index: int,
        cfg: FleetConfig,
        clock: SimClock | None,
        hosts: list[_SimHost],
    ) -> None:
        self.index = index
        self.cfg = cfg
        self.clock = clock
        self.rng = random.Random((cfg.seed << 16) ^ (index * 7919 + 1))
        self.metrics = MetricsStub()
        self.ledger: list[dict] = []
        # one injector per node->host edge: its seed (and therefore its
        # probabilistic draws AND its exported schedule) is a pure
        # function of (fleet seed, node, host)
        self.edge_injectors: dict[str, FaultInjector] = {
            h.target: FaultInjector(
                seed=cfg.seed * 7919 + index * 101 + h.index,
                sleep_fn=clock.sleep if clock is not None else None,
            )
            for h in hosts
        }
        servers = {h.target: h.server for h in hosts}
        base = local_transports(
            servers, clock=clock.monotonic if clock is not None else None
        )

        def wrapper(target: str, method: str, fn):
            return self.edge_injectors[target].wrap_transport(
                target, method, base(target, method, fn)
            )

        self.auditor = None
        if cfg.audit_rate > 0.0:
            self.auditor = OffloadAuditor(
                sampler=AuditSampler(
                    rate=cfg.audit_rate, seed=cfg.seed * 31 + index
                ),
                reference=lambda sets, exclude: (oracle_verify(sets), None),
                budget=1.0,
            )
        self.client = BlsOffloadClient(
            [h.target for h in hosts],
            timeout_s=cfg.timeout_s,
            # the driver probes synchronously at every slot start; the probe
            # thread fires once at startup and then sleeps out the run
            probe_interval_s=3600.0,
            metrics=self.metrics,
            transport_wrapper=wrapper,
            auditor=self.auditor,
            hedge_delay_ms=cfg.hedge_delay_ms,
            tenant=f"node{index}",
            quarantine_cooloff_s=None,  # lying helpers stay out
            breaker_clock=clock.monotonic if clock is not None else None,
        )
        for ep in self.client._endpoints:
            ep.breaker.jitter = 0.0  # reset schedule must replay exactly
        cpu = SyntheticCpuVerifier(clock, cfg.cpu_cost_s)
        self.deg = DegradingBlsVerifier([("offload", self.client), ("cpu", cpu)])
        self.queue = PriorityWorkQueue(
            time_fn=clock.monotonic_ns if clock is not None else time.monotonic_ns
        )
        chain = SimpleNamespace(bls=self.deg)
        self.processor = NetworkProcessor(
            chain,
            handlers={
                "beacon_block": self._gossip_handler(),
                "beacon_attestation": self._gossip_handler(),
            },
        )
        self._jid = 0

    # -- workload ---------------------------------------------------------------

    def _gossip_handler(self):
        async def handler(job: _Job, peer: str) -> None:
            self._enqueue(job)

        return handler

    def _enqueue(self, job: _Job) -> None:
        job.js = slo.job_begin(job.cls, job.slot)
        job.enqueued_at = self._now()
        self.queue.put_nowait(job, job.cls)

    def _now(self) -> float:
        return self.clock.time() if self.clock is not None else time.time()

    def _new_job(self, slot: int, cls: PriorityClass, n_sets: int, valid: bool) -> _Job:
        self._jid += 1
        return _Job(
            node=self.index,
            slot=slot,
            jid=f"n{self.index}-s{slot}-j{self._jid}",
            cls=cls,
            sets=[make_set(self.rng, valid) for _ in range(n_sets)],
            valid=valid,
        )

    def push_slot_workload(self, slot: int) -> None:
        """Mainnet-shaped synthetic slot: one gossip block, a validator-
        scaled burst of attestation packages, one API call, periodic
        range-sync bulk. Blocks are always valid (their liveness is the
        invariant under test); attestation/api validity draws from the
        node's seeded stream at `invalid_rate`."""
        cfg = self.cfg
        self.processor.push(
            "beacon_block",
            self._new_job(slot, PriorityClass.GOSSIP_BLOCK, 2, True),
            peer=f"peer{self.index}",
        )
        for _ in range(cfg.att_packages_per_slot()):
            valid = self.rng.random() >= cfg.invalid_rate
            self.processor.push(
                "beacon_attestation",
                self._new_job(slot, PriorityClass.GOSSIP_ATTESTATION, 4, valid),
                peer=f"peer{self.index}",
            )
        valid = self.rng.random() >= cfg.invalid_rate
        self._enqueue(self._new_job(slot, PriorityClass.API, 1, valid))
        if cfg.range_sync_every and slot and slot % cfg.range_sync_every == 0:
            self._enqueue(self._new_job(slot, PriorityClass.RANGE_SYNC, 16, True))

    # -- drive ------------------------------------------------------------------

    def probe(self) -> None:
        """Synchronous per-slot endpoint probe — the deterministic stand-
        in for the client's background probe loop (parked on a one-hour
        interval). Keeps `ep.healthy` converging with the fault state at
        slot granularity, and fires `note_probe_success` on recovery so
        a healed endpoint's breaker grants its half-open trial."""
        for ep in self.client._endpoints:
            if self.client._probe_one(ep):
                ep.consecutive_failures = 0
            else:
                ep.consecutive_failures += 1

    async def run_job(self, job: _Job, waited_ns: int) -> dict:
        slo.job_dequeued(job.js, waited_ns)
        slo.job_launch(job.js)
        error = None
        verdict: bool | None = None
        layer = None
        try:
            verdict = await self.deg.verify_signature_sets(
                job.sets, VerifySignatureOpts(priority=job.cls, slot=job.slot)
            )
            layer = self.deg.serving_layer()
        except Exception as e:  # every layer erred: fail closed
            error = f"{type(e).__name__}: {e}"[:120]
        if self.clock is not None:
            self.clock.advance(
                self.cfg.offload_cost_s if layer == "offload" else self.cfg.cpu_cost_s
            )
        slo.job_verdict(job.js, bool(verdict))
        line = {
            "node": job.node,
            "slot": job.slot,
            "jid": job.jid,
            "cls": job.cls.label,
            "n_sets": len(job.sets),
            "valid": job.valid,
            "verdict": verdict,
            "layer": layer,
            "error": error,
            "t_enqueue": round(job.enqueued_at, 6),
            "t_verdict": round(self._now(), 6),
            "slack_ms": (
                round((job.js.deadline_s - self._now()) * 1000.0, 3)
                if job.js is not None
                else None
            ),
        }
        self.ledger.append(line)
        return line

    async def drain(self) -> int:
        """One slot's service: processor tick into the scheduler queue,
        then stride-fair dequeue until empty."""
        await self.processor.execute_work()
        served = 0
        while True:
            out = self.queue.get_nowait()
            if out is None:
                return served
            job, _cls, waited_ns = out
            await self.run_job(job, waited_ns)
            served += 1

    async def api_flood(self, slot: int) -> None:
        """`api_burst` CONCURRENT same-tenant API jobs — the tenant-
        quota pressure source (tenant_flood scenario). Concurrency is
        real (executor threads), so this path is invariant-checked, not
        byte-compared."""
        jobs = []
        for _ in range(self.cfg.api_burst):
            job = self._new_job(slot, PriorityClass.API, 1, True)
            job.js = slo.job_begin(job.cls, job.slot)
            job.enqueued_at = self._now()
            jobs.append(job)
        await asyncio.gather(*(self.run_job(j, 0) for j in jobs))

    def drain_audit(self) -> None:
        if self.auditor is not None:
            self.auditor.drain(timeout_s=10.0)

    async def close(self) -> None:
        await self.deg.close()


# -- the driver ----------------------------------------------------------------


def _expand_edges(ev: FleetEvent, n_nodes: int, n_hosts: int):
    nodes = [ev.node] if ev.node is not None else list(range(n_nodes))
    hosts = [ev.host] if ev.host is not None else list(range(n_hosts))
    for n in nodes:
        for h in hosts:
            yield n, h


def _apply_event(
    ev: FleetEvent,
    nodes: list[_SimNode],
    hosts: list[_SimHost],
    active: set[tuple],
) -> None:
    if ev.action in ("wedge", "heal_wedge"):
        sel = [ev.host] if ev.host is not None else list(range(len(hosts)))
        for h in sel:
            hosts[h].set_wedged(ev.action == "wedge")
            for n in range(len(nodes)):
                key = ("wedge", n, h)
                active.add(key) if ev.action == "wedge" else active.discard(key)
        return
    for n, h in _expand_edges(ev, len(nodes), len(hosts)):
        inj = nodes[n].edge_injectors[hosts[h].target]
        target = hosts[h].target
        if ev.action == "partition":
            inj.partition(target)
        elif ev.action == "heal":
            inj.heal(target)
        elif ev.action == "latency":
            # replace-not-stack: a ramp is successive latency levels
            inj.rules = [r for r in inj.rules if r.kind is not FaultKind.LATENCY]
            if ev.delay_s > 0:
                inj.rules.append(
                    FaultRule(
                        kind=FaultKind.LATENCY,
                        delay_s=ev.delay_s,
                        targets=frozenset({target}),
                        methods=frozenset({"verify"}),
                    )
                )
        elif ev.action == "clear_latency":
            inj.rules = [r for r in inj.rules if r.kind is not FaultKind.LATENCY]
        elif ev.action == "lie":
            inj.rules.append(
                FaultRule(
                    kind=FaultKind.LIE_VERDICT,
                    probability=ev.probability,
                    targets=frozenset({target}),
                    methods=frozenset({"verify"}),
                )
            )
        elif ev.action == "clear_lie":
            inj.rules = [r for r in inj.rules if r.kind is not FaultKind.LIE_VERDICT]
        else:
            raise ValueError(f"unknown fleet event action: {ev.action!r}")
        kind = _DEGRADE_ACTIONS.get(ev.action) or _HEAL_ACTIONS.get(ev.action)
        key = (kind, n, h)
        if ev.action in _DEGRADE_ACTIONS and (
            ev.action != "latency" or ev.delay_s > 0
        ):
            active.add(key)
        else:
            active.discard(key)


async def _run_fleet(cfg: FleetConfig) -> FleetResult:
    clock = SimClock(0.0) if cfg.virtual_time else None
    genesis = 0.0 if clock is not None else time.time()
    slo.reset_slo()
    slo.configure_slo(
        genesis_time=genesis,
        seconds_per_slot=cfg.seconds_per_slot,
        time_fn=clock.time if clock is not None else time.time,
        monotonic_ns_fn=clock.monotonic_ns if clock is not None else time.monotonic_ns,
    )
    hosts = [_SimHost(i, cfg, clock) for i in range(cfg.hosts)]
    nodes = [_SimNode(i, cfg, clock, hosts) for i in range(cfg.nodes)]
    events_by_slot: dict[int, list[FleetEvent]] = {}
    for ev in cfg.events:
        events_by_slot.setdefault(ev.slot, []).append(ev)
    active: set[tuple] = set()
    degraded_slots: list[bool] = []
    heal_slots = [
        ev.slot for ev in cfg.events if ev.action in _HEAL_ACTIONS
    ]
    try:
        for slot in range(cfg.slots):
            if clock is not None:
                clock.advance_to(genesis + slot * cfg.seconds_per_slot)
            for ev in events_by_slot.get(slot, ()):
                _apply_event(ev, nodes, hosts, active)
            degraded_slots.append(bool(active))
            for node in nodes:
                node.probe()
                node.push_slot_workload(slot)
            for node in nodes:
                await node.drain()
                if cfg.api_burst:
                    await node.api_flood(slot)
                node.drain_audit()
        # leftovers (work a backpressured tick deferred): serve them so
        # every begun job reaches its exactly-once verdict accounting
        for node in nodes:
            for _ in range(3):
                if await node.drain() == 0 and node.processor.pending == 0:
                    break
            node.drain_audit()
        endpoint_states = {
            str(n.index): n.client.endpoint_states() for n in nodes
        }
        sli = slo.wait_budget()
    finally:
        for node in nodes:
            await node.close()
        slo.reset_slo()

    ledger: list[dict] = []
    for node in nodes:
        ledger.extend(node.ledger)
    ledger.sort(key=lambda ln: (ln["slot"], ln["node"], ln["jid"]))
    ledger_lines = [json.dumps(ln, sort_keys=True) for ln in ledger]
    fault_schedule = {
        f"node{n.index}->{target}": inj.export_trace()
        for n in nodes
        for target, inj in sorted(n.edge_injectors.items())
    }
    for h in hosts:
        fault_schedule[f"{h.target}-backend"] = h.backend_injector.export_trace()
    summary = _summarize(
        cfg, ledger, degraded_slots, heal_slots, nodes, endpoint_states, sli
    )
    return FleetResult(
        config=cfg,
        ledger=ledger,
        ledger_lines=ledger_lines,
        fault_schedule=fault_schedule,
        summary=summary,
        metrics={str(n.index): n.metrics.snapshot() for n in nodes},
        sli=sli,
        endpoint_states=endpoint_states,
    )


def _summarize(
    cfg: FleetConfig,
    ledger: list[dict],
    degraded_slots: list[bool],
    heal_slots: list[int],
    nodes: list[_SimNode],
    endpoint_states: dict,
    sli: dict,
) -> dict:
    per_slot: dict[int, int] = {s: 0 for s in range(cfg.slots)}
    wrong = 0
    served = {"offload": 0, "cpu": 0, "none": 0}
    for ln in ledger:
        per_slot[ln["slot"]] = per_slot.get(ln["slot"], 0) + 1
        if not ln["valid"] and ln["verdict"] is True:
            wrong += 1
        served[ln["layer"] if ln["layer"] in served else "none"] += 1
    base = [per_slot[s] for s in range(cfg.slots) if not degraded_slots[s]]
    degr = [per_slot[s] for s in range(cfg.slots) if degraded_slots[s]]
    baseline_tput = sum(base) / len(base) if base else 0.0
    degraded_tput = sum(degr) / len(degr) if degr else baseline_tput
    retention = (
        100.0 * degraded_tput / baseline_tput if baseline_tput > 0 else 100.0
    )
    recovery = 0
    if heal_slots:
        last_heal = max(heal_slots)
        recovery = max(0, cfg.slots - last_heal)
        for s in range(last_heal, cfg.slots):
            blocks = [
                ln
                for ln in ledger
                if ln["slot"] == s and ln["cls"] == "gossip_block"
            ]
            if blocks and all(ln["layer"] == "offload" for ln in blocks):
                recovery = s - last_heal
                break
    quarantined = [
        (node_idx, st["target"])
        for node_idx, states in sorted(endpoint_states.items())
        for st in states
        if st.get("quarantined")
    ]
    misses = sum(c["sli"]["miss"] for c in sli.get("classes", {}).values())
    lat = [
        (ln["t_verdict"] - ln["t_enqueue"]) * 1000.0
        for ln in ledger
        if ln["t_verdict"] is not None
    ]
    mean_latency = sum(lat) / len(lat) if lat else 0.0
    return {
        "scenario": cfg.name,
        "seed": cfg.seed,
        "total_jobs": len(ledger),
        "wrong_verdicts": wrong,
        "served_by_layer": served,
        "baseline_throughput_per_slot": round(baseline_tput, 3),
        "degraded_throughput_per_slot": round(degraded_tput, 3),
        "throughput_retention_pct": round(retention, 2),
        "recovery_slots": recovery,
        "degraded_slot_count": sum(degraded_slots),
        "sli_misses": misses,
        "mean_latency_ms": round(mean_latency, 3),
        "quarantined": quarantined,
        "hedges": sum(n.metrics.total("hedges") for n in nodes),
        "hedge_wins": sum(n.metrics.total("hedge_wins") for n in nodes),
        "failovers": sum(n.metrics.total("failovers") for n in nodes),
        "sheds": sum(n.metrics.total("shed") for n in nodes),
        "byzantine_events": sum(
            len(n.auditor.byzantine_events) for n in nodes if n.auditor is not None
        ),
    }


def run_fleet(cfg: FleetConfig) -> FleetResult:
    """Run one seeded fleet simulation to completion (blocking)."""
    return asyncio.run(_run_fleet(cfg))


# -- invariants ----------------------------------------------------------------


def check_invariants(result: FleetResult) -> list[str]:
    """The properties every scenario must hold, as violation strings
    (empty list == green):

    1. ZERO WRONG VERDICTS: no invalid set ever resolves True, under
       any fault class (fail-closed end to end).
    2. BLOCK IMPORT ALIVE: every gossip block reaches a True verdict
       with slot-deadline slack to spare — through partitions, the
       degradation chain must keep serving.
    3. EXACTLY-ONCE SLI: every job is counted once (good + miss ==
       total == ledger jobs); a retried or hedged job must not double-
       count its miss.
    """
    v: list[str] = []
    for ln in result.ledger:
        if not ln["valid"] and ln["verdict"] is True:
            v.append(f"WRONG VERDICT: invalid job {ln['jid']} resolved True")
    # a byzantine helper's True->False flip is an availability miss the
    # audit layer contains (quarantine) — under lie scenarios liveness
    # means a timely fail-closed answer; everywhere else the valid
    # block must actually import
    lies_injected = any(ev.action == "lie" for ev in result.config.events)
    for ln in result.ledger:
        if ln["cls"] != "gossip_block":
            continue
        if ln["error"] is not None or ln["verdict"] is None:
            v.append(
                f"BLOCK IMPORT DEAD: {ln['jid']} verdict={ln['verdict']} "
                f"error={ln['error']}"
            )
        elif ln["verdict"] is not True and not lies_injected:
            v.append(f"BLOCK REJECTED: valid block {ln['jid']} resolved False")
        elif ln["slack_ms"] is not None and ln["slack_ms"] < 0:
            v.append(
                f"BLOCK DEADLINE MISSED: {ln['jid']} slack_ms={ln['slack_ms']}"
            )
    # exactly-once SLI accounting, reconciled against the ledger: each
    # job contributes ONE total; good iff it resolved True with slack,
    # miss iff its slack went negative (an in-time False verdict is
    # neither — it met the deadline with an answer of 'invalid')
    classes = result.sli.get("classes", {})
    want: dict[str, dict[str, int]] = {}
    for ln in result.ledger:
        w = want.setdefault(ln["cls"], {"total": 0, "good": 0, "miss": 0})
        w["total"] += 1
        slack = ln["slack_ms"]
        met = slack is None or slack >= 0
        if ln["verdict"] is True and met:
            w["good"] += 1
        if not met:
            w["miss"] += 1
    for label, stats in classes.items():
        sli = stats["sli"]
        w = want.get(label, {"total": 0, "good": 0, "miss": 0})
        for k in ("total", "good", "miss"):
            if sli[k] != w[k]:
                v.append(
                    f"SLI MISCOUNT: {label} {k}={sli[k]} != ledger-expected "
                    f"{w[k]} (counted other than exactly once per job)"
                )
    return v


# -- scenario matrix -----------------------------------------------------------


def _smoke(seed: int) -> FleetConfig:
    """Tier-1 CI scenario: 2 nodes, 1 host, 5 virtual slots, full
    offload partition at slot 2, heal at slot 4."""
    return FleetConfig(
        name="smoke",
        nodes=2,
        hosts=1,
        slots=5,
        validators=512,
        seed=seed,
        events=(
            FleetEvent(slot=2, action="partition"),
            FleetEvent(slot=4, action="heal"),
        ),
    )


def _partition_storm(seed: int) -> FleetConfig:
    """Rolling partitions across both hosts, ending in a full blackout
    and a heal — failover, breaker recovery and CPU-fallback liveness
    in one run."""
    return FleetConfig(
        name="partition_storm",
        nodes=3,
        hosts=2,
        slots=12,
        validators=1024,
        seed=seed,
        invalid_rate=0.1,
        range_sync_every=4,
        events=(
            FleetEvent(slot=2, action="partition", host=0),
            FleetEvent(slot=4, action="heal", host=0),
            FleetEvent(slot=5, action="partition", host=1),
            FleetEvent(slot=7, action="heal", host=1),
            FleetEvent(slot=8, action="partition"),
            FleetEvent(slot=10, action="heal"),
        ),
    )


def _lying_helper(seed: int) -> FleetConfig:
    """Host 1 turns byzantine (LIE_VERDICT: re-signed lies the framing
    cannot catch) with the audit layer on at rate 1.0. The workload is
    all-valid, so every lie is a True→False flip: containment (audit
    quarantine) is observable and the zero-wrong-verdict invariant is
    meaningful — nothing invalid is in flight for a lie to whitewash."""
    return FleetConfig(
        name="lying_helper",
        nodes=2,
        hosts=2,
        slots=10,
        validators=512,
        seed=seed,
        audit_rate=1.0,
        # host 0 is the tie-break-preferred route: the liar is the host
        # actually SERVING, so every lie is observable and the audit
        # quarantine must visibly shift traffic to host 1
        events=(FleetEvent(slot=2, action="lie", host=0, probability=1.0),),
    )


def _latency_ramp(seed: int) -> FleetConfig:
    """Host 0's verify latency ramps 50ms → 400ms → 1.5s, then clears.
    Virtual-time: the ramp exercises deadline budgets and failover; the
    real-concurrency hedge race lives in the offload hedge tests."""
    return FleetConfig(
        name="latency_ramp",
        nodes=2,
        hosts=2,
        slots=10,
        validators=512,
        seed=seed,
        events=(
            FleetEvent(slot=2, action="latency", host=0, delay_s=0.05),
            FleetEvent(slot=4, action="latency", host=0, delay_s=0.4),
            FleetEvent(slot=6, action="latency", host=0, delay_s=1.5),
            FleetEvent(slot=8, action="clear_latency", host=0),
        ),
    )


def _chip_wedge(seed: int) -> FleetConfig:
    """Host 0's chip wedges (backend errors + wedged chip advertised);
    traffic must shift to host 1 and return after the heal."""
    return FleetConfig(
        name="chip_wedge",
        nodes=2,
        hosts=2,
        slots=8,
        validators=512,
        seed=seed,
        events=(
            FleetEvent(slot=2, action="wedge", host=0),
            FleetEvent(slot=5, action="heal_wedge", host=0),
        ),
    )


def _tenant_flood(seed: int) -> FleetConfig:
    """Node 1 floods the single shared host with concurrent API bursts
    against a tight per-tenant quota: sheds must hit the flooding
    tenant while gossip classes stay alive. Real concurrency —
    invariant-checked, not byte-compared."""
    return FleetConfig(
        name="tenant_flood",
        nodes=2,
        hosts=1,
        slots=6,
        validators=512,
        seed=seed,
        api_burst=8,
        tenant_quota_depth=2,
        # real time + a real backend hold: quota sheds need genuine
        # service-slot contention, which virtual sleeps cannot create
        virtual_time=False,
        backend_latency_s=0.02,
    )


def _hedge_race(seed: int) -> FleetConfig:
    """Real-time hedge-tuning arm: host 0 holds every verify 250ms from
    slot 1 on while host 1 stays fast. The hedge-delay sweep runs here
    because virtual sleeps return instantly in wall-clock terms — a
    wall-clock hedge timer can only race wall-clock latency. Scored on
    mean verdict latency; invariant-checked, not byte-compared."""
    return FleetConfig(
        name="hedge_race",
        nodes=2,
        hosts=2,
        slots=4,
        validators=512,
        seed=seed,
        virtual_time=False,
        hedge_delay_ms=30.0,
        events=(FleetEvent(slot=1, action="latency", host=0, delay_s=0.25),),
    )


SCENARIOS = {
    "smoke": _smoke,
    "partition_storm": _partition_storm,
    "lying_helper": _lying_helper,
    "latency_ramp": _latency_ramp,
    "chip_wedge": _chip_wedge,
    "tenant_flood": _tenant_flood,
    "hedge_race": _hedge_race,
}


def build_scenario(name: str, seed: int = 0, **overrides) -> FleetConfig:
    """A scenario config by name, with per-experiment knob overrides
    (the chaos experiment runner's sweep entry point)."""
    try:
        builder = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}"
        ) from None
    cfg = builder(seed)
    return replace(cfg, **overrides) if overrides else cfg
