"""Doppelganger protection: refuse to sign until freshly-added keys
have observed quiet epochs on the network.

Reference `validator/src/services/doppelgangerService.ts`: each
registered pubkey must watch DEFAULT_REMAINING_DETECTION_EPOCHS (2)
full epochs of liveness data; ANY observed activity for its validator
index means another instance is running the same key — signing is
blocked permanently (the reference shuts the process down). Liveness
comes from the beacon API's POST /eth/v1/validator/liveness/{epoch}.
"""

from __future__ import annotations

import enum

from lodestar_tpu.logger import get_logger

__all__ = ["DoppelgangerService", "DoppelgangerStatus", "DoppelgangerDetected"]

DEFAULT_REMAINING_DETECTION_EPOCHS = 2


class DoppelgangerStatus(enum.Enum):
    VERIFIED_SAFE = "VerifiedSafe"
    UNVERIFIED = "Unverified"
    UNKNOWN = "Unknown"
    DETECTED = "Detected"


class DoppelgangerDetected(Exception):
    pass


class DoppelgangerService:
    def __init__(self, detection_epochs: int = DEFAULT_REMAINING_DETECTION_EPOCHS):
        self.detection_epochs = detection_epochs
        self.log = get_logger(name="lodestar.doppelganger")
        # pubkey -> remaining epochs to observe (0 = verified safe, -1 = detected)
        self._remaining: dict[bytes, int] = {}
        self._registered_epoch: dict[bytes, int] = {}
        self._last_processed: dict[bytes, int] = {}

    def register_validator(self, pubkey: bytes, current_epoch: int) -> None:
        pubkey = bytes(pubkey)
        if pubkey in self._remaining:
            return
        # genesis-epoch registrations skip detection (reference: nothing
        # could have signed before the chain started)
        remaining = 0 if current_epoch == 0 else self.detection_epochs
        self._remaining[pubkey] = remaining
        self._registered_epoch[pubkey] = current_epoch

    def status(self, pubkey: bytes) -> DoppelgangerStatus:
        remaining = self._remaining.get(bytes(pubkey))
        if remaining is None:
            return DoppelgangerStatus.UNKNOWN
        if remaining < 0:
            return DoppelgangerStatus.DETECTED
        if remaining == 0:
            return DoppelgangerStatus.VERIFIED_SAFE
        return DoppelgangerStatus.UNVERIFIED

    def is_safe(self, pubkey: bytes) -> bool:
        """Unknown (never registered) keys are treated as safe — the
        service only gates keys explicitly enrolled for detection
        (reference getStatus default)."""
        return self.status(pubkey) in (
            DoppelgangerStatus.VERIFIED_SAFE,
            DoppelgangerStatus.UNKNOWN,
        )

    @property
    def detected(self) -> list[bytes]:
        return [pk for pk, r in self._remaining.items() if r < 0]

    def on_epoch_liveness(
        self, epoch: int, liveness_by_pubkey: dict[bytes, bool]
    ) -> list[bytes]:
        """Process one epoch of liveness data for the watched keys.
        Returns newly-detected pubkeys (and marks them blocked). A key
        only burns down its counter for epochs AFTER its registration
        (its own pre-registration activity is not a doppelganger)."""
        newly_detected = []
        for pubkey, live in liveness_by_pubkey.items():
            pubkey = bytes(pubkey)
            remaining = self._remaining.get(pubkey)
            if remaining is None or remaining <= 0:
                continue
            if epoch <= self._registered_epoch[pubkey]:
                continue
            if epoch <= self._last_processed.get(pubkey, -1):
                continue  # an epoch counts once; retries must not burn the window
            self._last_processed[pubkey] = epoch
            if live:
                self._remaining[pubkey] = -1
                newly_detected.append(pubkey)
                self.log.error(
                    "DOPPELGANGER DETECTED — blocking key",
                    {"pubkey": "0x" + pubkey.hex()[:16], "epoch": epoch},
                )
            else:
                self._remaining[pubkey] = remaining - 1
                if self._remaining[pubkey] == 0:
                    self.log.info(
                        "doppelganger detection complete, key is safe",
                        {"pubkey": "0x" + pubkey.hex()[:16]},
                    )
        return newly_detected
