"""Slashing protection DB: double votes, surround votes (min-max spans),
double proposals, lower bounds, EIP-3076 interchange.

Reference `validator/src/slashingProtection/`:
* attestation checks (`attestation/index.ts:39`): source<=target, double
  vote by target epoch, lower-bound gates, then min-max surround
  (`minMaxSurround/minMaxSurround.ts`, protolambda's scheme: minSpan[e] =
  min(target - e) over atts with source > e; maxSpan[e] = max(target - e)
  over atts with source < e < target; a new (s, t) is surrounding iff
  minSpan[s] < t - s, surrounded iff maxSpan[s] > t - s).
* block checks (`block/index.ts:24`): double proposal by slot + lower
  bound.
* interchange (EIP-3076 v5 complete format, `interchange/`).

Storage is the repo db layer using the reference's bucket ids (20-24).
"""

from __future__ import annotations

import json

from lodestar_tpu.db import Bucket, DbController, FilterOptions, encode_key

__all__ = [
    "SlashingProtection",
    "SlashingError",
    "SlashingErrorCode",
    "MAX_EPOCH_LOOKBACK",
]

MAX_EPOCH_LOOKBACK = 4096  # minMaxSurround.ts DEFAULT_MAX_EPOCH_LOOKBACK


class SlashingErrorCode:
    SOURCE_EXCEEDS_TARGET = "SOURCE_EXCEEDS_TARGET"
    DOUBLE_VOTE = "DOUBLE_VOTE"
    SURROUNDING_VOTE = "SURROUNDING_VOTE"
    SURROUNDED_VOTE = "SURROUNDED_VOTE"
    DOUBLE_BLOCK_PROPOSAL = "DOUBLE_BLOCK_PROPOSAL"
    BELOW_LOWER_BOUND = "BELOW_LOWER_BOUND"


class SlashingError(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code


def _u64(v: int) -> bytes:
    return int(v).to_bytes(8, "big")


class _PerPubkeyMap:
    """bucket[pubkey || key_u64] -> json payload."""

    def __init__(self, db: DbController, bucket: Bucket):
        self.db = db
        self.bucket = bucket

    def get(self, pubkey: bytes, key: int):
        raw = self.db.get(encode_key(self.bucket, pubkey + _u64(key)))
        return None if raw is None else json.loads(raw)

    def put(self, pubkey: bytes, key: int, value) -> None:
        self.db.put(encode_key(self.bucket, pubkey + _u64(key)), json.dumps(value).encode())

    def put_batch(self, pubkey: bytes, items: list[tuple[int, object]]) -> None:
        self.db.batch_put(
            [
                (encode_key(self.bucket, pubkey + _u64(k)), json.dumps(v).encode())
                for k, v in items
            ]
        )

    def entries(self, pubkey: bytes):
        lo = encode_key(self.bucket, pubkey)
        hi = encode_key(self.bucket, pubkey + b"\xff" * 9)
        for k, v in self.db.entries_stream(FilterOptions(gte=lo, lt=hi)):
            yield int.from_bytes(k[-8:], "big"), json.loads(v)


class SlashingProtection:
    def __init__(self, db: DbController, *, max_epoch_lookback: int = MAX_EPOCH_LOOKBACK):
        self._att_by_target = _PerPubkeyMap(db, Bucket.phase0_slashingProtectionAttestationByTarget)
        self._lower_bound = _PerPubkeyMap(db, Bucket.phase0_slashingProtectionAttestationLowerBound)
        self._min_span = _PerPubkeyMap(db, Bucket.index_slashingProtectionMinSpanDistance)
        self._max_span = _PerPubkeyMap(db, Bucket.index_slashingProtectionMaxSpanDistance)
        self._block_by_slot = _PerPubkeyMap(db, Bucket.phase0_slashingProtectionBlockBySlot)
        self.max_epoch_lookback = max_epoch_lookback

    # -- attestations ---------------------------------------------------------

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingError(SlashingErrorCode.SOURCE_EXCEEDS_TARGET)

        # double vote: same target epoch, different signing root
        existing = self._att_by_target.get(pubkey, target_epoch)
        if existing is not None:
            if bytes.fromhex(existing["signing_root"]) == signing_root and signing_root != b"\x00" * 32:
                return  # SAME_DATA: already recorded
            raise SlashingError(
                SlashingErrorCode.DOUBLE_VOTE, f"target epoch {target_epoch} already attested"
            )

        # interchange lower bound
        lb = self._lower_bound.get(pubkey, 0)
        if lb is not None:
            if source_epoch < lb.get("min_source", 0):
                raise SlashingError(SlashingErrorCode.BELOW_LOWER_BOUND, "source below lower bound")
            if target_epoch <= lb.get("min_target", -1):
                raise SlashingError(SlashingErrorCode.BELOW_LOWER_BOUND, "target below lower bound")

        # min-max surround
        self._assert_not_surrounding(pubkey, source_epoch, target_epoch)
        self._assert_not_surrounded(pubkey, source_epoch, target_epoch)

        # insert: spans then the by-target record
        self._update_min_span(pubkey, source_epoch, target_epoch)
        self._update_max_span(pubkey, source_epoch, target_epoch)
        self._att_by_target.put(
            pubkey,
            target_epoch,
            {"source_epoch": source_epoch, "signing_root": signing_root.hex()},
        )

    def _assert_not_surrounding(self, pubkey: bytes, source: int, target: int) -> None:
        """New att surrounds an existing one: minSpan[source] < target - source."""
        entry = self._min_span.get(pubkey, source)
        distance = target - source
        if entry is not None and 0 < entry < distance:
            raise SlashingError(
                SlashingErrorCode.SURROUNDING_VOTE,
                f"would surround attestation with target {source + entry}",
            )

    def _assert_not_surrounded(self, pubkey: bytes, source: int, target: int) -> None:
        """New att is surrounded: maxSpan[source] > target - source."""
        entry = self._max_span.get(pubkey, source)
        distance = target - source
        if entry is not None and entry > distance:
            raise SlashingError(
                SlashingErrorCode.SURROUNDED_VOTE,
                f"surrounded by attestation with target {source + entry}",
            )

    def _update_min_span(self, pubkey: bytes, source: int, target: int) -> None:
        until = max(0, source - 1 - self.max_epoch_lookback)
        values = []
        for epoch in range(source - 1, until - 1, -1):
            cur = self._min_span.get(pubkey, epoch)
            distance = target - epoch
            if cur is None or distance < cur:
                values.append((epoch, distance))
            else:
                break
        self._min_span.put_batch(pubkey, values)

    def _update_max_span(self, pubkey: bytes, source: int, target: int) -> None:
        values = []
        for epoch in range(source + 1, target):
            cur = self._max_span.get(pubkey, epoch)
            distance = target - epoch
            if cur is None or distance > cur:
                values.append((epoch, distance))
            else:
                break
        self._max_span.put_batch(pubkey, values)

    # -- blocks ---------------------------------------------------------------

    def check_and_insert_block_proposal(self, pubkey: bytes, slot: int, signing_root: bytes) -> None:
        existing = self._block_by_slot.get(pubkey, slot)
        if existing is not None:
            if bytes.fromhex(existing["signing_root"]) == signing_root and signing_root != b"\x00" * 32:
                return
            raise SlashingError(
                SlashingErrorCode.DOUBLE_BLOCK_PROPOSAL, f"slot {slot} already proposed"
            )
        lb = self._lower_bound.get(pubkey, 0)
        if lb is not None and slot <= lb.get("min_block_slot", -1):
            raise SlashingError(SlashingErrorCode.BELOW_LOWER_BOUND, "slot below lower bound")
        self._block_by_slot.put(pubkey, slot, {"signing_root": signing_root.hex()})

    # -- interchange (EIP-3076) ----------------------------------------------

    def export_interchange(self, genesis_validators_root: bytes, pubkeys: list[bytes]) -> dict:
        data = []
        for pk in pubkeys:
            atts = [
                {
                    "source_epoch": str(v["source_epoch"]),
                    "target_epoch": str(t),
                    "signing_root": "0x" + v["signing_root"],
                }
                for t, v in self._att_by_target.entries(pk)
            ]
            blocks = [
                {"slot": str(s), "signing_root": "0x" + v["signing_root"]}
                for s, v in self._block_by_slot.entries(pk)
            ]
            data.append({"pubkey": "0x" + pk.hex(), "signed_blocks": blocks, "signed_attestations": atts})
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, interchange: dict, genesis_validators_root: bytes) -> None:
        meta = interchange["metadata"]
        if bytes.fromhex(meta["genesis_validators_root"][2:]) != genesis_validators_root:
            raise ValueError("interchange genesis_validators_root mismatch")
        if meta["interchange_format_version"] != "5":
            raise ValueError("unsupported interchange version")
        for entry in interchange["data"]:
            pk = bytes.fromhex(entry["pubkey"][2:])
            max_target = -1
            max_source = 0
            max_slot = -1
            for att in entry.get("signed_attestations", []):
                s, t = int(att["source_epoch"]), int(att["target_epoch"])
                root = bytes.fromhex(att.get("signing_root", "0x" + "00" * 32)[2:])
                try:
                    self.check_and_insert_attestation(pk, s, t, root)
                except SlashingError:
                    pass  # keep the safest record; duplicates are fine
                max_target = max(max_target, t)
                max_source = max(max_source, s)
            for blk in entry.get("signed_blocks", []):
                slot = int(blk["slot"])
                root = bytes.fromhex(blk.get("signing_root", "0x" + "00" * 32)[2:])
                try:
                    self.check_and_insert_block_proposal(pk, slot, root)
                except SlashingError:
                    pass
                max_slot = max(max_slot, slot)
            # raise lower bounds so anything at or below imported history
            # is refused even if individual records were skipped
            lb = self._lower_bound.get(pk, 0) or {}
            self._lower_bound.put(
                pk,
                0,
                {
                    "min_source": max(lb.get("min_source", 0), max_source),
                    "min_target": max(lb.get("min_target", -1), max_target),
                    "min_block_slot": max(lb.get("min_block_slot", -1), max_slot),
                },
            )
