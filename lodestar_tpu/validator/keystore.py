"""EIP-2335 BLS keystores (encrypt/decrypt validator signing keys).

Reference `cli/src/cmds/validator/keymanager/` stores keys as EIP-2335
JSON (scrypt or pbkdf2 KDF + AES-128-CTR + sha256 checksum). hashlib
provides both KDFs; AES-128-CTR is implemented here directly over
hashlib-free primitives (pure-Python AES, acceptable for the small
32-byte payloads keystores carry).
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid

__all__ = ["encrypt_keystore", "decrypt_keystore", "KeystoreError"]


class KeystoreError(Exception):
    pass


# --- minimal AES-128 (encrypt-only; CTR needs just the forward cipher) -------

_SBOX = None


def _build_sbox():
    global _SBOX
    if _SBOX is not None:
        return
    p = q = 1
    sbox = [0] * 256
    while True:
        # multiply p by 3
        p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
        # divide q by 3
        q ^= (q << 1) & 0xFF
        q ^= (q << 2) & 0xFF
        q ^= (q << 4) & 0xFF
        if q & 0x80:
            q ^= 0x09
        x = q ^ ((q << 1) | (q >> 7)) & 0xFF ^ ((q << 2) | (q >> 6)) & 0xFF \
            ^ ((q << 3) | (q >> 5)) & 0xFF ^ ((q << 4) | (q >> 4)) & 0xFF
        sbox[p] = (x ^ 0x63) & 0xFF
        if p == 1:
            break
    sbox[0] = 0x63
    _SBOX = sbox


def _xtime(a: int) -> int:
    return ((a << 1) ^ 0x1B) & 0xFF if a & 0x80 else a << 1


def _expand_key(key: bytes) -> list[list[int]]:
    _build_sbox()
    w = [list(key[i : i + 4]) for i in range(0, 16, 4)]
    rcon = 1
    for i in range(4, 44):
        t = list(w[i - 1])
        if i % 4 == 0:
            t = t[1:] + t[:1]
            t = [_SBOX[b] for b in t]
            t[0] ^= rcon
            rcon = _xtime(rcon)
        w.append([a ^ b for a, b in zip(w[i - 4], t)])
    return [sum(w[4 * r : 4 * r + 4], []) for r in range(11)]


def _aes128_block(key_sched, block: bytes) -> bytes:
    # state is flat column-major (AES standard layout)
    state = list(block)

    def add_round_key(st, rk):
        return [a ^ b for a, b in zip(st, rk)]

    def sub_bytes(st):
        return [_SBOX[b] for b in st]

    def shift_rows(st):
        out = list(st)
        for r in range(1, 4):
            row = [st[r + 4 * c] for c in range(4)]
            row = row[r:] + row[:r]
            for c in range(4):
                out[r + 4 * c] = row[c]
        return out

    def mix_columns(st):
        out = [0] * 16
        for c in range(4):
            col = st[4 * c : 4 * c + 4]
            out[4 * c + 0] = _xtime(col[0]) ^ (_xtime(col[1]) ^ col[1]) ^ col[2] ^ col[3]
            out[4 * c + 1] = col[0] ^ _xtime(col[1]) ^ (_xtime(col[2]) ^ col[2]) ^ col[3]
            out[4 * c + 2] = col[0] ^ col[1] ^ _xtime(col[2]) ^ (_xtime(col[3]) ^ col[3])
            out[4 * c + 3] = (_xtime(col[0]) ^ col[0]) ^ col[1] ^ col[2] ^ _xtime(col[3])
        return out

    state = add_round_key(state, key_sched[0])
    for rnd in range(1, 10):
        state = sub_bytes(state)
        state = shift_rows(state)
        state = mix_columns(state)
        state = add_round_key(state, key_sched[rnd])
    state = sub_bytes(state)
    state = shift_rows(state)
    state = add_round_key(state, key_sched[10])
    return bytes(state)


def _aes128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    sched = _expand_key(key)
    out = bytearray()
    counter = int.from_bytes(iv, "big")
    for i in range(0, len(data), 16):
        ks = _aes128_block(sched, counter.to_bytes(16, "big"))
        chunk = data[i : i + 16]
        out += bytes(a ^ b for a, b in zip(chunk, ks))
        counter = (counter + 1) % (1 << 128)
    return bytes(out)


# --- EIP-2335 ----------------------------------------------------------------


def _kdf(password: bytes, params: dict, kind: str) -> bytes:
    salt = bytes.fromhex(params["salt"])
    if kind == "scrypt":
        return hashlib.scrypt(
            password, salt=salt, n=params["n"], r=params["r"], p=params["p"],
            dklen=params["dklen"], maxmem=2**31 - 1,
        )
    if kind == "pbkdf2":
        return hashlib.pbkdf2_hmac("sha256", password, salt, params["c"], dklen=params["dklen"])
    raise KeystoreError(f"unsupported kdf {kind}")


def _normalize_password(password: str) -> bytes:
    import unicodedata

    norm = unicodedata.normalize("NFKD", password)
    return "".join(c for c in norm if ord(c) >= 0x20 and ord(c) != 0x7F).encode()


def encrypt_keystore(
    secret: bytes, password: str, pubkey: bytes, *, path: str = "", kdf: str = "pbkdf2"
) -> dict:
    """secret (32-byte BLS sk, big-endian) -> EIP-2335 keystore JSON dict."""
    pw = _normalize_password(password)
    salt = os.urandom(32)
    iv = os.urandom(16)
    if kdf == "scrypt":
        kdf_params = {"dklen": 32, "n": 2**14, "r": 8, "p": 1, "salt": salt.hex()}
    else:
        kdf_params = {"dklen": 32, "c": 2**18, "prf": "hmac-sha256", "salt": salt.hex()}
    dk = _kdf(pw, kdf_params, kdf)
    cipher_text = _aes128_ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    return {
        "crypto": {
            "kdf": {"function": kdf, "params": kdf_params, "message": ""},
            "checksum": {"function": "sha256", "params": {}, "message": checksum.hex()},
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": cipher_text.hex(),
            },
        },
        "description": "",
        "pubkey": pubkey.hex(),
        "path": path,
        "uuid": str(uuid.uuid4()),
        "version": 4,
    }


def decrypt_keystore(keystore: dict, password: str) -> bytes:
    if keystore.get("version") != 4:
        raise KeystoreError("only EIP-2335 version 4 supported")
    crypto = keystore["crypto"]
    pw = _normalize_password(password)
    dk = _kdf(pw, crypto["kdf"]["params"], crypto["kdf"]["function"])
    cipher_text = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise KeystoreError("invalid password (checksum mismatch)")
    if crypto["cipher"]["function"] != "aes-128-ctr":
        raise KeystoreError("unsupported cipher")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return _aes128_ctr(dk[:16], iv, cipher_text)
