"""REST-mode validator: duties driven over the Beacon API.

Reference `packages/validator/src/validator.ts` + `services/` — the
production deployment shape: a separate validator process talking to the
beacon node purely through the standard REST endpoints (duties →
produce → sign → publish). The in-process `Validator` (this package's
__init__) is the dev/test shape; this client is the cross-process one.
All signing still flows through ValidatorStore (slashing-protected) and
the optional doppelganger gate.
"""

from __future__ import annotations

from lodestar_tpu.logger import get_logger
from lodestar_tpu.params import BeaconPreset, active_preset
from lodestar_tpu.ssz.json import from_json, to_json
from lodestar_tpu.types import ssz_types

from .slashing_protection import SlashingError
from .store import ValidatorStore

__all__ = ["RestValidator"]


class RestValidator:
    """Per-slot duty runner over a BeaconApiClient-compatible client
    (any object with get_proposer_duties / get_attester_duties /
    produce_block_v2 / produce_attestation_data / publish_block /
    submit_pool_attestations)."""

    def __init__(
        self,
        *,
        client,
        store: ValidatorStore,
        p: BeaconPreset | None = None,
        doppelganger=None,
    ):
        self.client = client
        self.store = store
        self.p = p or active_preset()
        self.doppelganger = doppelganger
        self.log = get_logger(name="lodestar.validator.rest")
        # validator index -> pubkey for OUR keys, filled lazily from the API
        self._index_to_pubkey: dict[int, bytes] = {}
        self._indices_epoch = -1

    def _may_sign(self, pubkey: bytes) -> bool:
        if not self.store.has_pubkey(pubkey):
            return False
        return self.doppelganger is None or self.doppelganger.is_safe(pubkey)

    def refresh_indices(self) -> None:
        """Map our pubkeys to validator indices via the state validators
        endpoint (reference indicesService.pollValidatorIndices)."""
        res = self.client.get_state_validators("head")
        ours = set(self.store.pubkeys)
        for entry in res.get("data", []):
            pk = bytes.fromhex(entry["validator"]["pubkey"][2:])
            if pk in ours:
                self._index_to_pubkey[int(entry["index"])] = pk

    def run_slot_duties(self, slot: int) -> dict:
        """Propose (if selected) then attest for `slot`. Synchronous —
        the REST calls are blocking; callers schedule per slot."""
        epoch = slot // self.p.SLOTS_PER_EPOCH
        if epoch != self._indices_epoch:
            # re-poll once per epoch: keymanager imports and fresh
            # activations must start performing duties without a restart
            # (reference indicesService.pollValidatorIndices cadence)
            self.refresh_indices()
            self._indices_epoch = epoch
        out = {"proposed": None, "attestations": []}
        t = ssz_types(self.p)

        # -- proposal (services/block.ts over the API) --
        duties = self.client.get_proposer_duties(epoch).get("data", [])
        my_duty = next(
            (
                d
                for d in duties
                if int(d["slot"]) == slot and int(d["validator_index"]) in self._index_to_pubkey
            ),
            None,
        )
        if my_duty is not None:
            pk = self._index_to_pubkey[int(my_duty["validator_index"])]
            if self._may_sign(pk):
                reveal = self.store.sign_randao(pk, epoch)
                res = self.client.produce_block_v2(slot, reveal)
                fork = res.get("version", "phase0")
                block = from_json(getattr(t, fork).BeaconBlock, res["data"])
                signed = self.store.sign_block(pk, block)
                signed_type = getattr(t, fork).SignedBeaconBlock
                self.client.publish_block(to_json(signed_type, signed))
                out["proposed"] = signed

        # -- attestations (services/attestation.ts over the API) --
        att_duties = self.client.get_attester_duties(
            epoch, sorted(self._index_to_pubkey)
        ).get("data", [])
        to_submit = []
        for duty in att_duties:
            if int(duty["slot"]) != slot:
                continue
            vi = int(duty["validator_index"])
            pk = self._index_to_pubkey.get(vi)
            if pk is None or not self._may_sign(pk):
                continue
            # per-duty isolation: one key's slashing refusal or concurrent
            # keymanager removal must not drop the other keys'
            # already-signed attestations for the slot (mirrors the
            # in-process Validator's per-duty guards). Only the SIGN call
            # is guarded — a malformed beacon response (from_json
            # ValueError) is a real bug and must surface, not be
            # misreported as a skipped duty.
            data_json = self.client.produce_attestation_data(
                slot, int(duty["committee_index"])
            )["data"]
            data = from_json(t.AttestationData, data_json)
            try:
                sig = self.store.sign_attestation(pk, data)
            except (SlashingError, ValueError) as e:
                self.log.warning(
                    "attestation duty skipped validator=%d: %s", vi, e
                )
                continue
            att = t.Attestation.default()
            bits = [False] * int(duty["committee_length"])
            bits[int(duty["validator_committee_index"])] = True
            att.aggregation_bits = bits
            att.data = data
            att.signature = sig
            to_submit.append(att)
        if to_submit:
            self.client.submit_pool_attestations(
                [to_json(t.Attestation, a) for a in to_submit]
            )
        out["attestations"] = to_submit

        # -- sync-committee duties over REST (services/syncCommittee.ts) --
        out["sync_messages"], out["sync_contributions"] = self._run_sync_duties_rest(
            slot, epoch, t
        )
        return out

    def _run_sync_duties_rest(self, slot: int, epoch: int, t) -> tuple[list, list]:
        """Sync-committee message + contribution flow entirely over the
        Beacon API (duties/sync, pool/sync_committees,
        sync_committee_contribution, contribution_and_proofs) — no
        in-process chain access."""
        from lodestar_tpu.chain.validation import is_sync_committee_aggregator
        from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT

        p = self.p
        try:
            duties = self.client.get_sync_committee_duties(
                epoch, sorted(self._index_to_pubkey)
            ).get("data", [])
        except Exception as e:
            self.log.warning("sync duties fetch failed: %s", e)
            return [], []
        if not duties:
            return [], []
        head_root = self.client.get_block_root("head")["data"]["root"]
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT

        messages, contributions = [], []
        msg_jsons = []
        for duty in duties:
            pk = bytes.fromhex(duty["pubkey"][2:])
            if not self._may_sign(pk):
                continue
            msg = t.SyncCommitteeMessage.default()
            msg.slot = slot
            msg.beacon_block_root = bytes.fromhex(head_root[2:])
            msg.validator_index = int(duty["validator_index"])
            try:
                msg.signature = self.store.sign_sync_committee_message(
                    pk, slot, bytes(msg.beacon_block_root)
                )
            except ValueError:
                continue  # key removed concurrently
            msg_jsons.append(to_json(t.SyncCommitteeMessage, msg))
            messages.append(msg)
        if msg_jsons:
            try:
                self.client.submit_pool_sync_committees(msg_jsons)
            except Exception as e:
                self.log.warning("sync message submit failed: %s", e)

        for duty in duties:
            pk = bytes.fromhex(duty["pubkey"][2:])
            if not self._may_sign(pk):
                continue
            for pos_str in duty.get("validator_sync_committee_indices", []):
                subnet = int(pos_str) // sub_size
                try:
                    proof = self.store.sign_sync_selection_proof(pk, slot, subnet)
                except ValueError:
                    continue
                if not is_sync_committee_aggregator(proof, p):
                    continue
                try:
                    res = self.client.produce_sync_committee_contribution(
                        slot, subnet, head_root
                    )
                except Exception:
                    continue  # no contribution available yet
                contribution = from_json(
                    t.SyncCommitteeContribution, res["data"]
                )
                cp = t.ContributionAndProof.default()
                cp.aggregator_index = int(duty["validator_index"])
                cp.contribution = contribution
                cp.selection_proof = proof
                signed = self.store.sign_contribution_and_proof(pk, cp)
                try:
                    self.client.publish_contribution_and_proofs(
                        [to_json(t.SignedContributionAndProof, signed)]
                    )
                    contributions.append(signed)
                except Exception as e:
                    self.log.warning("contribution publish failed: %s", e)
        return messages, contributions
