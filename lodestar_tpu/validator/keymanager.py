"""Keymanager API: runtime keystore management for the validator client.

Reference `packages/api/src/keymanager/routes.ts` (the standard
keymanager endpoints: /eth/v1/keystores GET/POST/DELETE, /remotekeys,
per-pubkey feerecipient + gas_limit) and the CLI-side impl
`cli/src/cmds/validator/keymanager/impl.ts`. Deleting keys exports the
EIP-3076 slashing-protection interchange for the deleted pubkeys — the
data a migrating validator must carry.
"""

from __future__ import annotations

import json

from lodestar_tpu.crypto.bls.api import SecretKey
from lodestar_tpu.logger import get_logger

from .keystore import KeystoreError, decrypt_keystore
from .store import ValidatorStore

__all__ = ["KeymanagerApi"]

DEFAULT_GAS_LIMIT = 30_000_000


class KeymanagerApi:
    def __init__(
        self,
        store: ValidatorStore,
        *,
        genesis_validators_root: bytes = b"\x00" * 32,
        default_fee_recipient: str = "0x" + "00" * 20,
    ) -> None:
        self.store = store
        self.gvr = bytes(genesis_validators_root)
        self.log = get_logger(name="lodestar.keymanager")
        self.default_fee_recipient = default_fee_recipient
        self._fee_recipients: dict[bytes, str] = {}
        self._gas_limits: dict[bytes, int] = {}
        self._remote_keys: dict[bytes, str] = {}  # pubkey -> signer url

    # -- local keystores (/eth/v1/keystores) -----------------------------------

    def list_keys(self) -> list[dict]:
        return [
            {
                "validating_pubkey": "0x" + pk.hex(),
                "derivation_path": "",
                "readonly": False,
            }
            for pk in self.store.pubkeys
        ]

    def import_keystores(
        self, keystores: list[str | dict], passwords: list[str], slashing_protection: str | None = None
    ) -> list[dict]:
        """Per-keystore status: imported | duplicate | error (reference
        importKeystores). The optional EIP-3076 interchange is imported
        FIRST so the new keys are protected before they can sign."""
        if slashing_protection:
            interchange = (
                json.loads(slashing_protection)
                if isinstance(slashing_protection, str)
                else slashing_protection
            )
            self.store.slashing.import_interchange(interchange, self.gvr)
        statuses = []
        for i, ks in enumerate(keystores):
            if i >= len(passwords):
                # statuses must stay index-aligned with the request
                statuses.append({"status": "error", "message": "missing password"})
                continue
            password = passwords[i]
            try:
                ks_dict = json.loads(ks) if isinstance(ks, str) else ks
                secret = decrypt_keystore(ks_dict, password)
                sk = SecretKey.from_bytes(secret)
                pk = sk.to_pubkey()
                if self.store.has_pubkey(pk):
                    statuses.append({"status": "duplicate", "message": ""})
                    continue
                self.store.add_secret_key(sk)
                statuses.append({"status": "imported", "message": ""})
            except (KeystoreError, ValueError, KeyError, json.JSONDecodeError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return statuses

    def delete_keys(self, pubkeys_hex: list[str]) -> dict:
        """Per-pubkey status + the interchange export for the deleted
        keys (reference deleteKeys: slashing data travels with the
        keys)."""
        statuses = []
        deleted: list[bytes] = []
        for pk_hex in pubkeys_hex:
            try:
                pk = self._pk(pk_hex)
            except ValueError as e:
                statuses.append({"status": "error", "message": str(e)})
                continue
            if self.store.has_pubkey(pk):
                self.store.remove_pubkey(pk)
                deleted.append(pk)
                statuses.append({"status": "deleted", "message": ""})
            else:
                statuses.append({"status": "not_found", "message": ""})
        interchange = self.store.slashing.export_interchange(self.gvr, deleted)
        return {"statuses": statuses, "slashing_protection": json.dumps(interchange)}

    # -- remote keys (/eth/v1/remotekeys) --------------------------------------

    def list_remote_keys(self) -> list[dict]:
        return [
            {"pubkey": "0x" + pk.hex(), "url": url, "readonly": False}
            for pk, url in self._remote_keys.items()
        ]

    def import_remote_keys(self, remote_keys: list[dict]) -> list[dict]:
        statuses = []
        for entry in remote_keys:
            try:
                pk = self._pk(entry["pubkey"])
                if pk in self._remote_keys or self.store.has_pubkey(pk):
                    statuses.append({"status": "duplicate", "message": ""})
                    continue
                self._remote_keys[pk] = entry.get("url", "")
                statuses.append({"status": "imported", "message": ""})
            except (KeyError, ValueError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return statuses

    def delete_remote_keys(self, pubkeys_hex: list[str]) -> list[dict]:
        statuses = []
        for pk_hex in pubkeys_hex:
            try:
                pk = self._pk(pk_hex)
            except ValueError as e:
                statuses.append({"status": "error", "message": str(e)})
                continue
            if self._remote_keys.pop(pk, None) is not None:
                statuses.append({"status": "deleted", "message": ""})
            else:
                statuses.append({"status": "not_found", "message": ""})
        return statuses

    # -- per-validator proposer config ----------------------------------------

    def _pk(self, pubkey_hex: str) -> bytes:
        pk = bytes.fromhex(pubkey_hex[2:] if pubkey_hex.startswith("0x") else pubkey_hex)
        if len(pk) != 48:
            raise ValueError(f"pubkey must be 48 bytes, got {len(pk)}")
        return pk

    def get_fee_recipient(self, pubkey_hex: str) -> dict:
        pk = self._pk(pubkey_hex)
        return {
            "pubkey": "0x" + pk.hex(),
            "ethaddress": self._fee_recipients.get(pk, self.default_fee_recipient),
        }

    def set_fee_recipient(self, pubkey_hex: str, ethaddress: str) -> None:
        addr = ethaddress.lower()
        if not (addr.startswith("0x") and len(addr) == 42):
            raise ValueError(f"bad fee recipient address {ethaddress!r}")
        self._fee_recipients[self._pk(pubkey_hex)] = addr

    def delete_fee_recipient(self, pubkey_hex: str) -> None:
        self._fee_recipients.pop(self._pk(pubkey_hex), None)

    def get_gas_limit(self, pubkey_hex: str) -> dict:
        pk = self._pk(pubkey_hex)
        return {
            "pubkey": "0x" + pk.hex(),
            "gas_limit": str(self._gas_limits.get(pk, DEFAULT_GAS_LIMIT)),
        }

    def set_gas_limit(self, pubkey_hex: str, gas_limit: int) -> None:
        if int(gas_limit) <= 0:
            raise ValueError("gas limit must be positive")
        self._gas_limits[self._pk(pubkey_hex)] = int(gas_limit)

    def delete_gas_limit(self, pubkey_hex: str) -> None:
        self._gas_limits.pop(self._pk(pubkey_hex), None)


# --- REST surface (reference api/src/keymanager/routes.ts) --------------------

KEYMANAGER_ROUTES = [
    ("GET", r"/eth/v1/keystores", "r_list_keys"),
    ("POST", r"/eth/v1/keystores", "r_import_keystores"),
    ("DELETE", r"/eth/v1/keystores", "r_delete_keys"),
    ("GET", r"/eth/v1/remotekeys", "r_list_remote"),
    ("POST", r"/eth/v1/remotekeys", "r_import_remote"),
    ("DELETE", r"/eth/v1/remotekeys", "r_delete_remote"),
    ("GET", r"/eth/v1/validator/(?P<pubkey>0x[0-9a-fA-F]+)/feerecipient", "r_get_fee"),
    ("POST", r"/eth/v1/validator/(?P<pubkey>0x[0-9a-fA-F]+)/feerecipient", "r_set_fee"),
    ("DELETE", r"/eth/v1/validator/(?P<pubkey>0x[0-9a-fA-F]+)/feerecipient", "r_del_fee"),
    ("GET", r"/eth/v1/validator/(?P<pubkey>0x[0-9a-fA-F]+)/gas_limit", "r_get_gas"),
    ("POST", r"/eth/v1/validator/(?P<pubkey>0x[0-9a-fA-F]+)/gas_limit", "r_set_gas"),
    ("DELETE", r"/eth/v1/validator/(?P<pubkey>0x[0-9a-fA-F]+)/gas_limit", "r_del_gas"),
]


class KeymanagerRouter:
    """Route table -> KeymanagerApi calls, same dispatch contract as the
    beacon API router so RestServer hosts either."""

    def __init__(self, km: KeymanagerApi):
        import re

        self.km = km
        self.table = [
            (method, re.compile("^" + pattern + "$"), getattr(self, handler))
            for method, pattern, handler in KEYMANAGER_ROUTES
        ]

    def dispatch(self, method: str, path: str, query: dict, body):
        from lodestar_tpu.api.impl import ApiError

        for m, rx, fn in self.table:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                try:
                    return fn(query=query, body=body, **match.groupdict())
                except (ValueError, KeyError, AttributeError, TypeError) as e:
                    raise ApiError(400, f"bad request: {e}") from e
        raise ApiError(404, f"route not found: {method} {path}")

    def r_list_keys(self, **kw):
        return {"data": self.km.list_keys()}

    def r_import_keystores(self, body, **kw):
        body = body if isinstance(body, dict) else {}
        return {
            "data": self.km.import_keystores(
                body.get("keystores", []),
                body.get("passwords", []),
                body.get("slashing_protection"),
            )
        }

    def r_delete_keys(self, body, **kw):
        body = body if isinstance(body, dict) else {}
        out = self.km.delete_keys(body.get("pubkeys", []))
        return {"data": out["statuses"], "slashing_protection": out["slashing_protection"]}

    def r_list_remote(self, **kw):
        return {"data": self.km.list_remote_keys()}

    def r_import_remote(self, body, **kw):
        body = body if isinstance(body, dict) else {}
        return {"data": self.km.import_remote_keys(body.get("remote_keys", []))}

    def r_delete_remote(self, body, **kw):
        body = body if isinstance(body, dict) else {}
        return {"data": self.km.delete_remote_keys(body.get("pubkeys", []))}

    def r_get_fee(self, pubkey, **kw):
        return {"data": self.km.get_fee_recipient(pubkey)}

    def r_set_fee(self, pubkey, body, **kw):
        self.km.set_fee_recipient(pubkey, body["ethaddress"])
        return 202

    def r_del_fee(self, pubkey, **kw):
        self.km.delete_fee_recipient(pubkey)
        return 204

    def r_get_gas(self, pubkey, **kw):
        return {"data": self.km.get_gas_limit(pubkey)}

    def r_set_gas(self, pubkey, body, **kw):
        self.km.set_gas_limit(pubkey, int(body["gas_limit"]))
        return 202

    def r_del_gas(self, pubkey, **kw):
        self.km.delete_gas_limit(pubkey)
        return 204


def create_keymanager_server(
    km: KeymanagerApi,
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    token_dir: str | None = None,
):
    """RestServer hosting the keymanager routes (reference runs this on
    the validator process, `keymanager/server/index.ts`).

    A bearer token is generated on startup and REQUIRED on every route
    (`Authorization: Bearer ...`) — key import/delete, interchange export
    and fee-recipient redirection must not be reachable by any co-resident
    process that can open the port. The token is exposed as
    `server.auth_token` and, when `token_dir` is given, written to
    `api-token.txt` in the standard format.
    """
    import secrets

    from lodestar_tpu.api.server import RestServer

    token = "api-token-0x" + secrets.token_hex(32)
    if token_dir is not None:
        import os

        os.makedirs(token_dir, exist_ok=True)
        path = os.path.join(token_dir, "api-token.txt")
        with open(path, "w") as f:
            f.write(token + "\n")
        try:
            os.chmod(path, 0o600)
        except OSError:
            pass
    return RestServer(KeymanagerRouter(km), host=host, port=port, auth_token=token)
