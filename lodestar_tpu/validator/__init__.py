"""Validator client (reference `packages/validator/src`).

`Validator` runs attestation + block-proposal duties per slot against an
injected beacon API (in-process BeaconChain adapter or a REST client —
the duty flow matches `validator.ts:187` + `services/attestation.ts` /
`services/block.ts`); all signing flows through `ValidatorStore`, which
is gated by the slashing-protection DB.
"""

from __future__ import annotations

from lodestar_tpu.params import BeaconPreset, active_preset
from lodestar_tpu.state_transition import EpochContext

from .slashing_protection import SlashingError, SlashingProtection  # noqa: F401
from .store import ValidatorStore  # noqa: F401

__all__ = ["Validator", "ValidatorStore", "SlashingProtection", "SlashingError"]


class Validator:
    """Duty loop over an in-process chain (the reference's
    getDevBeaconNode pattern): on each slot — propose if selected, attest
    at the committee assignment."""

    def __init__(
        self,
        *,
        chain,
        store: ValidatorStore,
        p: BeaconPreset | None = None,
        doppelganger=None,
    ):
        self.chain = chain
        self.store = store
        self.p = p or active_preset()
        self.doppelganger = doppelganger

    def _may_sign(self, pubkey: bytes) -> bool:
        """Key is managed AND (when doppelganger protection is on) has
        cleared its detection window (reference validatorStore
        isDoppelgangerSafe gate on every signing path)."""
        if not self.store.has_pubkey(pubkey):
            return False
        return self.doppelganger is None or self.doppelganger.is_safe(pubkey)

    async def run_slot_duties(self, slot: int) -> dict:
        """Propose + attest for `slot`. Returns a summary of what was
        produced (tests + dev runner introspection)."""
        out = {"proposed": None, "attestations": []}
        from lodestar_tpu.chain.produce_block import dial_to_slot

        head_state = self.chain.get_head_state()
        work, ctx = dial_to_slot(head_state, slot, self.p, self.chain.cfg)

        # register managed keys with the validator monitor — iterate the
        # SMALL set (local keys), not the full validator registry
        if self.chain.metrics is not None:
            monitor = self.chain.metrics.validator_monitor
            idx_map = ctx.pubkey_to_index(work)
            for pk in self.store.pubkeys:
                vi = idx_map.get(bytes(pk))
                if vi is not None:
                    monitor.register_local_validator(vi)

        # -- proposal (services/block.ts) --
        proposer_index = ctx.get_beacon_proposer(slot)
        proposer_pk = bytes(work.validators[proposer_index].pubkey)
        if self._may_sign(proposer_pk):
            from lodestar_tpu.chain.produce_block import produce_block

            epoch = slot // self.p.SLOTS_PER_EPOCH
            # only the store.sign_* calls may raise ValueError for
            # concurrent key removal — produce_block stays OUTSIDE the
            # guard so real production bugs surface instead of silently
            # skipping the proposal
            signed = None
            try:
                reveal = self.store.sign_randao(proposer_pk, epoch)
            except ValueError:
                reveal = None  # key removed concurrently by the keymanager
            if reveal is not None:
                block = produce_block(self.chain, slot=slot, randao_reveal=reveal)
                try:
                    signed = self.store.sign_block(proposer_pk, block)
                except ValueError:
                    signed = None  # key removed concurrently
            if signed is not None:
                await self.chain.process_block(signed, is_timely=True)
                out["proposed"] = signed
                # duties for the rest of the slot run on the new head
                work, ctx = dial_to_slot(
                    self.chain.get_head_state(), slot, self.p, self.chain.cfg
                )

        # -- attestations (services/attestation.ts) --
        from lodestar_tpu.chain.produce_block import make_attestation_data
        from lodestar_tpu.types import ssz_types

        t = ssz_types(self.p)
        epoch = slot // self.p.SLOTS_PER_EPOCH
        for committee_index in range(ctx.get_committee_count_per_slot(epoch)):
            committee = ctx.get_beacon_committee(slot, committee_index)
            data = make_attestation_data(self.chain, slot, committee_index)
            data_root = t.AttestationData.hash_tree_root(data)
            for pos, vi in enumerate(committee):
                pk = bytes(work.validators[int(vi)].pubkey)
                if not self._may_sign(pk):
                    continue
                try:
                    sig = self.store.sign_attestation(pk, data)
                except ValueError:
                    continue  # key removed concurrently by the keymanager
                att = t.Attestation.default()
                bits = [False] * len(committee)
                bits[pos] = True
                att.aggregation_bits = bits
                att.data = data
                att.signature = sig
                out["attestations"].append(att)
                self.chain.attestation_pool.add(att, data_root)
                self.chain.fork_choice.on_attestation(
                    [int(vi)], "0x" + bytes(data.beacon_block_root).hex(), epoch, slot
                )

        # -- aggregation round (services/attestation.ts second phase) --
        out["aggregates"] = self._run_aggregation(slot, work, ctx, t)

        # -- sync committee duties (services/syncCommittee.ts) --
        from lodestar_tpu.state_transition.block import fork_of

        if fork_of(work) != "phase0":
            out["sync_messages"], out["sync_contributions"] = self._run_sync_duties(
                slot, work, t, ctx
            )
        return out

    def _run_sync_duties(self, slot: int, work, t, ctx) -> tuple[list, list]:
        """Sign SyncCommitteeMessages for every managed member of the
        current sync committee, then run the contribution-aggregator
        phase over the message pool (reference
        services/syncCommittee.ts + syncCommitteeDuties.ts)."""
        from lodestar_tpu.chain.validation import is_sync_committee_aggregator
        from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT

        p = self.p
        head_root = self.chain.head_root
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        # the aggregate lands in the block at slot+1 and is verified
        # against THAT state's current committee — at the last slot of a
        # period the rotated (next_) committee must sign (the gossip
        # validator's _committee_for_slot handles the same boundary)
        period_len = p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD * p.SLOTS_PER_EPOCH
        committee = (
            work.next_sync_committee
            if (slot + 1) // period_len > int(work.slot) // period_len
            else work.current_sync_committee
        )
        committee_pks = [bytes(pk) for pk in committee.pubkeys]

        messages = []
        vi_by_pk = ctx.pubkey_to_index(work)  # cached on the context
        for pos, pk in enumerate(committee_pks):
            if not self._may_sign(pk):
                continue
            subnet = pos // sub_size
            msg = t.SyncCommitteeMessage.default()
            msg.slot = slot
            msg.beacon_block_root = head_root
            msg.validator_index = vi_by_pk.get(pk, 0)
            try:
                msg.signature = self.store.sign_sync_committee_message(pk, slot, head_root)
            except ValueError:
                continue  # key removed concurrently
            self.chain.sync_committee_message_pool.add(subnet, msg, pos % sub_size)
            messages.append(msg)

        contributions = []
        for subnet in range(SYNC_COMMITTEE_SUBNET_COUNT):
            window = committee_pks[subnet * sub_size : (subnet + 1) * sub_size]
            for pk in window:
                if not self._may_sign(pk):
                    continue
                try:
                    proof = self.store.sign_sync_selection_proof(pk, slot, subnet)
                except ValueError:
                    continue  # key removed concurrently
                if not is_sync_committee_aggregator(proof, p):
                    continue
                contribution = self.chain.sync_committee_message_pool.get_contribution(
                    subnet, slot, head_root
                )
                if contribution is None:
                    continue
                cp = t.ContributionAndProof.default()
                cp.aggregator_index = vi_by_pk.get(pk, 0)
                cp.contribution = contribution
                cp.selection_proof = proof
                signed = self.store.sign_contribution_and_proof(pk, cp)
                self.chain.sync_contribution_pool.add(cp)
                contributions.append(signed)
                break  # one aggregator per subnet suffices locally
        return messages, contributions

    def _run_aggregation(self, slot: int, work, ctx, t) -> list:
        """Selected aggregators publish SignedAggregateAndProof into the
        aggregated pool block production packs from."""
        from lodestar_tpu.chain.validation import is_aggregator

        epoch = slot // self.p.SLOTS_PER_EPOCH
        aggregates = []
        for committee_index in range(ctx.get_committee_count_per_slot(epoch)):
            committee = ctx.get_beacon_committee(slot, committee_index)
            for vi in committee:
                pk = bytes(work.validators[int(vi)].pubkey)
                if not self._may_sign(pk):
                    continue
                try:
                    proof = self.store.sign_selection_proof(pk, slot)
                except ValueError:
                    continue  # key removed concurrently
                if not is_aggregator(len(committee), proof):
                    continue
                # aggregate what the naive pool collected for this data
                data = None
                for root, entry in list(
                    self.chain.attestation_pool._by_slot.get(slot, {}).items()
                ):
                    if entry["data"].index != committee_index:
                        continue
                    agg_att = self.chain.attestation_pool.get_aggregate(slot, root)
                    if agg_att is None:
                        continue
                    aap = t.AggregateAndProof.default()
                    aap.aggregator_index = int(vi)
                    aap.aggregate = agg_att
                    aap.selection_proof = proof
                    signed_agg = self.store.sign_aggregate_and_proof(pk, aap)
                    aggregates.append(signed_agg)
                    self.chain.aggregated_attestation_pool.add(agg_att, root)
                break  # one aggregator per committee suffices locally
        return aggregates
