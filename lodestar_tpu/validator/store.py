"""ValidatorStore: keys + all duty signatures, gated by slashing
protection.

Reference `validator/src/services/validatorStore.ts` — signBlock /
signAttestation (both run the slashing-protection check on the SIGNING
ROOT before producing a signature), signRandao, selection proofs,
aggregate-and-proof envelopes, voluntary exits.
"""

from __future__ import annotations

from lodestar_tpu import ssz
from lodestar_tpu.config import BeaconConfig
from lodestar_tpu.crypto.bls.api import SecretKey, sign
from lodestar_tpu.params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    BeaconPreset,
    active_preset,
)
from lodestar_tpu.state_transition.util import compute_epoch_at_slot
from lodestar_tpu.types import ssz_types

from .slashing_protection import SlashingProtection

__all__ = ["ValidatorStore"]


def _signing_root(ssz_type, value, domain: bytes) -> bytes:
    from lodestar_tpu.config import compute_signing_root

    return compute_signing_root(ssz_type, value, domain)


class ValidatorStore:
    def __init__(
        self,
        config: BeaconConfig,
        slashing_protection: SlashingProtection,
        secret_keys: list[SecretKey],
        p: BeaconPreset | None = None,
    ) -> None:
        self.config = config
        self.slashing = slashing_protection
        self.p = p or active_preset()
        self._by_pubkey: dict[bytes, SecretKey] = {sk.to_pubkey(): sk for sk in secret_keys}

    @property
    def pubkeys(self) -> list[bytes]:
        return list(self._by_pubkey)

    def has_pubkey(self, pubkey: bytes) -> bool:
        return pubkey in self._by_pubkey

    def add_secret_key(self, sk: SecretKey) -> None:
        """Runtime key import (keymanager API)."""
        self._by_pubkey[sk.to_pubkey()] = sk

    def remove_pubkey(self, pubkey: bytes) -> bool:
        """Runtime key removal (keymanager API); slashing history stays."""
        return self._by_pubkey.pop(pubkey, None) is not None

    def _sk(self, pubkey: bytes) -> SecretKey:
        sk = self._by_pubkey.get(pubkey)
        if sk is None:
            raise ValueError(f"unknown validator pubkey 0x{pubkey.hex()[:16]}")
        return sk

    # -- duties ---------------------------------------------------------------

    def sign_block(self, pubkey: bytes, block) -> bytes:
        """Signed block — the slashing DB records the signing root BEFORE
        the signature leaves this process. Fork-aware: the block's own
        container type names the fork namespace."""
        from lodestar_tpu.state_transition.block import fork_of

        t = ssz_types(self.p)
        ns = getattr(t, fork_of(block))  # fork_of reads any container's type name
        epoch = compute_epoch_at_slot(block.slot, self.p)
        domain = self.config.get_domain(DOMAIN_BEACON_PROPOSER, epoch)
        root = _signing_root(ns.BeaconBlock, block, domain)
        self.slashing.check_and_insert_block_proposal(pubkey, block.slot, root)
        signed = ns.SignedBeaconBlock.default()
        signed.message = block
        signed.signature = sign(self._sk(pubkey), root)
        return signed

    def sign_attestation(self, pubkey: bytes, att_data) -> bytes:
        t = ssz_types(self.p)
        domain = self.config.get_domain(DOMAIN_BEACON_ATTESTER, att_data.target.epoch)
        root = _signing_root(t.AttestationData, att_data, domain)
        self.slashing.check_and_insert_attestation(
            pubkey, att_data.source.epoch, att_data.target.epoch, root
        )
        return sign(self._sk(pubkey), root)

    def sign_randao(self, pubkey: bytes, epoch: int) -> bytes:
        domain = self.config.get_domain(DOMAIN_RANDAO, epoch)
        return sign(self._sk(pubkey), _signing_root(ssz.uint64, epoch, domain))

    def sign_selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        epoch = compute_epoch_at_slot(slot, self.p)
        domain = self.config.get_domain(DOMAIN_SELECTION_PROOF, epoch)
        return sign(self._sk(pubkey), _signing_root(ssz.uint64, slot, domain))

    def sign_aggregate_and_proof(self, pubkey: bytes, agg_and_proof) -> bytes:
        t = ssz_types(self.p)
        epoch = compute_epoch_at_slot(agg_and_proof.aggregate.data.slot, self.p)
        domain = self.config.get_domain(DOMAIN_AGGREGATE_AND_PROOF, epoch)
        root = _signing_root(t.AggregateAndProof, agg_and_proof, domain)
        signed = t.SignedAggregateAndProof.default()
        signed.message = agg_and_proof
        signed.signature = sign(self._sk(pubkey), root)
        return signed

    def sign_sync_committee_message(self, pubkey: bytes, slot: int, block_root: bytes) -> bytes:
        """SyncCommitteeMessage signature over the head block root
        (reference signSyncCommitteeSignature). SigningData of a raw
        Root is sha256(root || domain)."""
        import hashlib

        from lodestar_tpu.params import DOMAIN_SYNC_COMMITTEE

        epoch = compute_epoch_at_slot(slot, self.p)
        domain = self.config.get_domain(DOMAIN_SYNC_COMMITTEE, epoch)
        return sign(self._sk(pubkey), hashlib.sha256(bytes(block_root) + domain).digest())

    def sign_sync_selection_proof(self, pubkey: bytes, slot: int, subcommittee_index: int) -> bytes:
        """SyncAggregatorSelectionData proof (reference
        signSyncCommitteeSelectionProof)."""
        from lodestar_tpu.params import DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF

        t = ssz_types(self.p)
        data = t.SyncAggregatorSelectionData.default()
        data.slot = slot
        data.subcommittee_index = subcommittee_index
        epoch = compute_epoch_at_slot(slot, self.p)
        domain = self.config.get_domain(DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch)
        return sign(self._sk(pubkey), _signing_root(t.SyncAggregatorSelectionData, data, domain))

    def sign_contribution_and_proof(self, pubkey: bytes, contribution_and_proof):
        """SignedContributionAndProof envelope (reference
        signContributionAndProof)."""
        from lodestar_tpu.params import DOMAIN_CONTRIBUTION_AND_PROOF

        t = ssz_types(self.p)
        epoch = compute_epoch_at_slot(contribution_and_proof.contribution.slot, self.p)
        domain = self.config.get_domain(DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
        root = _signing_root(t.ContributionAndProof, contribution_and_proof, domain)
        signed = t.SignedContributionAndProof.default()
        signed.message = contribution_and_proof
        signed.signature = sign(self._sk(pubkey), root)
        return signed

    def sign_voluntary_exit(self, pubkey: bytes, validator_index: int, epoch: int):
        t = ssz_types(self.p)
        exit_ = t.VoluntaryExit.default()
        exit_.epoch = epoch
        exit_.validator_index = validator_index
        domain = self.config.get_domain(DOMAIN_VOLUNTARY_EXIT, epoch)
        signed = t.SignedVoluntaryExit.default()
        signed.message = exit_
        signed.signature = sign(self._sk(pubkey), _signing_root(t.VoluntaryExit, exit_, domain))
        return signed
