"""Gossip validation: the spec accept/ignore/reject checks per topic.

Reference `beacon-node/src/chain/validation/` — `validateGossipAttestation`
(`attestation.ts`), `validateGossipAggregateAndProof`
(`aggregateAndProof.ts`), `validateGossipBlock` (`block.ts`). The BLS
checks yield `SignatureSet`s for the batched verifier rather than
verifying inline (the `batchable: true` path of the hot loop,
`attestation.ts:271`).
"""

from __future__ import annotations

import enum
import hashlib
from dataclasses import dataclass

import numpy as np

from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_SELECTION_PROOF,
)
from lodestar_tpu.state_transition import EpochContext, compute_epoch_at_slot
from lodestar_tpu.state_transition.signature_sets import indexed_attestation_signature_set
from lodestar_tpu.state_transition.util import compute_signing_root, get_domain
from lodestar_tpu.types import ssz_types

__all__ = [
    "GossipAction",
    "GossipValidationError",
    "validate_gossip_attestation",
    "validate_gossip_aggregate_and_proof",
    "validate_gossip_block",
    "is_aggregator",
]


class GossipAction(enum.Enum):
    IGNORE = "IGNORE"
    REJECT = "REJECT"


class GossipValidationError(Exception):
    def __init__(self, action: GossipAction, reason: str):
        super().__init__(f"{action.value}: {reason}")
        self.action = action
        self.reason = reason


def _state_dialed_to(chain, block_root: bytes, slot: int):
    """State of `block_root` advanced (copy-on-advance) so its epoch
    covers `slot` — epoch-boundary attestations need next-epoch
    shufflings the block's own post-state doesn't have (the reference
    regen dials to the target epoch, `attestation.ts:394-400`)."""
    from lodestar_tpu.state_transition import compute_epoch_at_slot as epoch_at
    from lodestar_tpu.state_transition import process_slots

    state = chain.get_state_by_block_root(block_root)
    if epoch_at(slot, chain.p) > epoch_at(state.slot, chain.p):
        state = state.copy()
        process_slots(state, slot, chain.p, chain.cfg)
    return state


@dataclass
class AttestationValidationResult:
    indexed_attestation: object
    attesting_indices: list[int]
    signature_sets: list[SignatureSet]


def validate_gossip_attestation(
    chain, attestation, subnet_id: int | None = None
) -> AttestationValidationResult:
    """Spec beacon_attestation topic checks (reference `attestation.ts`).
    `chain` provides: clock-ish current slot (fork_choice.current_slot),
    seen_attesters, fork_choice, head state ctx."""
    p = chain.p
    data = attestation.data
    target_epoch = data.target.epoch
    current_slot = chain.fork_choice.current_slot

    # [REJECT] one committee bit set exactly
    bits = list(attestation.aggregation_bits)
    if sum(1 for b in bits if b) != 1:
        raise GossipValidationError(GossipAction.REJECT, "not exactly one aggregation bit")
    # [REJECT] epoch matches slot
    if target_epoch != compute_epoch_at_slot(data.slot, p):
        raise GossipValidationError(GossipAction.REJECT, "target epoch != slot epoch")
    # [IGNORE] propagation window (slot +/- ATTESTATION_PROPAGATION_SLOT_RANGE)
    if not (data.slot <= current_slot <= data.slot + 32):
        raise GossipValidationError(GossipAction.IGNORE, "outside propagation window")
    # [IGNORE] known block root
    head_root_hex = "0x" + bytes(data.beacon_block_root).hex()
    block = chain.fork_choice.proto_array.get_block(head_root_hex)
    if block is None:
        raise GossipValidationError(GossipAction.IGNORE, "unknown beacon block root")
    # [REJECT] target must be the epoch-start ancestor of the attested block
    target_slot = target_epoch * p.SLOTS_PER_EPOCH
    expected_target = chain.fork_choice.proto_array._ancestor_or_none(head_root_hex, target_slot)
    if expected_target is None or bytes.fromhex(expected_target[2:]) != bytes(data.target.root):
        raise GossipValidationError(GossipAction.REJECT, "target is not the block's epoch ancestor")
    state = _state_dialed_to(chain, bytes(data.beacon_block_root), data.slot)
    ctx = EpochContext(state, p)
    try:
        committee = ctx.get_beacon_committee(data.slot, data.index)
    except ValueError as e:
        raise GossipValidationError(GossipAction.REJECT, f"bad committee: {e}") from e
    if len(bits) != len(committee):
        raise GossipValidationError(GossipAction.REJECT, "bits/committee length mismatch")
    attesting = [int(committee[i]) for i, b in enumerate(bits) if b]
    vi = attesting[0]
    # [IGNORE] first-seen per (target epoch, validator)
    if chain.seen_attesters.is_known(target_epoch, vi):
        raise GossipValidationError(GossipAction.IGNORE, "already seen attester")

    from lodestar_tpu.state_transition.block import get_indexed_attestation

    indexed = get_indexed_attestation(attestation, ctx)
    sig_set = indexed_attestation_signature_set(state, indexed, ctx)
    chain.seen_attesters.add(target_epoch, vi)
    return AttestationValidationResult(
        indexed_attestation=indexed,
        attesting_indices=attesting,
        signature_sets=[sig_set],
    )


TARGET_AGGREGATORS_PER_COMMITTEE = 16


def is_aggregator(committee_len: int, slot_signature: bytes) -> bool:
    """Spec is_aggregator: hash(sig) mod max(1, len//TARGET) == 0
    (reference `state-transition/src/util/aggregator.ts`)."""
    modulo = max(1, committee_len // TARGET_AGGREGATORS_PER_COMMITTEE)
    h = hashlib.sha256(slot_signature).digest()
    return int.from_bytes(h[:8], "little") % modulo == 0


def validate_gossip_aggregate_and_proof(chain, signed_agg) -> AttestationValidationResult:
    """beacon_aggregate_and_proof checks (reference `aggregateAndProof.ts`):
    structure + aggregator membership/selection + the three signature
    sets (selection proof, aggregate-and-proof envelope, aggregate)."""
    p = chain.p
    t = ssz_types(p)
    agg = signed_agg.message
    attestation = agg.aggregate
    data = attestation.data
    current_slot = chain.fork_choice.current_slot

    if not (data.slot <= current_slot <= data.slot + 32):
        raise GossipValidationError(GossipAction.IGNORE, "outside propagation window")
    if data.target.epoch != compute_epoch_at_slot(data.slot, p):
        raise GossipValidationError(GossipAction.REJECT, "target epoch != slot epoch")
    root_hex = "0x" + bytes(data.beacon_block_root).hex()
    if chain.fork_choice.proto_array.get_block(root_hex) is None:
        raise GossipValidationError(GossipAction.IGNORE, "unknown beacon block root")
    target_slot = data.target.epoch * p.SLOTS_PER_EPOCH
    expected_target = chain.fork_choice.proto_array._ancestor_or_none(root_hex, target_slot)
    if expected_target is None or bytes.fromhex(expected_target[2:]) != bytes(data.target.root):
        raise GossipValidationError(GossipAction.REJECT, "target is not the block's epoch ancestor")

    state = _state_dialed_to(chain, bytes(data.beacon_block_root), data.slot)
    ctx = EpochContext(state, p)
    try:
        committee = ctx.get_beacon_committee(data.slot, data.index)
    except ValueError as e:
        raise GossipValidationError(GossipAction.REJECT, f"bad committee: {e}") from e
    # [REJECT] aggregator in committee
    if agg.aggregator_index not in [int(i) for i in committee]:
        raise GossipValidationError(GossipAction.REJECT, "aggregator not in committee")
    # [REJECT] selection proof selects the aggregator
    if not is_aggregator(len(committee), bytes(agg.selection_proof)):
        raise GossipValidationError(GossipAction.REJECT, "selection proof does not select")

    from lodestar_tpu import ssz
    from lodestar_tpu.state_transition.block import get_indexed_attestation

    aggregator = state.validators[agg.aggregator_index]
    sets = [
        # selection proof over the slot
        SignatureSet(
            pubkey=bytes(aggregator.pubkey),
            message=compute_signing_root(
                ssz.uint64, data.slot, get_domain(state, DOMAIN_SELECTION_PROOF, data.target.epoch)
            ),
            signature=bytes(agg.selection_proof),
        ),
        # aggregate-and-proof envelope
        SignatureSet(
            pubkey=bytes(aggregator.pubkey),
            message=compute_signing_root(
                t.AggregateAndProof, agg, get_domain(state, DOMAIN_AGGREGATE_AND_PROOF, data.target.epoch)
            ),
            signature=bytes(signed_agg.signature),
        ),
    ]
    indexed = get_indexed_attestation(attestation, ctx)
    sets.append(indexed_attestation_signature_set(state, indexed, ctx))
    return AttestationValidationResult(
        indexed_attestation=indexed,
        attesting_indices=[int(i) for i in indexed.attesting_indices],
        signature_sets=sets,
    )


def validate_gossip_block(chain, signed_block) -> None:
    """beacon_block topic checks (reference `validation/block.ts`)."""
    p = chain.p
    block = signed_block.message
    current_slot = chain.fork_choice.current_slot
    if block.slot > current_slot:
        raise GossipValidationError(GossipAction.IGNORE, "future slot")
    finalized_slot = chain.fork_choice.finalized.epoch * p.SLOTS_PER_EPOCH
    if block.slot <= finalized_slot:
        raise GossipValidationError(GossipAction.IGNORE, "finalized slot")
    root_hex = "0x" + bytes(block.parent_root).hex()
    if chain.fork_choice.proto_array.get_block(root_hex) is None:
        raise GossipValidationError(GossipAction.IGNORE, "parent unknown")
    t = chain.types
    block_root = t.phase0.BeaconBlock.hash_tree_root(block)
    if chain.fork_choice.proto_array.has_block("0x" + block_root.hex()):
        raise GossipValidationError(GossipAction.IGNORE, "already known")
