"""Gossip validation: the spec accept/ignore/reject checks per topic.

Reference `beacon-node/src/chain/validation/` — `validateGossipAttestation`
(`attestation.ts`), `validateGossipAggregateAndProof`
(`aggregateAndProof.ts`), `validateGossipBlock` (`block.ts`). The BLS
checks yield `SignatureSet`s for the batched verifier rather than
verifying inline (the `batchable: true` path of the hot loop,
`attestation.ts:271`).
"""

from __future__ import annotations

import enum
import hashlib
from dataclasses import dataclass

import numpy as np

from lodestar_tpu import tracing
from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_SELECTION_PROOF,
)
from lodestar_tpu.state_transition import EpochContext, compute_epoch_at_slot
from lodestar_tpu.state_transition.signature_sets import indexed_attestation_signature_set
from lodestar_tpu.state_transition.util import compute_signing_root, get_domain
from lodestar_tpu.types import ssz_types

__all__ = [
    "GossipAction",
    "GossipValidationError",
    "validate_gossip_attestation",
    "validate_gossip_aggregate_and_proof",
    "validate_gossip_block",
    "is_aggregator",
]


class GossipAction(enum.Enum):
    IGNORE = "IGNORE"
    REJECT = "REJECT"


class GossipValidationError(Exception):
    def __init__(self, action: GossipAction, reason: str):
        super().__init__(f"{action.value}: {reason}")
        self.action = action
        self.reason = reason


def _state_dialed_to(chain, block_root: bytes, slot: int):
    """State of `block_root` advanced (copy-on-advance) so its epoch
    covers `slot` — epoch-boundary attestations need next-epoch
    shufflings the block's own post-state doesn't have (the reference
    regen dials to the target epoch, `attestation.ts:394-400`)."""
    from lodestar_tpu.state_transition import compute_epoch_at_slot as epoch_at
    from lodestar_tpu.state_transition import process_slots

    state = chain.get_state_by_block_root(block_root)
    if epoch_at(slot, chain.p) > epoch_at(state.slot, chain.p):
        state = state.copy()
        process_slots(state, slot, chain.p, chain.cfg)
    return state


@dataclass
class AttestationValidationResult:
    """`register_seen` must be called only AFTER the signature sets
    verify — registering earlier lets a bad-signature message censor the
    real one and fake liveness (same contract as the sync-committee
    results below)."""

    indexed_attestation: object
    attesting_indices: list[int]
    signature_sets: list[SignatureSet]
    register_seen: object = lambda: None


def validate_gossip_attestation(
    chain, attestation, subnet_id: int | None = None
) -> AttestationValidationResult:
    """Spec beacon_attestation topic checks (reference `attestation.ts`).
    `chain` provides: clock-ish current slot (fork_choice.current_slot),
    seen_attesters, fork_choice, head state ctx."""
    p = chain.p
    data = attestation.data
    target_epoch = data.target.epoch
    current_slot = chain.fork_choice.current_slot

    # [REJECT] one committee bit set exactly
    bits = list(attestation.aggregation_bits)
    if sum(1 for b in bits if b) != 1:
        raise GossipValidationError(GossipAction.REJECT, "not exactly one aggregation bit")
    # [REJECT] epoch matches slot
    if target_epoch != compute_epoch_at_slot(data.slot, p):
        raise GossipValidationError(GossipAction.REJECT, "target epoch != slot epoch")
    # [IGNORE] propagation window (slot +/- ATTESTATION_PROPAGATION_SLOT_RANGE)
    if not (data.slot <= current_slot <= data.slot + 32):
        raise GossipValidationError(GossipAction.IGNORE, "outside propagation window")
    # [IGNORE] known block root
    head_root_hex = "0x" + bytes(data.beacon_block_root).hex()
    block = chain.fork_choice.proto_array.get_block(head_root_hex)
    if block is None:
        raise GossipValidationError(GossipAction.IGNORE, "unknown beacon block root")
    # [REJECT] target must be the epoch-start ancestor of the attested block
    target_slot = target_epoch * p.SLOTS_PER_EPOCH
    expected_target = chain.fork_choice.proto_array._ancestor_or_none(head_root_hex, target_slot)
    if expected_target is None or bytes.fromhex(expected_target[2:]) != bytes(data.target.root):
        raise GossipValidationError(GossipAction.REJECT, "target is not the block's epoch ancestor")
    state = _state_dialed_to(chain, bytes(data.beacon_block_root), data.slot)
    ctx = EpochContext(state, p)
    try:
        committee = ctx.get_beacon_committee(data.slot, data.index)
    except ValueError as e:
        raise GossipValidationError(GossipAction.REJECT, f"bad committee: {e}") from e
    if len(bits) != len(committee):
        raise GossipValidationError(GossipAction.REJECT, "bits/committee length mismatch")
    attesting = [int(committee[i]) for i, b in enumerate(bits) if b]
    vi = attesting[0]
    # [IGNORE] first-seen per (target epoch, validator)
    if chain.seen_attesters.is_known(target_epoch, vi):
        raise GossipValidationError(GossipAction.IGNORE, "already seen attester")

    from lodestar_tpu.state_transition.block import get_indexed_attestation

    indexed = get_indexed_attestation(attestation, ctx)
    sig_set = indexed_attestation_signature_set(state, indexed, ctx)
    return AttestationValidationResult(
        indexed_attestation=indexed,
        attesting_indices=attesting,
        signature_sets=[sig_set],
        register_seen=lambda: chain.seen_attesters.add(target_epoch, vi),
    )


TARGET_AGGREGATORS_PER_COMMITTEE = 16


def is_aggregator(committee_len: int, slot_signature: bytes) -> bool:
    """Spec is_aggregator: hash(sig) mod max(1, len//TARGET) == 0
    (reference `state-transition/src/util/aggregator.ts`)."""
    modulo = max(1, committee_len // TARGET_AGGREGATORS_PER_COMMITTEE)
    h = hashlib.sha256(slot_signature).digest()
    return int.from_bytes(h[:8], "little") % modulo == 0


def validate_gossip_aggregate_and_proof(chain, signed_agg) -> AttestationValidationResult:
    """beacon_aggregate_and_proof checks (reference `aggregateAndProof.ts`):
    structure + aggregator membership/selection + the three signature
    sets (selection proof, aggregate-and-proof envelope, aggregate)."""
    p = chain.p
    t = ssz_types(p)
    agg = signed_agg.message
    attestation = agg.aggregate
    data = attestation.data
    current_slot = chain.fork_choice.current_slot

    if not (data.slot <= current_slot <= data.slot + 32):
        raise GossipValidationError(GossipAction.IGNORE, "outside propagation window")
    if data.target.epoch != compute_epoch_at_slot(data.slot, p):
        raise GossipValidationError(GossipAction.REJECT, "target epoch != slot epoch")
    root_hex = "0x" + bytes(data.beacon_block_root).hex()
    if chain.fork_choice.proto_array.get_block(root_hex) is None:
        raise GossipValidationError(GossipAction.IGNORE, "unknown beacon block root")
    target_slot = data.target.epoch * p.SLOTS_PER_EPOCH
    expected_target = chain.fork_choice.proto_array._ancestor_or_none(root_hex, target_slot)
    if expected_target is None or bytes.fromhex(expected_target[2:]) != bytes(data.target.root):
        raise GossipValidationError(GossipAction.REJECT, "target is not the block's epoch ancestor")

    state = _state_dialed_to(chain, bytes(data.beacon_block_root), data.slot)
    ctx = EpochContext(state, p)
    try:
        committee = ctx.get_beacon_committee(data.slot, data.index)
    except ValueError as e:
        raise GossipValidationError(GossipAction.REJECT, f"bad committee: {e}") from e
    # [REJECT] aggregator in committee
    if agg.aggregator_index not in [int(i) for i in committee]:
        raise GossipValidationError(GossipAction.REJECT, "aggregator not in committee")
    # [REJECT] selection proof selects the aggregator
    if not is_aggregator(len(committee), bytes(agg.selection_proof)):
        raise GossipValidationError(GossipAction.REJECT, "selection proof does not select")
    # [IGNORE] first aggregate per (target epoch, aggregator)
    if chain.seen_aggregators.is_known(int(data.target.epoch), int(agg.aggregator_index)):
        raise GossipValidationError(GossipAction.IGNORE, "already seen aggregator")

    from lodestar_tpu import ssz
    from lodestar_tpu.state_transition.block import get_indexed_attestation

    aggregator = state.validators[agg.aggregator_index]
    sets = [
        # selection proof over the slot
        SignatureSet(
            pubkey=bytes(aggregator.pubkey),
            message=compute_signing_root(
                ssz.uint64, data.slot, get_domain(state, DOMAIN_SELECTION_PROOF, data.target.epoch)
            ),
            signature=bytes(agg.selection_proof),
        ),
        # aggregate-and-proof envelope
        SignatureSet(
            pubkey=bytes(aggregator.pubkey),
            message=compute_signing_root(
                t.AggregateAndProof, agg, get_domain(state, DOMAIN_AGGREGATE_AND_PROOF, data.target.epoch)
            ),
            signature=bytes(signed_agg.signature),
        ),
    ]
    indexed = get_indexed_attestation(attestation, ctx)
    sets.append(indexed_attestation_signature_set(state, indexed, ctx))
    return AttestationValidationResult(
        indexed_attestation=indexed,
        attesting_indices=[int(i) for i in indexed.attesting_indices],
        signature_sets=sets,
        register_seen=lambda: chain.seen_aggregators.add(
            int(data.target.epoch), int(agg.aggregator_index)
        ),
    )


def validate_gossip_block(chain, signed_block) -> None:
    """beacon_block topic checks (reference `validation/block.ts`)."""
    with tracing.span("gossip_validation") as sp:
        if sp:
            sp.set(topic="beacon_block")
        p = chain.p
        block = signed_block.message
        current_slot = chain.fork_choice.current_slot
        if block.slot > current_slot:
            raise GossipValidationError(GossipAction.IGNORE, "future slot")
        finalized_slot = chain.fork_choice.finalized.epoch * p.SLOTS_PER_EPOCH
        if block.slot <= finalized_slot:
            raise GossipValidationError(GossipAction.IGNORE, "finalized slot")
        root_hex = "0x" + bytes(block.parent_root).hex()
        if chain.fork_choice.proto_array.get_block(root_hex) is None:
            raise GossipValidationError(GossipAction.IGNORE, "parent unknown")
        block_type, _signed = chain.block_type_at_slot(int(block.slot))
        block_root = block_type.hash_tree_root(block)
        if chain.fork_choice.proto_array.has_block("0x" + block_root.hex()):
            raise GossipValidationError(GossipAction.IGNORE, "already known")


# --- sync committee topics ----------------------------------------------------
# Reference `validation/syncCommittee.ts` (sync_committee_{subnet_id}) and
# `validation/syncCommitteeContributionAndProof.ts`.


@dataclass
class SyncCommitteeValidationResult:
    """`register_seen` MUST be called only after the signature sets have
    verified — marking earlier would let a garbage-signature message
    censor the real one for the slot (the reference registers its seen
    caches post-verification)."""

    indices_in_subcommittee: list
    signature_sets: list
    register_seen: object  # () -> None

    @property
    def index_in_subcommittee(self) -> int:
        return self.indices_in_subcommittee[0] if self.indices_in_subcommittee else -1


def _sync_signing_root(block_root: bytes, domain: bytes) -> bytes:
    # SigningData(object_root=Root, domain) root == sha256(root || domain)
    return hashlib.sha256(bytes(block_root) + domain).digest()


# (id(committee), subnet) -> (committee ref, pubkeys, pubkey->positions).
# The strong committee ref keeps the id stable while the entry lives;
# sync committees rotate once per period so a tiny cache suffices.
_SUBCOMMITTEE_CACHE: dict = {}


def _committee_for_slot(state, slot: int, p):
    """The committee that signs sync messages AT `slot`: their aggregate
    lands in the block at slot+1 and verifies against THAT state's
    current committee, so the last slot of every period is signed by the
    rotated (next) committee — matching the duty producer
    (validator/__init__.py _run_sync_duties) and process_sync_aggregate.
    A message whose inclusion period precedes the head state's is
    unverifiable from here (the old committee is gone) — IGNORE it
    rather than REJECT-penalizing an honest boundary peer."""
    period_len = p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD * p.SLOTS_PER_EPOCH
    inclusion_period = (int(slot) + 1) // period_len
    state_period = int(state.slot) // period_len
    if inclusion_period == state_period + 1:
        return state.next_sync_committee
    if inclusion_period < state_period:
        raise GossipValidationError(
            GossipAction.IGNORE, "message from a previous sync-committee period"
        )
    return state.current_sync_committee


def _subcommittee_pubkeys(state, subnet: int, p, slot: int | None = None) -> tuple[list[bytes], dict]:
    from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT

    committee = (
        _committee_for_slot(state, slot, p) if slot is not None else state.current_sync_committee
    )
    key = (id(committee), int(subnet))
    hit = _SUBCOMMITTEE_CACHE.get(key)
    if hit is not None and hit[0] is committee:
        return hit[1], hit[2]
    sub = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    pks = [bytes(pk) for pk in list(committee.pubkeys)[subnet * sub : (subnet + 1) * sub]]
    positions: dict = {}
    for i, pk in enumerate(pks):  # sampled with replacement: dup positions
        positions.setdefault(pk, []).append(i)
    if len(_SUBCOMMITTEE_CACHE) > 64:
        _SUBCOMMITTEE_CACHE.clear()
    _SUBCOMMITTEE_CACHE[key] = (committee, pks, positions)
    return pks, positions


def validate_sync_committee_message(chain, message, subnet: int) -> SyncCommitteeValidationResult:
    """sync_committee_{subnet} topic checks; returns the signature set
    for the batched verifier plus the subcommittee position needed by
    the message pool."""
    p = chain.p
    slot = int(message.slot)
    current_slot = chain.fork_choice.current_slot
    # [IGNORE] message for the current slot (+- one slot of disparity)
    if not (current_slot - 1 <= slot <= current_slot + 1):
        raise GossipValidationError(GossipAction.IGNORE, "not current slot")

    state = chain.get_head_state()
    vi = int(message.validator_index)
    if vi >= len(state.validators):
        raise GossipValidationError(GossipAction.REJECT, "unknown validator index")
    pubkey = bytes(state.validators[vi].pubkey)
    _sub_pks, positions = _subcommittee_pubkeys(state, subnet, p, slot)
    indices = positions.get(pubkey)
    if not indices:
        raise GossipValidationError(GossipAction.REJECT, "validator not in subcommittee")

    # [IGNORE] first message per (slot, validator, subnet)
    if chain.seen_sync_messages.is_known(slot, vi, subnet):
        raise GossipValidationError(GossipAction.IGNORE, "already seen sync message")

    from lodestar_tpu.params import DOMAIN_SYNC_COMMITTEE

    epoch = slot // p.SLOTS_PER_EPOCH
    domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch)
    sig_set = SignatureSet(
        pubkey=pubkey,
        message=_sync_signing_root(bytes(message.beacon_block_root), domain),
        signature=bytes(message.signature),
    )
    return SyncCommitteeValidationResult(
        indices_in_subcommittee=list(indices),
        signature_sets=[sig_set],
        register_seen=lambda: chain.seen_sync_messages.add(slot, vi, subnet),
    )


def is_sync_committee_aggregator(selection_proof: bytes, p) -> bool:
    """Spec is_sync_committee_aggregator (reference
    `state-transition/src/util/aggregator.ts isSyncCommitteeAggregator`)."""
    from lodestar_tpu.params import (
        SYNC_COMMITTEE_SUBNET_COUNT,
        TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )

    modulo = max(
        1,
        p.SYNC_COMMITTEE_SIZE
        // SYNC_COMMITTEE_SUBNET_COUNT
        // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )
    h = hashlib.sha256(bytes(selection_proof)).digest()
    return int.from_bytes(h[:8], "little") % modulo == 0


def validate_sync_committee_contribution(chain, signed) -> SyncCommitteeValidationResult:
    """sync_committee_contribution_and_proof topic checks; returns three
    signature sets (selection proof, outer signature, aggregate
    contribution)."""
    from lodestar_tpu.crypto.bls.api import aggregate_pubkeys
    from lodestar_tpu.params import (
        DOMAIN_CONTRIBUTION_AND_PROOF,
        DOMAIN_SYNC_COMMITTEE,
        DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
        SYNC_COMMITTEE_SUBNET_COUNT,
    )

    p = chain.p
    t = ssz_types(p)
    cp = signed.message
    contribution = cp.contribution
    slot = int(contribution.slot)
    subnet = int(contribution.subcommittee_index)
    current_slot = chain.fork_choice.current_slot

    if not (current_slot - 1 <= slot <= current_slot + 1):
        raise GossipValidationError(GossipAction.IGNORE, "not current slot")
    if subnet >= SYNC_COMMITTEE_SUBNET_COUNT:
        raise GossipValidationError(GossipAction.REJECT, "bad subcommittee index")
    bits = list(contribution.aggregation_bits)
    if not any(bits):
        raise GossipValidationError(GossipAction.REJECT, "empty contribution")
    if not is_sync_committee_aggregator(bytes(cp.selection_proof), p):
        raise GossipValidationError(GossipAction.REJECT, "selection proof not aggregator")

    state = chain.get_head_state()
    ai = int(cp.aggregator_index)
    if ai >= len(state.validators):
        raise GossipValidationError(GossipAction.REJECT, "unknown aggregator index")
    agg_pubkey = bytes(state.validators[ai].pubkey)
    sub_pks, positions = _subcommittee_pubkeys(state, subnet, p, slot)
    if agg_pubkey not in positions:
        raise GossipValidationError(GossipAction.REJECT, "aggregator not in subcommittee")
    if chain.seen_sync_aggregators.is_known(slot, ai, subnet):
        raise GossipValidationError(GossipAction.IGNORE, "already seen contribution aggregator")

    epoch = slot // p.SLOTS_PER_EPOCH
    sel_data = t.SyncAggregatorSelectionData.default()
    sel_data.slot = slot
    sel_data.subcommittee_index = subnet
    sel_domain = get_domain(state, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch)
    selection_set = SignatureSet(
        pubkey=agg_pubkey,
        message=compute_signing_root(t.SyncAggregatorSelectionData, sel_data, sel_domain),
        signature=bytes(cp.selection_proof),
    )
    outer_domain = get_domain(state, DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
    outer_set = SignatureSet(
        pubkey=agg_pubkey,
        message=compute_signing_root(t.ContributionAndProof, cp, outer_domain),
        signature=bytes(signed.signature),
    )
    participating = [sub_pks[i] for i, b in enumerate(bits) if b]
    sync_domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch)
    contribution_set = SignatureSet(
        pubkey=aggregate_pubkeys(participating),
        message=_sync_signing_root(bytes(contribution.beacon_block_root), sync_domain),
        signature=bytes(contribution.signature),
    )
    return SyncCommitteeValidationResult(
        indices_in_subcommittee=[],
        signature_sets=[selection_set, outer_set, contribution_set],
        register_seen=lambda: chain.seen_sync_aggregators.add(slot, ai, subnet),
    )


def validate_gossip_block_and_blobs_sidecar(chain, signed_coupled) -> None:
    """beacon_block_and_blobs_sidecar topic (reference
    `validation/blobsSidecar.ts validateGossipBlobsSidecar` + the block
    checks): commitments are valid G1 points, match the payload's blob
    transactions, and the coupled sidecar's aggregate KZG proof verifies
    against the block's commitments."""
    from lodestar_tpu.crypto.bls import curve as _curve
    from lodestar_tpu.crypto.bls.serdes import PointDecodeError, g1_from_bytes
    from lodestar_tpu.crypto.kzg import KzgError, validate_blobs_sidecar
    from lodestar_tpu.state_transition.deneb import (
        verify_kzg_commitments_against_transactions,
    )

    signed_block = signed_coupled.beacon_block
    sidecar = signed_coupled.blobs_sidecar
    block = signed_block.message
    validate_gossip_block(chain, signed_block)

    commitments = [bytes(c) for c in block.body.blob_kzg_commitments]
    # [REJECT] commitments KeyValidate: decodable G1 points IN the
    # subgroup (g1_from_bytes raises on malformed encodings and defers
    # the subgroup check to the caller)
    for i, c in enumerate(commitments):
        try:
            pt = g1_from_bytes(c)
        except PointDecodeError as e:
            raise GossipValidationError(
                GossipAction.REJECT, f"bad KZG commitment {i}: {e}"
            ) from e
        if pt is not None and not _curve.g1_in_subgroup(pt):
            raise GossipValidationError(
                GossipAction.REJECT, f"KZG commitment {i} outside subgroup"
            )
    # [REJECT] commitments match the blob transactions' versioned hashes
    try:
        verify_kzg_commitments_against_transactions(
            list(block.body.execution_payload.transactions), commitments
        )
    except Exception as e:
        raise GossipValidationError(GossipAction.REJECT, f"commitments vs txs: {e}") from e
    # [REJECT] coupled sidecar binds to this block and its proof verifies
    t = chain.types
    block_root = t.deneb.BeaconBlock.hash_tree_root(block)
    try:
        validate_blobs_sidecar(
            int(block.slot), block_root, commitments, sidecar
        )
    except KzgError as e:
        raise GossipValidationError(GossipAction.REJECT, f"blobs sidecar: {e}") from e
