"""Archiver: migrate finalized data hot -> cold on finalization.

Reference `beacon-node/src/chain/archiver/index.ts:40` (Archiver),
`archiveBlocks.ts` (canonical blocks hot->blockArchive keyed by slot +
root/parent-root indexes; non-canonical hot blocks deleted) and
`archiveStates.ts` (StatesArchiver.maybeArchiveState — persist one
finalized state per `archive_state_epoch_frequency` window, prune
intermediate stored states within the window).
"""

from __future__ import annotations

from lodestar_tpu.db import Bucket, DbController, Repository, encode_key
from lodestar_tpu.logger import get_logger

__all__ = ["Archiver", "StatesArchiver"]

# reference cli default `chain.archiveStateEpochFrequency` (1024 epochs)
DEFAULT_ARCHIVE_STATE_EPOCH_FREQUENCY = 1024
# spec MIN_EPOCHS_FOR_BLOBS_SIDECARS_REQUESTS: sidecars older than this
# are prunable (reference archiveBlocks.ts blob expiry)
MIN_EPOCHS_FOR_BLOBS_SIDECARS_REQUESTS = 4096


def _hex(b: bytes) -> str:
    return "0x" + b.hex()


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s[2:])


def decode_archived_state(db: DbController, types, raw: bytes, slot: int, *, cfg=None, p=None):
    """Decode a slot-keyed archived state: the archiver's recorded fork
    name is authoritative, then the state's own fork version bytes
    (every BeaconState starts genesis_time u64 | gvr 32 | slot 8 |
    fork{prev4 current4 ...}), then the config schedule. Shared by the
    in-process cold reads and the restart-from-db loader so the record
    format lives in ONE place."""
    candidates: list[str] = []
    recorded = db.get(encode_key(Bucket.index_chainInfo, f"state_fork_{slot:020d}"))
    if recorded:
        candidates.append(recorded.decode())
    current_version = bytes(raw[52:56]) if len(raw) >= 56 else b""
    if cfg is not None:
        from lodestar_tpu.config import FORK_ORDER, fork_name_at_epoch

        for name in reversed(FORK_ORDER):
            if cfg.fork_version(name) == current_version:
                candidates.append(name)
                break
        if p is not None:
            candidates.append(fork_name_at_epoch(cfg, slot // p.SLOTS_PER_EPOCH))
    elif current_version and current_version[0] < 5:
        from lodestar_tpu.config import FORK_ORDER

        candidates.append(FORK_ORDER[current_version[0]])
    # blind probe last (capella/deneb share a layout — only reached when
    # nothing above matched)
    candidates += ["deneb", "capella", "bellatrix", "altair", "phase0"]
    for name in dict.fromkeys(candidates):
        ns = getattr(types, name, None)
        if ns is None:
            continue
        try:
            return ns.BeaconState.deserialize(raw), name
        except (ValueError, KeyError):
            continue
    return None, None


class StatesArchiver:
    """Persist finalized states on the epoch-frequency cadence
    (reference archiveStates.ts:27)."""

    def __init__(
        self,
        chain,
        db: DbController,
        frequency: int = DEFAULT_ARCHIVE_STATE_EPOCH_FREQUENCY,
    ) -> None:
        self.chain = chain
        self.db = db
        self.frequency = frequency
        self._last_stored_epoch = -1

    def maybe_archive_state(self, finalized_cp) -> None:
        """Archive the finalized state if we crossed a frequency window
        (or every finalization when frequency == 0, useful in tests)."""
        epoch = int(finalized_cp.epoch)
        if self.frequency > 0:
            last_window = self._last_stored_epoch // self.frequency
            if self._last_stored_epoch >= 0 and epoch // self.frequency <= last_window:
                return
        self.archive_state(finalized_cp)

    def archive_state(self, finalized_cp) -> None:
        root = bytes(finalized_cp.root)
        state = self.chain.state_cache.get(root)
        if state is None:
            return
        from lodestar_tpu.state_transition.block import fork_of

        slot = int(state.slot)
        # serialize with the state's own (fork-versioned) type, not the
        # repository's anchor type; record the fork name so restart can
        # decode WITHOUT guessing from the config (a state's actual fork
        # can lag the schedule, e.g. genesis-epoch activations)
        self.chain.states_db.put_binary(slot, state.type.serialize(state))
        self.db.put(
            encode_key(Bucket.index_chainInfo, f"state_fork_{slot:020d}"),
            fork_of(state).encode(),
        )
        state_root = state.type.hash_tree_root(state)
        self.db.put(
            encode_key(Bucket.index_stateArchiveRootIndex, state_root),
            slot.to_bytes(8, "big"),
        )
        self._last_stored_epoch = int(finalized_cp.epoch)


class Archiver:
    """Subscribes to the chain's finalization and moves finalized data
    to the archive buckets (reference archiver/index.ts:40)."""

    def __init__(
        self,
        chain,
        db: DbController,
        archive_state_epoch_frequency: int = DEFAULT_ARCHIVE_STATE_EPOCH_FREQUENCY,
    ) -> None:
        self.chain = chain
        self.db = db
        self.log = get_logger(name="lodestar.archiver")
        self.states_archiver = StatesArchiver(chain, db, archive_state_epoch_frequency)
        t = chain.types
        self.block_archive = Repository(db, Bucket.allForks_blockArchive, t.phase0.SignedBeaconBlock)

    def on_finalized(self, finalized_cp) -> None:
        """archiveBlocks + maybeArchiveState + cache pruning. Runs
        BEFORE fork-choice prune so the dead-fork nodes are still
        enumerable (the reference keeps them until archiving completes,
        archiver/index.ts processFinalizedCheckpoint)."""
        self.archive_blocks(finalized_cp)
        self.states_archiver.maybe_archive_state(finalized_cp)

    def archive_blocks(self, finalized_cp) -> None:
        chain = self.chain
        root_hex = _hex(bytes(finalized_cp.root))
        canonical = chain.fork_choice.get_all_ancestor_blocks(root_hex)
        non_canonical = chain.fork_choice.get_all_non_ancestor_blocks(root_hex)
        finalized_slot = int(finalized_cp.epoch) * chain.p.SLOTS_PER_EPOCH

        # hot -> cold: cold key is the slot; root + parent-root indexes
        # let by-root lookups fall through to the archive
        migrated = 0
        for node in canonical:
            block_root = _unhex(node.block_root)
            raw = chain.blocks_db.get_binary(block_root)
            if raw is None:
                continue
            self.block_archive.put_binary(node.slot, raw)
            self.db.put(
                encode_key(Bucket.index_blockArchiveRootIndex, block_root),
                int(node.slot).to_bytes(8, "big"),
            )
            self.db.put(
                encode_key(Bucket.index_blockArchiveParentRootIndex, _unhex(node.parent_root)),
                int(node.slot).to_bytes(8, "big"),
            )
            chain.blocks_db.delete(block_root)
            migrated += 1

        # dead forks at or below the finalized slot leave the hot db,
        # their sidecars with them
        dropped = 0
        for node in non_canonical:
            if node.slot <= finalized_slot:
                chain.blocks_db.delete(_unhex(node.block_root))
                chain.blobs_db.delete(_unhex(node.block_root))
                dropped += 1

        # blob retention window (spec MIN_EPOCHS_FOR_BLOBS_SIDECARS_REQUESTS)
        floor_slot = (
            int(finalized_cp.epoch) - MIN_EPOCHS_FOR_BLOBS_SIDECARS_REQUESTS
        ) * chain.p.SLOTS_PER_EPOCH
        if floor_slot > 0:
            for key, sidecar in list(chain.blobs_db.entries()):
                if int(sidecar.beacon_block_slot) < floor_slot:
                    chain.blobs_db.delete(bytes(key))

        if migrated or dropped:
            self.log.debug(
                "archived blocks",
                {"migrated": migrated, "dropped": dropped, "epoch": finalized_cp.epoch},
            )

    # -- cold lookups ----------------------------------------------------------

    def get_archived_state_by_slot(self, slot: int):
        """Deserialize a slot-keyed archived state with its
        fork-versioned type (the repository's pinned type is only the
        anchor fork)."""
        raw = self.chain.states_db.get_binary(int(slot))
        if raw is None:
            return None
        return self._decode_state(int(slot), raw)

    def get_archived_state_by_root(self, state_root: bytes):
        raw = self.db.get(encode_key(Bucket.index_stateArchiveRootIndex, bytes(state_root)))
        if raw is None:
            return None
        return self.get_archived_state_by_slot(int.from_bytes(raw, "big"))

    def get_archived_state_at_or_before(self, slot: int):
        """Newest archived state with state.slot <= slot (checkpoint-sync
        style lookup, reference stateArchive.lastValue semantics)."""
        keys = self.chain.states_db.keys(lt=int(slot) + 1)
        if not keys:
            return None
        found_slot = int.from_bytes(keys[-1], "big")
        raw = self.chain.states_db.get_binary(found_slot)
        return None if raw is None else self._decode_state(found_slot, raw)

    def _decode_state(self, slot: int, raw: bytes):
        chain = self.chain
        state, _fork = decode_archived_state(
            self.db, chain.types, raw, slot, cfg=chain.cfg, p=chain.p
        )
        return state

    def get_archived_block_by_slot(self, slot: int):
        raw = self.block_archive.get_binary(int(slot))
        if raw is None:
            return None
        chain = self.chain
        _, signed_type = chain.block_type_at_slot(int(slot))
        return signed_type.deserialize(raw)

    def get_archived_block_by_root(self, block_root: bytes):
        raw = self.db.get(encode_key(Bucket.index_blockArchiveRootIndex, bytes(block_root)))
        if raw is None:
            return None
        return self.get_archived_block_by_slot(int.from_bytes(raw, "big"))
