"""Sync-committee message + contribution pools.

Reference `beacon-node/src/chain/opPools/syncCommitteeMessagePool.ts`
(per-(slot, root, subnet) aggregation of gossip messages into
contributions, SLOTS_RETAINED=3) and `syncContributionAndProofPool.ts`
(best contribution per subnet, merged into the block's SyncAggregate,
SLOTS_RETAINED=8, MAX_ITEMS_PER_SLOT=512). Aggregation is plain BLS
signature aggregation through the crypto API; the device batch path
only matters for verification, not aggregation.
"""

from __future__ import annotations

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT, BeaconPreset, active_preset
from lodestar_tpu.types import ssz_types

from .op_pools import InsertOutcome, OpPoolError

__all__ = ["SyncCommitteeMessagePool", "SyncContributionAndProofPool"]

G2_INFINITY = bls.G2_INFINITY

MESSAGE_SLOTS_RETAINED = 3
CONTRIBUTION_SLOTS_RETAINED = 8
MAX_ITEMS_PER_SLOT = 512


class _Aggregate:
    """Mutable (bits, signature, participants) accumulator over one
    subcommittee (reference SyncContributionFast)."""

    __slots__ = ("bits", "signatures", "participants")

    def __init__(self, size: int):
        self.bits = [False] * size
        self.signatures: list[bytes] = []
        self.participants = 0

    def add(self, index_in_subcommittee: int, signature: bytes) -> InsertOutcome:
        if self.bits[index_in_subcommittee]:
            return InsertOutcome.ALREADY_KNOWN
        self.bits[index_in_subcommittee] = True
        self.signatures.append(bytes(signature))
        self.participants += 1
        return InsertOutcome.AGGREGATED

    def signature(self) -> bytes:
        if not self.signatures:
            return G2_INFINITY
        return bls.aggregate_signatures(self.signatures)


class SyncCommitteeMessagePool:
    """Aggregates individual gossip SyncCommitteeMessages into per-subnet
    contributions for the aggregator duty (reference
    syncCommitteeMessagePool.ts)."""

    def __init__(self, p: BeaconPreset | None = None):
        self.p = p or active_preset()
        # (slot, block_root, subnet) -> _Aggregate
        self._by_key: dict[tuple[int, bytes, int], _Aggregate] = {}
        self._count_by_slot: dict[int, int] = {}
        self.lowest_permissible_slot = 0

    @property
    def subcommittee_size(self) -> int:
        return self.p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT

    def add(self, subnet: int, message, index_in_subcommittee: int) -> InsertOutcome:
        if not (0 <= int(subnet) < SYNC_COMMITTEE_SUBNET_COUNT):
            raise OpPoolError(f"bad subnet {subnet}")
        if not (0 <= int(index_in_subcommittee) < self.subcommittee_size):
            raise OpPoolError(f"bad subcommittee position {index_in_subcommittee}")
        slot = int(message.slot)
        if slot < self.lowest_permissible_slot:
            return InsertOutcome.OLD
        key = (slot, bytes(message.beacon_block_root), int(subnet))
        agg = self._by_key.get(key)
        if agg is None:
            if self._count_by_slot.get(slot, 0) >= MAX_ITEMS_PER_SLOT:
                return InsertOutcome.REACHED_MAX_PER_SLOT
            agg = self._by_key[key] = _Aggregate(self.subcommittee_size)
            self._count_by_slot[slot] = self._count_by_slot.get(slot, 0) + 1
        return agg.add(int(index_in_subcommittee), bytes(message.signature))

    def get_contribution(self, subnet: int, slot: int, block_root: bytes):
        """SyncCommitteeContribution for the aggregator's
        ContributionAndProof, or None."""
        agg = self._by_key.get((int(slot), bytes(block_root), int(subnet)))
        if agg is None:
            return None
        t = ssz_types(self.p)
        c = t.SyncCommitteeContribution.default()
        c.slot = slot
        c.beacon_block_root = bytes(block_root)
        c.subcommittee_index = subnet
        c.aggregation_bits = list(agg.bits)
        c.signature = agg.signature()
        return c

    def prune(self, clock_slot: int) -> None:
        self.lowest_permissible_slot = max(0, clock_slot - MESSAGE_SLOTS_RETAINED)
        for k in [k for k in self._by_key if k[0] < self.lowest_permissible_slot]:
            del self._by_key[k]
        for s in [s for s in self._count_by_slot if s < self.lowest_permissible_slot]:
            del self._count_by_slot[s]


class SyncContributionAndProofPool:
    """Keeps the best (most participants) contribution per (slot, root,
    subnet) and merges them into the block SyncAggregate (reference
    syncContributionAndProofPool.ts getSyncAggregate)."""

    def __init__(self, p: BeaconPreset | None = None):
        self.p = p or active_preset()
        # (slot, block_root) -> {subnet: (participants, bits, signature)}
        self._best: dict[tuple[int, bytes], dict[int, tuple[int, list[bool], bytes]]] = {}
        self._count_by_slot: dict[int, int] = {}
        self.lowest_permissible_slot = 0

    @property
    def subcommittee_size(self) -> int:
        return self.p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT

    def add(self, contribution_and_proof) -> InsertOutcome:
        contribution = contribution_and_proof.contribution
        subnet = int(contribution.subcommittee_index)
        bits = list(contribution.aggregation_bits)
        # reject malformed input at ingest, not in produce_block
        if not (0 <= subnet < SYNC_COMMITTEE_SUBNET_COUNT):
            raise OpPoolError(f"bad subcommittee index {subnet}")
        if len(bits) != self.subcommittee_size:
            raise OpPoolError(f"bad aggregation bits length {len(bits)}")
        slot = int(contribution.slot)
        if slot < self.lowest_permissible_slot:
            return InsertOutcome.OLD
        key = (slot, bytes(contribution.beacon_block_root))
        if key not in self._best:
            if self._count_by_slot.get(slot, 0) >= MAX_ITEMS_PER_SLOT:
                return InsertOutcome.REACHED_MAX_PER_SLOT
            self._count_by_slot[slot] = self._count_by_slot.get(slot, 0) + 1
        by_subnet = self._best.setdefault(key, {})
        participants = sum(bits)
        cur = by_subnet.get(subnet)
        if cur is not None and cur[0] >= participants:
            return InsertOutcome.NOT_BETTER_THAN
        by_subnet[subnet] = (participants, bits, bytes(contribution.signature))
        return InsertOutcome.NEW_DATA

    def get_sync_aggregate(self, slot: int, block_root: bytes):
        """SyncAggregate over the previous block root for block
        production; empty participation carries the G2 infinity
        signature."""
        t = ssz_types(self.p)
        p = self.p
        agg = t.SyncAggregate.default()
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        bits = [False] * p.SYNC_COMMITTEE_SIZE
        sigs: list[bytes] = []
        by_subnet = self._best.get((int(slot), bytes(block_root)), {})
        for subnet, (_n, sub_bits, sig) in by_subnet.items():
            for i, b in enumerate(sub_bits):
                if b:
                    bits[subnet * sub_size + i] = True
            sigs.append(sig)
        agg.sync_committee_bits = bits
        agg.sync_committee_signature = bls.aggregate_signatures(sigs) if sigs else G2_INFINITY
        return agg

    def prune(self, clock_slot: int) -> None:
        self.lowest_permissible_slot = max(0, clock_slot - CONTRIBUTION_SLOTS_RETAINED)
        for k in [k for k in self._best if k[0] < self.lowest_permissible_slot]:
            del self._best[k]
        for s in [s for s in self._count_by_slot if s < self.lowest_permissible_slot]:
            del self._count_by_slot[s]


class SeenSlotKeyed:
    """First-seen dedup keyed by (slot, *ids) — the sync-committee
    equivalents of the attester seen caches (reference
    `seenCache/seenCommittee.ts`, `seenCommitteeContribution.ts`)."""

    def __init__(self):
        self._seen: set[tuple] = set()

    def is_known(self, slot: int, *ids) -> bool:
        return (int(slot), *ids) in self._seen

    def add(self, slot: int, *ids) -> None:
        self._seen.add((int(slot), *ids))

    def prune(self, lowest_permissible_slot: int) -> None:
        self._seen = {k for k in self._seen if k[0] >= lowest_permissible_slot}
