"""Operation pools + seen caches.

Reference `beacon-node/src/chain/opPools/` + `chain/seenCache/`:

* `AttestationPool` — naive aggregation: single-signature gossip
  attestations OR-merged per AttestationData root
  (`attestationPool.ts:58`), SLOTS_RETAINED window, per-slot cap.
* `AggregatedAttestationPool` — aggregates grouped for block inclusion
  with greedy not-yet-seen scoring (`aggregatedAttestationPool.ts:54,110`).
* `OpPool` — exits / proposer slashings / attester slashings / bls
  changes keyed for dedup + block packing (`opPool.ts`).
* Seen caches — first-seen dedup per epoch: attesters, aggregators
  (`seenCache/seenAttesters.ts`).
"""

from __future__ import annotations

import enum
from collections import defaultdict

import numpy as np

from lodestar_tpu.crypto.bls.api import aggregate_signatures
from lodestar_tpu.types import ssz_types

__all__ = [
    "InsertOutcome",
    "AttestationPool",
    "AggregatedAttestationPool",
    "OpPool",
    "SeenAttesters",
    "SeenAggregators",
]

SLOTS_RETAINED = 3
MAX_ATTESTATIONS_PER_SLOT = 16_384


class InsertOutcome(enum.Enum):
    NEW_DATA = "NewData"
    AGGREGATED = "Aggregated"
    ALREADY_KNOWN = "AlreadyKnown"
    OLD = "Old"
    REACHED_MAX_PER_SLOT = "ReachedMaxPerSlot"
    NOT_BETTER_THAN = "NotBetterThan"


class OpPoolError(Exception):
    pass


class AttestationPool:
    """Naive aggregation pool for single-signature gossip attestations."""

    def __init__(self) -> None:
        # slot -> data_root -> {bits: list[bool], data, sigs: list[bytes]}
        self._by_slot: dict[int, dict[bytes, dict]] = defaultdict(dict)
        self._lowest_permissible_slot = 0

    def add(self, attestation, att_data_root: bytes) -> InsertOutcome:
        slot = attestation.data.slot
        if slot < self._lowest_permissible_slot:
            return InsertOutcome.OLD
        by_root = self._by_slot[slot]
        if len(by_root) >= MAX_ATTESTATIONS_PER_SLOT:
            raise OpPoolError("reached max attestations per slot")

        bits = list(attestation.aggregation_bits)
        entry = by_root.get(att_data_root)
        if entry is None:
            by_root[att_data_root] = {
                "bits": bits,
                "data": attestation.data,
                "sigs": [bytes(attestation.signature)],
            }
            return InsertOutcome.NEW_DATA
        if len(entry["bits"]) != len(bits):
            raise OpPoolError("aggregation bits length mismatch")
        new_idx = [i for i, b in enumerate(bits) if b]
        if all(entry["bits"][i] for i in new_idx):
            return InsertOutcome.ALREADY_KNOWN
        if any(entry["bits"][i] for i in new_idx):
            # overlapping multi-bit merge unsupported in the naive pool
            # (gossip attestations carry exactly one bit)
            return InsertOutcome.ALREADY_KNOWN
        for i in new_idx:
            entry["bits"][i] = True
        entry["sigs"].append(bytes(attestation.signature))
        return InsertOutcome.AGGREGATED

    def get_aggregate(self, slot: int, att_data_root: bytes):
        entry = self._by_slot.get(slot, {}).get(att_data_root)
        if entry is None:
            return None
        t = ssz_types()
        att = t.Attestation.default()
        att.aggregation_bits = list(entry["bits"])
        att.data = entry["data"]
        att.signature = aggregate_signatures(entry["sigs"])
        return att

    def prune(self, clock_slot: int) -> None:
        self._lowest_permissible_slot = max(0, clock_slot - SLOTS_RETAINED)
        for slot in [s for s in self._by_slot if s < self._lowest_permissible_slot]:
            del self._by_slot[slot]

    def attestation_count(self) -> int:
        return sum(len(m) for m in self._by_slot.values())


class AggregatedAttestationPool:
    """Aggregates ready for block inclusion, greedily packed by
    not-yet-on-chain attester count (reference
    `aggregatedAttestationPool.ts:110` getAttestationsForBlock)."""

    def __init__(self) -> None:
        # slot -> data_root -> list of {bits, attestation}
        self._by_slot: dict[int, dict[bytes, list]] = defaultdict(lambda: defaultdict(list))
        self._lowest_permissible_slot = 0

    def add(self, attestation, att_data_root: bytes) -> InsertOutcome:
        slot = attestation.data.slot
        if slot < self._lowest_permissible_slot:
            return InsertOutcome.OLD
        group = self._by_slot[slot][att_data_root]
        bits = np.asarray(list(attestation.aggregation_bits), dtype=bool)
        for existing in group:
            if existing["bits"].shape == bits.shape and bool(np.all(existing["bits"] >= bits)):
                return InsertOutcome.ALREADY_KNOWN
        group.append({"bits": bits, "attestation": attestation})
        # keep the densest few per data (reference keeps MAX_RETAINED... trims)
        group.sort(key=lambda e: int(e["bits"].sum()), reverse=True)
        del group[4:]
        return InsertOutcome.NEW_DATA

    @staticmethod
    def _on_chain_bits(state) -> dict[bytes, np.ndarray]:
        """Union of aggregation bits already on chain, per AttestationData
        root (from the state's pending attestations — phase0's record of
        included votes)."""
        from lodestar_tpu.types import ssz_types

        t = ssz_types()
        seen: dict[bytes, np.ndarray] = {}
        for pending in list(state.previous_epoch_attestations) + list(
            state.current_epoch_attestations
        ):
            root = t.AttestationData.hash_tree_root(pending.data)
            bits = np.asarray(list(pending.aggregation_bits), dtype=bool)
            prev = seen.get(root)
            seen[root] = bits if prev is None else (prev | bits)
        return seen

    def get_attestations_for_block(
        self, state, p, max_attestations: int | None = None, ctx=None
    ) -> list:
        """Greedy selection of includable aggregates for a block built on
        `state` (already advanced to the block slot), scored by how many
        NEW attesters each contributes over what the state has on chain.
        phase0 reads pending attestations; altair+ reads the TIMELY_TARGET
        participation flags through the committee (reference
        `aggregatedAttestationPool.ts:110` getNotSeenValidatorsFn)."""
        max_attestations = max_attestations or p.MAX_ATTESTATIONS
        is_phase0 = hasattr(state, "previous_epoch_attestations")
        on_chain = self._on_chain_bits(state) if is_phase0 else None
        if not is_phase0 and ctx is None:
            from lodestar_tpu.state_transition import EpochContext

            ctx = EpochContext(state, p)
        state_slot = state.slot
        scored = []
        for slot in sorted(self._by_slot, reverse=True):
            if not (slot + p.MIN_ATTESTATION_INCLUSION_DELAY <= state_slot <= slot + p.SLOTS_PER_EPOCH):
                continue
            for root, group in self._by_slot[slot].items():
                chain_bits = on_chain.get(root) if on_chain is not None else None
                for entry in group:
                    bits = entry["bits"]
                    if is_phase0:
                        fresh = (
                            int(bits.sum())
                            if chain_bits is None or chain_bits.shape != bits.shape
                            else int((bits & ~chain_bits).sum())
                        )
                    else:
                        fresh = self._fresh_count_altair(
                            state, ctx, entry["attestation"], bits, p
                        )
                    if fresh > 0:
                        scored.append((fresh, slot, entry["attestation"]))
        scored.sort(key=lambda x: (x[0], x[1]), reverse=True)
        return [att for _, _, att in scored[:max_attestations]]

    @staticmethod
    def _fresh_count_altair(state, ctx, attestation, bits: np.ndarray, p) -> int:
        """Attesters in `bits` whose TIMELY_TARGET flag is not yet set in
        the state's participation for the attestation's epoch."""
        data = attestation.data
        cur_epoch = state.slot // p.SLOTS_PER_EPOCH
        if data.target.epoch == cur_epoch:
            flags = state.current_epoch_participation
        elif data.target.epoch == cur_epoch - 1:
            flags = state.previous_epoch_participation
        else:
            return 0
        try:
            committee = ctx.get_beacon_committee(data.slot, data.index)
        except ValueError:
            return 0
        if len(committee) != bits.shape[0]:
            return 0
        from lodestar_tpu.params import TIMELY_TARGET_FLAG_INDEX

        timely_target = 1 << TIMELY_TARGET_FLAG_INDEX
        return sum(
            1
            for i, b in enumerate(bits)
            if b and not (int(flags[int(committee[i])]) & timely_target)
        )

    def prune(self, clock_slot: int) -> None:
        self._lowest_permissible_slot = max(0, clock_slot - SLOTS_RETAINED)
        for slot in [s for s in self._by_slot if s < self._lowest_permissible_slot]:
            del self._by_slot[slot]


class OpPool:
    """Exits, slashings, bls-to-execution changes (reference `opPool.ts`)."""

    def __init__(self) -> None:
        self._exits: dict[int, object] = {}  # validator index -> SignedVoluntaryExit
        self._proposer_slashings: dict[int, object] = {}
        self._attester_slashings: dict[bytes, object] = {}  # root -> slashing
        self._bls_changes: dict[int, object] = {}

    def insert_voluntary_exit(self, signed_exit) -> None:
        self._exits.setdefault(signed_exit.message.validator_index, signed_exit)

    def has_exit(self, validator_index: int) -> bool:
        return validator_index in self._exits

    def insert_proposer_slashing(self, slashing) -> None:
        self._proposer_slashings.setdefault(
            slashing.signed_header_1.message.proposer_index, slashing
        )

    def insert_attester_slashing(self, slashing, root: bytes) -> None:
        self._attester_slashings.setdefault(root, slashing)

    def insert_bls_to_execution_change(self, change) -> None:
        self._bls_changes.setdefault(change.message.validator_index, change)

    def get_slashings_and_exits(self, state, p) -> tuple[list, list, list]:
        """(attester_slashings, proposer_slashings, exits) packable into a
        block on `state` — filtered to still-slashable/exitable targets."""
        from lodestar_tpu.params import FAR_FUTURE_EPOCH
        from lodestar_tpu.state_transition.util import get_current_epoch, is_slashable_validator

        epoch = get_current_epoch(state)
        n = len(state.validators)
        att_slashings = []
        for s in self._attester_slashings.values():
            common = set(s.attestation_1.attesting_indices) & set(s.attestation_2.attesting_indices)
            if any(
                i < n and is_slashable_validator(state.validators[i], epoch) for i in common
            ):
                att_slashings.append(s)
                if len(att_slashings) >= p.MAX_ATTESTER_SLASHINGS:
                    break
        prop_slashings = [
            s
            for i, s in self._proposer_slashings.items()
            if i < n and is_slashable_validator(state.validators[i], epoch)
        ][: p.MAX_PROPOSER_SLASHINGS]
        exits = [
            e
            for i, e in self._exits.items()
            if i < n and state.validators[i].exit_epoch == FAR_FUTURE_EPOCH
        ][: p.MAX_VOLUNTARY_EXITS]
        return att_slashings, prop_slashings, exits

    def prune_all(self, state) -> None:
        from lodestar_tpu.params import FAR_FUTURE_EPOCH

        n = len(state.validators)
        for i in [i for i in self._exits if i < n and state.validators[i].exit_epoch != FAR_FUTURE_EPOCH]:
            del self._exits[i]
        for i in [i for i in self._proposer_slashings if i < n and state.validators[i].slashed]:
            del self._proposer_slashings[i]


class _EpochKeyedSet:
    """First-seen dedup keyed by (epoch, index) with pruning below the
    finalized epoch (reference `seenCache/seenAttesters.ts`)."""

    def __init__(self) -> None:
        self._by_epoch: dict[int, set[int]] = defaultdict(set)
        self._lowest_permissible_epoch = 0

    def is_known(self, epoch: int, index: int) -> bool:
        return index in self._by_epoch.get(epoch, ())

    def add(self, epoch: int, index: int) -> None:
        if epoch < self._lowest_permissible_epoch:
            raise ValueError(f"epoch {epoch} below pruned horizon")
        self._by_epoch[epoch].add(index)

    def prune(self, finalized_epoch: int) -> None:
        self._lowest_permissible_epoch = finalized_epoch
        for e in [e for e in self._by_epoch if e < finalized_epoch]:
            del self._by_epoch[e]


class SeenAttesters(_EpochKeyedSet):
    pass


class SeenAggregators(_EpochKeyedSet):
    pass
