"""Beacon chain runtime layer (reference `beacon-node/src/chain/`)."""
