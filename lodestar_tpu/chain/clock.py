"""Slot/epoch clock (reference `beacon-node/src/util/clock.ts:66`).

Asyncio re-design of the EventEmitter clock: slot/epoch callbacks fire
from one timer task; gossip-disparity helpers mirror the reference's
MAXIMUM_GOSSIP_CLOCK_DISPARITY (500 ms) semantics. A injectable
`time_fn` makes the clock fully deterministic in tests.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable

__all__ = ["Clock", "MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC"]

MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC = 0.5


class Clock:
    def __init__(
        self,
        *,
        genesis_time: int,
        seconds_per_slot: int,
        slots_per_epoch: int,
        time_fn: Callable[[], float] = time.time,
    ) -> None:
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self.slots_per_epoch = slots_per_epoch
        self._time = time_fn
        self._on_slot: list[Callable[[int], None]] = []
        self._on_epoch: list[Callable[[int], None]] = []
        self._task: asyncio.Task | None = None

    # -- pure time math -------------------------------------------------------

    @property
    def current_slot(self) -> int:
        return max(0, int(self._time() - self.genesis_time) // self.seconds_per_slot)

    @property
    def current_epoch(self) -> int:
        return self.current_slot // self.slots_per_epoch

    def time_at_slot(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def sec_from_slot(self, slot: int, to_sec: float | None = None) -> float:
        return (to_sec if to_sec is not None else self._time()) - self.time_at_slot(slot)

    def slot_with_future_tolerance(self, tolerance_sec: float) -> int:
        return max(0, int(self._time() + tolerance_sec - self.genesis_time) // self.seconds_per_slot)

    def slot_with_past_tolerance(self, tolerance_sec: float) -> int:
        return max(0, int(self._time() - tolerance_sec - self.genesis_time) // self.seconds_per_slot)

    @property
    def current_slot_with_gossip_disparity(self) -> int:
        cur = self.current_slot
        next_slot_time = self.time_at_slot(cur + 1)
        if next_slot_time - self._time() < MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC:
            return cur + 1
        return cur

    def is_current_slot_given_gossip_disparity(self, slot: int) -> bool:
        return (
            self.slot_with_past_tolerance(MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC)
            <= slot
            <= self.slot_with_future_tolerance(MAXIMUM_GOSSIP_CLOCK_DISPARITY_SEC)
        )

    # -- events ---------------------------------------------------------------

    def on_slot(self, fn: Callable[[int], None]) -> None:
        self._on_slot.append(fn)

    def on_epoch(self, fn: Callable[[int], None]) -> None:
        self._on_epoch.append(fn)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            cur = self.current_slot
            next_time = self.time_at_slot(cur + 1)
            await asyncio.sleep(max(0.0, next_time - self._time()))
            slot = self.current_slot
            for fn in self._on_slot:
                fn(slot)
            if slot % self.slots_per_epoch == 0:
                for fn in self._on_epoch:
                    fn(slot // self.slots_per_epoch)

    async def wait_for_slot(self, slot: int) -> None:
        while self.current_slot < slot:
            await asyncio.sleep(
                max(0.01, self.time_at_slot(slot) - self._time())
            )
