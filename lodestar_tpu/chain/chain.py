"""BeaconChain orchestrator + block import pipeline.

Reference `beacon-node/src/chain/chain.ts:88` + `chain/blocks/`:

* sanity checks (known root, finalized horizon, known parent) —
  `verifyBlocksSanityChecks.ts`
* verify: pre-state via the state cache/regen, then the reference's
  parallel split (`verifyBlock.ts:89-111`): signature-free STF and the
  batched signature verification run CONCURRENTLY — the STF on the host
  event loop, the signature sets through the async device verifier pool
  (`asyncio.gather` is the asyncio translation of the Promise.all).
* import: fork-choice onBlock + operation attestations into fork choice
  + head update + hot-db persist + state cache (`importBlock.ts:51`).
* regen: replay blocks from the nearest cached/stored state
  (`chain/regen/regen.ts` without the queue; the job queue lives in
  the caller).
"""

from __future__ import annotations

import threading
from typing import Callable

from lodestar_tpu import slo, tracing
from lodestar_tpu.db import Bucket, DbController, Repository
from lodestar_tpu.fork_choice import Checkpoint, ForkChoice, ProtoBlock
from lodestar_tpu.logger import get_logger
from lodestar_tpu.params import BeaconPreset, active_preset
from lodestar_tpu.scheduler import PriorityClass
from lodestar_tpu.state_transition import (
    EpochContext,
    compute_epoch_at_slot,
    drop_tracker,
    process_block,
    process_slots,
    state_hash_tree_root,
)
from lodestar_tpu.state_transition.signature_sets import get_block_signature_sets
from lodestar_tpu.state_transition.util import effective_balances_array
from lodestar_tpu.types import ssz_types

from .bls import IBlsVerifier, VerifySignatureOpts
from .op_pools import AggregatedAttestationPool, AttestationPool, OpPool, SeenAttesters

__all__ = ["BeaconChain", "BlockError", "BlockErrorCode"]


class BlockErrorCode:
    ALREADY_KNOWN = "ALREADY_KNOWN"
    PARENT_UNKNOWN = "PARENT_UNKNOWN"
    WOULD_REVERT_FINALIZED = "WOULD_REVERT_FINALIZED"
    PRESTATE_MISSING = "PRESTATE_MISSING"
    INVALID_SIGNATURES = "INVALID_SIGNATURES"
    INVALID_STATE_TRANSITION = "INVALID_STATE_TRANSITION"
    FUTURE_SLOT = "FUTURE_SLOT"


class BlockError(Exception):
    #: set True on rejections produced while the BLS verifier stack was
    #: in outage (every degradation layer erred): the gossip processor
    #: must NOT downscore the sending peer for a local incident
    verifier_outage: bool = False

    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code

    @property
    def action(self):
        """Gossip scoring action (mirrors GossipValidationError.action):
        provably-invalid content REJECTs — and downscores the sender —
        while availability/ordering codes (parent unknown, future slot,
        already known) carry no peer evidence."""
        if self.code in (
            BlockErrorCode.INVALID_SIGNATURES,
            BlockErrorCode.INVALID_STATE_TRANSITION,
        ):
            from .validation import GossipAction

            return GossipAction.REJECT
        return None


def _hex(b: bytes) -> str:
    return "0x" + b.hex()


class StateCache:
    """LRU hot-state cache by block root (reference
    `stateCache/stateContextCache.ts`, max 96)."""

    def __init__(self, max_states: int = 96):
        self.max_states = max_states
        self._by_root: dict[bytes, object] = {}

    def get(self, block_root: bytes):
        st = self._by_root.get(block_root)
        if st is not None:
            # refresh LRU position
            self._by_root.pop(block_root)
            self._by_root[block_root] = st
        return st

    def add(self, block_root: bytes, state) -> None:
        # a cached state is dormant: every consumer copies before
        # mutating (and copy() drops the HTR tracker), so its
        # incremental-root snapshots would be pinned dead weight —
        # hundreds of MB per state at the 1M-validator target
        drop_tracker(state)
        self._by_root[block_root] = state
        while len(self._by_root) > self.max_states:
            self._by_root.pop(next(iter(self._by_root)))

    def prune_except(self, keep_roots: set[bytes]) -> None:
        for root in [r for r in self._by_root if r not in keep_roots]:
            del self._by_root[root]


class BeaconChain:
    def __init__(
        self,
        *,
        anchor_state,
        bls_verifier: IBlsVerifier,
        db: DbController,
        p: BeaconPreset | None = None,
        cfg=None,
        genesis_block_root: bytes | None = None,
        current_slot: int | None = None,
        metrics=None,
        archive_state_epoch_frequency: int | None = None,
    ) -> None:
        self.p = p = p or active_preset()
        self.cfg = cfg
        self.bls = bls_verifier
        self.metrics = metrics
        self.log = get_logger(name="lodestar.chain")
        t = ssz_types(p)
        self.types = t

        self.blocks_db: Repository = Repository(db, Bucket.allForks_block, t.phase0.SignedBeaconBlock)
        # coupled early-4844 sidecars, keyed by block root (reference
        # db allForks_blobsSidecar)
        self.blobs_db: Repository = Repository(
            db, Bucket.allForks_blobsSidecar, t.deneb.BlobsSidecar
        )
        self.states_db: Repository = Repository(db, Bucket.allForks_stateArchive, anchor_state.type)

        self.state_cache = StateCache()
        # serializes chain mutations across threads: the asyncio gossip
        # drain (event-loop thread) and the threaded REST server both
        # import blocks/attestations — the structures below have no
        # internal locking (the reference is single-threaded Node.js)
        self.import_lock = threading.RLock()
        from .archiver import DEFAULT_ARCHIVE_STATE_EPOCH_FREQUENCY, Archiver
        from .regen import QueuedStateRegenerator

        self.regen = QueuedStateRegenerator(self)
        self.archiver = Archiver(
            self,
            db,
            DEFAULT_ARCHIVE_STATE_EPOCH_FREQUENCY
            if archive_state_epoch_frequency is None
            else archive_state_epoch_frequency,
        )
        self.attestation_pool = AttestationPool()
        self.aggregated_attestation_pool = AggregatedAttestationPool()
        self.op_pool = OpPool()
        from .sync_pools import (
            SeenSlotKeyed,
            SyncCommitteeMessagePool,
            SyncContributionAndProofPool,
        )

        self.sync_committee_message_pool = SyncCommitteeMessagePool(p)
        self.sync_contribution_pool = SyncContributionAndProofPool(p)
        self.seen_sync_messages = SeenSlotKeyed()
        self.seen_sync_aggregators = SeenSlotKeyed()
        # optional eth1 provider for block production (execution.eth1)
        self.eth1 = None
        # optional light-client server (chain.light_client_server)
        self.light_client_server = None
        self.seen_attesters = SeenAttesters()
        from .op_pools import SeenAggregators, _EpochKeyedSet

        self.seen_aggregators = SeenAggregators()
        self.seen_block_proposers = _EpochKeyedSet()
        # block-INCLUDED attesters tracked separately from the gossip
        # dedup cache (reference SeenBlockAttesters vs SeenAttesters):
        # marking them "seen" for gossip would IGNORE late-arriving
        # legitimate gossip attestations
        self.seen_block_attesters = _EpochKeyedSet()

        # anchor: latest block header of the anchor state defines the root
        header = anchor_state.latest_block_header.copy()
        if bytes(header.state_root) == b"\x00" * 32:
            header.state_root = anchor_state.type.hash_tree_root(anchor_state)
        anchor_root = genesis_block_root or t.BeaconBlockHeader.hash_tree_root(header)
        self.state_cache.add(anchor_root, anchor_state)

        # anchor checkpoint = (epoch of the anchor slot, anchor block
        # root) for BOTH store checkpoints; for a non-genesis anchor the
        # justified epoch is bumped +1 so the chain cannot justify with
        # a block that doesn't also finalize the anchor — head stays at
        # the anchor until a real justification lands (reference
        # `chain/forkChoice/index.ts initializeForkChoice`)
        anchor_epoch = compute_epoch_at_slot(anchor_state.slot, p)
        finalized_cp = Checkpoint(anchor_epoch, _hex(anchor_root))
        justified_cp = Checkpoint(
            anchor_epoch if anchor_epoch == 0 else anchor_epoch + 1, _hex(anchor_root)
        )
        proto = ProtoBlock(
            slot=anchor_state.slot,
            block_root=_hex(anchor_root),
            parent_root=_hex(b"\xff" * 32),
            state_root=_hex(bytes(header.state_root)),
            target_root=_hex(anchor_root),
            justified_epoch=justified_cp.epoch,
            justified_root=justified_cp.root,
            finalized_epoch=finalized_cp.epoch,
            finalized_root=finalized_cp.root,
            unrealized_justified_epoch=justified_cp.epoch,
            unrealized_finalized_epoch=finalized_cp.epoch,
        )
        self.fork_choice = ForkChoice.from_anchor(
            proto,
            current_slot=current_slot if current_slot is not None else anchor_state.slot,
            justified_balances=effective_balances_array(anchor_state),
            slots_per_epoch=p.SLOTS_PER_EPOCH,
        )
        self._subscribers: dict[str, list[Callable]] = {"block": [], "head": [], "finalized": []}

    # -- fork-aware types ------------------------------------------------------

    def fork_name_at_slot(self, slot: int) -> str:
        if self.cfg is None:
            return "phase0"
        from lodestar_tpu.config import fork_name_at_epoch

        return fork_name_at_epoch(self.cfg, slot // self.p.SLOTS_PER_EPOCH)

    def block_type_at_slot(self, slot: int):
        ns = getattr(self.types, self.fork_name_at_slot(slot))
        return ns.BeaconBlock, ns.SignedBeaconBlock

    # -- events ---------------------------------------------------------------

    def on(self, event: str, fn: Callable) -> None:
        self._subscribers[event].append(fn)

    def off(self, event: str, fn: Callable) -> None:
        """Detach a subscriber (safe from other threads — _emit iterates
        a snapshot, so concurrent removal never skips a neighbor)."""
        subs = self._subscribers.get(event, [])
        if fn in subs:
            subs.remove(fn)

    def _emit(self, event: str, *args) -> None:
        for fn in tuple(self._subscribers.get(event, ())):
            fn(*args)

    # -- clock ----------------------------------------------------------------

    def on_slot(self, slot: int) -> None:
        if slot <= self.fork_choice.current_slot:
            return  # a stale timer tick must never rewind the store clock
        prev_epoch = self.fork_choice.current_slot // self.p.SLOTS_PER_EPOCH
        self.fork_choice.on_tick(slot)
        self.attestation_pool.prune(slot)
        self.aggregated_attestation_pool.prune(slot)
        self.sync_committee_message_pool.prune(slot)
        self.sync_contribution_pool.prune(slot)
        self.seen_sync_messages.prune(slot - 3)
        self.seen_sync_aggregators.prune(slot - 3)
        if self.metrics is not None:
            self.metrics.clock_slot.set(slot)
            epoch = slot // self.p.SLOTS_PER_EPOCH
            if epoch > prev_epoch:
                summary = self.metrics.validator_monitor.on_epoch(epoch)
                if summary and summary.get("missed"):
                    self.log.info(
                        f"validator monitor epoch {summary['epoch']}: "
                        f"{summary['attested']} attested, {summary['missed']} missed"
                    )

    # -- block store -----------------------------------------------------------

    def get_block_by_root(self, block_root: bytes):
        """Fork-aware decode from the hot block db, falling through to
        the finalized archive (root index -> slot -> cold bucket). When
        the proto node is gone (pruned orphan), the slot is read straight
        from the serialized block — every SignedBeaconBlock starts
        offset4 | signature96 | message{slot u64le} — so the right fork
        container is still chosen."""
        raw = self.blocks_db.get_binary(block_root)
        if raw is None:
            return self.archiver.get_archived_block_by_root(block_root)
        node = self.fork_choice.proto_array.get_block(_hex(block_root))
        if node is not None:
            slot = node.slot
        elif len(raw) >= 108:
            slot = int.from_bytes(raw[100:108], "little")
        else:
            slot = 0
        _, signed_type = self.block_type_at_slot(slot)
        return signed_type.deserialize(raw)

    # -- regen ----------------------------------------------------------------

    def get_state_by_block_root(self, block_root: bytes):
        """Hot-cache hit or replay from the nearest stored ancestor state
        (reference `regen/regen.ts` getState)."""
        st = self.state_cache.get(block_root)
        if st is not None:
            return st
        # walk ancestors in fork choice until a cached state is found
        chain: list[bytes] = []
        root = block_root
        while True:
            chain.append(root)
            node = self.fork_choice.proto_array.get_block(_hex(root))
            if node is None:
                raise BlockError(BlockErrorCode.PRESTATE_MISSING, _hex(root))
            parent = bytes.fromhex(node.parent_root[2:])
            st = self.state_cache.get(parent)
            if st is not None:
                break
            root = parent
        # replay forward
        for r in reversed(chain):
            signed = self.get_block_by_root(r)
            if signed is None:
                raise BlockError(BlockErrorCode.PRESTATE_MISSING, f"block {_hex(r)} not in db")
            st = self._replay_block(st, signed)
            self.state_cache.add(r, st)
        return st

    def _replay_block(self, pre_state, signed_block):
        post = pre_state.copy()
        block = signed_block.message
        if block.slot > post.slot:
            ctx = process_slots(post, block.slot, self.p, self.cfg)
        else:
            ctx = EpochContext(post, self.p)
        process_block(post, block, ctx, verify_signatures=False, cfg=self.cfg)
        return post

    # -- block import ---------------------------------------------------------

    async def process_block(self, signed_block, *, is_timely: bool = False, priority=None):
        """Full import pipeline for one gossip/sync block. Serialized
        with other chain mutations via import_lock (REST threads vs the
        gossip drain loop). `priority` is the scheduler launch class the
        block's signature batch carries into the device queue; None maps
        to GOSSIP_BLOCK when is_timely (slot-deadline gossip import),
        API otherwise — sync paths pass their own class."""
        with self.import_lock:
            return await self._process_block_locked(
                signed_block, is_timely=is_timely, priority=priority
            )

    # sanity rejections before any pipeline work — their traces are
    # discarded so no-op imports (sync duplicates) don't flood the ring
    _NOOP_IMPORT_CODES = frozenset(
        (
            BlockErrorCode.ALREADY_KNOWN,
            BlockErrorCode.PARENT_UNKNOWN,
            BlockErrorCode.WOULD_REVERT_FINALIZED,
            BlockErrorCode.FUTURE_SLOT,
        )
    )

    async def _process_block_locked(
        self, signed_block, *, is_timely: bool = False, priority=None
    ):
        # root when called directly (sync/REST paths); child span when the
        # gossip processor already opened the slot's block_import trace
        with tracing.root("process_block", slot=int(signed_block.message.slot)):
            try:
                return await self._process_block_traced(
                    signed_block, is_timely=is_timely, priority=priority
                )
            except BlockError as e:
                # the post-verification ALREADY_KNOWN race re-check sets
                # pipeline_ran: that trace measured real device/STF work
                # and must survive for the slow-slot dump
                if e.code in self._NOOP_IMPORT_CODES and not getattr(
                    e, "pipeline_ran", False
                ):
                    tracing.discard()
                raise

    async def _process_block_traced(
        self, signed_block, *, is_timely: bool = False, priority=None
    ):
        if priority is None:
            priority = PriorityClass.GOSSIP_BLOCK if is_timely else PriorityClass.API
        t = self.types
        block = signed_block.message
        block_type, signed_type = self.block_type_at_slot(block.slot)
        block_root = block_type.hash_tree_root(block)

        # 1. sanity (verifyBlocksSanityChecks.ts)
        if self.fork_choice.proto_array.has_block(_hex(block_root)):
            raise BlockError(BlockErrorCode.ALREADY_KNOWN, _hex(block_root))
        finalized_slot = self.fork_choice.finalized.epoch * self.p.SLOTS_PER_EPOCH
        if block.slot <= finalized_slot:
            raise BlockError(
                BlockErrorCode.WOULD_REVERT_FINALIZED, f"slot {block.slot} <= {finalized_slot}"
            )
        if block.slot > self.fork_choice.current_slot:
            raise BlockError(BlockErrorCode.FUTURE_SLOT, f"slot {block.slot}")
        parent_root = bytes(block.parent_root)
        parent = self.fork_choice.proto_array.get_block(_hex(parent_root))
        if parent is None:
            raise BlockError(BlockErrorCode.PARENT_UNKNOWN, _hex(parent_root))

        # 2. pre-state + dial to block slot
        with tracing.span("pre_state_regen"):
            pre_state = self.get_state_by_block_root(parent_root)
            work_state = pre_state.copy()
            if block.slot > work_state.slot:
                ctx = process_slots(work_state, block.slot, self.p, self.cfg)
            else:
                ctx = EpochContext(work_state, self.p)

        # 3. parallel: signature-free STF on this task + batched signature
        # verification through the device pool (verifyBlock.ts:89-111)
        import asyncio

        sets = get_block_signature_sets(work_state, signed_block, ctx)

        async def run_sigs():
            # own task: ensure_future snapshots the context, so the span
            # stitches under this import's trace; pool jobs capture it as
            # their parent for the buffer-wait/device-launch spans
            with tracing.span("bls_verify") as sp:
                if sp:
                    sp.set(sets=len(sets))
                ok = await self.bls.verify_signature_sets(
                    sets,
                    VerifySignatureOpts(
                        batchable=False, priority=priority, slot=int(block.slot)
                    ),
                )
                if sp:
                    # remaining slot-deadline slack when the verdict
                    # landed (None = SLO layer off) — the slow-slot dump
                    # answers "did we still make the deadline" inline
                    slack = slo.slack_ms(priority, int(block.slot))
                    if slack is not None:
                        sp.set(slack_ms=slack)
                    # DegradingBlsVerifier names the layer that actually
                    # served — a slow-slot dump shows degraded imports.
                    # serving_layer() is a contextvar read: this TASK's
                    # verdict, not whichever import finished last
                    serving = getattr(self.bls, "serving_layer", None)
                    layer = (
                        serving() if callable(serving)
                        else getattr(self.bls, "last_layer", None)
                    )
                    if layer is not None:
                        sp.set(verifier_layer=layer)
                return ok

        sig_task = asyncio.ensure_future(run_sigs())
        stf_parent = tracing.current()  # executor threads don't see contextvars

        def run_stf():
            from lodestar_tpu.state_transition import BlockProcessError, StateTransitionError

            post = work_state  # already copied + dialed
            try:
                with tracing.span("state_transition", parent=stf_parent):
                    process_block(post, block, ctx, verify_signatures=False, cfg=self.cfg)
            except (BlockProcessError, StateTransitionError) as e:
                raise BlockError(BlockErrorCode.INVALID_STATE_TRANSITION, str(e)) from e
            with tracing.span("hash_tree_root", parent=stf_parent):
                # the dirty-subtree collector when --htr-device selects
                # it; the tracker is warm from process_slots on this
                # same post-state, so only the block's mutations flush
                got = state_hash_tree_root(post)
            if got != bytes(block.state_root):
                raise BlockError(BlockErrorCode.INVALID_STATE_TRANSITION, "state root mismatch")
            return post

        stf_task = asyncio.get_event_loop().run_in_executor(None, run_stf)
        results = await asyncio.gather(stf_task, sig_task, return_exceptions=True)
        stf_res, sig_res = results
        if isinstance(stf_res, BaseException):
            # gather(return_exceptions=True) already waited out sig_task;
            # a failing STF still pays for the in-flight verification
            raise stf_res
        if isinstance(sig_res, BaseException):
            # fail closed: a verifier/transport error rejects the block
            # import, it never resolves valid (multithread/index.ts:386-393).
            # A verifier ERROR is never evidence about the block (only a
            # served False verdict is): the rejection is local fail-closed
            # policy, so it is ALWAYS marked as a verifier fault and gossip
            # scoring spares the honest sender (network/processor.py). This
            # is per-rejection state riding the exception itself — no
            # shared flag to race against a concurrently recovering import.
            err = BlockError(
                BlockErrorCode.INVALID_SIGNATURES, f"verifier error: {sig_res!r}"
            )
            err.verifier_outage = True
            raise err
        post_state, sigs_ok = stf_res, sig_res
        if not sigs_ok:
            raise BlockError(BlockErrorCode.INVALID_SIGNATURES, _hex(block_root))

        # 4. import (importBlock.ts:51). Re-check ALREADY_KNOWN: another
        # task may have imported the same block while this one awaited
        # signature verification (asyncio interleaves at awaits; the
        # RLock only excludes across threads)
        if self.fork_choice.proto_array.has_block(_hex(block_root)):
            err = BlockError(BlockErrorCode.ALREADY_KNOWN, _hex(block_root))
            err.pipeline_ran = True
            raise err
        with tracing.span("persist_block"):
            self.blocks_db.put_binary(block_root, signed_type.serialize(signed_block))
            self.state_cache.add(block_root, post_state)

        blk_epoch = compute_epoch_at_slot(block.slot, self.p)
        jc = post_state.current_justified_checkpoint
        fc_cp = post_state.finalized_checkpoint
        proto = ProtoBlock(
            slot=block.slot,
            block_root=_hex(block_root),
            parent_root=_hex(parent_root),
            state_root=_hex(bytes(block.state_root)),
            target_root=_hex(self._target_root(post_state, blk_epoch, block_root)),
            justified_epoch=jc.epoch,
            justified_root=_hex(bytes(jc.root)),
            finalized_epoch=fc_cp.epoch,
            finalized_root=_hex(bytes(fc_cp.root)),
            unrealized_justified_epoch=jc.epoch,
            unrealized_finalized_epoch=fc_cp.epoch,
        )
        prev_finalized = self.fork_choice.finalized.epoch
        with tracing.span("fork_choice"):
            self.fork_choice.on_block(
                proto,
                is_timely=is_timely,
                justified_checkpoint=Checkpoint(jc.epoch, _hex(bytes(jc.root))),
                finalized_checkpoint=Checkpoint(fc_cp.epoch, _hex(bytes(fc_cp.root))),
                justified_balances=effective_balances_array(post_state),
            )

            # operation attestations feed LMD votes (importBlock.ts:130) and
            # the liveness record (doppelganger data source: on-chain activity
            # counts, not just gossip — reference validatorMonitor). Child
            # span: committee computation + monitor bookkeeping dominate
            # here and must not read as fork-choice time in dumps/metrics
            with tracing.span("attestation_ops"):
                blk_proposer_epoch = compute_epoch_at_slot(block.slot, self.p)
                self.seen_block_proposers.add(blk_proposer_epoch, int(block.proposer_index))
                monitor = self.metrics.validator_monitor if self.metrics is not None else None
                if monitor is not None:
                    monitor.on_block_imported(int(block.slot), int(block.proposer_index))
                for att in block.body.attestations:
                    try:
                        attesting = ctx.get_attesting_indices(att.data, att.aggregation_bits)
                    except ValueError:
                        continue
                    for i in attesting:
                        self.seen_block_attesters.add(int(att.data.target.epoch), int(i))
                    if monitor is not None:
                        monitor.on_attestation_in_block(
                            int(att.data.target.epoch),
                            [int(i) for i in attesting],
                            int(block.slot) - int(att.data.slot),
                        )
                    self.fork_choice.on_attestation(
                        [int(i) for i in attesting],
                        _hex(bytes(att.data.beacon_block_root)),
                        att.data.target.epoch,
                        att.data.slot,
                    )

            head = self.fork_choice.update_head()
        if self.light_client_server is not None:
            self.light_client_server.on_imported_block(signed_block, post_state)
        self._emit("block", block_root, signed_block)
        self._emit("head", head)
        if self.metrics is not None:
            self.metrics.head_slot.set(block.slot)
            self.metrics.finalized_epoch.set(fc_cp.epoch)
            self.metrics.justified_epoch.set(jc.epoch)

        if fc_cp.epoch > prev_finalized:
            self._on_finalized(fc_cp)
        return block_root

    def _target_root(self, state, epoch: int, block_root: bytes) -> bytes:
        from lodestar_tpu.state_transition.util import get_block_root

        try:
            return get_block_root(state, epoch, self.p)
        except ValueError:
            return block_root

    def _on_finalized(self, cp) -> None:
        """Archive then prune on finalization (reference `archiver/`):
        block/state migration runs while the dead-fork nodes are still
        in the proto array, then fork choice + caches are pruned."""
        root = bytes(cp.root)
        self.archiver.on_finalized(cp)
        self.fork_choice.prune()
        keep = {bytes.fromhex(n.block_root[2:]) for n in self.fork_choice.proto_array.nodes}
        self.state_cache.prune_except(keep)
        self.regen.prune_on_finalized(cp.epoch)
        for seen in (
            self.seen_attesters,
            self.seen_aggregators,
            self.seen_block_attesters,
            self.seen_block_proposers,
        ):
            seen.prune(cp.epoch)
        st = self.state_cache.get(root)
        if st is not None:
            self.op_pool.prune_all(st)
        self._emit("finalized", cp)

    # -- head accessors -------------------------------------------------------

    @property
    def head_root(self) -> bytes:
        return bytes.fromhex(self.fork_choice.head[2:])

    def get_head_state(self):
        return self.get_state_by_block_root(self.head_root)

    def put_blobs_sidecar(self, sidecar) -> None:
        self.blobs_db.put(bytes(sidecar.beacon_block_root), sidecar)

    def get_blobs_sidecar(self, block_root: bytes):
        return self.blobs_db.get(bytes(block_root))

    def get_finalized_state(self):
        """State at the finalized checkpoint: hot cache, else regen from
        the finalized block (still in fork choice), else replay the
        archived canonical blocks forward from the newest archived state
        — never a silently-stale snapshot. The cold replay can be tens
        of thousands of STF steps (archive cadence), so its result is
        memoized per finalized root."""
        root = bytes.fromhex(self.fork_choice.finalized.root[2:])
        st = self.state_cache.get(root)
        if st is not None:
            return st
        memo = getattr(self, "_finalized_replay_memo", None)
        if memo is not None and memo[0] == root:
            return memo[1]
        try:
            return self.get_state_by_block_root(root)
        except BlockError:
            pass
        node = self.fork_choice.proto_array.get_block(self.fork_choice.finalized.root)
        finalized_slot = (
            node.slot if node is not None else self.fork_choice.finalized.epoch * self.p.SLOTS_PER_EPOCH
        )
        st = self.archiver.get_archived_state_at_or_before(finalized_slot)
        if st is None:
            return None
        for slot in range(int(st.slot) + 1, finalized_slot + 1):
            signed = self.archiver.get_archived_block_by_slot(slot)
            if signed is not None:
                st = self._replay_block(st, signed)
        if int(st.slot) < finalized_slot:
            st = st.copy()
            process_slots(st, finalized_slot, self.p, self.cfg)
        # cache under the block root ONLY if the replay actually reached
        # the finalized block AND stopped at its slot — caching a
        # padded-forward state under the root would poison regen for
        # every descendant between the block's slot and the pad target
        header = st.latest_block_header.copy()
        if bytes(header.state_root) == b"\x00" * 32:
            # transient: rides a tracker left warm by the replay's
            # process_slots, but never cold-builds one for a dormant
            # cached state's single root
            header.state_root = state_hash_tree_root(st, transient=True)
        if (
            int(st.slot) == int(st.latest_block_header.slot)
            and self.types.BeaconBlockHeader.hash_tree_root(header) == root
        ):
            self.state_cache.add(root, st)
        # the memo state is dormant too (replay consumers copy first):
        # drop tracking even when the cache-add condition was skipped
        drop_tracker(st)
        self._finalized_replay_memo = (root, st)
        return st
