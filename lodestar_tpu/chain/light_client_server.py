"""Light-client server: produce bootstraps and updates from chain data.

Reference `beacon-node/src/chain/lightClient/index.ts:168` + `proofs.ts`:
on block import the server captures (attested header, sync aggregate,
state proofs) and serves LightClientBootstrap / LightClientUpdate /
FinalityUpdate / OptimisticUpdate. Proof production reuses
`light_client.produce_state_field_branch` over the typed state.
"""

from __future__ import annotations

from lodestar_tpu.light_client import is_better_update, produce_state_field_branch
from lodestar_tpu.types import ssz_types

__all__ = ["LightClientServer"]


class LightClientServer:
    def __init__(self, chain):
        self.chain = chain
        self.p = chain.p
        self._best_by_period: dict[int, object] = {}
        self._latest_finality_update = None
        self._latest_optimistic_update = None

    # -- production (called from block import) --------------------------------

    def on_imported_block(self, signed_block, post_state) -> None:
        """Build an update whose attested header is the block's PARENT
        (the header the block's sync aggregate signs)."""
        from lodestar_tpu.state_transition.block import fork_of

        if fork_of(post_state) == "phase0":
            return  # no sync committees before altair
        t = ssz_types(self.p)
        block = signed_block.message
        parent_root = bytes(block.parent_root)
        try:
            attested_state = self.chain.get_state_by_block_root(parent_root)
        except Exception:
            return
        parent_node = self.chain.fork_choice.proto_array.get_block("0x" + parent_root.hex())
        if parent_node is None:
            return

        # the attested header must reconstruct EXACTLY (clients verify the
        # sync aggregate against hash_tree_root(attested_header)); without
        # the stored parent block (e.g. the anchor) no valid update exists
        parent_block = self.chain.get_block_by_root(parent_root)
        if parent_block is None:
            return
        if fork_of(attested_state) == "phase0":
            # the first altair block attests a phase0 parent: no sync
            # committee to prove yet
            return

        update = t.LightClientUpdate.default()
        att = t.LightClientHeader.default()
        att.beacon.slot = parent_node.slot
        att.beacon.parent_root = bytes.fromhex(parent_node.parent_root[2:])
        att.beacon.state_root = bytes.fromhex(parent_node.state_root[2:])
        from lodestar_tpu.state_transition.block import block_types_for

        _, body_t = block_types_for(attested_state, self.p)
        att.beacon.body_root = body_t.hash_tree_root(parent_block.message.body)
        att.beacon.proposer_index = parent_block.message.proposer_index
        update.attested_header = att

        # next sync committee proof from the attested state
        update.next_sync_committee = attested_state.next_sync_committee
        update.next_sync_committee_branch = produce_state_field_branch(
            attested_state, "next_sync_committee"
        )

        # finality: prove the attested state's finalized checkpoint
        fin_cp = attested_state.finalized_checkpoint
        fin_block = self.chain.get_block_by_root(bytes(fin_cp.root))
        if fin_block is not None:
            fin_hdr = t.LightClientHeader.default()
            fin_hdr.beacon.slot = fin_block.message.slot
            fin_hdr.beacon.proposer_index = fin_block.message.proposer_index
            fin_hdr.beacon.parent_root = bytes(fin_block.message.parent_root)
            fin_hdr.beacon.state_root = bytes(fin_block.message.state_root)
            from lodestar_tpu.state_transition.block import block_types_for

            _, body_t = block_types_for(attested_state, self.p)
            fin_hdr.beacon.body_root = body_t.hash_tree_root(fin_block.message.body)
            update.finalized_header = fin_hdr
            epoch_root = t.Checkpoint.fields[0][1].hash_tree_root(fin_cp.epoch)
            update.finality_branch = [epoch_root] + produce_state_field_branch(
                attested_state, "finalized_checkpoint"
            )

        update.sync_aggregate = block.body.sync_aggregate
        update.signature_slot = block.slot

        period = parent_node.slot // (
            self.p.SLOTS_PER_EPOCH * self.p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        )
        best = self._best_by_period.get(period)
        if best is None or is_better_update(update, best):
            self._best_by_period[period] = update
        if update.finalized_header.beacon.slot != 0:
            self._latest_finality_update = update
        self._latest_optimistic_update = update

    # -- serving (the light-client reqresp/REST handlers) ---------------------

    def get_bootstrap(self, block_root: bytes):
        """LightClientBootstrap anchored at `block_root`."""
        t = ssz_types(self.p)
        state = self.chain.get_state_by_block_root(block_root)
        node = self.chain.fork_choice.proto_array.get_block("0x" + block_root.hex())
        if node is None:
            raise KeyError(f"unknown block 0x{block_root.hex()[:16]}")
        # the FULL header: clients verify hash_tree_root(header) against
        # their trusted block root (reference lightclient bootstrap); an
        # unreconstructible header would fail client-side anyway, so a
        # missing block is a clean not-found
        signed = self.chain.get_block_by_root(block_root)
        if signed is None:
            return None
        boot = t.LightClientBootstrap.default()
        boot.header.beacon.slot = node.slot
        boot.header.beacon.state_root = bytes.fromhex(node.state_root[2:])
        msg = signed.message
        boot.header.beacon.proposer_index = int(msg.proposer_index)
        boot.header.beacon.parent_root = bytes(msg.parent_root)
        from lodestar_tpu.state_transition.block import fork_of

        ns = getattr(t, fork_of(msg))
        boot.header.beacon.body_root = ns.BeaconBlockBody.hash_tree_root(msg.body)
        boot.current_sync_committee = state.current_sync_committee
        boot.current_sync_committee_branch = produce_state_field_branch(
            state, "current_sync_committee"
        )
        return boot

    def get_updates(self, start_period: int, count: int) -> list:
        return [
            self._best_by_period[p]
            for p in range(start_period, start_period + count)
            if p in self._best_by_period
        ]

    def get_finality_update(self):
        return self._latest_finality_update

    def get_optimistic_update(self):
        return self._latest_optimistic_update
