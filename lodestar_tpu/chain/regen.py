"""State regeneration: queued regen + checkpoint-state cache.

Reference `beacon-node/src/chain/regen/queued.ts:29` (QueuedStateRegenerator:
bounded job queue, canAcceptWork admission at jobLen < 16) and
`chain/stateCache/stateContextCheckpointsCache.ts` (checkpoint states
keyed by epoch:root, pruned to MAX_EPOCHS). The underlying replay is
`BeaconChain.get_state_by_block_root` (chain.py — regen.ts without the
queue); this module adds the scheduling/backpressure layer the gossip
processor gates on (`processor/index.ts:316-330`).
"""

from __future__ import annotations

from lodestar_tpu.state_transition import process_slots
from lodestar_tpu.utils.queue import JobItemQueue

__all__ = ["CheckpointStateCache", "QueuedStateRegenerator", "RegenCaller"]

REGEN_QUEUE_MAX_LEN = 256
REGEN_CAN_ACCEPT_WORK_THRESHOLD = 16
MAX_CHECKPOINT_EPOCHS = 10


class RegenCaller:
    """Why a state was requested — the reference threads this through for
    metrics attribution (`regen/interface.ts RegenCaller`)."""

    processBlock = "processBlock"
    produceBlock = "produceBlock"
    validateGossipBlock = "validateGossipBlock"
    validateGossipAttestation = "validateGossipAttestation"
    precomputeEpoch = "precomputeEpoch"
    restApi = "restApi"


class CheckpointStateCache:
    """Checkpoint (epoch, root) -> dialed state at the epoch's start
    slot. Insertion-ordered dict doubles as the prune queue."""

    def __init__(self, max_epochs: int = MAX_CHECKPOINT_EPOCHS):
        self.max_epochs = max_epochs
        self._cache: dict[tuple[int, bytes], object] = {}

    @staticmethod
    def _key(epoch: int, root: bytes) -> tuple[int, bytes]:
        return (int(epoch), bytes(root))

    def get(self, epoch: int, root: bytes):
        return self._cache.get(self._key(epoch, root))

    def add(self, epoch: int, root: bytes, state) -> None:
        self._cache[self._key(epoch, root)] = state
        epochs = sorted({e for e, _ in self._cache})
        if len(epochs) > self.max_epochs:
            cutoff = epochs[len(epochs) - self.max_epochs]
            for k in [k for k in self._cache if k[0] < cutoff]:
                del self._cache[k]

    def get_latest(self, root: bytes, max_epoch: int):
        """Most-recent cached state for this block root at or below
        max_epoch (reference getLatest)."""
        best = None
        best_epoch = -1
        for (e, r), st in self._cache.items():
            if r == bytes(root) and best_epoch < e <= max_epoch:
                best, best_epoch = st, e
        return best

    def prune_finalized(self, finalized_epoch: int) -> None:
        for k in [k for k in self._cache if k[0] < finalized_epoch]:
            del self._cache[k]

    def __len__(self) -> int:
        return len(self._cache)


class QueuedStateRegenerator:
    """Async facade over the chain's synchronous regen with a bounded
    FIFO job queue. State requests from gossip validation, block
    production, and the REST API all funnel through here so replay work
    is serialized and sheddable."""

    def __init__(self, chain, max_length: int = REGEN_QUEUE_MAX_LEN):
        self.chain = chain
        self.checkpoint_states = CheckpointStateCache()
        self._queue = JobItemQueue(self._run_job, max_length=max_length)

    def can_accept_work(self) -> bool:
        return self._queue.job_len < REGEN_CAN_ACCEPT_WORK_THRESHOLD

    @property
    def job_len(self) -> int:
        return self._queue.job_len

    # -- sync fast paths (cache hits cost nothing, reference queued.ts
    # checks caches before queueing) --------------------------------------

    def get_cached_state(self, block_root: bytes):
        return self.chain.state_cache.get(bytes(block_root))

    def get_checkpoint_state_sync(self, epoch: int, root: bytes):
        return self.checkpoint_states.get(epoch, root)

    # -- queued paths ------------------------------------------------------

    async def get_state(self, block_root: bytes, caller: str = RegenCaller.restApi):
        """State after the given block (hot-cache hit bypasses the
        queue)."""
        st = self.get_cached_state(block_root)
        if st is not None:
            return st
        return await self._queue.push("state", bytes(block_root), None)

    async def get_pre_state(self, block, caller: str = RegenCaller.processBlock):
        """Pre-state for a block: parent state dialed to the block's
        slot (reference getPreState = getBlockSlotState(parent))."""
        return await self.get_block_slot_state(
            bytes(block.parent_root), int(block.slot), caller
        )

    async def get_block_slot_state(
        self, block_root: bytes, slot: int, caller: str = RegenCaller.processBlock
    ):
        return await self._queue.push("block_slot", bytes(block_root), int(slot))

    async def get_checkpoint_state(
        self, epoch: int, root: bytes, caller: str = RegenCaller.validateGossipAttestation
    ):
        """State of `root` dialed to the start of `epoch` — the
        attestation-target state (reference getCheckpointState)."""
        st = self.checkpoint_states.get(epoch, root)
        if st is not None:
            return st
        p = self.chain.p
        return await self._queue.push("block_slot_cp", bytes(root), int(epoch) * p.SLOTS_PER_EPOCH)

    # -- job runner --------------------------------------------------------

    def _run_job(self, kind: str, block_root: bytes, slot: int | None):
        chain = self.chain
        state = chain.get_state_by_block_root(block_root)
        if kind == "state" or slot is None:
            return state
        if state.slot < slot:
            state = state.copy()
            process_slots(state, slot, chain.p, chain.cfg)
        elif state.slot > slot:
            raise ValueError(f"state at slot {state.slot} is past requested {slot}")
        if kind == "block_slot_cp":
            p = chain.p
            self.checkpoint_states.add(slot // p.SLOTS_PER_EPOCH, block_root, state)
        return state

    def prune_on_finalized(self, finalized_epoch: int) -> None:
        self.checkpoint_states.prune_finalized(finalized_epoch)

    def drop_all(self) -> None:
        self._queue.drop_all()
