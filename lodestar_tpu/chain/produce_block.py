"""Block production: assemble a body from the pools + compute state root.

Reference `beacon-node/src/chain/produceBlock/produceBlockBody.ts` +
`computeNewStateRoot.ts`: op-pool selections (aggregated attestations
scored by fresh attesters, exits, slashings), randao reveal + graffiti
from the caller, eth1 vote passthrough, then one signature-free STF to
fill in the state root.
"""

from __future__ import annotations

from lodestar_tpu import tracing
from lodestar_tpu.state_transition import (
    EpochContext,
    process_block,
    process_slots,
    state_hash_tree_root,
)
from lodestar_tpu.types import ssz_types

__all__ = ["produce_block", "compute_new_state_root", "dial_to_slot", "make_attestation_data"]


def dial_to_slot(state, slot: int, p, cfg=None):
    """(state', ctx) with state' advanced to `slot` (copy-on-advance)."""
    if slot > state.slot:
        work = state.copy()
        ctx = process_slots(work, slot, p, cfg)
        return work, ctx
    return state, EpochContext(state, p)


def make_attestation_data(chain, slot: int, committee_index: int):
    """AttestationData for (slot, committee) on the current head — shared
    by the validator duty loop and the REST producer (reference
    `api/impl/validator` produceAttestationData)."""
    from lodestar_tpu.state_transition.util import get_block_root

    p = chain.p
    t = ssz_types(p)
    head_state = chain.get_state_by_block_root(chain.head_root)
    work, _ctx = dial_to_slot(head_state, slot, p, chain.cfg)
    epoch = slot // p.SLOTS_PER_EPOCH
    data = t.AttestationData.default()
    data.slot = slot
    data.index = committee_index
    data.beacon_block_root = chain.head_root
    data.source = work.current_justified_checkpoint
    tgt = t.Checkpoint.default()
    tgt.epoch = epoch
    try:
        tgt.root = get_block_root(work, epoch, p)
    except ValueError:
        tgt.root = chain.head_root
    data.target = tgt
    return data


def produce_block(
    chain,
    *,
    slot: int,
    randao_reveal: bytes,
    graffiti: bytes = b"",
    parent_root: bytes | None = None,
):
    """Unsigned BeaconBlock proposal for `slot` on the current head
    (reference `chain.produceBlock` -> produceBlockBody). Traced as its
    own root (`block_production` > state advance / op-pool packing /
    STF+htr) so a missed proposal's latency is attributable; the root
    carries the device scheduler's occupancy at production start — a
    proposal that raced a saturated verifier pool says so in its trace."""
    with tracing.root("block_production", slot=slot) as rsp:
        if rsp:
            occ = getattr(chain.bls, "occupancy", None)
            if occ is not None:
                rsp.set(sched_occupancy_permille=occ.occupancy_permille())
        return _produce_block_traced(
            chain,
            slot=slot,
            randao_reveal=randao_reveal,
            graffiti=graffiti,
            parent_root=parent_root,
        )


def _produce_block_traced(chain, *, slot, randao_reveal, graffiti, parent_root):
    p = chain.p
    t = ssz_types(p)
    head_root = parent_root if parent_root is not None else chain.head_root
    pre_state = chain.get_state_by_block_root(head_root)
    work = pre_state.copy()
    with tracing.span("produce_state_advance"):
        ctx = (
            process_slots(work, slot, p, chain.cfg)
            if slot > work.slot
            else EpochContext(work, p)
        )

    from lodestar_tpu.state_transition.block import block_types_for

    block_type, _ = block_types_for(work, p)
    block = block_type.default()
    block.slot = slot
    block.proposer_index = ctx.get_beacon_proposer(slot)
    block.parent_root = head_root

    with tracing.span("produce_op_pool_packing") as psp:
        body = block.body
        body.randao_reveal = randao_reveal
        body.graffiti = (graffiti or b"").ljust(32, b"\x00")[:32]
        eth1 = getattr(chain, "eth1", None)
        if eth1 is not None:
            body.eth1_data, deposits = eth1.get_eth1_data_and_deposits(work)
            body.deposits = deposits[: p.MAX_DEPOSITS]
        else:
            body.eth1_data = work.eth1_data

        from lodestar_tpu.state_transition.block import fork_of

        if fork_of(work) != "phase0":
            # sync aggregate over the parent root from the contribution pool;
            # with no contributions this yields empty bits + the G2 infinity
            # signature (the eth_fast_aggregate_verify empty-participation case)
            body.sync_aggregate = chain.sync_contribution_pool.get_sync_aggregate(
                slot - 1, bytes(head_root)
            )

        att_slashings, prop_slashings, exits = chain.op_pool.get_slashings_and_exits(work, p)
        body.proposer_slashings = prop_slashings
        body.attester_slashings = att_slashings
        body.voluntary_exits = exits
        body.attestations = chain.aggregated_attestation_pool.get_attestations_for_block(
            work, p, ctx=ctx
        )
        if psp:
            psp.set(attestations=len(body.attestations), exits=len(exits))

    block.state_root = compute_new_state_root(chain, work, block, ctx)
    return block


def compute_new_state_root(chain, dialed_state, block, ctx) -> bytes:
    """STF without signature verification, root only (reference
    `computeNewStateRoot.ts` — runs the transition on a throwaway clone)."""
    post = dialed_state.copy()
    with tracing.span("produce_stf"):
        process_block(post, block, ctx, verify_signatures=False, cfg=chain.cfg)
    with tracing.span("produce_hash_tree_root"):
        # transient: `post` is a throwaway clone — never cold-build
        # tracker snapshots just to discard them with it
        return state_hash_tree_root(post, transient=True)
