"""Verifier mesh: per-device launch lanes behind one verifier pool.

The device core passed the 8-device dryrun (`verify_signature_sets_sharded`,
MULTICHIP_r0*.json) but until PR 8 the production pool drove one chip.
This module is the mesh's serving shape:

* `MeshLane` — one chip: its own verify callable, its own EWMA
  `OccupancyTracker`, and its own wedge `CircuitBreaker` so a sick
  device (driver hang, OOM loop) degrades the pool to an (N-1)-chip
  mesh instead of tripping the whole pool.
* `VerifierMesh` — the lane set plus an optional data-parallel sharded
  verify callable (bulk range-sync/backfill batches run one launch
  across several idle chips). The mesh also answers the fleet-level
  questions the offload Status frame ships to clients: aggregate
  occupancy over *available* chips and the per-chip table (a wedged
  chip drops out of the advertised capacity).
* `build_device_mesh` — production construction from the models layer's
  device enumeration. `"auto"` engages only when the Pallas backend is
  live AND more than one device is visible (the same doctrine as
  `--bls-device-prep auto`): on the CPU-forced 8-device test platform
  auto stays single-lane, so a default pool behaves exactly like the
  pre-mesh code unless a test asks for the mesh explicitly.

Placement policy lives in the pool (`chain/bls/pool.py`): latency-class
work dequeues to the least-occupied free lane; bulk work shards across
idle lanes when at least two are free and the batch is large enough to
amortize the collective launch.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

from lodestar_tpu import telemetry
from lodestar_tpu.scheduler import OccupancyTracker

__all__ = [
    "MeshLane",
    "VerifierMesh",
    "PreparedSets",
    "build_device_mesh",
    "single_lane_mesh",
    "mesh_launch",
    "MESH_MODES",
    "LANE_WEDGE_THRESHOLD",
    "SHARD_MIN_SETS_PER_LANE",
    "SHARD_DISABLE_THRESHOLD",
]

#: pool-facing mesh modes. cli.py keeps a literal copy: importing this
#: module at argparse time would pull the chain.bls package __init__
#: and with it the crypto self-check asserts (~2s on --help); the
#: wiring doctrine is that node/BeaconNodeOptions validates against
#: THIS tuple post-parse, so a drifted CLI copy fails loudly there
MESH_MODES = ("auto", "on", "off")

# consecutive launch errors before ONE lane reports itself wedged —
# same rationale as the pre-mesh pool-wide DEVICE_WEDGE_THRESHOLD:
# high enough that one bad batch + its retries can't trip it, low
# enough to stop a launch storm against a hung driver
LANE_WEDGE_THRESHOLD = 8
LANE_WEDGE_RESET_S = 5.0
LANE_WEDGE_MAX_RESET_S = 60.0

#: a bulk batch shards over at most len(sets)//this lanes — a 32-set
#: batch across 8 chips would pay 8 collective dispatches to save one
#: small launch
SHARD_MIN_SETS_PER_LANE = 16

#: consecutive sharded-launch errors before the mesh stops trying the
#: collective program (single-lane launches attribute errors to the
#: exact sick chip; the sharded launch cannot, so it gets its own gate)
SHARD_DISABLE_THRESHOLD = 3


class PreparedSets:
    """Staged prep output for one launch unit (the pipelined pool's
    hand-off between its prep and verify stages).

    `inputs` is the `build_device_inputs` tuple, or None when prep
    REJECTED the batch (a structural verdict — final, never re-prepped).
    `error` carries a prep-stage exception; a launch seeing one re-preps
    through the lane's plain `verify_fn`, which re-raises through the
    exact pre-pipeline fail-closed path."""

    __slots__ = ("inputs", "error", "info")

    def __init__(self, inputs=None, error: Exception | None = None, info=None):
        self.inputs = inputs
        self.error = error
        self.info = info  # prep span record carried across threads


class MeshLane:
    """One device lane: verify callable + occupancy + wedge breaker.

    `inflight` is dispatcher state (how many packages the pool has in
    flight on this lane) and is only touched on the event loop; the
    occupancy tracker and breaker are thread-safe because the launches
    themselves run on executor threads. `verify_prepared_fn` (optional)
    verifies a `PreparedSets.inputs` staged by the pipelined pool's
    prep stage (either staged shape — see models verify_prepared);
    lanes without one always re-prep inline. `verify_single_fn`
    (optional) is the lane-pinned single-launch entry
    (models `make_lane_verify_single_fn`): `mesh_launch` prefers it for
    unstaged work while `--bls-single-launch` resolves active, so a
    whole batch is one resident program on this lane's die."""

    def __init__(
        self,
        index: int,
        verify_fn: Callable,
        *,
        label: str | None = None,
        wedge_threshold: int = LANE_WEDGE_THRESHOLD,
        wedge_reset_s: float = LANE_WEDGE_RESET_S,
        verify_prepared_fn: Callable | None = None,
        verify_single_fn: Callable | None = None,
    ) -> None:
        from lodestar_tpu.offload.resilience import CircuitBreaker

        self.index = index
        self.label = label if label is not None else f"dev{index}"
        self.verify_fn = verify_fn
        self.verify_prepared_fn = verify_prepared_fn
        self.verify_single_fn = verify_single_fn
        self.occupancy = OccupancyTracker()
        self.breaker = CircuitBreaker(
            failure_threshold=wedge_threshold,
            reset_timeout_s=wedge_reset_s,
            max_reset_timeout_s=LANE_WEDGE_MAX_RESET_S,
        )
        self.inflight = 0  # guarded by: event-loop (dispatcher-owned)
        self.wedge_trips = 0  # guarded by: advisory-only (monotonic trip count, read by tests/metrics)
        self.launches = 0  # guarded by: advisory-only (monotonic launch count)

    @property
    def wedged(self) -> bool:
        return self.breaker.is_open

    def state(self) -> dict:
        return {
            "device": self.label,
            "occupancy_permille": self.occupancy.occupancy_permille(),
            "wedged": self.wedged,
            "inflight": self.inflight,
            "wedge_trips": self.wedge_trips,
            "launches": self.launches,
        }


class VerifierMesh:
    """Lane set + optional sharded collective. Duck-types the occupancy
    interface `AdmissionController` expects (`occupancy()`), reporting
    the MEAN busy fraction over available lanes — the admission
    thresholds (0.75 / 0.95) grade fleet headroom, not "any chip busy".
    With one lane this is exactly that lane's tracker value, so the
    pre-mesh admission behavior is unchanged."""

    def __init__(self, lanes: Sequence[MeshLane], *, sharded_fn: Callable | None = None):
        if not lanes:
            raise ValueError("a verifier mesh needs at least one lane")
        self.lanes = list(lanes)
        #: sharded_fn(sets, device_indices) -> bool over >=2 lanes
        self.sharded_fn = sharded_fn
        from lodestar_tpu.offload.resilience import CircuitBreaker

        # gates the collective program only: a sharded error cannot name
        # the sick chip, so it must not wedge per-lane breakers — instead
        # repeated collective failures park the sharded path while
        # single-lane launches keep attributing errors per chip
        self.sharded_breaker = CircuitBreaker(
            failure_threshold=SHARD_DISABLE_THRESHOLD,
            reset_timeout_s=LANE_WEDGE_RESET_S,
            max_reset_timeout_s=LANE_WEDGE_MAX_RESET_S,
        )

    def __len__(self) -> int:
        return len(self.lanes)

    def available(self) -> list[MeshLane]:
        """Lanes whose wedge breaker admits work (the (N-1) degradation
        set). May be empty — the pool then fails fast like the pre-mesh
        wedged-device path."""
        return [lane for lane in self.lanes if not lane.wedged]

    def sharding_available(self) -> bool:
        return self.sharded_fn is not None and not self.sharded_breaker.is_open

    def occupancy(self) -> float:
        lanes = self.available() or self.lanes
        return sum(lane.occupancy.occupancy() for lane in lanes) / len(lanes)

    def occupancy_permille(self) -> int:
        return max(0, min(1000, int(round(self.occupancy() * 1000.0))))

    def chip_table(self) -> list[tuple[int, bool]]:
        """(occupancy_permille, wedged) per chip — the Status frame's
        mesh trailer. A wedged chip stays listed (so operators see it)
        but flagged, and clients drop it from advertised capacity."""
        return [
            (lane.occupancy.occupancy_permille(), lane.wedged) for lane in self.lanes
        ]

    def lane_states(self) -> list[dict]:
        return [lane.state() for lane in self.lanes]


def _single_launch_active() -> bool:
    """Whether `--bls-single-launch` resolves active right now. Only
    consulted when a lane carries a `verify_single_fn` (which came from
    the models layer), so mock-lane meshes never pay the import."""
    from lodestar_tpu.models.batch_verify import single_launch_active

    return single_launch_active()


def mesh_launch(
    mesh: VerifierMesh,
    sets,
    *,
    prefer: MeshLane | None = None,
    on_launch: Callable | None = None,
    on_wedge: Callable | None = None,
    prepared: "PreparedSets | None" = None,
) -> tuple[bool, MeshLane]:
    """One verify launch with per-lane wedge accounting and cross-lane
    error retry — the single-launch core shared by the pool's executor
    path and the standalone offload host's backend.

    Starts on `prefer` (default: the least-occupied available lane;
    every lane when all are wedged, failing fast through the sick chip
    so its breaker earns the half-open retrial). A backend ERROR
    records the failing lane's breaker — firing `on_wedge(lane)` on the
    closed→open transition — and retries on each remaining available
    sibling, least-occupied first; the verdict is unchanged and the
    call raises only when every candidate errored. `on_launch(lane)`
    fires per attempt (metrics). Returns (ok, lane_that_served).

    `prepared` (pipelined pool) short-circuits the prep half: a staged
    structural REJECT is the final verdict (ok=False, no re-prep); clean
    staged inputs go through the lane's `verify_prepared_fn`; a staged
    prep ERROR — or a lane without a prepared callable — re-preps
    through the plain `verify_fn`, so the fail-closed degradation chain
    is byte-for-byte the pre-pipeline one."""
    if prefer is None or (prefer.wedged and mesh.available()):
        # no preference, or the preferred lane wedged since dispatch
        # (mid-package: chunk N trips the breaker, chunk N+1 must not
        # keep feeding the hung driver): start on a healthy lane
        lanes = mesh.available() or mesh.lanes
        prefer = min(lanes, key=lambda l: l.occupancy.occupancy())
    tried: list[MeshLane] = []
    current = prefer
    while True:
        tried.append(current)
        try:
            # launch telemetry at the lane seam: wall time of the whole
            # verify launch this lane serves (staged-inputs verify, or
            # the full re-prep + verify chain), labeled with the lane so
            # a mesh slot's launches name their chips. Size class is the
            # pow-2 bucket of the set count — the verify programs' own
            # compile-cache bucketing.
            t0 = time.perf_counter() if telemetry.launch_telemetry_active() else 0.0
            dispatched = True
            with current.occupancy.launch():
                use_staged = prepared is not None and prepared.error is None
                if use_staged and prepared.inputs is None:
                    ok = False  # prep rejected the batch: verdict final
                    dispatched = False  # no backend call — not a launch
                elif use_staged and current.verify_prepared_fn is not None:
                    ok = bool(current.verify_prepared_fn(prepared.inputs))
                elif (
                    current.verify_single_fn is not None
                    and _single_launch_active()
                ):
                    # lane-pinned single-launch road (one resident
                    # program per batch); its single→split degradation
                    # lives in the model layer, so an error here means
                    # even the split schedule failed on this lane — the
                    # same breaker/cross-lane semantics as verify_fn
                    ok = bool(current.verify_single_fn(sets))
                else:
                    ok = bool(current.verify_fn(sets))
            if t0 and dispatched:
                telemetry.record_launch(
                    "bls_lane_verify",
                    telemetry.size_class_of(len(sets)),
                    time.perf_counter() - t0,
                    lane=current.label,
                )
        except Exception:
            # an error on a staged-inputs attempt may be input-bound
            # (arrays committed to the sick die, a malformed staging) —
            # sibling retries re-prep inline so the cross-lane recovery
            # is exactly the pre-pipeline one, not N copies of the same
            # poisoned inputs wedging every healthy breaker
            prepared = None
            was_open = current.breaker.is_open
            current.breaker.record_failure()
            if not was_open and current.breaker.is_open:
                current.wedge_trips += 1
                if on_wedge is not None:
                    on_wedge(current)
            current.launches += 1
            if on_launch is not None:
                on_launch(current)
            candidates = [l for l in mesh.available() if l not in tried]
            if not candidates:
                raise
            current = min(candidates, key=lambda l: l.occupancy.occupancy())
            continue
        current.breaker.record_success()
        current.launches += 1
        if on_launch is not None:
            on_launch(current)
        return ok, current


def single_lane_mesh(
    verify_fn: Callable,
    *,
    wedge_threshold: int = LANE_WEDGE_THRESHOLD,
    verify_prepared_fn: Callable | None = None,
    verify_single_fn: Callable | None = None,
) -> VerifierMesh:
    """The pre-mesh shape: one lane, no sharded collective."""
    return VerifierMesh(
        [
            MeshLane(
                0,
                verify_fn,
                wedge_threshold=wedge_threshold,
                verify_prepared_fn=verify_prepared_fn,
                verify_single_fn=verify_single_fn,
            )
        ]
    )


def build_device_mesh(
    mode: str = "auto",
    *,
    fallback_verify_fn: Callable | None = None,
    wedge_threshold: int = LANE_WEDGE_THRESHOLD,
) -> VerifierMesh:
    """Production mesh from the models layer's device enumeration.

    mode "off" (or any enumeration problem, or a single visible device)
    yields the single-lane shape around `fallback_verify_fn` (default:
    `verify_signature_sets_device`) — bit-identical to the pre-mesh
    pool. mode "auto" requires the Pallas backend live (same doctrine
    as device prep auto); mode "on" forces the mesh whenever more than
    one device is visible."""
    if mode not in MESH_MODES:
        raise ValueError(f"bls_mesh must be one of {MESH_MODES}, got {mode!r}")

    def _single() -> VerifierMesh:
        fn = fallback_verify_fn
        prepared_fn = None
        single_fn = None
        if fn is None:
            try:
                from lodestar_tpu.models.batch_verify import (
                    verify_prepared,
                    verify_sets_single_launch,
                    verify_signature_sets_device,
                )

                fn = verify_signature_sets_device
                prepared_fn = verify_prepared
                single_fn = verify_sets_single_launch
            except Exception:
                # a host without a usable jax stack (the standalone
                # offload server historically served the pure-CPU
                # oracle) must degrade, not crash at startup
                from lodestar_tpu.crypto.bls.api import verify_signature_sets

                fn = verify_signature_sets
        return single_lane_mesh(
            fn,
            wedge_threshold=wedge_threshold,
            verify_prepared_fn=prepared_fn,
            verify_single_fn=single_fn,
        )

    if mode == "off":
        return _single()
    try:
        from lodestar_tpu.models import batch_verify as bv

        if mode == "auto":
            from lodestar_tpu.ops import fp_pallas

            if not fp_pallas.use_pallas():
                return _single()
        n = bv.mesh_device_count()
        if n <= 1:
            return _single()
        lanes = [
            MeshLane(
                i,
                bv.make_lane_verify_fn(i),
                wedge_threshold=wedge_threshold,
                verify_prepared_fn=bv.make_lane_verify_prepared_fn(i),
                verify_single_fn=bv.make_lane_verify_single_fn(i),
            )
            for i in range(n)
        ]
        return VerifierMesh(lanes, sharded_fn=bv.make_mesh_sharded_fn())
    except Exception:
        # enumeration failures must not take the verifier down — serve
        # on the single-device path the pool always supported
        return _single()
