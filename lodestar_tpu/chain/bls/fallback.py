"""Verified degradation chain: offload → local device pool → CPU oracle.

The offload leg fails CLOSED — correct, but availability-hostile: with
the accelerator host down, every block import rejects until it returns.
`DegradingBlsVerifier` restores availability WITHOUT weakening the
fail-closed invariant: each layer in the chain is a full `IBlsVerifier`
that actually RE-VERIFIES the signature sets (`crypto/bls/api.py` is
the documented oracle + fallback); a layer's *error* hands the same
sets to the next layer, a layer's *False* is final (an invalid-set
verdict is an answer, not a failure — falling through on False would
let a strict layer be shopped around for a lenient one).

So across every layer: no path resolves True except a layer genuinely
verifying the sets, and the chain only raises when every layer erred —
exactly the old single-verifier fail-closed semantics, now reached far
less often. Layers that report `is_down()` (offload with every breaker
open, a wedged device pool) are skipped without an attempt, so
degradation costs no RPC timeout. Down is deliberately distinct from
busy: a saturated-but-alive layer is still attempted and still governs
`can_accept_work()`, so gossip backpressure keeps shedding instead of
silently funneling every verify onto a slower fallback layer.

Every downgrade records a `bls_fallback` trace span and a
`lodestar_resilience_fallback_*` metric. The layer that served a
verdict is reported per-CALL through a contextvar (`serving_layer()`),
so two imports interleaving at the event loop each stamp THEIR OWN
`verifier_layer` on the `bls_verify` span — `last_layer` (one shared
slot, kept for dashboards/tests that want "most recent") is explicitly
not call-accurate under concurrency.

`in_outage()` reports the all-layers-erred terminal state (telemetry /
notifier signal; any layer serving a verdict clears it). Peer-scoring
does NOT read this shared flag — the chain stamps `verifier_outage`
on the rejection exception itself, so classification is per-call and
cannot race a concurrently recovering import."""

from __future__ import annotations

from contextvars import ContextVar

from lodestar_tpu import tracing
from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.logger import get_logger

from .interface import IBlsVerifier, VerifySignatureOpts

__all__ = ["DegradingBlsVerifier"]

#: the layer that served the CURRENT task's most recent verdict. A
#: contextvar, not an attribute: concurrent imports run in separate
#: asyncio tasks (separate contexts), so each caller reads the layer
#: that served ITS verdict, never a sibling's.
_serving_layer: ContextVar[str | None] = ContextVar("bls_serving_layer", default=None)


class DegradingBlsVerifier(IBlsVerifier):
    def __init__(self, layers: list[tuple[str, IBlsVerifier]], *, metrics=None) -> None:
        """`layers`: ordered (name, verifier) pairs, preferred first.
        The degrader owns them — `close()` closes every layer."""
        if not layers:
            raise ValueError("at least one verifier layer required")
        self.layers = list(layers)
        self.last_layer: str | None = None  # guarded by: advisory-only (shared slot; per-call truth is the serving_layer() contextvar)
        self._outage = False  # guarded by: advisory-only (telemetry slot; scoring rides the per-rejection verifier_outage mark)
        self._metrics = metrics
        self._log = get_logger(name="lodestar.bls-degrade")

    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        last_err: Exception | None = None
        primary = self.layers[0][0]
        for name, layer in self.layers:
            if _layer_down(layer):
                self._note_skip(name)
                continue
            if name != primary or last_err is not None:
                self._note_fallback(name, last_err)
            try:
                verdict = await layer.verify_signature_sets(sets, opts)
            except Exception as e:  # this layer erred: degrade, re-verify
                last_err = e
                self._log.warn(
                    "bls verifier layer failed, degrading",
                    {"layer": name, "error": str(e)[:120]},
                )
                continue
            self.last_layer = name
            _serving_layer.set(name)
            self._outage = False  # some layer answers: not an outage
            if self._metrics is not None:
                self._metrics.fallback_active.set(0 if name == primary else 1)
                if name != primary:
                    # counted on SERVE, not attempt: a fallback layer that
                    # also errs must not show up as having served verdicts
                    self._metrics.fallback_verifications.labels(name).inc()
            return verdict
        # every layer erred or refused: fail closed with the last error.
        # This IS the verifier outage. The flag is advisory telemetry
        # only — scoring reads the per-rejection `verifier_outage` mark
        # the chain stamps on the exception, never this shared slot.
        self._outage = True
        if last_err is not None:
            raise last_err
        raise RuntimeError("no bls verifier layer accepts work")

    def serving_layer(self) -> str | None:
        """The layer that served THIS task's most recent verdict
        (call-accurate under concurrent imports, unlike `last_layer`)."""
        return _serving_layer.get()

    def in_outage(self) -> bool:
        """True after a verify had every layer err/refuse, until any
        layer serves again. Advisory (dashboards, notifier, tests): a
        shared slot, so concurrent imports can flip it — scoring
        decisions ride the rejection exception instead (chain.py sets
        `verifier_outage` per call)."""
        return self._outage

    def _note_skip(self, name: str) -> None:
        if self._metrics is not None:
            self._metrics.fallback_skipped.labels(name).inc()

    def _note_fallback(self, name: str, err: Exception | None) -> None:
        parent = tracing.current()
        if parent is not None:
            import time

            now = time.monotonic_ns()
            attrs = {"layer": name}
            if err is not None:
                attrs["after_error"] = str(err)[:120]
            tracing.record(parent, "bls_fallback", now, now, attrs)

    def can_accept_work(self) -> bool:
        """The first layer still in rotation governs admission: a DOWN
        primary hands the decision to its fallback, but a merely
        SATURATED primary's refusal stands — the gossip processor must
        shed (the pre-degradation backpressure contract) rather than
        drain every queue into the slowest layer."""
        for _, layer in self.layers:
            if _layer_down(layer):
                continue
            return layer.can_accept_work()
        return False

    async def close(self) -> None:
        for _, layer in self.layers:
            try:
                await layer.close()
            except Exception:
                pass


def _layer_down(layer: IBlsVerifier) -> bool:
    """A layer is out of rotation only when it SAYS it's down (offload
    client / device pool expose `is_down`); verifiers without the
    concept are always attempted — their errors degrade anyway, and
    inferring down from can_accept_work would reintroduce the
    silent-degradation-on-saturation this module exists to prevent."""
    is_down = getattr(layer, "is_down", None)
    return bool(is_down()) if callable(is_down) else False
