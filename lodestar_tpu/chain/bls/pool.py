"""Device BLS verifier pool: buffering, chunking, retry, fail-closed.

Asyncio re-design of `BlsMultiThreadWorkerPool`
(reference `beacon-node/src/chain/bls/multithread/index.ts:103`) with the
N-worker thread pool replaced by one device pipeline:

* **Buffering** (`index.ts:277-291`): batchable jobs accumulate up to
  MAX_BUFFER_WAIT_MS (100 ms) or MAX_BUFFERED_SIGS (32), then flush as one
  batch — gossip bursts amortize into single device launches.
* **Chunking** (`index.ts:34-39`): big arrays (sync submits ~8k sets) are
  split ≤ MAX_SIGNATURE_SETS_PER_JOB (128) per job; jobs queue
  independently so a long sync batch never head-of-line-blocks gossip.
* **Batch-then-retry** (`worker.ts:52-96`): batchable chunks ≥
  BATCHABLE_MIN_PER_CHUNK are RLC-batch-verified; an invalid batch is
  re-verified per-job so one bad signature can't poison its neighbors.
  `batch_retries` / `batch_sigs_success` counters keep the reference's
  metric semantics.
* **Fail-closed** (`index.ts:386-393` analogue): any backend error rejects
  the job with the error — it never resolves True. Callers treat rejection
  as invalid-block/peer-downscore, exactly like the reference.
* **Wedge detection** (`offload/resilience.CircuitBreaker`): consecutive
  backend errors open a device breaker and `can_accept_work()` goes
  False — a wedged device (driver hang, OOM loop) stops attracting work
  and a `DegradingBlsVerifier` skips the pool without paying one failed
  launch per call; after the reset delay the pool self-offers again.
* **Admission** (`index.ts:143-149`): can_accept_work() false once
  MAX_JOBS_CAN_ACCEPT_WORK (512) jobs are outstanding — backpressure
  signal for the gossip processor.
* **Scheduling** (`lodestar_tpu/scheduler`): launches dequeue through a
  priority-class queue (gossip block > gossip attestation > API >
  range sync > backfill; stride-weighted-fair + starvation aging)
  instead of FIFO, so a slot-deadline block never queues behind a
  backfill batch. Bulk-class jobs run one per package — the bound on
  how long they can head-of-line-block an arriving urgent job. Device
  launches feed an EWMA occupancy tracker (busy-ns/wall-ns) and a
  graded ACCEPT/SHED_BULK/REJECT admission view the offload server
  ships to clients. `scheduler_enabled=False` restores arrival order
  (the control arm for the saturation tests).

The verify backend is injected as a callable (default: the device model
`models.batch_verify.verify_signature_sets_device`), which keeps the seam
mockable and lets tests drive the retry paths deterministically.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Sequence

from lodestar_tpu import tracing
from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.logger import get_logger
from lodestar_tpu.scheduler import (
    BULK_CLASSES,
    AdmissionController,
    AdmissionState,
    OccupancyTracker,
    PriorityClass,
    PriorityWorkQueue,
)

from .interface import IBlsVerifier, VerifySignatureOpts

__all__ = [
    "BlsDeviceVerifierPool",
    "chunkify_maximize_chunk_size",
    "MAX_SIGNATURE_SETS_PER_JOB",
    "MAX_BUFFERED_SIGS",
    "MAX_BUFFER_WAIT_MS",
    "MAX_JOBS_CAN_ACCEPT_WORK",
    "BATCHABLE_MIN_PER_CHUNK",
]

# tuning constants — same values/rationale as the reference (index.ts:30-62)
MAX_SIGNATURE_SETS_PER_JOB = 128
MAX_BUFFERED_SIGS = 32
MAX_BUFFER_WAIT_MS = 100
MAX_JOBS_CAN_ACCEPT_WORK = 512
BATCHABLE_MIN_PER_CHUNK = 16  # worker.ts:11-17
# consecutive backend errors before the pool reports itself wedged
# (can_accept_work False) — high enough that one bad batch + its retries
# can't trip it, low enough to stop a launch storm against a hung driver
DEVICE_WEDGE_THRESHOLD = 8
# sets per launch package under the scheduler: a queued attestation
# flood must not coalesce into one giant package that head-of-line
# blocks an arriving gossip block for its whole duration
MAX_PACKAGE_SETS = 4 * MAX_SIGNATURE_SETS_PER_JOB


def chunkify_maximize_chunk_size(arr: Sequence, max_len: int) -> list[list]:
    """Split into the fewest chunks of size ≤ max_len, sizes as equal as
    possible (reference `multithread/utils.ts` chunkifyMaximizeChunkSize)."""
    if not arr:
        return []
    n_chunks = (len(arr) + max_len - 1) // max_len
    base = len(arr) // n_chunks
    extra = len(arr) % n_chunks
    out, pos = [], 0
    for i in range(n_chunks):
        size = base + (1 if i < extra else 0)
        out.append(list(arr[pos : pos + size]))
        pos += size
    return out


class _Job:
    __slots__ = ("sets", "batchable", "priority", "future", "added_ns", "trace_parent")

    def __init__(
        self,
        sets: list[SignatureSet],
        batchable: bool,
        priority: PriorityClass = PriorityClass.API,
    ):
        self.sets = sets
        self.batchable = batchable
        self.priority = priority
        self.future: asyncio.Future[bool] = asyncio.get_event_loop().create_future()
        # the submitting task's span (None when tracing is off): the
        # executor thread parents its buffer-wait/device-launch spans on
        # it explicitly, since run_in_executor drops contextvars. The
        # clock read rides the same gate — untraced jobs pay nothing
        self.trace_parent = tracing.current()
        self.added_ns = time.monotonic_ns() if self.trace_parent is not None else 0


class BlsDeviceVerifierPool(IBlsVerifier):
    def __init__(
        self,
        verify_fn: Callable[[list[SignatureSet]], bool] | None = None,
        *,
        buffer_wait_ms: float = MAX_BUFFER_WAIT_MS,
        max_buffered_sigs: int = MAX_BUFFERED_SIGS,
        scheduler_enabled: bool = True,
        aging_ms: float | None = None,
        sched_metrics=None,
    ) -> None:
        if verify_fn is None:
            from lodestar_tpu.models.batch_verify import verify_signature_sets_device

            verify_fn = verify_signature_sets_device
        self._verify_fn = verify_fn
        self._buffer_wait_ms = buffer_wait_ms
        self._max_buffered_sigs = max_buffered_sigs
        self._log = get_logger(name="lodestar.bls-pool")
        # wedge detection: consecutive launch errors open it, a success
        # (or the reset delay elapsing) re-offers the pool for work
        from lodestar_tpu.offload.resilience import CircuitBreaker

        self.device_breaker = CircuitBreaker(
            failure_threshold=DEVICE_WEDGE_THRESHOLD,
            reset_timeout_s=5.0,
            max_reset_timeout_s=60.0,
        )

        self.scheduler_enabled = scheduler_enabled
        self._sched_metrics = sched_metrics
        queue_kwargs = {"fifo": not scheduler_enabled, "metrics": sched_metrics}
        if aging_ms is not None:
            queue_kwargs["aging_ms"] = aging_ms
        self._jobs: PriorityWorkQueue = PriorityWorkQueue(**queue_kwargs)
        self.occupancy = OccupancyTracker()
        self.admission = AdmissionController(
            self.occupancy,
            depth_fn=lambda: self._outstanding,
            shed_bulk_depth=MAX_JOBS_CAN_ACCEPT_WORK // 2,
            reject_depth=MAX_JOBS_CAN_ACCEPT_WORK,
            can_accept=lambda: not self._closed,
        )
        self._outstanding = 0  # guarded by: event-loop (writers; scrape-time depth_fn readers tolerate a stale int)
        if sched_metrics is not None:
            # scrape-time evaluation: the EWMA decays on read, so an idle
            # pool reports decaying occupancy instead of freezing at the
            # last launch's value
            sched_metrics.occupancy_permille.set_function(
                lambda: self.occupancy.occupancy_permille()
            )
            sched_metrics.admission_state.set_function(lambda: int(self.admission.state()))
        self._buffered: list[_Job] = []  # guarded by: event-loop (single-threaded)
        self._buffered_sigs = 0  # guarded by: event-loop (single-threaded)
        self._buffer_timer: asyncio.TimerHandle | None = None  # guarded by: event-loop (single-threaded)
        self._closed = False  # guarded by: event-loop (one-way flag; executor readers see it at worst one package late)
        self._runner: asyncio.Task | None = None  # guarded by: event-loop (single-threaded)

        # metric counters (reference blsThreadPool.* taxonomy)
        self.metrics = {  # guarded by: runner-serialized (one package in flight at a time; scrapers read stale-by-one)
            "jobs_started": 0,
            "sig_sets_started": 0,
            "batch_retries": 0,
            "batch_sigs_success": 0,
            "errors": 0,
        }

    # -- IBlsVerifier ---------------------------------------------------------

    def is_down(self) -> bool:
        """Wedged device (breaker open) or closed — the degradation
        chain routes around the pool; mere queue saturation is NOT down
        (that's backpressure, handled by can_accept_work)."""
        return self._closed or self.device_breaker.is_open

    def can_accept_work(self) -> bool:
        return not self.is_down() and self._outstanding < MAX_JOBS_CAN_ACCEPT_WORK

    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        if self._closed:
            raise RuntimeError("verifier pool is closed")
        if not sets:
            raise ValueError("empty signature-set array")
        opts = opts or VerifySignatureOpts()

        if opts.verify_on_main_thread:
            # inline path for cheap time-critical single sets
            from lodestar_tpu.crypto.bls.api import verify_signature_sets

            return verify_signature_sets(sets)

        priority = (
            PriorityClass(opts.priority) if opts.priority is not None else PriorityClass.API
        )
        self._ensure_runner()
        jobs = [
            self._enqueue(_Job(chunk, opts.batchable, priority))
            for chunk in chunkify_maximize_chunk_size(sets, MAX_SIGNATURE_SETS_PER_JOB)
        ]
        results = await asyncio.gather(*(j.future for j in jobs))
        return all(results)

    async def close(self) -> None:
        self._closed = True
        if self._buffer_timer is not None:
            self._buffer_timer.cancel()
        err = asyncio.CancelledError("bls pool closed")
        for job in self._buffered:
            if not job.future.done():
                job.future.set_exception(err)
        self._buffered.clear()
        for job, _cls, _waited in self._jobs.drain():
            if not job.future.done():
                job.future.set_exception(err)
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None

    # -- queueing -------------------------------------------------------------

    def _ensure_runner(self) -> None:
        if self._runner is None or self._runner.done():
            self._runner = asyncio.get_event_loop().create_task(self._run_jobs())

    def _enqueue(self, job: _Job) -> _Job:
        self._outstanding += 1
        job.future.add_done_callback(lambda _f: self._dec_outstanding())
        if job.batchable:
            self._buffered.append(job)
            self._buffered_sigs += len(job.sets)
            if self._buffered_sigs > self._max_buffered_sigs:
                self._flush_buffer()
            elif self._buffer_timer is None:
                loop = asyncio.get_event_loop()
                self._buffer_timer = loop.call_later(
                    self._buffer_wait_ms / 1000.0, self._flush_buffer
                )
        else:
            self._jobs.put_nowait(job, job.priority)
        return job

    def _dec_outstanding(self) -> None:
        self._outstanding -= 1

    def _flush_buffer(self) -> None:
        if self._buffer_timer is not None:
            self._buffer_timer.cancel()
            self._buffer_timer = None
        jobs, self._buffered = self._buffered, []
        self._buffered_sigs = 0
        for job in jobs:
            self._jobs.put_nowait(job, job.priority)

    # -- execution ------------------------------------------------------------

    def _record_sched_dequeue(self, job: _Job, cls: PriorityClass, waited_ns: int) -> None:
        """`sched_queue_wait` span per traced job: enqueue -> dequeue —
        the number the saturation acceptance test bounds."""
        if job.trace_parent is not None:
            end_ns = time.monotonic_ns()
            tracing.record(
                job.trace_parent,
                "sched_queue_wait",
                end_ns - waited_ns,
                end_ns,
                {"class": cls.label, "sets": len(job.sets)},
            )

    async def _run_jobs(self) -> None:
        while not self._closed:
            job, cls, waited_ns = await self._jobs.get()
            self._record_sched_dequeue(job, cls, waited_ns)
            package = [job]
            # drain immediately-available work into the package: same
            # class only under the scheduler, capped at MAX_PACKAGE_SETS
            # (and bulk runs ONE job per package) — both bound how long an
            # arriving gossip block can wait behind the in-flight launch;
            # everything available in FIFO mode (the pre-scheduler arm)
            if not (self.scheduler_enabled and cls in BULK_CLASSES):
                drain_cls = cls if self.scheduler_enabled else None
                package_sets = len(job.sets)
                while not self.scheduler_enabled or package_sets < MAX_PACKAGE_SETS:
                    nxt = self._jobs.get_nowait(drain_cls)
                    if nxt is None:
                        break
                    self._record_sched_dequeue(*nxt)
                    package.append(nxt[0])
                    package_sets += len(nxt[0].sets)
            try:
                await asyncio.get_event_loop().run_in_executor(
                    None, self._verify_package, package
                )
            except Exception as e:  # fail closed: reject, never resolve True
                self.metrics["errors"] += len(package)
                self._log.error(f"bls verify package failed: {e!r}")
                for j in package:
                    if not j.future.done():
                        j.future.set_exception(e)

    def _verify_package(self, package: list[_Job]) -> None:
        """Runs in a thread executor (device dispatch releases the GIL)."""
        self.metrics["jobs_started"] += len(package)
        self.metrics["sig_sets_started"] += sum(len(j.sets) for j in package)

        # tracing work (incl. the clock reads) only when some job in the
        # package was submitted under an active trace — the disabled path
        # pays the flag checks hidden in trace_parent alone
        traced = any(j.trace_parent is not None for j in package)
        if traced:
            # buffer-wait spans: from job submission to the launch this
            # thread is about to perform (buffering + queue time)
            launch_ns = time.monotonic_ns()
            for j in package:
                if j.trace_parent is not None:
                    tracing.record(
                        j.trace_parent, "bls_buffer_wait", j.added_ns, launch_ns,
                        {"sets": len(j.sets)},
                    )

        batchable = [j for j in package if j.batchable]
        individual = [j for j in package if not j.batchable]

        # RLC-batch the batchable jobs in ≥16-set chunks; invalid batch →
        # retry each job individually (worker.ts:52-96)
        from lodestar_tpu.utils.tracing import trace_region

        for chunk in chunkify_maximize_chunk_size(batchable, BATCHABLE_MIN_PER_CHUNK):
            all_sets = [s for j in chunk for s in j.sets]
            t0 = time.monotonic_ns() if traced else 0
            try:
                with trace_region("bls_batch_verify"), self.occupancy.launch():
                    ok = self._verify_fn(all_sets)
                self.device_breaker.record_success()
            except Exception:
                self.device_breaker.record_failure()
                self.metrics["batch_retries"] += 1
                if traced:
                    self._trace_prep(chunk, t0)
                    self._trace_launch(chunk, t0, len(all_sets), "batch_error")
                individual.extend(chunk)
                continue
            if traced:
                self._trace_prep(chunk, t0)
                self._trace_launch(chunk, t0, len(all_sets), "batch")
            if ok:
                self.metrics["batch_sigs_success"] += len(all_sets)
                for j in chunk:
                    self._resolve(j, True)
            else:
                self.metrics["batch_retries"] += 1
                individual.extend(chunk)

        for j in individual:
            t0 = time.monotonic_ns() if traced else 0
            try:
                with self.occupancy.launch():
                    ok = self._verify_fn(j.sets)
                self.device_breaker.record_success()
                if traced:
                    self._trace_prep([j], t0)
                    self._trace_launch([j], t0, len(j.sets), "single")
                self._resolve(j, ok)
            except Exception as e:
                self.device_breaker.record_failure()
                if traced:
                    self._trace_prep([j], t0)
                    self._trace_launch([j], t0, len(j.sets), "single_error")
                if not j.future.done():
                    j.future.get_loop().call_soon_threadsafe(self._reject, j, e)

    @staticmethod
    def _trace_prep(jobs: list[_Job], launch_start_ns: int) -> None:
        """`bls_prep` span per traced job: input preparation inside the
        launch this thread just performed, with the serving layer
        (device on-chip pipeline vs host native/python) stamped as an
        attribute — mirroring how `verifier_layer` attributes the verify.
        The model layer leaves the timing in a thread-local (it runs on
        this executor thread, below any tracer context); consuming it
        here keeps untraced launches free of tracer work. Records that
        predate this launch are discarded: untraced launches (and mock
        backends layered over earlier real ones) leave stale info on the
        executor thread, and attributing an old prep's timestamps to this
        trace would corrupt its span window."""
        from lodestar_tpu.models.batch_verify import consume_prep_info

        info = consume_prep_info()
        if info is None or info["end_ns"] < launch_start_ns:
            return
        attrs = {"layer": info["layer"], "sets": info["sets"]}
        if info["rejected"]:
            attrs["rejected"] = True
        for j in jobs:
            if j.trace_parent is not None:
                tracing.record(
                    j.trace_parent, "bls_prep", info["start_ns"], info["end_ns"], attrs
                )

    @staticmethod
    def _trace_launch(jobs: list[_Job], start_ns: int, n_sets: int, mode: str) -> None:
        """Per-traced-job device-launch span; a batch covering jobs from
        several traces lands one identically-timed span in each. A
        batchable job verified in the single pass got there because its
        batch failed — that's the reference's batch-then-retry path, so
        it's labeled bls_batch_retry to keep the decomposition visible."""
        end_ns = time.monotonic_ns()
        for j in jobs:
            if j.trace_parent is not None:
                retried = j.batchable and mode.startswith("single")
                tracing.record(
                    j.trace_parent,
                    "bls_batch_retry" if retried else "bls_device_launch",
                    start_ns,
                    end_ns,
                    {"sets": n_sets, "mode": mode},
                )

    def _resolve(self, job: _Job, result: bool) -> None:
        if not job.future.done():
            job.future.get_loop().call_soon_threadsafe(self._set_result, job, result)

    @staticmethod
    def _set_result(job: _Job, result: bool) -> None:
        if not job.future.done():
            job.future.set_result(result)

    @staticmethod
    def _reject(job: _Job, err: Exception) -> None:
        if not job.future.done():
            job.future.set_exception(err)
