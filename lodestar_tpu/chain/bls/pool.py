"""Device BLS verifier pool: buffering, chunking, retry, fail-closed.

Asyncio re-design of `BlsMultiThreadWorkerPool`
(reference `beacon-node/src/chain/bls/multithread/index.ts:103`) with the
N-worker thread pool replaced by one device pipeline:

* **Buffering** (`index.ts:277-291`): batchable jobs accumulate up to
  MAX_BUFFER_WAIT_MS (100 ms) or MAX_BUFFERED_SIGS (32), then flush as one
  batch — gossip bursts amortize into single device launches.
* **Chunking** (`index.ts:34-39`): big arrays (sync submits ~8k sets) are
  split ≤ MAX_SIGNATURE_SETS_PER_JOB (128) per job; jobs queue
  independently so a long sync batch never head-of-line-blocks gossip.
* **Batch-then-retry** (`worker.ts:52-96`): batchable chunks ≥
  BATCHABLE_MIN_PER_CHUNK are RLC-batch-verified; an invalid batch is
  re-verified per-job so one bad signature can't poison its neighbors.
  `batch_retries` / `batch_sigs_success` counters keep the reference's
  metric semantics.
* **Fail-closed** (`index.ts:386-393` analogue): any backend error rejects
  the job with the error — it never resolves True. Callers treat rejection
  as invalid-block/peer-downscore, exactly like the reference.
* **Mesh lanes** (`chain/bls/mesh.py`): the pool serves a `VerifierMesh`
  of per-device launch lanes. One dispatcher waits for a free lane,
  dequeues through the shared priority queue, and places the package:
  latency-class work goes to the least-occupied free chip; bulk
  range-sync/backfill batches big enough to amortize a collective go
  data-parallel (`verify_signature_sets_sharded`) across the idle chips.
  With a single visible device the mesh is one lane and the launch
  schedule is bit-identical to the pre-mesh pool (regression-tested).
* **Wedge detection** (`offload/resilience.CircuitBreaker`): each lane
  carries its OWN wedge breaker — consecutive launch errors on a chip
  open it, the dispatcher stops placing work there, and in-flight work
  retries on a sibling lane, so one sick device degrades the pool to an
  (N-1)-chip mesh. Only when EVERY lane is wedged does the pool report
  is_down() and the degradation chain routes around it; after the reset
  delay a wedged lane self-offers again.
* **Admission** (`index.ts:143-149`): can_accept_work() false once
  MAX_JOBS_CAN_ACCEPT_WORK (512) jobs are outstanding — backpressure
  signal for the gossip processor.
* **Scheduling** (`lodestar_tpu/scheduler`): launches dequeue through a
  priority-class queue (gossip block > gossip attestation > API >
  range sync > backfill; stride-weighted-fair + starvation aging)
  instead of FIFO, so a slot-deadline block never queues behind a
  backfill batch. Bulk-class jobs run one per package — the bound on
  how long they can head-of-line-block an arriving urgent job. Device
  launches feed per-lane EWMA occupancy trackers whose mesh aggregate
  backs a graded ACCEPT/SHED_BULK/REJECT admission view the offload
  server ships to clients. `scheduler_enabled=False` restores arrival
  order (the control arm for the saturation tests).

The verify backend is injected as a callable (default: the device model
`models.batch_verify.verify_signature_sets_device`), which keeps the seam
mockable and lets tests drive the retry paths deterministically; passing
an explicit callable pins the pool to a single lane (a mock cannot be
enumerated per device). Tests inject multi-lane topologies via `mesh=`.
"""

from __future__ import annotations

import asyncio
import contextlib
import threading
import time
from typing import Awaitable, Callable, Sequence

from lodestar_tpu import slo, tracing
from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.logger import get_logger
from lodestar_tpu.scheduler import (
    BULK_CLASSES,
    AdmissionController,
    PriorityClass,
    PriorityWorkQueue,
)

from .interface import IBlsVerifier, VerifySignatureOpts
from .mesh import (
    LANE_WEDGE_THRESHOLD,
    MESH_MODES,
    SHARD_MIN_SETS_PER_LANE,
    MeshLane,
    PreparedSets,
    VerifierMesh,
    build_device_mesh,
    single_lane_mesh,
)

__all__ = [
    "BlsDeviceVerifierPool",
    "chunkify_maximize_chunk_size",
    "MAX_SIGNATURE_SETS_PER_JOB",
    "MAX_BUFFERED_SIGS",
    "MAX_BUFFER_WAIT_MS",
    "MAX_JOBS_CAN_ACCEPT_WORK",
    "BATCHABLE_MIN_PER_CHUNK",
    "PIPELINE_MODES",
]

#: prep→verify pipeline modes (--bls-pipeline): "auto" double-buffers
#: only when the mesh has a sibling lane to stage prep on (a 1-lane /
#: no-mesh pool keeps the exact pre-pipeline launch schedule), "on"
#: forces the overlap even on one chip (prep of batch k+1 interleaves
#: with the verify of batch k on the same die — the host byte work and
#: the prep launches slot into the verify program's gaps), "off" keeps
#: prep inline with the launch. Under --bls-single-launch the staged
#: prep is host byte-parse only (the whole device chain is batch k's
#: one launch), so the overlap is host parse of k+1 vs the single
#: launch of k.
PIPELINE_MODES = ("auto", "on", "off")

# tuning constants — same values/rationale as the reference (index.ts:30-62)
MAX_SIGNATURE_SETS_PER_JOB = 128
MAX_BUFFERED_SIGS = 32
MAX_BUFFER_WAIT_MS = 100
MAX_JOBS_CAN_ACCEPT_WORK = 512
BATCHABLE_MIN_PER_CHUNK = 16  # worker.ts:11-17
# consecutive backend errors before ONE LANE reports itself wedged —
# the pre-mesh pool-wide threshold carried over per chip. THE value
# lives in mesh.py (LANE_WEDGE_THRESHOLD, shared with the standalone
# offload host); this alias keeps the pre-mesh export name
DEVICE_WEDGE_THRESHOLD = LANE_WEDGE_THRESHOLD
# sets per launch package under the scheduler: a queued attestation
# flood must not coalesce into one giant package that head-of-line
# blocks an arriving gossip block for its whole duration
MAX_PACKAGE_SETS = 4 * MAX_SIGNATURE_SETS_PER_JOB


def chunkify_maximize_chunk_size(arr: Sequence, max_len: int) -> list[list]:
    """Split into the fewest chunks of size ≤ max_len, sizes as equal as
    possible (reference `multithread/utils.ts` chunkifyMaximizeChunkSize)."""
    if not arr:
        return []
    n_chunks = (len(arr) + max_len - 1) // max_len
    base = len(arr) // n_chunks
    extra = len(arr) % n_chunks
    out, pos = [], 0
    for i in range(n_chunks):
        size = base + (1 if i < extra else 0)
        out.append(list(arr[pos : pos + size]))
        pos += size
    return out


class _Job:
    __slots__ = ("sets", "batchable", "priority", "future", "added_ns", "trace_parent", "slo")

    def __init__(
        self,
        sets: list[SignatureSet],
        batchable: bool,
        priority: PriorityClass = PriorityClass.API,
        slot: int | None = None,
    ):
        self.sets = sets
        self.batchable = batchable
        self.priority = priority
        self.future: asyncio.Future[bool] = asyncio.get_event_loop().create_future()
        # the submitting task's span (None when tracing is off): the
        # executor thread parents its buffer-wait/device-launch spans on
        # it explicitly, since run_in_executor drops contextvars. The
        # clock read rides the same gate — untraced jobs pay nothing
        self.trace_parent = tracing.current()
        self.added_ns = time.monotonic_ns() if self.trace_parent is not None else 0
        # slot-deadline slack ledger (None when the SLO layer is off —
        # the unconfigured path pays one None check per lifecycle edge)
        self.slo = slo.job_begin(priority, slot)


class _OverlapTracker:
    """Wall-clock pipeline accounting: how much of the verify stages'
    busy time had a prep stage in flight — the number behind the
    `prep_verify_overlap_occupancy_pct` bench line (and the tier-1
    overlap test). Count-based interval algebra: every begin/end of
    either stage advances the three accumulators by the elapsed window,
    attributed to whichever stages were active during it."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._prep_n = 0  # guarded by: _lock
        self._verify_n = 0  # guarded by: _lock
        self._last_ns = 0  # guarded by: _lock
        self._prep_ns = 0  # guarded by: _lock
        self._verify_ns = 0  # guarded by: _lock
        self._overlap_ns = 0  # guarded by: _lock

    def _transition(self, dprep: int, dverify: int) -> None:
        with self._lock:
            now = time.monotonic_ns()
            if self._last_ns:
                dt = now - self._last_ns
                if self._prep_n:
                    self._prep_ns += dt
                if self._verify_n:
                    self._verify_ns += dt
                if self._prep_n and self._verify_n:
                    self._overlap_ns += dt
            self._last_ns = now
            self._prep_n += dprep
            self._verify_n += dverify

    @contextlib.contextmanager
    def prep(self):
        self._transition(1, 0)
        try:
            yield
        finally:
            self._transition(-1, 0)

    @contextlib.contextmanager
    def verify(self):
        self._transition(0, 1)
        try:
            yield
        finally:
            self._transition(0, -1)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "prep_ns": self._prep_ns,
                "verify_ns": self._verify_ns,
                "overlap_ns": self._overlap_ns,
            }


class _PrepUnit:
    """One staged launch unit: the jobs it covers, their flattened sets,
    and the prep outcome (PreparedSets: inputs / reject / error)."""

    __slots__ = ("jobs", "sets", "prepared")

    def __init__(self, jobs: list[_Job], sets: list, prepared: PreparedSets):
        self.jobs = jobs
        self.sets = sets
        self.prepared = prepared


class _PreppedPackage:
    """Staged launch units for one package (the package itself and its
    class ride the _Staged entry — this is just the prep output)."""

    __slots__ = ("chunks", "singles")

    def __init__(self, chunks, singles):
        self.chunks = chunks  # batchable RLC chunks, prep staged
        self.singles = singles  # non-batchable jobs, prep staged


class _Staged:
    """Staging-queue entry: the dequeued package plus the (possibly
    still-running) prep future; `prep` is None for bulk packages, which
    keep the inline-prep sharded road."""

    __slots__ = ("package", "cls", "prep")

    def __init__(self, package, cls, prep):
        self.package = package
        self.cls = cls
        self.prep = prep


class BlsDeviceVerifierPool(IBlsVerifier):
    def __init__(
        self,
        verify_fn: Callable[[list[SignatureSet]], bool] | None = None,
        *,
        buffer_wait_ms: float = MAX_BUFFER_WAIT_MS,
        max_buffered_sigs: int = MAX_BUFFERED_SIGS,
        scheduler_enabled: bool = True,
        aging_ms: float | None = None,
        sched_metrics=None,
        mesh: VerifierMesh | None = None,
        mesh_mode: str | None = None,
        pipeline: str = "auto",
        prep_fn: Callable | None = None,
        pipeline_metrics=None,
    ) -> None:
        explicit_fn = verify_fn is not None
        if verify_fn is None:
            from lodestar_tpu.models.batch_verify import verify_signature_sets_device

            verify_fn = verify_signature_sets_device
        self._verify_fn = verify_fn
        self._buffer_wait_ms = buffer_wait_ms
        self._max_buffered_sigs = max_buffered_sigs
        self._log = get_logger(name="lodestar.bls-pool")

        # mesh construction: an injected mesh wins (tests/topologies);
        # a mesh_mode builds from the device enumeration unless the
        # caller pinned an explicit verify_fn (a mock can't be
        # enumerated per device); default is the single-lane pre-mesh
        # shape around verify_fn
        if mesh is not None:
            self.mesh = mesh
        elif mesh_mode is not None and mesh_mode not in MESH_MODES:
            raise ValueError(f"bls_mesh must be one of {MESH_MODES}, got {mesh_mode!r}")
        elif mesh_mode in ("auto", "on") and not explicit_fn:
            self.mesh = build_device_mesh(
                mesh_mode, wedge_threshold=DEVICE_WEDGE_THRESHOLD
            )
        else:
            prepared_fn = None
            single_fn = None
            if not explicit_fn:
                # the default backend can verify staged inputs directly
                # and serve the single-launch road; an injected mock
                # only speaks sets, so its lane leaves both seams unset
                # and mesh_launch re-preps inline through the mock
                from lodestar_tpu.models.batch_verify import (
                    verify_prepared,
                    verify_sets_single_launch,
                )

                prepared_fn = verify_prepared
                single_fn = verify_sets_single_launch
            self.mesh = single_lane_mesh(
                verify_fn,
                wedge_threshold=DEVICE_WEDGE_THRESHOLD,
                verify_prepared_fn=prepared_fn,
                verify_single_fn=single_fn,
            )

        # prep→verify double buffering: stage prep of package k+1 while
        # the lanes verify package k. "auto" engages only with a sibling
        # lane to stage on — the 1-lane default keeps the pre-pipeline
        # launch schedule exactly (regression-tested). Staging also
        # requires lanes that can CONSUME staged inputs (or an injected
        # prep_fn): a mesh of plain verify callables would pay real prep
        # for inputs nobody uses — and a prep-stage structural reject
        # would overrule a backend that never saw the sets
        if pipeline not in PIPELINE_MODES:
            raise ValueError(
                f"bls_pipeline must be one of {PIPELINE_MODES}, got {pipeline!r}"
            )
        self.pipeline_mode = pipeline
        stageable = prep_fn is not None or all(
            lane.verify_prepared_fn is not None for lane in self.mesh.lanes
        )
        if pipeline == "on" and not stageable:
            self._log.warn(
                "bls pipeline forced on but no lane can verify staged inputs; "
                "running unpipelined"
            )
        self._pipeline_enabled = stageable and (
            pipeline == "on" or (pipeline == "auto" and len(self.mesh) > 1)
        )
        self._prep_fn = prep_fn if prep_fn is not None else self._default_prep_fn
        self._staged_q: asyncio.Queue | None = None  # guarded by: event-loop (built by _ensure_runner)
        self._stage_slot: asyncio.Semaphore | None = None  # guarded by: event-loop (built by _ensure_runner)
        self._verify_runner: asyncio.Task | None = None  # guarded by: event-loop (single-threaded)
        self._overlap = _OverlapTracker()
        self._staged_packages = 0  # guarded by: advisory-only (monotonic count, prep threads under the GIL)
        if pipeline_metrics is not None:
            # scrape-time evaluation (the occupancy-gauge pattern): the
            # previously process-trapped pipeline_stats() numbers become
            # live lodestar_bls_pipeline_* gauges — overlap occupancy,
            # staged packages, and the prep/verify busy accumulators
            pipeline_metrics.overlap_occupancy_pct.set_function(
                lambda: self.pipeline_stats()["overlap_occupancy_pct"]
            )
            pipeline_metrics.staged_packages.set_function(
                lambda: self._staged_packages
            )
            pipeline_metrics.prep_seconds.set_function(
                lambda: self._overlap.snapshot()["prep_ns"] / 1e9
            )
            pipeline_metrics.verify_seconds.set_function(
                lambda: self._overlap.snapshot()["verify_ns"] / 1e9
            )

        self.scheduler_enabled = scheduler_enabled
        self._sched_metrics = sched_metrics
        queue_kwargs = {"fifo": not scheduler_enabled, "metrics": sched_metrics}
        if aging_ms is not None:
            queue_kwargs["aging_ms"] = aging_ms
        self._jobs: PriorityWorkQueue = PriorityWorkQueue(**queue_kwargs)
        # the mesh IS the occupancy view: mean busy fraction over
        # available lanes (one lane -> exactly the pre-mesh tracker)
        self.occupancy = self.mesh
        self.admission = AdmissionController(
            self.mesh,
            depth_fn=lambda: self._outstanding,
            shed_bulk_depth=MAX_JOBS_CAN_ACCEPT_WORK // 2,
            reject_depth=MAX_JOBS_CAN_ACCEPT_WORK,
            can_accept=lambda: not self._closed,
        )
        self._outstanding = 0  # guarded by: event-loop (writers; scrape-time depth_fn readers tolerate a stale int)
        if sched_metrics is not None:
            # scrape-time evaluation: the EWMA decays on read, so an idle
            # pool reports decaying occupancy instead of freezing at the
            # last launch's value
            sched_metrics.occupancy_permille.set_function(
                lambda: self.mesh.occupancy_permille()
            )
            sched_metrics.admission_state.set_function(lambda: int(self.admission.state()))
            sched_metrics.mesh_lanes.set_function(lambda: len(self.mesh.available()))
            for lane in self.mesh.lanes:
                sched_metrics.lane_occupancy.labels(lane.label).set_function(
                    lambda lane=lane: lane.occupancy.occupancy_permille()
                )
        self._buffered: list[_Job] = []  # guarded by: event-loop (single-threaded)
        self._buffered_sigs = 0  # guarded by: event-loop (single-threaded)
        self._buffer_timer: asyncio.TimerHandle | None = None  # guarded by: event-loop (single-threaded)
        self._closed = False  # guarded by: event-loop (one-way flag; executor readers see it at worst one package late)
        self._runner: asyncio.Task | None = None  # guarded by: event-loop (single-threaded)
        self._launch_tasks: set[asyncio.Task] = set()  # guarded by: event-loop (single-threaded)
        self._lane_free = asyncio.Event()  # guarded by: event-loop (single-threaded)
        self._lane_free.set()

        # metric counters (reference blsThreadPool.* taxonomy)
        self.metrics = {  # guarded by: advisory-only (incremented from executor threads under the GIL; scrapers read stale-by-one)
            "jobs_started": 0,
            "sig_sets_started": 0,
            "batch_retries": 0,
            "batch_sigs_success": 0,
            "errors": 0,
            "sharded_launches": 0,
            "sharded_fallbacks": 0,
        }

    @property
    def device_breaker(self):
        """Back-compat alias: the first lane's wedge breaker (THE wedge
        breaker on a single-lane pool)."""
        return self.mesh.lanes[0].breaker

    # -- IBlsVerifier ---------------------------------------------------------

    def is_down(self) -> bool:
        """Every lane wedged (breaker open) or closed — the degradation
        chain routes around the pool; mere queue saturation is NOT down
        (that's backpressure, handled by can_accept_work). One wedged
        chip out of N is NOT down: the mesh serves on the rest."""
        return self._closed or not self.mesh.available()

    def can_accept_work(self) -> bool:
        return not self.is_down() and self._outstanding < MAX_JOBS_CAN_ACCEPT_WORK

    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        if self._closed:
            raise RuntimeError("verifier pool is closed")
        if not sets:
            raise ValueError("empty signature-set array")
        opts = opts or VerifySignatureOpts()

        if opts.verify_on_main_thread:
            # inline path for cheap time-critical single sets
            from lodestar_tpu.crypto.bls.api import verify_signature_sets

            return verify_signature_sets(sets)

        priority = (
            PriorityClass(opts.priority) if opts.priority is not None else PriorityClass.API
        )
        self._ensure_runner()
        jobs = [
            self._enqueue(_Job(chunk, opts.batchable, priority, opts.slot))
            for chunk in chunkify_maximize_chunk_size(sets, MAX_SIGNATURE_SETS_PER_JOB)
        ]
        results = await asyncio.gather(*(j.future for j in jobs))
        return all(results)

    async def close(self) -> None:
        self._closed = True
        if self._buffer_timer is not None:
            self._buffer_timer.cancel()
        err = asyncio.CancelledError("bls pool closed")
        for job in self._buffered:
            if not job.future.done():
                job.future.set_exception(err)
        self._buffered.clear()
        for job, _cls, _waited in self._jobs.drain():
            if not job.future.done():
                job.future.set_exception(err)
        self._lane_free.set()  # unblock a dispatcher parked on a busy mesh
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        if self._verify_runner is not None:
            self._verify_runner.cancel()
            try:
                await self._verify_runner
            except asyncio.CancelledError:
                pass
            self._verify_runner = None
        # drain the staging queue: a package parked between the prep and
        # verify stages has no other owner left to fail its futures (and
        # its still-running prep future nobody left to await — consume
        # the eventual outcome so a late prep error isn't logged as an
        # unretrieved exception at shutdown)
        if self._staged_q is not None:
            while True:
                try:
                    staged = self._staged_q.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if staged.prep is not None:
                    staged.prep.add_done_callback(
                        lambda f: f.cancelled() or f.exception()
                    )
                for job in staged.package:
                    if not job.future.done():
                        job.future.set_exception(err)
        # in-flight launches: cancel the awaiting tasks (the executor
        # threads run to completion and resolve futures thread-safe,
        # exactly like the pre-mesh abandoned run_in_executor)
        for t in list(self._launch_tasks):
            t.cancel()
        if self._launch_tasks:
            await asyncio.gather(*self._launch_tasks, return_exceptions=True)
        self._launch_tasks.clear()

    # -- queueing -------------------------------------------------------------

    def _ensure_runner(self) -> None:
        loop = asyncio.get_event_loop()
        if self._pipeline_enabled:
            # BOTH stage tasks self-heal independently: a dead dispatch
            # stage with a live staging stage would otherwise fill the
            # 1-deep queue and hang every later verify with no restart
            if self._staged_q is None:
                # depth 1 IS the double buffer: one package staged
                # (prep in flight) beyond whatever is launching
                self._staged_q = asyncio.Queue(maxsize=1)
                self._stage_slot = asyncio.Semaphore(1)
            if self._runner is None or self._runner.done():
                self._runner = loop.create_task(self._stage_jobs())
            if self._verify_runner is None or self._verify_runner.done():
                self._verify_runner = loop.create_task(self._dispatch_staged())
        elif self._runner is None or self._runner.done():
            self._runner = loop.create_task(self._run_jobs())

    def _enqueue(self, job: _Job) -> _Job:
        self._outstanding += 1
        job.future.add_done_callback(lambda f, j=job: self._on_job_done(j, f))
        if job.batchable:
            self._buffered.append(job)
            self._buffered_sigs += len(job.sets)
            if self._buffered_sigs > self._max_buffered_sigs:
                self._flush_buffer()
            elif self._buffer_timer is None:
                loop = asyncio.get_event_loop()
                self._buffer_timer = loop.call_later(
                    self._buffer_wait_ms / 1000.0, self._flush_buffer
                )
        else:
            self._jobs.put_nowait(job, job.priority)
        return job

    def _dec_outstanding(self) -> None:
        self._outstanding -= 1

    def _on_job_done(self, job: _Job, f: "asyncio.Future[bool]") -> None:
        """The job future resolves exactly once — however many batch
        retries the verdict took — so this callback is the one place a
        per-job SLO verdict can't double-count. Cancellation (shutdown)
        is not a deadline miss and records nothing."""
        self._dec_outstanding()
        if job.slo is not None and not f.cancelled():
            ok = f.exception() is None and f.result() is True
            slo.job_verdict(job.slo, ok)

    def _flush_buffer(self) -> None:
        if self._buffer_timer is not None:
            self._buffer_timer.cancel()
            self._buffer_timer = None
        jobs, self._buffered = self._buffered, []
        self._buffered_sigs = 0
        for job in jobs:
            slo.job_flushed(job.slo)
            self._jobs.put_nowait(job, job.priority)

    # -- execution ------------------------------------------------------------

    def _record_sched_dequeue(self, job: _Job, cls: PriorityClass, waited_ns: int) -> None:
        """`sched_queue_wait` span per traced job: enqueue -> dequeue —
        the number the saturation acceptance test bounds."""
        slo.job_dequeued(job.slo, waited_ns)
        if job.trace_parent is not None:
            end_ns = time.monotonic_ns()
            tracing.record(
                job.trace_parent,
                "sched_queue_wait",
                end_ns - waited_ns,
                end_ns,
                {"class": cls.label, "sets": len(job.sets)},
            )

    # -- lane placement --------------------------------------------------------

    def _free_lanes(self) -> list[MeshLane]:
        """Lanes eligible for a new package. While ANY healthy lane
        exists, only healthy free lanes count — a busy-but-healthy mesh
        makes the dispatcher WAIT rather than dispatch onto an idle
        wedged chip (which would feed a launch storm into the hung
        driver the breaker just isolated). Only when every lane is
        wedged does the dispatcher place work on a sick chip: it fails
        fast, tripping futures with the error — the pre-mesh
        wedged-pool behavior, and how a wedged breaker earns its
        half-open retrial."""
        avail = self.mesh.available()
        if avail:
            return [lane for lane in avail if lane.inflight == 0]
        return [lane for lane in self.mesh.lanes if lane.inflight == 0]

    async def _wait_free_lane(self) -> None:
        """Park the dispatcher until some lane can take a package. The
        wait happens BEFORE the dequeue, so jobs stay in the priority
        queue (and keep reordering under arriving urgent work) until
        the mesh actually has capacity — with one lane this is exactly
        the pre-mesh serialized schedule."""
        while not self._free_lanes():
            self._lane_free.clear()
            await self._lane_free.wait()

    def _pick_placement(
        self, cls: PriorityClass, package: list[_Job], free: list[MeshLane]
    ) -> tuple[str, list[MeshLane]]:
        """("sharded", lanes) for a bulk package big enough to amortize
        a collective launch over >=2 idle healthy chips; otherwise
        ("single", [least-occupied free lane]). `free` is non-empty by
        contract (the dispatcher re-waits when a lane wedges out from
        under it). Sharded lane sets are occupancy-CHOSEN but
        index-ORDERED: the sharded executable cache keys on device
        order, so a canonical ordering keeps one compile per subset
        instead of one per occupancy permutation."""
        if (
            self.scheduler_enabled
            and cls in BULK_CLASSES
            and self.mesh.sharding_available()
        ):
            healthy_free = [lane for lane in free if not lane.wedged]
            n_sets = sum(len(j.sets) for j in package)
            want = n_sets // SHARD_MIN_SETS_PER_LANE
            if len(healthy_free) >= 2 and want >= 2:
                chosen = sorted(healthy_free, key=lambda l: l.occupancy.occupancy())
                picked = chosen[: min(len(chosen), want)]
                return "sharded", sorted(picked, key=lambda l: l.index)
        lane = min(free, key=lambda l: (l.wedged, l.occupancy.occupancy()))
        return "single", [lane]

    async def _next_package(self) -> tuple[list[_Job], PriorityClass]:
        """Dequeue one job and drain immediately-available work into the
        package: same class only under the scheduler, capped at
        MAX_PACKAGE_SETS (and bulk runs ONE job per package) — both
        bound how long an arriving gossip block can wait behind the
        in-flight launch; everything available in FIFO mode (the
        pre-scheduler arm)."""
        job, cls, waited_ns = await self._jobs.get()
        self._record_sched_dequeue(job, cls, waited_ns)
        package = [job]
        if not (self.scheduler_enabled and cls in BULK_CLASSES):
            drain_cls = cls if self.scheduler_enabled else None
            package_sets = len(job.sets)
            while not self.scheduler_enabled or package_sets < MAX_PACKAGE_SETS:
                nxt = self._jobs.get_nowait(drain_cls)
                if nxt is None:
                    break
                self._record_sched_dequeue(*nxt)
                package.append(nxt[0])
                package_sets += len(nxt[0].sets)
        return package, cls

    async def _place_and_launch(self, package, cls, prepped=None) -> None:
        """Shared dispatch tail: the in-hand wait-for-capacity /
        placement / launch-task sequence, with the in-hand cancellation
        contract — from here to create_task, any await must fail the
        package's futures on cancellation (close() only drains the
        queue, it cannot see this package)."""
        try:
            while True:
                free = self._free_lanes()
                if free:
                    break
                # a free lane wedged between the capacity check and
                # placement (a cross-lane retry on an executor
                # thread can trip any breaker): healthy lanes exist
                # but are busy — their in-flight completions set
                # _lane_free, so this wait always terminates
                self._lane_free.clear()
                await self._lane_free.wait()
                if self._closed:
                    raise asyncio.CancelledError("bls pool closed")
            mode, lanes = self._pick_placement(cls, package, free)
        except asyncio.CancelledError:
            err = asyncio.CancelledError("bls pool closed")
            for j in package:
                if not j.future.done():
                    j.future.set_exception(err)
            raise
        for lane in lanes:
            lane.inflight += 1
        task = asyncio.get_event_loop().create_task(
            self._launch(package, mode, lanes, prepped=prepped)
        )
        self._launch_tasks.add(task)
        task.add_done_callback(self._launch_tasks.discard)

    async def _run_jobs(self) -> None:
        while not self._closed:
            await self._wait_free_lane()
            if self._closed:
                return
            package, cls = await self._next_package()
            await self._place_and_launch(package, cls)

    # -- prep→verify pipeline (dispatcher split into two stages) ---------------

    async def _stage_jobs(self) -> None:
        """Pipeline stage 1: reserve the staging slot, dequeue, submit
        prep to an executor thread, hand the package to the verify
        dispatcher through the 1-deep staging queue. The slot is
        acquired BEFORE the dequeue, so package k+2 is not even taken
        out of the priority queue until the dispatcher consumed k+1 —
        the lookahead beyond the in-flight launches is exactly one
        package, the same bound the pre-pipeline dispatcher's in-hand
        package had."""
        loop = asyncio.get_event_loop()
        while not self._closed:
            await self._stage_slot.acquire()
            if self._closed:
                self._stage_slot.release()
                return
            try:
                package, cls = await self._next_package()
            except BaseException:
                # nothing dequeued: release the slot so a restarted
                # stage loop (the self-heal contract) isn't deadlocked
                # on a permit this dead task took to its grave
                self._stage_slot.release()
                raise
            try:
                if self.scheduler_enabled and cls in BULK_CLASSES:
                    # bulk may shard across lanes; the collective launch
                    # preps inline exactly like the unpipelined pool
                    prep = None
                else:
                    prep = loop.run_in_executor(
                        None, self._prep_package, package
                    )
                # the slot reservation guarantees room: never blocks
                self._staged_q.put_nowait(_Staged(package, cls, prep))
            except BaseException as e:
                # ANY failure here (cancellation, an executor refusing
                # work at shutdown, ...) must fail the in-hand package's
                # futures — no one else can see it — and return the
                # staging permit before the task dies
                self._stage_slot.release()
                err = (
                    asyncio.CancelledError("bls pool closed")
                    if isinstance(e, asyncio.CancelledError)
                    else e
                )
                for j in package:
                    if not j.future.done():
                        j.future.set_exception(err)
                raise

    async def _dispatch_staged(self) -> None:
        """Pipeline stage 2: wait for lane capacity, take the staged
        package (releasing the staging slot), await its prep, place and
        launch. Placement policy, verdict semantics, and the
        fail-closed chain are the unpipelined dispatcher's — only the
        prep wall time moved off the critical path."""
        while not self._closed:
            await self._wait_free_lane()
            if self._closed:
                return
            staged = await self._staged_q.get()
            self._stage_slot.release()
            try:
                prepped = await staged.prep if staged.prep is not None else None
            except asyncio.CancelledError:
                err = asyncio.CancelledError("bls pool closed")
                for j in staged.package:
                    if not j.future.done():
                        j.future.set_exception(err)
                raise
            except Exception as e:  # prep infrastructure failure: fail closed
                for j in staged.package:
                    if not j.future.done():
                        j.future.set_exception(e)
                continue
            await self._place_and_launch(staged.package, staged.cls, prepped=prepped)

    def _default_prep_fn(self, sets: list[SignatureSet], lane_hint: int | None):
        from lodestar_tpu.models.batch_verify import prepare_inputs_for_lane

        return prepare_inputs_for_lane(sets, lane_hint)

    def _prep_lane_hint(self) -> int | None:
        """A free sibling lane to stage prep on (mesh with >1 chip);
        None interleaves prep on whatever chip is current. Advisory
        read of dispatcher-owned state from the prep thread: a stale
        pick costs placement quality, never correctness."""
        if len(self.mesh.lanes) < 2:
            return None
        free = [l for l in self.mesh.available() if l.inflight == 0]
        if not free:
            return None
        return min(free, key=lambda l: l.occupancy.occupancy()).index

    def _prep_unit(self, jobs: list[_Job], sets: list) -> _PrepUnit:
        """Stage prep for one launch unit (prep executor thread). Errors
        are CAPTURED, not raised: the launch re-preps through the plain
        verify path so a prep fault takes the exact pre-pipeline
        degradation road (device→host inside build_device_inputs;
        anything worse raises at launch time and fails closed)."""
        from lodestar_tpu.models.batch_verify import consume_prep_info

        t0_ns = time.monotonic_ns()
        inputs = None
        error: Exception | None = None
        with self._overlap.prep():
            try:
                inputs = self._prep_fn(sets, self._prep_lane_hint())
            except Exception as e:
                error = e
        info = consume_prep_info()
        if info is not None and info["end_ns"] < t0_ns:
            info = None  # stale record from an earlier launch on this thread
        return _PrepUnit(jobs, sets, PreparedSets(inputs, error, info))

    def _prep_package(self, package: list[_Job]) -> _PreppedPackage:
        """Prep every launch unit the verify stage will dispatch: the
        RLC chunks of the batchable jobs plus each non-batchable job —
        the same unit boundaries `_verify_package` launches, so the
        launch schedule is unchanged."""
        self._staged_packages += 1
        batchable = [j for j in package if j.batchable]
        individual = [j for j in package if not j.batchable]
        chunks = [
            self._prep_unit(chunk, [s for j in chunk for s in j.sets])
            for chunk in chunkify_maximize_chunk_size(batchable, BATCHABLE_MIN_PER_CHUNK)
        ]
        singles = [self._prep_unit([j], j.sets) for j in individual]
        return _PreppedPackage(chunks, singles)

    def pipeline_stats(self) -> dict:
        """Pipeline wall-clock accounting: prep/verify busy time, their
        overlap, the overlap share of verify time, and the staged
        package count (0 = pipeline never engaged). The device path per
        batch is either the split schedule (3-launch fused prep + the
        RLC verify dispatch) or, under --bls-single-launch, ONE
        resident program — in which case the prep accumulator measures
        the staged host byte-parse and the verify accumulator the
        single launch."""
        s = self._overlap.snapshot()
        v = s["verify_ns"]
        s["overlap_occupancy_pct"] = (100.0 * s["overlap_ns"] / v) if v else 0.0
        s["staged_packages"] = self._staged_packages
        s["pipeline_enabled"] = self._pipeline_enabled
        return s

    def _release_lanes_early(self, to_release: list[MeshLane], held: list[MeshLane]) -> None:
        """Loop-side early release: the sharded fallback returns unused
        lanes to the dispatcher before its (possibly long) single-lane
        retry finishes. `held` is the launch's live accounting — the
        finally below decrements exactly what is still held."""
        for lane in to_release:
            if lane in held:
                held.remove(lane)
                lane.inflight -= 1
        self._lane_free.set()

    def _with_verify_window(self, fn, *args) -> None:
        """Executor-thread entry: every verify path runs inside the
        overlap tracker's verify window (the denominator of the
        pipeline's overlap-occupancy number)."""
        with self._overlap.verify():
            fn(*args)

    async def _launch(
        self,
        package: list[_Job],
        mode: str,
        lanes: list[MeshLane],
        prepped: _PreppedPackage | None = None,
    ) -> None:
        held = list(lanes)  # guarded by: event-loop (early releases and the finally both run on the loop)
        try:
            if mode == "sharded":
                await asyncio.get_event_loop().run_in_executor(
                    None, self._with_verify_window,
                    self._verify_package_sharded, package, lanes, held,
                )
            else:
                await asyncio.get_event_loop().run_in_executor(
                    None, self._with_verify_window,
                    self._verify_package, package, lanes[0], False, prepped,
                )
        except asyncio.CancelledError:
            # close() cancels launch tasks; if the executor work item
            # had not STARTED yet it never runs and nobody else will
            # resolve these futures — fail them closed (done futures,
            # resolved by an already-running executor thread, no-op)
            err = asyncio.CancelledError("bls pool closed")
            for j in package:
                if not j.future.done():
                    j.future.set_exception(err)
            raise
        except Exception as e:  # fail closed: reject, never resolve True
            self.metrics["errors"] += len(package)
            self._log.error(f"bls verify package failed: {e!r}")
            for j in package:
                if not j.future.done():
                    j.future.set_exception(e)
        finally:
            for lane in held:
                lane.inflight -= 1
            # clear so a LATE _release_lanes_early (scheduled by an
            # executor thread that outlives a cancelled launch task)
            # finds nothing left to double-decrement
            held.clear()
            self._lane_free.set()

    # -- device launches (executor threads) ------------------------------------

    def _on_lane_wedge(self, lane: MeshLane) -> None:
        """closed->open transition on one chip's wedge breaker."""
        self._log.warn(
            "device lane wedged, degrading to remaining chips",
            {"device": lane.label, "lanes_left": len(self.mesh.available())},
        )
        m = self._sched_metrics
        if m is not None:
            m.lane_wedge_trips.labels(lane.label).inc()

    def _count_lane_launch(self, lane: MeshLane, mode: str) -> None:
        m = self._sched_metrics
        if m is not None:
            m.lane_launches.labels(lane.label, mode).inc()

    def _launch_sets(
        self,
        lane: MeshLane,
        sets: list[SignatureSet],
        prepared: PreparedSets | None = None,
    ):
        """One verify launch, preferring `lane` (mesh_launch: breaker
        accounting + cross-lane error retry — a sick chip degrades its
        work onto the rest of the mesh with the verdict unchanged;
        raises only when every candidate lane errored, which with one
        lane is exactly the pre-mesh fail-closed behavior). `prepared`
        carries staged pipeline inputs (see mesh_launch). Returns
        (ok, lane_that_served)."""
        from .mesh import mesh_launch

        return mesh_launch(
            self.mesh,
            sets,
            prefer=lane,
            prepared=prepared,
            on_launch=lambda l: self._count_lane_launch(l, "single"),
            on_wedge=self._on_lane_wedge,
        )

    def _verify_package(
        self,
        package: list[_Job],
        lane: MeshLane,
        counted: bool = False,
        prepped: _PreppedPackage | None = None,
    ) -> None:
        """Runs in a thread executor (device dispatch releases the GIL).

        `prepped` carries the pipeline's staged launch units — the SAME
        unit boundaries as the inline path, so the launch schedule is
        identical; only where prep ran differs. The batch-then-retry
        road always re-preps INLINE (fresh blinding, fresh prep — one
        bad signature can't poison its neighbors, and a stale staged
        prep can't poison the retry)."""
        if not counted:
            self.metrics["jobs_started"] += len(package)
            self.metrics["sig_sets_started"] += sum(len(j.sets) for j in package)
            # SLO launch stamp once per job: the sharded fallback road
            # (counted=True) already stamped at its collective launch
            for j in package:
                slo.job_launch(j.slo)

        # tracing work (incl. the clock reads) only when some job in the
        # package was submitted under an active trace — the disabled path
        # pays the flag checks hidden in trace_parent alone
        traced = any(j.trace_parent is not None for j in package)
        if traced:
            # buffer-wait spans: from job submission to the launch this
            # thread is about to perform (buffering + queue time)
            launch_ns = time.monotonic_ns()
            for j in package:
                if j.trace_parent is not None:
                    tracing.record(
                        j.trace_parent, "bls_buffer_wait", j.added_ns, launch_ns,
                        {"sets": len(j.sets)},
                    )

        batchable = [j for j in package if j.batchable]
        individual = [j for j in package if not j.batchable]
        if prepped is None:
            chunk_units = [
                (chunk, [s for j in chunk for s in j.sets], None)
                for chunk in chunkify_maximize_chunk_size(
                    batchable, BATCHABLE_MIN_PER_CHUNK
                )
            ]
            single_units = [([j], j.sets, None) for j in individual]
        else:
            chunk_units = [(u.jobs, u.sets, u.prepared) for u in prepped.chunks]
            single_units = [(u.jobs, u.sets, u.prepared) for u in prepped.singles]

        # RLC-batch the batchable jobs in ≥16-set chunks; invalid batch →
        # retry each job individually (worker.ts:52-96)
        from lodestar_tpu.utils.tracing import trace_region

        retries: list[_Job] = []
        for jobs, all_sets, staged in chunk_units:
            t0 = time.monotonic_ns() if traced else 0
            try:
                with trace_region("bls_batch_verify"):
                    ok, served = self._launch_sets(lane, all_sets, prepared=staged)
            except Exception:
                self.metrics["batch_retries"] += 1
                if traced:
                    self._trace_unit_prep(jobs, staged, t0)
                    self._trace_launch(
                        jobs, t0, len(all_sets), "batch_error", lane.label,
                        lane=str(lane.index),
                    )
                retries.extend(jobs)
                continue
            if traced:
                self._trace_unit_prep(jobs, staged, t0)
                self._trace_launch(
                    jobs, t0, len(all_sets), "batch", served.label,
                    lane=str(served.index),
                )
            if ok:
                self.metrics["batch_sigs_success"] += len(all_sets)
                for j in jobs:
                    self._resolve(j, True)
            else:
                self.metrics["batch_retries"] += 1
                retries.extend(jobs)

        for jobs, sets_, staged in single_units + [([j], j.sets, None) for j in retries]:
            j = jobs[0]
            t0 = time.monotonic_ns() if traced else 0
            try:
                ok, served = self._launch_sets(lane, sets_, prepared=staged)
                if traced:
                    self._trace_unit_prep([j], staged, t0)
                    self._trace_launch(
                        [j], t0, len(sets_), "single", served.label,
                        lane=str(served.index),
                    )
                self._resolve(j, ok)
            except Exception as e:
                if traced:
                    self._trace_unit_prep([j], staged, t0)
                    self._trace_launch(
                        [j], t0, len(sets_), "single_error", lane.label,
                        lane=str(lane.index),
                    )
                if not j.future.done():
                    j.future.get_loop().call_soon_threadsafe(self._reject, j, e)

    def _trace_unit_prep(self, jobs: list[_Job], staged, t0: int) -> None:
        """`bls_prep` span for one launch unit: from the thread-local
        record for inline prep, or from the record the prep STAGE
        carried across threads on its PreparedSets."""
        if staged is None:
            self._trace_prep(jobs, t0)
        else:
            self._trace_prep_info(jobs, staged.info)

    @staticmethod
    def _trace_prep_info(jobs: list[_Job], info) -> None:
        """`bls_prep` span from a record the prep STAGE carried across
        threads (the pipelined twin of `_trace_prep`, which reads the
        launch thread's TLS): staged prep ran on the prep executor, so
        the record rides the _PrepUnit instead."""
        if info is None:
            return
        attrs = {"layer": info["layer"], "sets": info["sets"], "staged": True}
        if info["rejected"]:
            attrs["rejected"] = True
        for j in jobs:
            if j.trace_parent is not None:
                tracing.record(
                    j.trace_parent, "bls_prep", info["start_ns"], info["end_ns"], attrs
                )

    def _verify_package_sharded(
        self, package: list[_Job], lanes: list[MeshLane], held: list[MeshLane] | None = None
    ) -> None:
        """One data-parallel launch over idle lanes (executor thread).
        A collective ERROR cannot name the sick chip, so it feeds the
        mesh's sharded breaker (parking the collective path) and the
        package degrades to the attributable single-lane path; an
        invalid VERDICT takes the same retry road the RLC batch does —
        re-verified per job so one bad signature can't poison its
        package (and so a lying collective can't be weaker than the
        single-device policy)."""
        self.metrics["jobs_started"] += len(package)
        self.metrics["sig_sets_started"] += sum(len(j.sets) for j in package)
        for j in package:
            slo.job_launch(j.slo)
        all_sets = [s for j in package for s in j.sets]
        traced = any(j.trace_parent is not None for j in package)
        if traced:
            launch_ns = time.monotonic_ns()
            for j in package:
                if j.trace_parent is not None:
                    tracing.record(
                        j.trace_parent, "bls_buffer_wait", j.added_ns, launch_ns,
                        {"sets": len(j.sets)},
                    )
        t0 = time.monotonic_ns() if traced else 0
        import contextlib

        try:
            with contextlib.ExitStack() as stack:
                for lane in lanes:
                    stack.enter_context(lane.occupancy.launch())
                ok = bool(
                    self.mesh.sharded_fn(all_sets, [lane.index for lane in lanes])
                )
            self.mesh.sharded_breaker.record_success()
            self.metrics["sharded_launches"] += 1
            for lane in lanes:
                lane.launches += 1
                self._count_lane_launch(lane, "sharded")
        except Exception:
            self.mesh.sharded_breaker.record_failure()
            self.metrics["sharded_fallbacks"] += 1
            self.metrics["batch_retries"] += 1
            if traced:
                self._trace_launch(
                    package, t0, len(all_sets), "sharded_error",
                    ",".join(lane.label for lane in lanes),
                    lane=",".join(str(lane.index) for lane in lanes),
                )
            fallback = min(lanes, key=lambda l: l.occupancy.occupancy())
            self._release_unused(lanes, fallback, held, package)
            self._verify_package(package, fallback, counted=True)
            return
        if traced:
            self._trace_launch(
                package, t0, len(all_sets), "sharded",
                ",".join(lane.label for lane in lanes),
                lane=",".join(str(lane.index) for lane in lanes),
            )
        if ok:
            self.metrics["batch_sigs_success"] += len(all_sets)
            for j in package:
                self._resolve(j, True)
        else:
            self.metrics["batch_retries"] += 1
            fallback = min(lanes, key=lambda l: l.occupancy.occupancy())
            self._release_unused(lanes, fallback, held, package)
            self._verify_package(package, fallback, counted=True)

    def _release_unused(
        self,
        lanes: list[MeshLane],
        fallback: MeshLane,
        held: "list[MeshLane] | None",
        package: list[_Job],
    ) -> None:
        """Executor-side entry to the loop-side early release: the
        sharded fallback keeps ONE lane for its (possibly long)
        single-lane retry — the other chips go back to the dispatcher
        now instead of idling behind this package's finally."""
        if held is None:
            return
        unused = [lane for lane in lanes if lane is not fallback]
        if unused:
            package[0].future.get_loop().call_soon_threadsafe(
                self._release_lanes_early, unused, held
            )

    @staticmethod
    def _trace_prep(jobs: list[_Job], launch_start_ns: int) -> None:
        """`bls_prep` span per traced job: input preparation inside the
        launch this thread just performed, with the serving layer
        (device on-chip pipeline vs host native/python) stamped as an
        attribute — mirroring how `verifier_layer` attributes the verify.
        The model layer leaves the timing in a thread-local (it runs on
        this executor thread, below any tracer context); consuming it
        here keeps untraced launches free of tracer work. Records that
        predate this launch are discarded: untraced launches (and mock
        backends layered over earlier real ones) leave stale info on the
        executor thread, and attributing an old prep's timestamps to this
        trace would corrupt its span window."""
        from lodestar_tpu.models.batch_verify import consume_prep_info

        info = consume_prep_info()
        if info is None or info["end_ns"] < launch_start_ns:
            return
        attrs = {"layer": info["layer"], "sets": info["sets"]}
        if info["rejected"]:
            attrs["rejected"] = True
        for j in jobs:
            if j.trace_parent is not None:
                tracing.record(
                    j.trace_parent, "bls_prep", info["start_ns"], info["end_ns"], attrs
                )

    @staticmethod
    def _trace_launch(
        jobs: list[_Job],
        start_ns: int,
        n_sets: int,
        mode: str,
        device: str = "dev0",
        lane: str | None = None,
    ) -> None:
        """Per-traced-job device-launch span; a batch covering jobs from
        several traces lands one identically-timed span in each. A
        batchable job verified in the single pass got there because its
        batch failed — that's the reference's batch-then-retry path, so
        it's labeled bls_batch_retry to keep the decomposition visible.
        The serving lane rides along as the `device` attribute (plus the
        `lane` index when known), and is ALSO stamped onto the job's
        trace parent — for chain imports that is the `bls_verify` span,
        so a Chrome-trace export of a mesh slot names its chips at the
        top level (a job served across several launches keeps the last
        serving lane, the one that produced its verdict)."""
        end_ns = time.monotonic_ns()
        attrs = {"sets": n_sets, "mode": mode, "device": device}
        if lane is not None:
            attrs["lane"] = lane
        for j in jobs:
            if j.trace_parent is not None:
                retried = j.batchable and mode.startswith("single")
                tracing.record(
                    j.trace_parent,
                    "bls_batch_retry" if retried else "bls_device_launch",
                    start_ns,
                    end_ns,
                    attrs,
                )
                j.trace_parent.set(device=device, **({"lane": lane} if lane is not None else {}))

    def _resolve(self, job: _Job, result: bool) -> None:
        if not job.future.done():
            job.future.get_loop().call_soon_threadsafe(self._set_result, job, result)

    @staticmethod
    def _set_result(job: _Job, result: bool) -> None:
        if not job.future.done():
            job.future.set_result(result)

    @staticmethod
    def _reject(job: _Job, err: Exception) -> None:
        if not job.future.done():
            job.future.set_exception(err)
