"""Device BLS verifier pool: buffering, chunking, retry, fail-closed.

Asyncio re-design of `BlsMultiThreadWorkerPool`
(reference `beacon-node/src/chain/bls/multithread/index.ts:103`) with the
N-worker thread pool replaced by one device pipeline:

* **Buffering** (`index.ts:277-291`): batchable jobs accumulate up to
  MAX_BUFFER_WAIT_MS (100 ms) or MAX_BUFFERED_SIGS (32), then flush as one
  batch — gossip bursts amortize into single device launches.
* **Chunking** (`index.ts:34-39`): big arrays (sync submits ~8k sets) are
  split ≤ MAX_SIGNATURE_SETS_PER_JOB (128) per job; jobs queue
  independently so a long sync batch never head-of-line-blocks gossip.
* **Batch-then-retry** (`worker.ts:52-96`): batchable chunks ≥
  BATCHABLE_MIN_PER_CHUNK are RLC-batch-verified; an invalid batch is
  re-verified per-job so one bad signature can't poison its neighbors.
  `batch_retries` / `batch_sigs_success` counters keep the reference's
  metric semantics.
* **Fail-closed** (`index.ts:386-393` analogue): any backend error rejects
  the job with the error — it never resolves True. Callers treat rejection
  as invalid-block/peer-downscore, exactly like the reference.
* **Mesh lanes** (`chain/bls/mesh.py`): the pool serves a `VerifierMesh`
  of per-device launch lanes. One dispatcher waits for a free lane,
  dequeues through the shared priority queue, and places the package:
  latency-class work goes to the least-occupied free chip; bulk
  range-sync/backfill batches big enough to amortize a collective go
  data-parallel (`verify_signature_sets_sharded`) across the idle chips.
  With a single visible device the mesh is one lane and the launch
  schedule is bit-identical to the pre-mesh pool (regression-tested).
* **Wedge detection** (`offload/resilience.CircuitBreaker`): each lane
  carries its OWN wedge breaker — consecutive launch errors on a chip
  open it, the dispatcher stops placing work there, and in-flight work
  retries on a sibling lane, so one sick device degrades the pool to an
  (N-1)-chip mesh. Only when EVERY lane is wedged does the pool report
  is_down() and the degradation chain routes around it; after the reset
  delay a wedged lane self-offers again.
* **Admission** (`index.ts:143-149`): can_accept_work() false once
  MAX_JOBS_CAN_ACCEPT_WORK (512) jobs are outstanding — backpressure
  signal for the gossip processor.
* **Scheduling** (`lodestar_tpu/scheduler`): launches dequeue through a
  priority-class queue (gossip block > gossip attestation > API >
  range sync > backfill; stride-weighted-fair + starvation aging)
  instead of FIFO, so a slot-deadline block never queues behind a
  backfill batch. Bulk-class jobs run one per package — the bound on
  how long they can head-of-line-block an arriving urgent job. Device
  launches feed per-lane EWMA occupancy trackers whose mesh aggregate
  backs a graded ACCEPT/SHED_BULK/REJECT admission view the offload
  server ships to clients. `scheduler_enabled=False` restores arrival
  order (the control arm for the saturation tests).

The verify backend is injected as a callable (default: the device model
`models.batch_verify.verify_signature_sets_device`), which keeps the seam
mockable and lets tests drive the retry paths deterministically; passing
an explicit callable pins the pool to a single lane (a mock cannot be
enumerated per device). Tests inject multi-lane topologies via `mesh=`.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Sequence

from lodestar_tpu import tracing
from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.logger import get_logger
from lodestar_tpu.scheduler import (
    BULK_CLASSES,
    AdmissionController,
    PriorityClass,
    PriorityWorkQueue,
)

from .interface import IBlsVerifier, VerifySignatureOpts
from .mesh import (
    LANE_WEDGE_THRESHOLD,
    MESH_MODES,
    SHARD_MIN_SETS_PER_LANE,
    MeshLane,
    VerifierMesh,
    build_device_mesh,
    single_lane_mesh,
)

__all__ = [
    "BlsDeviceVerifierPool",
    "chunkify_maximize_chunk_size",
    "MAX_SIGNATURE_SETS_PER_JOB",
    "MAX_BUFFERED_SIGS",
    "MAX_BUFFER_WAIT_MS",
    "MAX_JOBS_CAN_ACCEPT_WORK",
    "BATCHABLE_MIN_PER_CHUNK",
]

# tuning constants — same values/rationale as the reference (index.ts:30-62)
MAX_SIGNATURE_SETS_PER_JOB = 128
MAX_BUFFERED_SIGS = 32
MAX_BUFFER_WAIT_MS = 100
MAX_JOBS_CAN_ACCEPT_WORK = 512
BATCHABLE_MIN_PER_CHUNK = 16  # worker.ts:11-17
# consecutive backend errors before ONE LANE reports itself wedged —
# the pre-mesh pool-wide threshold carried over per chip. THE value
# lives in mesh.py (LANE_WEDGE_THRESHOLD, shared with the standalone
# offload host); this alias keeps the pre-mesh export name
DEVICE_WEDGE_THRESHOLD = LANE_WEDGE_THRESHOLD
# sets per launch package under the scheduler: a queued attestation
# flood must not coalesce into one giant package that head-of-line
# blocks an arriving gossip block for its whole duration
MAX_PACKAGE_SETS = 4 * MAX_SIGNATURE_SETS_PER_JOB


def chunkify_maximize_chunk_size(arr: Sequence, max_len: int) -> list[list]:
    """Split into the fewest chunks of size ≤ max_len, sizes as equal as
    possible (reference `multithread/utils.ts` chunkifyMaximizeChunkSize)."""
    if not arr:
        return []
    n_chunks = (len(arr) + max_len - 1) // max_len
    base = len(arr) // n_chunks
    extra = len(arr) % n_chunks
    out, pos = [], 0
    for i in range(n_chunks):
        size = base + (1 if i < extra else 0)
        out.append(list(arr[pos : pos + size]))
        pos += size
    return out


class _Job:
    __slots__ = ("sets", "batchable", "priority", "future", "added_ns", "trace_parent")

    def __init__(
        self,
        sets: list[SignatureSet],
        batchable: bool,
        priority: PriorityClass = PriorityClass.API,
    ):
        self.sets = sets
        self.batchable = batchable
        self.priority = priority
        self.future: asyncio.Future[bool] = asyncio.get_event_loop().create_future()
        # the submitting task's span (None when tracing is off): the
        # executor thread parents its buffer-wait/device-launch spans on
        # it explicitly, since run_in_executor drops contextvars. The
        # clock read rides the same gate — untraced jobs pay nothing
        self.trace_parent = tracing.current()
        self.added_ns = time.monotonic_ns() if self.trace_parent is not None else 0


class BlsDeviceVerifierPool(IBlsVerifier):
    def __init__(
        self,
        verify_fn: Callable[[list[SignatureSet]], bool] | None = None,
        *,
        buffer_wait_ms: float = MAX_BUFFER_WAIT_MS,
        max_buffered_sigs: int = MAX_BUFFERED_SIGS,
        scheduler_enabled: bool = True,
        aging_ms: float | None = None,
        sched_metrics=None,
        mesh: VerifierMesh | None = None,
        mesh_mode: str | None = None,
    ) -> None:
        explicit_fn = verify_fn is not None
        if verify_fn is None:
            from lodestar_tpu.models.batch_verify import verify_signature_sets_device

            verify_fn = verify_signature_sets_device
        self._verify_fn = verify_fn
        self._buffer_wait_ms = buffer_wait_ms
        self._max_buffered_sigs = max_buffered_sigs
        self._log = get_logger(name="lodestar.bls-pool")

        # mesh construction: an injected mesh wins (tests/topologies);
        # a mesh_mode builds from the device enumeration unless the
        # caller pinned an explicit verify_fn (a mock can't be
        # enumerated per device); default is the single-lane pre-mesh
        # shape around verify_fn
        if mesh is not None:
            self.mesh = mesh
        elif mesh_mode is not None and mesh_mode not in MESH_MODES:
            raise ValueError(f"bls_mesh must be one of {MESH_MODES}, got {mesh_mode!r}")
        elif mesh_mode in ("auto", "on") and not explicit_fn:
            self.mesh = build_device_mesh(
                mesh_mode, wedge_threshold=DEVICE_WEDGE_THRESHOLD
            )
        else:
            self.mesh = single_lane_mesh(
                verify_fn, wedge_threshold=DEVICE_WEDGE_THRESHOLD
            )

        self.scheduler_enabled = scheduler_enabled
        self._sched_metrics = sched_metrics
        queue_kwargs = {"fifo": not scheduler_enabled, "metrics": sched_metrics}
        if aging_ms is not None:
            queue_kwargs["aging_ms"] = aging_ms
        self._jobs: PriorityWorkQueue = PriorityWorkQueue(**queue_kwargs)
        # the mesh IS the occupancy view: mean busy fraction over
        # available lanes (one lane -> exactly the pre-mesh tracker)
        self.occupancy = self.mesh
        self.admission = AdmissionController(
            self.mesh,
            depth_fn=lambda: self._outstanding,
            shed_bulk_depth=MAX_JOBS_CAN_ACCEPT_WORK // 2,
            reject_depth=MAX_JOBS_CAN_ACCEPT_WORK,
            can_accept=lambda: not self._closed,
        )
        self._outstanding = 0  # guarded by: event-loop (writers; scrape-time depth_fn readers tolerate a stale int)
        if sched_metrics is not None:
            # scrape-time evaluation: the EWMA decays on read, so an idle
            # pool reports decaying occupancy instead of freezing at the
            # last launch's value
            sched_metrics.occupancy_permille.set_function(
                lambda: self.mesh.occupancy_permille()
            )
            sched_metrics.admission_state.set_function(lambda: int(self.admission.state()))
            sched_metrics.mesh_lanes.set_function(lambda: len(self.mesh.available()))
            for lane in self.mesh.lanes:
                sched_metrics.lane_occupancy.labels(lane.label).set_function(
                    lambda lane=lane: lane.occupancy.occupancy_permille()
                )
        self._buffered: list[_Job] = []  # guarded by: event-loop (single-threaded)
        self._buffered_sigs = 0  # guarded by: event-loop (single-threaded)
        self._buffer_timer: asyncio.TimerHandle | None = None  # guarded by: event-loop (single-threaded)
        self._closed = False  # guarded by: event-loop (one-way flag; executor readers see it at worst one package late)
        self._runner: asyncio.Task | None = None  # guarded by: event-loop (single-threaded)
        self._launch_tasks: set[asyncio.Task] = set()  # guarded by: event-loop (single-threaded)
        self._lane_free = asyncio.Event()  # guarded by: event-loop (single-threaded)
        self._lane_free.set()

        # metric counters (reference blsThreadPool.* taxonomy)
        self.metrics = {  # guarded by: advisory-only (incremented from executor threads under the GIL; scrapers read stale-by-one)
            "jobs_started": 0,
            "sig_sets_started": 0,
            "batch_retries": 0,
            "batch_sigs_success": 0,
            "errors": 0,
            "sharded_launches": 0,
            "sharded_fallbacks": 0,
        }

    @property
    def device_breaker(self):
        """Back-compat alias: the first lane's wedge breaker (THE wedge
        breaker on a single-lane pool)."""
        return self.mesh.lanes[0].breaker

    # -- IBlsVerifier ---------------------------------------------------------

    def is_down(self) -> bool:
        """Every lane wedged (breaker open) or closed — the degradation
        chain routes around the pool; mere queue saturation is NOT down
        (that's backpressure, handled by can_accept_work). One wedged
        chip out of N is NOT down: the mesh serves on the rest."""
        return self._closed or not self.mesh.available()

    def can_accept_work(self) -> bool:
        return not self.is_down() and self._outstanding < MAX_JOBS_CAN_ACCEPT_WORK

    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        if self._closed:
            raise RuntimeError("verifier pool is closed")
        if not sets:
            raise ValueError("empty signature-set array")
        opts = opts or VerifySignatureOpts()

        if opts.verify_on_main_thread:
            # inline path for cheap time-critical single sets
            from lodestar_tpu.crypto.bls.api import verify_signature_sets

            return verify_signature_sets(sets)

        priority = (
            PriorityClass(opts.priority) if opts.priority is not None else PriorityClass.API
        )
        self._ensure_runner()
        jobs = [
            self._enqueue(_Job(chunk, opts.batchable, priority))
            for chunk in chunkify_maximize_chunk_size(sets, MAX_SIGNATURE_SETS_PER_JOB)
        ]
        results = await asyncio.gather(*(j.future for j in jobs))
        return all(results)

    async def close(self) -> None:
        self._closed = True
        if self._buffer_timer is not None:
            self._buffer_timer.cancel()
        err = asyncio.CancelledError("bls pool closed")
        for job in self._buffered:
            if not job.future.done():
                job.future.set_exception(err)
        self._buffered.clear()
        for job, _cls, _waited in self._jobs.drain():
            if not job.future.done():
                job.future.set_exception(err)
        self._lane_free.set()  # unblock a dispatcher parked on a busy mesh
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        # in-flight launches: cancel the awaiting tasks (the executor
        # threads run to completion and resolve futures thread-safe,
        # exactly like the pre-mesh abandoned run_in_executor)
        for t in list(self._launch_tasks):
            t.cancel()
        if self._launch_tasks:
            await asyncio.gather(*self._launch_tasks, return_exceptions=True)
        self._launch_tasks.clear()

    # -- queueing -------------------------------------------------------------

    def _ensure_runner(self) -> None:
        if self._runner is None or self._runner.done():
            self._runner = asyncio.get_event_loop().create_task(self._run_jobs())

    def _enqueue(self, job: _Job) -> _Job:
        self._outstanding += 1
        job.future.add_done_callback(lambda _f: self._dec_outstanding())
        if job.batchable:
            self._buffered.append(job)
            self._buffered_sigs += len(job.sets)
            if self._buffered_sigs > self._max_buffered_sigs:
                self._flush_buffer()
            elif self._buffer_timer is None:
                loop = asyncio.get_event_loop()
                self._buffer_timer = loop.call_later(
                    self._buffer_wait_ms / 1000.0, self._flush_buffer
                )
        else:
            self._jobs.put_nowait(job, job.priority)
        return job

    def _dec_outstanding(self) -> None:
        self._outstanding -= 1

    def _flush_buffer(self) -> None:
        if self._buffer_timer is not None:
            self._buffer_timer.cancel()
            self._buffer_timer = None
        jobs, self._buffered = self._buffered, []
        self._buffered_sigs = 0
        for job in jobs:
            self._jobs.put_nowait(job, job.priority)

    # -- execution ------------------------------------------------------------

    def _record_sched_dequeue(self, job: _Job, cls: PriorityClass, waited_ns: int) -> None:
        """`sched_queue_wait` span per traced job: enqueue -> dequeue —
        the number the saturation acceptance test bounds."""
        if job.trace_parent is not None:
            end_ns = time.monotonic_ns()
            tracing.record(
                job.trace_parent,
                "sched_queue_wait",
                end_ns - waited_ns,
                end_ns,
                {"class": cls.label, "sets": len(job.sets)},
            )

    # -- lane placement --------------------------------------------------------

    def _free_lanes(self) -> list[MeshLane]:
        """Lanes eligible for a new package. While ANY healthy lane
        exists, only healthy free lanes count — a busy-but-healthy mesh
        makes the dispatcher WAIT rather than dispatch onto an idle
        wedged chip (which would feed a launch storm into the hung
        driver the breaker just isolated). Only when every lane is
        wedged does the dispatcher place work on a sick chip: it fails
        fast, tripping futures with the error — the pre-mesh
        wedged-pool behavior, and how a wedged breaker earns its
        half-open retrial."""
        avail = self.mesh.available()
        if avail:
            return [lane for lane in avail if lane.inflight == 0]
        return [lane for lane in self.mesh.lanes if lane.inflight == 0]

    async def _wait_free_lane(self) -> None:
        """Park the dispatcher until some lane can take a package. The
        wait happens BEFORE the dequeue, so jobs stay in the priority
        queue (and keep reordering under arriving urgent work) until
        the mesh actually has capacity — with one lane this is exactly
        the pre-mesh serialized schedule."""
        while not self._free_lanes():
            self._lane_free.clear()
            await self._lane_free.wait()

    def _pick_placement(
        self, cls: PriorityClass, package: list[_Job], free: list[MeshLane]
    ) -> tuple[str, list[MeshLane]]:
        """("sharded", lanes) for a bulk package big enough to amortize
        a collective launch over >=2 idle healthy chips; otherwise
        ("single", [least-occupied free lane]). `free` is non-empty by
        contract (the dispatcher re-waits when a lane wedges out from
        under it). Sharded lane sets are occupancy-CHOSEN but
        index-ORDERED: the sharded executable cache keys on device
        order, so a canonical ordering keeps one compile per subset
        instead of one per occupancy permutation."""
        if (
            self.scheduler_enabled
            and cls in BULK_CLASSES
            and self.mesh.sharding_available()
        ):
            healthy_free = [lane for lane in free if not lane.wedged]
            n_sets = sum(len(j.sets) for j in package)
            want = n_sets // SHARD_MIN_SETS_PER_LANE
            if len(healthy_free) >= 2 and want >= 2:
                chosen = sorted(healthy_free, key=lambda l: l.occupancy.occupancy())
                picked = chosen[: min(len(chosen), want)]
                return "sharded", sorted(picked, key=lambda l: l.index)
        lane = min(free, key=lambda l: (l.wedged, l.occupancy.occupancy()))
        return "single", [lane]

    async def _run_jobs(self) -> None:
        while not self._closed:
            await self._wait_free_lane()
            if self._closed:
                return
            job, cls, waited_ns = await self._jobs.get()
            self._record_sched_dequeue(job, cls, waited_ns)
            package = [job]
            # drain immediately-available work into the package: same
            # class only under the scheduler, capped at MAX_PACKAGE_SETS
            # (and bulk runs ONE job per package) — both bound how long an
            # arriving gossip block can wait behind the in-flight launch;
            # everything available in FIFO mode (the pre-scheduler arm)
            if not (self.scheduler_enabled and cls in BULK_CLASSES):
                drain_cls = cls if self.scheduler_enabled else None
                package_sets = len(job.sets)
                while not self.scheduler_enabled or package_sets < MAX_PACKAGE_SETS:
                    nxt = self._jobs.get_nowait(drain_cls)
                    if nxt is None:
                        break
                    self._record_sched_dequeue(*nxt)
                    package.append(nxt[0])
                    package_sets += len(nxt[0].sets)
            # a package is now IN HAND: from here to create_task, any
            # await must fail the package's futures on cancellation —
            # close() only drains the queue, it cannot see this package
            try:
                while True:
                    free = self._free_lanes()
                    if free:
                        break
                    # a free lane wedged between the capacity check and
                    # placement (a cross-lane retry on an executor
                    # thread can trip any breaker): healthy lanes exist
                    # but are busy — their in-flight completions set
                    # _lane_free, so this wait always terminates
                    self._lane_free.clear()
                    await self._lane_free.wait()
                    if self._closed:
                        raise asyncio.CancelledError("bls pool closed")
                mode, lanes = self._pick_placement(cls, package, free)
            except asyncio.CancelledError:
                err = asyncio.CancelledError("bls pool closed")
                for j in package:
                    if not j.future.done():
                        j.future.set_exception(err)
                raise
            for lane in lanes:
                lane.inflight += 1
            task = asyncio.get_event_loop().create_task(
                self._launch(package, mode, lanes)
            )
            self._launch_tasks.add(task)
            task.add_done_callback(self._launch_tasks.discard)

    def _release_lanes_early(self, to_release: list[MeshLane], held: list[MeshLane]) -> None:
        """Loop-side early release: the sharded fallback returns unused
        lanes to the dispatcher before its (possibly long) single-lane
        retry finishes. `held` is the launch's live accounting — the
        finally below decrements exactly what is still held."""
        for lane in to_release:
            if lane in held:
                held.remove(lane)
                lane.inflight -= 1
        self._lane_free.set()

    async def _launch(self, package: list[_Job], mode: str, lanes: list[MeshLane]) -> None:
        held = list(lanes)  # guarded by: event-loop (early releases and the finally both run on the loop)
        try:
            if mode == "sharded":
                await asyncio.get_event_loop().run_in_executor(
                    None, self._verify_package_sharded, package, lanes, held
                )
            else:
                await asyncio.get_event_loop().run_in_executor(
                    None, self._verify_package, package, lanes[0]
                )
        except asyncio.CancelledError:
            # close() cancels launch tasks; if the executor work item
            # had not STARTED yet it never runs and nobody else will
            # resolve these futures — fail them closed (done futures,
            # resolved by an already-running executor thread, no-op)
            err = asyncio.CancelledError("bls pool closed")
            for j in package:
                if not j.future.done():
                    j.future.set_exception(err)
            raise
        except Exception as e:  # fail closed: reject, never resolve True
            self.metrics["errors"] += len(package)
            self._log.error(f"bls verify package failed: {e!r}")
            for j in package:
                if not j.future.done():
                    j.future.set_exception(e)
        finally:
            for lane in held:
                lane.inflight -= 1
            # clear so a LATE _release_lanes_early (scheduled by an
            # executor thread that outlives a cancelled launch task)
            # finds nothing left to double-decrement
            held.clear()
            self._lane_free.set()

    # -- device launches (executor threads) ------------------------------------

    def _on_lane_wedge(self, lane: MeshLane) -> None:
        """closed->open transition on one chip's wedge breaker."""
        self._log.warn(
            "device lane wedged, degrading to remaining chips",
            {"device": lane.label, "lanes_left": len(self.mesh.available())},
        )
        m = self._sched_metrics
        if m is not None:
            m.lane_wedge_trips.labels(lane.label).inc()

    def _count_lane_launch(self, lane: MeshLane, mode: str) -> None:
        m = self._sched_metrics
        if m is not None:
            m.lane_launches.labels(lane.label, mode).inc()

    def _launch_sets(self, lane: MeshLane, sets: list[SignatureSet]):
        """One verify launch, preferring `lane` (mesh_launch: breaker
        accounting + cross-lane error retry — a sick chip degrades its
        work onto the rest of the mesh with the verdict unchanged;
        raises only when every candidate lane errored, which with one
        lane is exactly the pre-mesh fail-closed behavior). Returns
        (ok, lane_that_served)."""
        from .mesh import mesh_launch

        return mesh_launch(
            self.mesh,
            sets,
            prefer=lane,
            on_launch=lambda l: self._count_lane_launch(l, "single"),
            on_wedge=self._on_lane_wedge,
        )

    def _verify_package(self, package: list[_Job], lane: MeshLane, counted: bool = False) -> None:
        """Runs in a thread executor (device dispatch releases the GIL)."""
        if not counted:
            self.metrics["jobs_started"] += len(package)
            self.metrics["sig_sets_started"] += sum(len(j.sets) for j in package)

        # tracing work (incl. the clock reads) only when some job in the
        # package was submitted under an active trace — the disabled path
        # pays the flag checks hidden in trace_parent alone
        traced = any(j.trace_parent is not None for j in package)
        if traced:
            # buffer-wait spans: from job submission to the launch this
            # thread is about to perform (buffering + queue time)
            launch_ns = time.monotonic_ns()
            for j in package:
                if j.trace_parent is not None:
                    tracing.record(
                        j.trace_parent, "bls_buffer_wait", j.added_ns, launch_ns,
                        {"sets": len(j.sets)},
                    )

        batchable = [j for j in package if j.batchable]
        individual = [j for j in package if not j.batchable]

        # RLC-batch the batchable jobs in ≥16-set chunks; invalid batch →
        # retry each job individually (worker.ts:52-96)
        from lodestar_tpu.utils.tracing import trace_region

        for chunk in chunkify_maximize_chunk_size(batchable, BATCHABLE_MIN_PER_CHUNK):
            all_sets = [s for j in chunk for s in j.sets]
            t0 = time.monotonic_ns() if traced else 0
            try:
                with trace_region("bls_batch_verify"):
                    ok, served = self._launch_sets(lane, all_sets)
            except Exception:
                self.metrics["batch_retries"] += 1
                if traced:
                    self._trace_prep(chunk, t0)
                    self._trace_launch(chunk, t0, len(all_sets), "batch_error", lane.label)
                individual.extend(chunk)
                continue
            if traced:
                self._trace_prep(chunk, t0)
                self._trace_launch(chunk, t0, len(all_sets), "batch", served.label)
            if ok:
                self.metrics["batch_sigs_success"] += len(all_sets)
                for j in chunk:
                    self._resolve(j, True)
            else:
                self.metrics["batch_retries"] += 1
                individual.extend(chunk)

        for j in individual:
            t0 = time.monotonic_ns() if traced else 0
            try:
                ok, served = self._launch_sets(lane, j.sets)
                if traced:
                    self._trace_prep([j], t0)
                    self._trace_launch([j], t0, len(j.sets), "single", served.label)
                self._resolve(j, ok)
            except Exception as e:
                if traced:
                    self._trace_prep([j], t0)
                    self._trace_launch([j], t0, len(j.sets), "single_error", lane.label)
                if not j.future.done():
                    j.future.get_loop().call_soon_threadsafe(self._reject, j, e)

    def _verify_package_sharded(
        self, package: list[_Job], lanes: list[MeshLane], held: list[MeshLane] | None = None
    ) -> None:
        """One data-parallel launch over idle lanes (executor thread).
        A collective ERROR cannot name the sick chip, so it feeds the
        mesh's sharded breaker (parking the collective path) and the
        package degrades to the attributable single-lane path; an
        invalid VERDICT takes the same retry road the RLC batch does —
        re-verified per job so one bad signature can't poison its
        package (and so a lying collective can't be weaker than the
        single-device policy)."""
        self.metrics["jobs_started"] += len(package)
        self.metrics["sig_sets_started"] += sum(len(j.sets) for j in package)
        all_sets = [s for j in package for s in j.sets]
        traced = any(j.trace_parent is not None for j in package)
        if traced:
            launch_ns = time.monotonic_ns()
            for j in package:
                if j.trace_parent is not None:
                    tracing.record(
                        j.trace_parent, "bls_buffer_wait", j.added_ns, launch_ns,
                        {"sets": len(j.sets)},
                    )
        t0 = time.monotonic_ns() if traced else 0
        import contextlib

        try:
            with contextlib.ExitStack() as stack:
                for lane in lanes:
                    stack.enter_context(lane.occupancy.launch())
                ok = bool(
                    self.mesh.sharded_fn(all_sets, [lane.index for lane in lanes])
                )
            self.mesh.sharded_breaker.record_success()
            self.metrics["sharded_launches"] += 1
            for lane in lanes:
                lane.launches += 1
                self._count_lane_launch(lane, "sharded")
        except Exception:
            self.mesh.sharded_breaker.record_failure()
            self.metrics["sharded_fallbacks"] += 1
            self.metrics["batch_retries"] += 1
            if traced:
                self._trace_launch(
                    package, t0, len(all_sets), "sharded_error",
                    ",".join(lane.label for lane in lanes),
                )
            fallback = min(lanes, key=lambda l: l.occupancy.occupancy())
            self._release_unused(lanes, fallback, held, package)
            self._verify_package(package, fallback, counted=True)
            return
        if traced:
            self._trace_launch(
                package, t0, len(all_sets), "sharded",
                ",".join(lane.label for lane in lanes),
            )
        if ok:
            self.metrics["batch_sigs_success"] += len(all_sets)
            for j in package:
                self._resolve(j, True)
        else:
            self.metrics["batch_retries"] += 1
            fallback = min(lanes, key=lambda l: l.occupancy.occupancy())
            self._release_unused(lanes, fallback, held, package)
            self._verify_package(package, fallback, counted=True)

    def _release_unused(
        self,
        lanes: list[MeshLane],
        fallback: MeshLane,
        held: "list[MeshLane] | None",
        package: list[_Job],
    ) -> None:
        """Executor-side entry to the loop-side early release: the
        sharded fallback keeps ONE lane for its (possibly long)
        single-lane retry — the other chips go back to the dispatcher
        now instead of idling behind this package's finally."""
        if held is None:
            return
        unused = [lane for lane in lanes if lane is not fallback]
        if unused:
            package[0].future.get_loop().call_soon_threadsafe(
                self._release_lanes_early, unused, held
            )

    @staticmethod
    def _trace_prep(jobs: list[_Job], launch_start_ns: int) -> None:
        """`bls_prep` span per traced job: input preparation inside the
        launch this thread just performed, with the serving layer
        (device on-chip pipeline vs host native/python) stamped as an
        attribute — mirroring how `verifier_layer` attributes the verify.
        The model layer leaves the timing in a thread-local (it runs on
        this executor thread, below any tracer context); consuming it
        here keeps untraced launches free of tracer work. Records that
        predate this launch are discarded: untraced launches (and mock
        backends layered over earlier real ones) leave stale info on the
        executor thread, and attributing an old prep's timestamps to this
        trace would corrupt its span window."""
        from lodestar_tpu.models.batch_verify import consume_prep_info

        info = consume_prep_info()
        if info is None or info["end_ns"] < launch_start_ns:
            return
        attrs = {"layer": info["layer"], "sets": info["sets"]}
        if info["rejected"]:
            attrs["rejected"] = True
        for j in jobs:
            if j.trace_parent is not None:
                tracing.record(
                    j.trace_parent, "bls_prep", info["start_ns"], info["end_ns"], attrs
                )

    @staticmethod
    def _trace_launch(
        jobs: list[_Job], start_ns: int, n_sets: int, mode: str, device: str = "dev0"
    ) -> None:
        """Per-traced-job device-launch span; a batch covering jobs from
        several traces lands one identically-timed span in each. A
        batchable job verified in the single pass got there because its
        batch failed — that's the reference's batch-then-retry path, so
        it's labeled bls_batch_retry to keep the decomposition visible.
        The serving lane rides along as the `device` attribute."""
        end_ns = time.monotonic_ns()
        for j in jobs:
            if j.trace_parent is not None:
                retried = j.batchable and mode.startswith("single")
                tracing.record(
                    j.trace_parent,
                    "bls_batch_retry" if retried else "bls_device_launch",
                    start_ns,
                    end_ns,
                    {"sets": n_sets, "mode": mode, "device": device},
                )

    def _resolve(self, job: _Job, result: bool) -> None:
        if not job.future.done():
            job.future.get_loop().call_soon_threadsafe(self._set_result, job, result)

    @staticmethod
    def _set_result(job: _Job, result: bool) -> None:
        if not job.future.done():
            job.future.set_result(result)

    @staticmethod
    def _reject(job: _Job, err: Exception) -> None:
        if not job.future.done():
            job.future.set_exception(err)
