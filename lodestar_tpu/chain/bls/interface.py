"""The BLS verifier seam — the narrow boundary the whole offload design
hangs on.

Counterpart of `IBlsVerifier` (reference
`beacon-node/src/chain/bls/interface.ts:20`): three methods —
verify_signature_sets / can_accept_work / close — proven sufficient by the
reference, where a mock (`test/utils/mocks/bls.ts:3`), a single-thread
impl and the worker pool all swap freely behind it
(`chain/chain.ts:200-202`). Here the impls are the CPU-oracle verifier
and the device pool (`pool.py`); the device program replaces the worker
boundary at `multithread/index.ts:348`.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

from lodestar_tpu.crypto.bls.api import SignatureSet

__all__ = ["VerifySignatureOpts", "IBlsVerifier", "BlsSingleThreadVerifier", "BlsVerifierMock"]


@dataclass(frozen=True)
class VerifySignatureOpts:
    """Reference `VerifySignatureOpts` (`interface.ts:3-18`).

    batchable: the set MAY be held up to the buffer window and verified
    together with others (random-linear-combination). Only non-time-
    critical gossip objects should set it.
    verify_on_main_thread: bypass the pool entirely (cheap single sets on
    the hot path where the job round-trip costs more than the pairing).
    priority: scheduler launch class (`scheduler.PriorityClass`) carried
    from the call site — gossip block > gossip attestation > API >
    range sync > backfill. None means API (the neutral middle class);
    verifiers without a scheduler ignore it.
    slot: the subject slot of the work (a block's slot), anchoring the
    SLO layer's deadline math (`lodestar_tpu/slo`). None anchors at the
    wall-clock slot when the job is enqueued — right for work with no
    subject slot (attestation aggregates, API batches); verifiers
    without slack accounting ignore it.
    """

    batchable: bool = False
    verify_on_main_thread: bool = False
    priority: "int | None" = None
    slot: "int | None" = None


class IBlsVerifier(abc.ABC):
    @abc.abstractmethod
    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        """Verify 1+ signature sets (signatures untrusted wire bytes)."""

    @abc.abstractmethod
    def can_accept_work(self) -> bool:
        """True if the verifier is ready for more jobs — the gossip
        processor gates queue draining on this (reference
        `processor/index.ts:316-330`)."""

    @abc.abstractmethod
    async def close(self) -> None:
        """Drain/abort outstanding jobs and release the backend."""


class BlsSingleThreadVerifier(IBlsVerifier):
    """Inline oracle verification (reference `singleThread.ts`)."""

    def __init__(self) -> None:
        self._closed = False

    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        from lodestar_tpu.crypto.bls.api import verify_signature_sets

        return verify_signature_sets(sets)

    def can_accept_work(self) -> bool:
        return not self._closed

    async def close(self) -> None:
        self._closed = True


class BlsVerifierMock(IBlsVerifier):
    """Fixed-verdict mock (reference `test/utils/mocks/bls.ts:3`) — proof
    the seam stays mockable."""

    def __init__(self, verdict: bool = True) -> None:
        self.verdict = verdict
        self.calls: list[int] = []

    async def verify_signature_sets(
        self, sets: list[SignatureSet], opts: VerifySignatureOpts | None = None
    ) -> bool:
        self.calls.append(len(sets))
        return self.verdict

    def can_accept_work(self) -> bool:
        return True

    async def close(self) -> None:
        return None
