"""BLS verification seam + device pool (reference `chain/bls/`)."""

from .fallback import DegradingBlsVerifier  # noqa: F401
from .interface import (  # noqa: F401
    BlsSingleThreadVerifier,
    BlsVerifierMock,
    IBlsVerifier,
    VerifySignatureOpts,
)
from .mesh import (  # noqa: F401
    MESH_MODES,
    MeshLane,
    VerifierMesh,
    build_device_mesh,
    single_lane_mesh,
)
from .pool import (  # noqa: F401
    BATCHABLE_MIN_PER_CHUNK,
    MAX_BUFFER_WAIT_MS,
    MAX_BUFFERED_SIGS,
    MAX_JOBS_CAN_ACCEPT_WORK,
    MAX_SIGNATURE_SETS_PER_JOB,
    BlsDeviceVerifierPool,
    chunkify_maximize_chunk_size,
)
