"""Runtime chain configuration: fork schedule, domains, networks.

Counterpart of the reference `packages/config/src`
(`beaconConfig.ts:17,25` createChainForkConfig/createBeaconConfig,
`chainConfig/` value tables, `networks.ts`). A ChainConfig is runtime data
(fork epochs, genesis parameters); the preset remains a separate
compile-frozen value (see lodestar_tpu.params).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace
from functools import lru_cache

from lodestar_tpu.params import FAR_FUTURE_EPOCH

__all__ = [
    "ChainConfig",
    "ForkInfo",
    "BeaconConfig",
    "mainnet_chain_config",
    "minimal_chain_config",
    "gnosis_chain_config",
    "goerli_chain_config",
    "sepolia_chain_config",
    "create_beacon_config",
    "compute_fork_data_root",
    "compute_domain",
    "compute_signing_root",
    "NETWORKS",
]

FORK_ORDER = ("phase0", "altair", "bellatrix", "capella", "deneb")


@dataclass(frozen=True)
class ChainConfig:
    """Spec runtime config values (reference `chainConfig/types.ts`)."""

    PRESET_BASE: str = "mainnet"
    CONFIG_NAME: str = "mainnet"
    # genesis
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT: int = 16384
    MIN_GENESIS_TIME: int = 1606824000
    GENESIS_FORK_VERSION: bytes = bytes.fromhex("00000000")
    GENESIS_DELAY: int = 604800
    # forks
    ALTAIR_FORK_VERSION: bytes = bytes.fromhex("01000000")
    ALTAIR_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    BELLATRIX_FORK_VERSION: bytes = bytes.fromhex("02000000")
    BELLATRIX_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    CAPELLA_FORK_VERSION: bytes = bytes.fromhex("03000000")
    CAPELLA_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    DENEB_FORK_VERSION: bytes = bytes.fromhex("04000000")
    DENEB_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    # merge
    TERMINAL_TOTAL_DIFFICULTY: int = 2**256 - 2**10
    TERMINAL_BLOCK_HASH: bytes = b"\x00" * 32
    TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH: int = FAR_FUTURE_EPOCH
    # time
    SECONDS_PER_SLOT: int = 12
    SECONDS_PER_ETH1_BLOCK: int = 14
    ETH1_FOLLOW_DISTANCE: int = 2048
    # validator cycle
    EJECTION_BALANCE: int = 16_000_000_000
    MIN_PER_EPOCH_CHURN_LIMIT: int = 4
    CHURN_LIMIT_QUOTIENT: int = 65536
    INACTIVITY_SCORE_BIAS: int = 4
    INACTIVITY_SCORE_RECOVERY_RATE: int = 16
    PROPOSER_SCORE_BOOST: int = 40
    # deposit contract
    DEPOSIT_CHAIN_ID: int = 1
    DEPOSIT_NETWORK_ID: int = 1
    DEPOSIT_CONTRACT_ADDRESS: bytes = bytes(20)

    def replace(self, **overrides) -> "ChainConfig":
        return replace(self, **overrides)

    def fork_version(self, fork: str) -> bytes:
        if fork == "phase0":
            return self.GENESIS_FORK_VERSION
        return getattr(self, f"{fork.upper()}_FORK_VERSION")

    def fork_epoch(self, fork: str) -> int:
        if fork == "phase0":
            return 0
        return getattr(self, f"{fork.upper()}_FORK_EPOCH")


@dataclass(frozen=True)
class ForkInfo:
    name: str
    epoch: int
    version: bytes
    prev_version: bytes
    prev_fork_name: str


def fork_name_at_epoch(cfg: ChainConfig, epoch: int) -> str:
    """Active fork name at an epoch for a plain ChainConfig (shared by
    the chain runtime and restart/checkpoint loaders)."""
    name = FORK_ORDER[0]
    for fork in FORK_ORDER[1:]:
        if cfg.fork_epoch(fork) <= epoch:
            name = fork
    return name


def _fork_schedule(cfg: ChainConfig) -> tuple[ForkInfo, ...]:
    out = []
    prev_version = cfg.GENESIS_FORK_VERSION
    prev_name = "phase0"
    for name in FORK_ORDER:
        epoch = cfg.fork_epoch(name)
        version = cfg.fork_version(name)
        out.append(ForkInfo(name, epoch, version, prev_version, prev_name))
        prev_version, prev_name = version, name
    return tuple(out)


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    """hash_tree_root(ForkData) — 2-leaf merkle (spec compute_fork_data_root)."""
    leaf0 = current_version.ljust(32, b"\x00")
    return hashlib.sha256(leaf0 + genesis_validators_root).digest()


def compute_domain(
    domain_type: bytes, fork_version: bytes, genesis_validators_root: bytes
) -> bytes:
    return domain_type + compute_fork_data_root(fork_version, genesis_validators_root)[:28]


def compute_signing_root(ssz_type, value, domain: bytes) -> bytes:
    """hash_tree_root(SigningData) (spec compute_signing_root)."""
    object_root = ssz_type.hash_tree_root(value)
    return hashlib.sha256(object_root + domain).digest()


# Module-level caches keyed on pure inputs: instance-method lru_cache would
# pin every BeaconConfig (and its fork schedule) in a class-global cache.
@lru_cache(maxsize=512)
def _cached_domain(domain_type: bytes, fork_version: bytes, gvr: bytes) -> bytes:
    return compute_domain(domain_type, fork_version, gvr)


@lru_cache(maxsize=128)
def _cached_fork_digest(fork_version: bytes, gvr: bytes) -> bytes:
    return compute_fork_data_root(fork_version, gvr)[:4]


@dataclass(frozen=True)
class BeaconConfig:
    """ChainConfig bound to a genesis_validators_root with cached domains
    (reference `beaconConfig.ts:25` createBeaconConfig + forkDigest caches)."""

    chain: ChainConfig
    genesis_validators_root: bytes
    forks: tuple[ForkInfo, ...] = field(default_factory=tuple)

    def fork_name_at_epoch(self, epoch: int) -> str:
        name = "phase0"
        for f in self.forks:
            if epoch >= f.epoch:
                name = f.name
        return name

    def fork_info_at_epoch(self, epoch: int) -> ForkInfo:
        info = self.forks[0]
        for f in self.forks:
            if epoch >= f.epoch:
                info = f
        return info

    def fork_name_at_slot(self, slot: int, slots_per_epoch: int) -> str:
        return self.fork_name_at_epoch(slot // slots_per_epoch)

    def fork_digest(self, fork_name: str) -> bytes:
        """4-byte digest for gossip topics / ENR (spec compute_fork_digest)."""
        version = self.chain.fork_version(fork_name)
        return _cached_fork_digest(version, self.genesis_validators_root)

    def get_domain_by_version(self, domain_type: bytes, fork_version: bytes) -> bytes:
        return _cached_domain(domain_type, fork_version, self.genesis_validators_root)

    def get_domain(self, domain_type: bytes, epoch: int) -> bytes:
        """Domain for signing at an epoch, using that epoch's fork version
        (spec get_domain with state fork resolved from the schedule)."""
        return self.get_domain_by_version(
            domain_type, self.fork_info_at_epoch(epoch).version
        )


def create_beacon_config(chain: ChainConfig, genesis_validators_root: bytes) -> BeaconConfig:
    return BeaconConfig(
        chain=chain,
        genesis_validators_root=genesis_validators_root,
        forks=_fork_schedule(chain),
    )


def mainnet_chain_config() -> ChainConfig:
    """Ethereum mainnet (reference `networks/mainnet.ts`)."""
    return ChainConfig(
        PRESET_BASE="mainnet",
        CONFIG_NAME="mainnet",
        ALTAIR_FORK_EPOCH=74240,
        BELLATRIX_FORK_EPOCH=144896,
        CAPELLA_FORK_EPOCH=194048,
        TERMINAL_TOTAL_DIFFICULTY=58_750_000_000_000_000_000_000,
        DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("00000000219ab540356cbb839cbe05303d7705fa"),
    )


def minimal_chain_config() -> ChainConfig:
    """Minimal-preset dev config (all forks at genesis, fast slots)."""
    return ChainConfig(
        PRESET_BASE="minimal",
        CONFIG_NAME="minimal",
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
        MIN_GENESIS_TIME=1578009600,
        GENESIS_FORK_VERSION=bytes.fromhex("00000001"),
        ALTAIR_FORK_VERSION=bytes.fromhex("01000001"),
        ALTAIR_FORK_EPOCH=0,
        BELLATRIX_FORK_VERSION=bytes.fromhex("02000001"),
        BELLATRIX_FORK_EPOCH=0,
        CAPELLA_FORK_VERSION=bytes.fromhex("03000001"),
        CAPELLA_FORK_EPOCH=0,
        DENEB_FORK_VERSION=bytes.fromhex("04000001"),
        GENESIS_DELAY=300,
        SECONDS_PER_SLOT=6,
        ETH1_FOLLOW_DISTANCE=16,
        DEPOSIT_CHAIN_ID=5,
        DEPOSIT_NETWORK_ID=5,
    )


def gnosis_chain_config() -> ChainConfig:
    """Gnosis chain (reference `chainConfig/networks/gnosis.ts` — public
    chain constants from the eth-clients configs)."""
    return ChainConfig(
        PRESET_BASE="gnosis",
        CONFIG_NAME="gnosis",
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=4096,
        MIN_GENESIS_TIME=1638968400,
        GENESIS_FORK_VERSION=bytes.fromhex("00000064"),
        GENESIS_DELAY=6000,
        ALTAIR_FORK_VERSION=bytes.fromhex("01000064"),
        ALTAIR_FORK_EPOCH=512,
        BELLATRIX_FORK_VERSION=bytes.fromhex("02000064"),
        BELLATRIX_FORK_EPOCH=385536,
        CAPELLA_FORK_VERSION=bytes.fromhex("03000064"),
        TERMINAL_TOTAL_DIFFICULTY=8626000000000000000000058750000000000000000000,
        SECONDS_PER_SLOT=5,
        SECONDS_PER_ETH1_BLOCK=6,
        ETH1_FOLLOW_DISTANCE=1024,
        CHURN_LIMIT_QUOTIENT=4096,
        DEPOSIT_CHAIN_ID=100,
        DEPOSIT_NETWORK_ID=100,
        DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("0b98057ea310f4d31f2a452b414647007d1645d9"),
    )


def goerli_chain_config() -> ChainConfig:
    """Goerli/Prater testnet (reference `chainConfig/networks/goerli.ts`)."""
    return ChainConfig(
        PRESET_BASE="mainnet",
        CONFIG_NAME="goerli",
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16384,
        MIN_GENESIS_TIME=1614588812,
        GENESIS_FORK_VERSION=bytes.fromhex("00001020"),
        GENESIS_DELAY=1919188,
        ALTAIR_FORK_VERSION=bytes.fromhex("01001020"),
        ALTAIR_FORK_EPOCH=36660,
        BELLATRIX_FORK_VERSION=bytes.fromhex("02001020"),
        BELLATRIX_FORK_EPOCH=112260,
        CAPELLA_FORK_VERSION=bytes.fromhex("03001020"),
        CAPELLA_FORK_EPOCH=162304,
        TERMINAL_TOTAL_DIFFICULTY=10790000,
        DEPOSIT_CHAIN_ID=5,
        DEPOSIT_NETWORK_ID=5,
        DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("ff50ed3d0ec03ac01d4c79aad74928bff48a7b2b"),
    )


def sepolia_chain_config() -> ChainConfig:
    """Sepolia testnet (reference `chainConfig/networks/sepolia.ts`)."""
    return ChainConfig(
        PRESET_BASE="mainnet",
        CONFIG_NAME="sepolia",
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=1300,
        MIN_GENESIS_TIME=1655647200,
        GENESIS_FORK_VERSION=bytes.fromhex("90000069"),
        ALTAIR_FORK_VERSION=bytes.fromhex("90000070"),
        ALTAIR_FORK_EPOCH=50,
        BELLATRIX_FORK_VERSION=bytes.fromhex("90000071"),
        BELLATRIX_FORK_EPOCH=100,
        CAPELLA_FORK_VERSION=bytes.fromhex("90000072"),
        CAPELLA_FORK_EPOCH=56832,
        TERMINAL_TOTAL_DIFFICULTY=17000000000000000,
        DEPOSIT_CHAIN_ID=11155111,
        DEPOSIT_NETWORK_ID=11155111,
        DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("7f02c3e3c98b133055b8b348b2ac625669ed295d"),
    )


NETWORKS = {
    "mainnet": mainnet_chain_config,
    "minimal": minimal_chain_config,
    "gnosis": gnosis_chain_config,
    "goerli": goerli_chain_config,
    "sepolia": sepolia_chain_config,
}
