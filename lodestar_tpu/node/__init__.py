"""BeaconNode composition root (reference `beacon-node/src/node/nodejs.ts:141`).

`BeaconNode.init` wires the full runtime in the reference's order: db →
metrics (+ scrape server) → chain (BLS verifier pool + fork choice +
pools) → clock → REST API → status notifier. `close()` runs the abort
cascade in reverse (`nodejs.ts:146-152`).
"""

from __future__ import annotations

import asyncio

from lodestar_tpu.api import BeaconApiImpl, BeaconRestApiServer
from lodestar_tpu.chain.bls import BlsSingleThreadVerifier, IBlsVerifier
from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.clock import Clock
from lodestar_tpu.db import DbController, FileDbController, MemoryDbController
from lodestar_tpu.logger import get_logger
from lodestar_tpu.metrics import BeaconMetrics, MetricsServer, create_metrics
from lodestar_tpu.params import BeaconPreset, active_preset

__all__ = ["BeaconNode", "BeaconNodeOptions"]


class BeaconNodeOptions:
    def __init__(
        self,
        *,
        db_path: str | None = None,
        rest_port: int = 9596,
        rest_enabled: bool = True,
        metrics_port: int = 8008,
        metrics_enabled: bool = False,
        use_device_verifier: bool = False,
        manual_clock: bool = False,
        p2p_enabled: bool = False,
        p2p_port: int = 0,
        bootnodes: list[tuple[str, int]] | None = None,
        on_shutdown_request=None,
        tracing_enabled: bool = False,
        tracing_slow_slot_ms: float = 2000.0,
        tracing_export_dir: str | None = None,
        tracing_export_max_files: int = 256,
        tracing_export_max_age_s: float | None = None,
        offload_endpoints: list[str] | None = None,
        offload_breaker_threshold: int | None = None,
        offload_breaker_reset_s: float | None = None,
        offload_hedge_delay_ms: float | None = None,
        offload_fallback: str = "cpu",
        offload_audit_rate: float | None = None,
        offload_audit_budget: float | None = None,
        offload_audit_via: str = "cpu",
        offload_audit_seed: int | None = None,
        offload_quarantine_cooloff_s: float | None = None,
        offload_unquarantine: list[str] | None = None,
        scheduler_enabled: bool = True,
        bls_device_prep: str = "auto",
        bls_pipeline: str = "auto",
        bls_single_launch: str = "auto",
        htr_device: str = "auto",
        bls_mesh: str = "auto",
        offload_tenant: str | None = None,
        launch_telemetry: str = "auto",
        slo_enabled: bool = True,
        slo_slack_floor_ms: float = 0.0,
    ):
        self.db_path = db_path
        self.rest_port = rest_port
        self.rest_enabled = rest_enabled
        self.metrics_port = metrics_port
        self.metrics_enabled = metrics_enabled
        self.use_device_verifier = use_device_verifier
        self.manual_clock = manual_clock
        self.p2p_enabled = p2p_enabled
        self.p2p_port = p2p_port
        self.bootnodes = list(bootnodes or [])
        # fatal-error callback (reference ProcessShutdownCallback): the
        # embedding process decides how to die; None = log only
        self.on_shutdown_request = on_shutdown_request
        # per-slot pipeline tracing (lodestar_tpu.tracing): off by default
        self.tracing_enabled = tracing_enabled
        self.tracing_slow_slot_ms = tracing_slow_slot_ms
        self.tracing_export_dir = tracing_export_dir
        self.tracing_export_max_files = tracing_export_max_files
        self.tracing_export_max_age_s = tracing_export_max_age_s
        # BLS offload endpoints (host:port); non-empty routes the chain's
        # verifier through BlsOffloadClient with load-aware routing
        self.offload_endpoints = list(offload_endpoints or [])
        # per-endpoint circuit breaker tuning; None = the resilience
        # module's defaults (the one definition of those numbers)
        from lodestar_tpu.offload.resilience import (
            DEFAULT_FAILURE_THRESHOLD,
            DEFAULT_RESET_TIMEOUT_S,
        )

        self.offload_breaker_threshold = (
            DEFAULT_FAILURE_THRESHOLD
            if offload_breaker_threshold is None
            else offload_breaker_threshold
        )
        self.offload_breaker_reset_s = (
            DEFAULT_RESET_TIMEOUT_S
            if offload_breaker_reset_s is None
            else offload_breaker_reset_s
        )
        # true hedged requests: a concurrent second RPC fires when the
        # primary is silent past this delay (first verdict wins, the
        # loser's verdict is discarded). None/<=0 = sequential
        # split-budget retry (the legacy hedge). The shipped default
        # lives in resilience.py with TUNING.md provenance.
        self.offload_hedge_delay_ms = (
            None
            if offload_hedge_delay_ms is None or offload_hedge_delay_ms <= 0
            else float(offload_hedge_delay_ms)
        )
        # degradation chain below the offload client: "cpu" (offload →
        # CPU oracle), "device" (offload → local device pool → CPU), or
        # "none" (offload errors reject blocks until the host returns)
        if offload_fallback not in ("none", "cpu", "device"):
            raise ValueError(f"offload_fallback must be none|cpu|device, got {offload_fallback!r}")
        self.offload_fallback = offload_fallback
        # Byzantine audit (offload/audit.py): randomized cross-checking
        # of offload verdicts against an independent verifier. rate 0
        # disables; "helper" re-verifies on a second endpoint (CPU
        # arbitration) when more than one is configured.
        from lodestar_tpu.offload.audit import DEFAULT_AUDIT_BUDGET, DEFAULT_AUDIT_RATE
        from lodestar_tpu.offload.resilience import DEFAULT_QUARANTINE_COOLOFF_S

        self.offload_audit_rate = (
            DEFAULT_AUDIT_RATE if offload_audit_rate is None else offload_audit_rate
        )
        self.offload_audit_budget = (
            DEFAULT_AUDIT_BUDGET if offload_audit_budget is None else offload_audit_budget
        )
        if offload_audit_via not in ("cpu", "helper"):
            raise ValueError(f"offload_audit_via must be cpu|helper, got {offload_audit_via!r}")
        self.offload_audit_via = offload_audit_via
        self.offload_audit_seed = offload_audit_seed
        # quarantine cool-off after a Byzantine event; 0 = until the
        # operator lifts it (--offload-unquarantine)
        self.offload_quarantine_cooloff_s = (
            DEFAULT_QUARANTINE_COOLOFF_S
            if offload_quarantine_cooloff_s is None
            else offload_quarantine_cooloff_s
        )
        self.offload_unquarantine = list(offload_unquarantine or [])
        # device work scheduler (lodestar_tpu.scheduler) for the in-process
        # pool; False restores FIFO launches (debug/comparison only)
        self.scheduler_enabled = scheduler_enabled
        # batch-verify input prep placement (models/batch_verify prep
        # modes): "auto" runs decompression/subgroup/hash-to-G2 on the
        # device only when the Pallas backend is live; "on"/"off" force.
        # Validated against the model layer's canonical mode set (cli.py
        # keeps a literal copy — argparse choices must not import jax)
        from lodestar_tpu.models.batch_verify import PREP_MODES

        if bls_device_prep not in PREP_MODES:
            raise ValueError(
                f"bls_device_prep must be one of {PREP_MODES}, got {bls_device_prep!r}"
            )
        self.bls_device_prep = bls_device_prep
        # prep→verify double buffering (chain/bls/pool.py): "auto"
        # overlaps prep of batch k+1 with verify of batch k only when
        # the mesh has a sibling lane; "on"/"off" force. Validated
        # against the pool's canonical mode set (cli.py keeps a literal
        # copy — argparse choices must not import the chain.bls package)
        from lodestar_tpu.chain.bls.pool import PIPELINE_MODES

        if bls_pipeline not in PIPELINE_MODES:
            raise ValueError(
                f"bls_pipeline must be one of {PIPELINE_MODES}, got {bls_pipeline!r}"
            )
        self.bls_pipeline = bls_pipeline
        # single-launch verification (models/batch_verify.py): "auto"
        # verifies each batch as ONE resident device program when the
        # Pallas backend is live (an explicit device-prep "off" pin
        # keeps the split schedule); "on"/"off" force. Single-launch
        # errors degrade per batch to the split prep-then-verify
        # schedule, then host prep. Validated against the model layer's
        # canonical mode set (cli.py keeps a literal copy — argparse
        # choices must not import jax)
        from lodestar_tpu.models.batch_verify import SINGLE_LAUNCH_MODES

        if bls_single_launch not in SINGLE_LAUNCH_MODES:
            raise ValueError(
                f"bls_single_launch must be one of {SINGLE_LAUNCH_MODES}, "
                f"got {bls_single_launch!r}"
            )
        self.bls_single_launch = bls_single_launch
        # state hashTreeRoot placement (ssz/device_htr.py collector):
        # "auto" flushes dirty subtrees through the device SHA-256
        # kernel only when the Pallas backend is live; "on"/"off" force.
        # Device errors degrade to the CPU incremental path (counted).
        from lodestar_tpu.ssz.device_htr import HTR_MODES

        if htr_device not in HTR_MODES:
            raise ValueError(
                f"htr_device must be one of {HTR_MODES}, got {htr_device!r}"
            )
        self.htr_device = htr_device
        # verifier mesh placement (chain/bls/mesh.py): "auto" serves the
        # local pool on per-chip launch lanes only when the Pallas
        # backend is live and >1 device is visible; "on"/"off" force.
        # A wedged chip degrades the pool to the remaining lanes.
        from lodestar_tpu.chain.bls.mesh import MESH_MODES

        if bls_mesh not in MESH_MODES:
            raise ValueError(f"bls_mesh must be one of {MESH_MODES}, got {bls_mesh!r}")
        self.bls_mesh = bls_mesh
        # tenant identity for the offload client (multi-tenant serving
        # hosts meter quotas and stride-fair shares per tenant) —
        # validated here so a config typo is a startup error, not a
        # per-verify offload outage
        if offload_tenant is not None:
            from lodestar_tpu.offload import validate_tenant

            try:
                validate_tenant(offload_tenant)
            except Exception as e:
                raise ValueError(f"offload_tenant: {e}") from e
        self.offload_tenant = offload_tenant
        # device launch telemetry (lodestar_tpu/telemetry.py): per-
        # dispatch wall time / program / size class / compile detection
        # at the counted launch seams. "auto" records once the node
        # installs the metric sink (i.e. on every node); "off" leaves
        # the seams one flag check from free. Validated against the
        # telemetry module's canonical tuple (cli.py keeps a literal
        # copy per the argparse-import doctrine)
        from lodestar_tpu.telemetry import TELEMETRY_MODES

        if launch_telemetry not in TELEMETRY_MODES:
            raise ValueError(
                f"launch_telemetry must be one of {TELEMETRY_MODES}, got {launch_telemetry!r}"
            )
        self.launch_telemetry = launch_telemetry
        # slot-deadline SLO accounting (lodestar_tpu/slo): per-priority-
        # class deadline slack at enqueue/dispatch/verdict plus the
        # good/total SLI pairs. The slack floor widens the miss margin
        # (0 = miss only when the deadline is actually blown); negative
        # would silently forgive real misses, so it is a startup error
        if slo_slack_floor_ms < 0:
            raise ValueError(
                f"slo_slack_floor_ms must be >= 0, got {slo_slack_floor_ms!r}"
            )
        self.slo_enabled = slo_enabled
        self.slo_slack_floor_ms = slo_slack_floor_ms


class BeaconNode:
    def __init__(
        self, *, chain, clock, db, metrics, rest_server, metrics_server, bls, processor=None
    ):
        self.chain = chain
        self.clock = clock
        self.db = db
        self.metrics = metrics
        self.rest_server = rest_server
        self.metrics_server = metrics_server
        self.bls = bls
        self.processor = processor
        self.network = None  # Libp2pBeaconNetwork when p2p is enabled
        self._drain_task = None
        self.log = get_logger(name="lodestar.node")

    def on_gossip(self, topic: str, message, peer: str = "") -> bool:
        """Ingress point for the network layer: enqueue a gossip message
        for validated processing (reference network -> NetworkProcessor)."""
        return self.processor.push(topic, message, peer) if self.processor else False

    def start_gossip_drain(self, interval_s: float = 0.05) -> None:
        """Background drain loop over the processor's queues (reference
        NetworkProcessor executeWork scheduling)."""
        if self.processor is None or self._drain_task is not None:
            return

        async def loop():
            while True:
                try:
                    n = await self.processor.execute_work()
                except Exception as e:  # keep draining through handler storms
                    self.log.warn("gossip drain error", {"error": str(e)[:120]})
                    n = 0
                await asyncio.sleep(0 if n else interval_s)

        self._drain_task = asyncio.ensure_future(loop())

    @classmethod
    async def init(
        cls,
        *,
        anchor_state,
        chain_config=None,
        opts: BeaconNodeOptions | None = None,
        p: BeaconPreset | None = None,
        time_fn=None,
        db: DbController | None = None,
    ) -> "BeaconNode":
        opts = opts or BeaconNodeOptions()
        p = p or active_preset()

        # 1. db (a pre-opened controller — e.g. from the restart-from-db
        # anchor probe — takes precedence; the WAL replays only once)
        if db is None:
            if opts.db_path:
                db = FileDbController(opts.db_path)
            else:
                db = MemoryDbController()

        # 2. metrics
        metrics: BeaconMetrics = create_metrics()
        metrics_server = None
        if opts.metrics_enabled:
            metrics_server = MetricsServer(metrics, port=opts.metrics_port)
            metrics_server.start()

        # 2b. pipeline tracing: the span tracer is process-global (the
        # pipeline crosses layers that never see the node object); only
        # an explicit opt-in reconfigures it, so embedded/test tracers
        # set up by the caller are left alone
        if opts.tracing_enabled:
            from lodestar_tpu import tracing as _tracing

            _tracing.configure(
                enabled=True,
                slow_slot_ms=opts.tracing_slow_slot_ms,
                export_dir=opts.tracing_export_dir,
                export_max_files=opts.tracing_export_max_files,
                export_max_age_s=opts.tracing_export_max_age_s,
                metrics=metrics.trace,
            )

        # 2c. event-loop lag sampler: a fixed-interval sleep whose
        # overshoot IS the scheduling lag — feeds the (previously
        # unobserved) lodestar_event_loop_lag_seconds histogram and the
        # slow-slot dumps, separating loop starvation from device slowness
        from lodestar_tpu.metrics.monitoring import EventLoopLagSampler

        lag_sampler = EventLoopLagSampler(metrics.process.event_loop_lag)
        if opts.tracing_enabled:
            from lodestar_tpu import tracing as _tracing

            _tracing.configure(lag_ms_supplier=lag_sampler.last_lag_ms)

        # 2d. batch-verify input prep placement + lodestar_bls_prep_*
        # metrics: process-global like the tracer (the prep runs inside
        # the model layer, below any node object)
        from lodestar_tpu.models.batch_verify import (
            configure_device_prep,
            configure_single_launch,
        )

        configure_device_prep(mode=opts.bls_device_prep, metrics=metrics.bls_prep)
        # single-launch verification mode rides the same process-global
        # seam (the router lives in the model layer, below any node
        # object); metrics are shared with the prep family above
        configure_single_launch(mode=opts.bls_single_launch)

        # 2e. state hashTreeRoot placement + lodestar_ssz_htr_* metrics:
        # process-global like the prep mode (the collector runs inside
        # the ssz/state-transition layers, below any node object)
        from lodestar_tpu.ssz.device_htr import configure_device_htr

        configure_device_htr(mode=opts.htr_device, metrics=metrics.ssz_htr)

        # KZG device-pairing degradation counter: process-global like the
        # prep/HTR seams (the fallback happens inside crypto/kzg.py,
        # below any node object)
        from lodestar_tpu.crypto.kzg import configure_kzg_fallback_counter

        configure_kzg_fallback_counter(metrics.kzg.device_fallbacks)

        # 2f. device launch telemetry: mode + the lodestar_device_launch_*
        # sink (process-global — the dispatch seams live in ops/ssz/mesh
        # layers below any node object); the slow-slot dump hook makes a
        # slow slot name its launches inline
        from lodestar_tpu import telemetry as _telemetry

        _telemetry.configure_launch_telemetry(
            mode=opts.launch_telemetry, metrics=metrics.device_launch
        )
        if opts.tracing_enabled:
            from lodestar_tpu import tracing as _tracing

            _tracing.configure(launches_supplier=_telemetry.slow_slot_launches)

        # 3. bls verifier — offload endpoints get the resilience stack:
        # breaker-guarded client, then the verified degradation chain
        # (every layer re-verifies; errors degrade, verdicts are final)
        bls: IBlsVerifier
        if opts.offload_endpoints:
            from lodestar_tpu.offload.client import BlsOffloadClient

            # 3a. Byzantine audit: seeded sampler + background
            # re-verification. Forensics + quarantine persistence:
            # prefer the tracing export dir (next to the slow-slot
            # dumps), else a subdirectory of the data dir — only a
            # fully in-memory node runs without persistence
            audit_dir = opts.tracing_export_dir
            if audit_dir is None and opts.db_path:
                import os as _os

                # db_path is the WAL *file* (cli passes <dir>/wal.log):
                # persist beside it, inside the data directory
                audit_dir = _os.path.join(
                    _os.path.dirname(_os.path.abspath(opts.db_path)), "offload-audit"
                )
            from lodestar_tpu.offload.audit import AuditSampler, OffloadAuditor

            # ALWAYS constructed: with --offload-audit-rate 0 it is
            # passive (no sampling thread) but still owns quarantine
            # persistence, gauges and rehabilitation — a standing
            # Byzantine verdict keeps its lifecycle regardless of the
            # sampling knob
            auditor = OffloadAuditor(
                sampler=AuditSampler(
                    opts.offload_audit_rate, seed=opts.offload_audit_seed
                ),
                budget=opts.offload_audit_budget,
                dump_dir=audit_dir,
                quarantine_cooloff_s=opts.offload_quarantine_cooloff_s or None,
                metrics=metrics.audit,
                start=opts.offload_audit_rate > 0,
            )
            client = BlsOffloadClient(
                opts.offload_endpoints,
                breaker_threshold=opts.offload_breaker_threshold,
                breaker_reset_s=opts.offload_breaker_reset_s,
                hedge_delay_ms=opts.offload_hedge_delay_ms,
                metrics=metrics.resilience,
                auditor=auditor,
                quarantine_cooloff_s=opts.offload_quarantine_cooloff_s or None,
                tenant=opts.offload_tenant,
            )
            if opts.offload_audit_via == "helper" and len(opts.offload_endpoints) > 1:
                from lodestar_tpu.offload.audit import cross_helper_reference

                auditor.set_reference(cross_helper_reference(client))
            # operator lifts first, then re-apply persisted Byzantine
            # quarantines — a restart must not silently re-trust a caught
            # liar, and that holds even at --offload-audit-rate 0 (the
            # passive auditor still reads/writes the quarantine file)
            persisted_before = set(auditor.load_quarantined())
            for target in opts.offload_unquarantine:
                if target not in opts.offload_endpoints and target not in persisted_before:
                    # a typo'd lift silently no-opping would leave the
                    # operator believing the quarantine was cleared
                    client.log.warn(
                        "--offload-unquarantine target matches no configured "
                        "endpoint and no persisted quarantine record",
                        {"target": target},
                    )
                    continue
                # clears breaker state AND (via the bound auditor) the
                # persisted record — the lift logic lives in one place
                client.unquarantine_endpoint(target)
            import time as _time

            from lodestar_tpu.offload.audit import remaining_cooloff

            cool = opts.offload_quarantine_cooloff_s or None
            now = _time.time()
            for target, rec in auditor.load_quarantined().items():
                if target in opts.offload_endpoints:
                    client.quarantine_endpoint(
                        target,
                        cooloff_s=remaining_cooloff(rec, cool, now),
                        reason="persisted_byzantine",
                    )
            if opts.offload_fallback == "none":
                bls = client
            else:
                from lodestar_tpu.chain.bls import DegradingBlsVerifier

                layers: list = [("offload", client)]
                if opts.offload_fallback == "device":
                    from lodestar_tpu.chain.bls import BlsDeviceVerifierPool

                    layers.append(
                        (
                            "device_pool",
                            BlsDeviceVerifierPool(
                                scheduler_enabled=opts.scheduler_enabled,
                                sched_metrics=metrics.sched,
                                mesh_mode=opts.bls_mesh,
                                pipeline=opts.bls_pipeline,
                                pipeline_metrics=metrics.bls_pipeline,
                            ),
                        )
                    )
                layers.append(("cpu", BlsSingleThreadVerifier()))
                bls = DegradingBlsVerifier(layers, metrics=metrics.resilience)
        elif opts.use_device_verifier:
            from lodestar_tpu.chain.bls import BlsDeviceVerifierPool

            bls = BlsDeviceVerifierPool(
                scheduler_enabled=opts.scheduler_enabled,
                sched_metrics=metrics.sched,
                mesh_mode=opts.bls_mesh,
                pipeline=opts.bls_pipeline,
                pipeline_metrics=metrics.bls_pipeline,
            )
        else:
            bls = BlsSingleThreadVerifier()

        # 4. clock from genesis time
        clock_kwargs = dict(
            genesis_time=anchor_state.genesis_time,
            seconds_per_slot=chain_config.SECONDS_PER_SLOT if chain_config else 12,
            slots_per_epoch=p.SLOTS_PER_EPOCH,
        )
        if time_fn is not None:
            clock_kwargs["time_fn"] = time_fn
        clock = Clock(**clock_kwargs)

        # 4b. slot-deadline SLO accounting: process-global like the
        # tracer (the verify pool and gossip processor live below any
        # node object). Configured here because this is the first point
        # where genesis_time is known; shares the clock's time_fn so a
        # manual/dev clock keeps the deadline math deterministic
        from lodestar_tpu import slo as _slo

        slo_kwargs = dict(
            enabled=opts.slo_enabled,
            genesis_time=anchor_state.genesis_time,
            seconds_per_slot=clock_kwargs["seconds_per_slot"],
            slots_per_epoch=p.SLOTS_PER_EPOCH,
            metrics=metrics.slo,
            slack_floor_ms=opts.slo_slack_floor_ms,
        )
        if time_fn is not None:
            slo_kwargs["time_fn"] = time_fn
        _slo.configure_slo(**slo_kwargs)

        # 5. chain
        chain = BeaconChain(
            anchor_state=anchor_state,
            bls_verifier=bls,
            db=db,
            p=p,
            cfg=chain_config,
            current_slot=max(clock.current_slot, anchor_state.slot),
            metrics=metrics,
        )
        # light-client server: serves bootstraps/updates once the chain
        # runs altair+ (reference chain/lightClient/index.ts wired in
        # BeaconChain's constructor)
        from lodestar_tpu.params import FAR_FUTURE_EPOCH

        if chain_config is not None and chain_config.ALTAIR_FORK_EPOCH != FAR_FUTURE_EPOCH:
            from lodestar_tpu.chain.light_client_server import LightClientServer

            chain.light_client_server = LightClientServer(chain)
        clock.on_slot(chain.on_slot)
        if not opts.manual_clock:
            clock.start()

        # 6. gossip processor (network ingress -> validated dispatch);
        # the chain remembers the node's loop so REST handler threads can
        # route mutations onto it (single-threaded chain semantics)
        import asyncio as _asyncio

        chain.loop = _asyncio.get_running_loop()
        from lodestar_tpu.network.processor import NetworkProcessor

        processor = NetworkProcessor(chain, metrics=metrics)

        # 7. REST API
        rest_server = None
        if opts.rest_enabled:
            rest_server = BeaconRestApiServer(BeaconApiImpl(chain), port=opts.rest_port)
            rest_server.start()

        node = cls(
            chain=chain, clock=clock, db=db, metrics=metrics,
            rest_server=rest_server, metrics_server=metrics_server, bls=bls,
            processor=processor,
        )

        # status notifier + fatal-error policy (reference node/notifier.ts
        # + chain/chain.ts processShutdownCallback)
        from lodestar_tpu.node.notifier import ProcessFaultPolicy, StatusNotifier

        node.fault = ProcessFaultPolicy(opts.on_shutdown_request)
        chain.fault = node.fault
        node.notifier = StatusNotifier(chain)
        node.lag_sampler = lag_sampler
        if not opts.manual_clock:
            clock.on_slot(node.notifier.on_slot)
            node.start_gossip_drain()
            lag_sampler.start()

        # 8. P2P network (TCP + noise + mplex + gossipsub + reqresp)
        if opts.p2p_enabled:
            from lodestar_tpu.network.service import Libp2pBeaconNetwork

            node.network = Libp2pBeaconNetwork(
                node=node,
                chain=chain,
                listen_port=opts.p2p_port,
                bootnodes=opts.bootnodes,
            )
            await node.network.start()
            node.notifier.network = node.network
            # reqresp + router metric bridges (ReqRespMetrics hook; the
            # notifier's per-slot tick snapshots router/peer gauges)
            node.network.reqresp.metrics = metrics.reqresp
        node.log.info(
            f"beacon node up: slot {clock.current_slot}, "
            f"rest {'on :' + str(rest_server.port) if rest_server else 'off'}"
        )
        return node

    async def close(self) -> None:
        """Abort cascade, reverse init order (nodejs.ts:146-152)."""
        if self.network is not None:
            try:
                await self.network.stop()
            except Exception:
                pass
            self.network = None
        if self._drain_task is not None:
            self._drain_task.cancel()
            try:
                await self._drain_task  # let a mid-import handler finish/unwind
            except BaseException:
                pass
            self._drain_task = None
        if self.rest_server is not None:
            self.rest_server.stop()
        if getattr(self, "lag_sampler", None) is not None:
            await self.lag_sampler.stop()
        await self.clock.stop()
        await self.bls.close()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        self.db.close()
