"""Checkpoint sync: initialize a beacon node from a trusted provider's
finalized state instead of replaying from genesis.

Reference `cli/src/cmds/beacon/initBeaconState.ts`
(fetchWeakSubjectivityState: download the finalized state from a
trusted beacon API, verify it is within the weak-subjectivity horizon,
anchor the node on it) — the "wss sync" leg of SURVEY §5
checkpoint/resume.
"""

from __future__ import annotations

from lodestar_tpu.logger import get_logger
from lodestar_tpu.params import BeaconPreset, active_preset
from lodestar_tpu.ssz.json import from_json
from lodestar_tpu.types import ssz_types

__all__ = ["fetch_checkpoint_state", "CheckpointSyncError"]

# ~54 hours of mainnet epochs; the reference computes the period from
# validator counts (mainnet lands around 256-665 epochs) — a fixed
# conservative value keeps the check dependency-free here
DEFAULT_WSS_EPOCHS = 512


class CheckpointSyncError(Exception):
    pass


def fetch_checkpoint_state(
    client,
    *,
    state_id: str = "finalized",
    p: BeaconPreset | None = None,
    current_slot: int | None = None,
    wss_epochs: int = DEFAULT_WSS_EPOCHS,
    allow_stale: bool = False,
):
    """Download + decode the anchor state from a trusted beacon API.

    `client` is any object with `get_debug_state_v2(state_id) -> dict`
    (the BeaconApiClient, or an in-process impl for tests). The state is
    decoded with its own fork's container and gated by the
    weak-subjectivity horizon. The gate is opt-OUT: callers must supply
    `current_slot` (or explicitly pass allow_stale=True) — silently
    skipping the wss check is exactly the long-range-attack door this
    module exists to close."""
    p = p or active_preset()
    log = get_logger(name="lodestar.checkpoint_sync")
    if current_slot is None and not allow_stale:
        raise CheckpointSyncError(
            "current_slot is required for the weak-subjectivity check "
            "(pass allow_stale=True to explicitly skip it)"
        )
    res = client.get_debug_state_v2(state_id)
    if not isinstance(res, dict) or "data" not in res:
        raise CheckpointSyncError(f"malformed state response: {type(res)}")
    fork = res.get("version", "phase0")
    t = ssz_types(p)
    ns = getattr(t, fork, None)
    if ns is None:
        raise CheckpointSyncError(f"unknown fork version {fork!r}")
    try:
        state = from_json(ns.BeaconState, res["data"])
    except (KeyError, ValueError, TypeError) as e:
        raise CheckpointSyncError(f"cannot decode {fork} state: {e}") from e

    if current_slot is not None:
        age_epochs = (int(current_slot) - int(state.slot)) // p.SLOTS_PER_EPOCH
        if age_epochs > wss_epochs:
            raise CheckpointSyncError(
                f"checkpoint state is {age_epochs} epochs old — beyond the "
                f"weak-subjectivity horizon ({wss_epochs}); refusing to anchor"
            )
        if int(state.slot) > int(current_slot):
            raise CheckpointSyncError("checkpoint state is from the future")

    log.info(
        "checkpoint state fetched",
        {"fork": fork, "slot": int(state.slot), "validators": len(state.validators)},
    )
    return state


def load_anchor_state_from_db(db, p: BeaconPreset | None = None, cfg=None):
    """Restart-from-db: the newest archived finalized state in the data
    directory, fork-decoded, or None for a fresh datadir (reference
    `initBeaconState.ts` db branch — mechanism (3) of SURVEY §5
    checkpoint/resume; the archiver wrote these at finalization)."""
    from lodestar_tpu.db import Bucket, Repository
    from lodestar_tpu.ssz import uint64

    p = p or active_preset()
    repo = Repository(db, Bucket.allForks_stateArchive, uint64)  # keys only
    keys = repo.keys()
    if not keys:
        return None
    slot = int.from_bytes(keys[-1], "big")
    raw = repo.get_binary(slot)
    if raw is None:
        return None
    from lodestar_tpu.chain.archiver import decode_archived_state

    t = ssz_types(p)
    state, fork = decode_archived_state(db, t, raw, slot, cfg=cfg, p=p)
    if state is None:
        raise CheckpointSyncError(f"archived state at slot {slot} matches no known fork")
    get_logger(name="lodestar.checkpoint_sync").info(
        "resuming from archived state", {"slot": slot, "fork": fork}
    )
    return state
