"""Node status notifier + process-fault policy (reference
`node/notifier.ts:29` runNodeNotifier and `chain/chain.ts:151`
processShutdownCallback).

`StatusNotifier` logs one human status line per slot (head vs clock,
sync distance, peers, finalized epoch) and warns on low peer count.
`ProcessFaultPolicy` is the abort seam: subsystems report fatal errors
(`on_fatal`), which invoke the node's shutdown callback exactly once —
the reference wires the same callback into the chain so corrupted state
triggers a clean process exit instead of limping on.
"""

from __future__ import annotations

import time

from lodestar_tpu.logger import get_logger

__all__ = ["StatusNotifier", "ProcessFaultPolicy"]

LOW_PEER_COUNT = 3


class ProcessFaultPolicy:
    """Fatal-error funnel: first `on_fatal` fires the shutdown callback
    (reference ProcessShutdownCallback), later ones only log."""

    def __init__(self, shutdown_callback=None):
        self._shutdown = shutdown_callback
        self.fired = False
        self.reason: str | None = None
        self.log = get_logger(name="lodestar.fault")

    def on_fatal(self, subsystem: str, err: BaseException | str) -> None:
        msg = f"fatal error in {subsystem}: {err}"
        if self.fired:
            self.log.error(f"{msg} (shutdown already requested: {self.reason})")
            return
        self.fired = True
        self.reason = msg
        self.log.error(f"{msg} — requesting process shutdown")
        if self._shutdown is not None:
            try:
                self._shutdown(msg)
            except Exception as e:  # the callback must never mask the fault
                self.log.error(f"shutdown callback failed: {e!r}")


class StatusNotifier:
    """Per-slot status line, driven by the node clock."""

    def __init__(self, chain, *, network=None, time_fn=time.monotonic):
        self.chain = chain
        self.network = network
        self._time = time_fn
        self.metrics = getattr(chain, "metrics", None)
        self._last_head_slot = 0
        self._last_t = time_fn()
        self.log = get_logger(name="lodestar.notifier")

    def on_slot(self, clock_slot: int) -> str:
        fc = self.chain.fork_choice
        head = fc.proto_array.get_block(fc.head)
        head_slot = head.slot if head else 0
        skipped = max(0, clock_slot - head_slot)
        peers = len(self.network.host.peers()) if self.network is not None else 0

        now = self._time()
        dt = max(now - self._last_t, 1e-9)
        speed = (head_slot - self._last_head_slot) / dt
        self._last_head_slot, self._last_t = head_slot, now

        if skipped <= 3:
            state = "synced"
        else:
            state = f"syncing ({speed:.2f} slots/s, -{skipped} behind)"
        line = (
            f"{state} - slot: {clock_slot}"
            + (f" (head -{skipped})" if skipped else "")
            + f" - head: {head_slot} {head.block_root[:12] if head else '-'}"
            + f" - finalized: {fc.finalized.epoch}"
            + f" - peers: {peers}"
        )
        self.log.info(line)
        m = self.metrics
        if m is not None:
            m.sync_detail.head_distance.set(skipped)
            m.sync_detail.status.set(2 if skipped <= 3 else (1 if speed > 0 else 0))
            m.peer.peer_count.set(peers)
            if self.network is not None:
                gs = getattr(self.network, "gossip", None)
                if gs is not None:
                    m.gossip_detail.mcache_size.set(
                        sum(len(w) for w in gs.mcache)
                    )
                    for topic, mesh in gs.mesh.items():
                        scores = [gs._score(pid) for pid in mesh] or [0.0]
                        m.gossip_detail.peer_score_by_topic.labels(
                            topic=topic.split("/")[-2] if topic.count("/") >= 3 else topic
                        ).set(sum(scores) / len(scores))
                d5 = getattr(self.network, "discv5", None)
                if d5 is not None:
                    m.peer.discv5_sessions.set(len(getattr(d5, "sessions", {})))
        if self.network is not None and peers < LOW_PEER_COUNT:
            self.log.warn(f"low peer count: {peers}")
        return line
