"""Slot-deadline SLO accounting: remaining-slack stamps on every
verification job.

Everything the fleet measures — launch latency, queue depth, buffer
waits — is throughput telemetry; none of it answers the only question
consensus serving actually asks: *did the verdict land before the slot
deadline?* A verdict 50 ms after the attestation cutoff is a miss no
ops/s line can see (the committee-consensus measurements in PAPERS.md
benchmark signature work against protocol deadlines for exactly this
reason). This module is the seam that relates the two:

* `SlotDeadlineModel` — per-priority-class deadlines anchored at the
  protocol's wall-clock ``genesis_time`` (same slot math as
  ``chain/clock.py``): a gossip block must land by the attestation
  cutoff (1/3 slot), a gossip attestation by the aggregation cutoff
  (2/3 slot), an API submission by end-of-slot, and sync/backfill get
  multi-slot budgets — they have no slot deadline, only an
  "eventually" bound the model makes explicit.
* A process-global accountant (`configure_slo` / `job_begin` /
  `job_flushed` / `job_dequeued` / `job_launch` / `job_verdict`)
  stamping each job's remaining slack at enqueue, dispatch, and
  verdict into the ``lodestar_slo_*`` families: slack histograms by
  class and stage, deadline-miss counters, and good/total SLI pairs
  (the numerator/denominator shape multi-window burn-rate alerts
  consume — see ``tools/gen_alerts.py``).
* A wait-budget profile (`wait_budget`) decomposing each job's life
  into four legs — buffer wait, queue wait, staging, device launch —
  from the accountant's own monotonic stamps, so the legs partition
  the end-to-end span *exactly* by construction. This is the
  machine-readable artifact the ROADMAP's continuous batch former
  consumes (``GET /eth/v0/debug/slo`` / ``tools/wait_budget_profile.py``).

Doctrine (mirrors ``telemetry.py``): stdlib-only, never imports JAX or
chain code, import cost is a few dataclasses. Deadlines are wall-clock
(slots are wall-clock anchored; monotonic has no epoch) but every
*duration* leg uses monotonic stamps — the wall clock never enters a
subtraction between two process-local events. Hot-path cost when
unconfigured: one None check.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

from lodestar_tpu.scheduler import PriorityClass

__all__ = [
    "DEADLINE_FRACTIONS",
    "SLO_STAGES",
    "WAIT_LEGS",
    "SlotDeadlineModel",
    "JobSlo",
    "configure_slo",
    "reset_slo",
    "slo_active",
    "job_begin",
    "job_flushed",
    "job_dequeued",
    "job_launch",
    "job_verdict",
    "slack_ms",
    "wait_budget",
    "debug_view",
    "slow_slot_slack",
]

#: per-class deadline as a fraction of (or multiple of) the slot
#: length, measured from the start of the job's anchor slot. The
#: gossip cutoffs mirror the honest-validator timeline: attesters vote
#: at 1/3 slot (a block verified later missed its attestations),
#: aggregates are due at 2/3 slot. API work is useful until the slot
#: rolls over. Sync/backfill have no protocol deadline; the multi-slot
#: budgets make "eventually" a measurable bound instead of a shrug.
DEADLINE_FRACTIONS: dict[PriorityClass, float] = {
    PriorityClass.GOSSIP_BLOCK: 1.0 / 3.0,
    PriorityClass.GOSSIP_ATTESTATION: 2.0 / 3.0,
    PriorityClass.API: 1.0,
    PriorityClass.RANGE_SYNC: 8.0,
    PriorityClass.BACKFILL: 32.0,
}

#: lifecycle stages a slack sample is labelled with
SLO_STAGES = ("enqueue", "dispatch", "verdict")

#: the four legs that partition added→verdict (see `wait_budget`)
WAIT_LEGS = ("buffer", "queue", "stage", "launch")

#: ring depth per (class, leg) quantile window — enough for stable
#: p99 at steady state, bounded so an idle class costs nothing
_SAMPLE_WINDOW = 512

_NS = 1e-9


class SlotDeadlineModel:
    """Genesis-anchored per-class deadlines (``chain/clock.py`` math).

    The injectable ``time_fn`` keeps every test deterministic; the
    wall clock is read through it exclusively.
    """

    def __init__(
        self,
        *,
        genesis_time: float,
        seconds_per_slot: int,
        slots_per_epoch: int = 32,
        time_fn: Callable[[], float] = time.time,
    ) -> None:
        if seconds_per_slot <= 0:
            raise ValueError(f"seconds_per_slot must be positive, got {seconds_per_slot}")
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self.slots_per_epoch = slots_per_epoch
        self._time = time_fn

    def now(self) -> float:
        return self._time()

    @property
    def current_slot(self) -> int:
        return max(0, int(self._time() - self.genesis_time) // self.seconds_per_slot)

    def time_at_slot(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def deadline_for(self, cls: PriorityClass, slot: int | None = None) -> float:
        """Absolute wall-clock deadline for `cls` work anchored at
        `slot` (the job's subject slot — a block's slot, not the slot
        the work happened to arrive in). ``slot=None`` anchors at the
        current slot, the right call for work with no subject slot
        (API batches, attestation aggregates)."""
        anchor = self.current_slot if slot is None else slot
        return self.time_at_slot(anchor) + DEADLINE_FRACTIONS[cls] * self.seconds_per_slot

    def slack_s(self, cls: PriorityClass, slot: int | None = None, now: float | None = None) -> float:
        """Remaining slack in seconds (negative = past the deadline)."""
        t = self._time() if now is None else now
        return self.deadline_for(cls, slot) - t


class JobSlo:
    """Per-job slack/leg ledger: monotonic stamps at each lifecycle
    edge plus the absolute deadline frozen at enqueue (so every stage
    measures against the same anchor). `done` makes verdict recording
    idempotent — a job future resolves once, but belt and braces."""

    __slots__ = (
        "cls",
        "slot",
        "deadline_s",
        "t_added_ns",
        "t_flush_ns",
        "t_dequeue_ns",
        "t_launch_ns",
        "queue_wait_ns",
        "done",
    )

    def __init__(self, cls: PriorityClass, slot: int | None, deadline_s: float, now_ns: int):
        self.cls = cls
        self.slot = slot
        self.deadline_s = deadline_s
        self.t_added_ns = now_ns
        # unbuffered jobs never flush: the buffer leg collapses to 0
        self.t_flush_ns = now_ns
        self.t_dequeue_ns = now_ns
        self.t_launch_ns = now_ns
        self.queue_wait_ns = 0
        self.done = False


class _SloAccountant:
    """Process-global slack/SLI/wait-budget state behind one lock.

    All mutation paths are O(1) appends/increments; the quantile fold
    happens only when a debug endpoint or profiler asks."""

    def __init__(self) -> None:
        self.model: SlotDeadlineModel | None = None
        self.metrics = None  # SloMetrics | None
        self.slack_floor_s = 0.0
        # injectable monotonic source so the chaos harness can stamp
        # job legs on virtual time (SimClock.monotonic_ns)
        self.monotonic_ns: Callable[[], int] = time.monotonic_ns
        self._lock = threading.Lock()
        # (class, leg) -> ring of leg durations (seconds)
        self._legs: dict[tuple[PriorityClass, str], deque] = {}
        # class -> ring of end-to-end durations (seconds)
        self._e2e: dict[PriorityClass, deque] = {}
        # class -> ring of verdict-stage slack samples (seconds)
        self._slack: dict[PriorityClass, deque] = {}
        self._good: dict[PriorityClass, int] = {c: 0 for c in PriorityClass}
        self._total: dict[PriorityClass, int] = {c: 0 for c in PriorityClass}
        self._miss: dict[PriorityClass, int] = {c: 0 for c in PriorityClass}

    def _ring(self, table: dict, key) -> deque:
        ring = table.get(key)
        if ring is None:
            ring = table[key] = deque(maxlen=_SAMPLE_WINDOW)
        return ring

    def observe_slack(self, cls: PriorityClass, stage: str, slack_s: float) -> None:
        if self.metrics is not None:
            self.metrics.slack_seconds.labels(cls.label, stage).observe(slack_s)

    def record_verdict(self, js: JobSlo, ok: bool, now_ns: int, slack_s: float) -> None:
        with self._lock:
            if js.done:
                return
            js.done = True
            cls = js.cls
            self._ring(self._legs, (cls, "buffer")).append(
                max(0, js.t_flush_ns - js.t_added_ns) * _NS
            )
            self._ring(self._legs, (cls, "queue")).append(
                max(0, js.t_dequeue_ns - js.t_flush_ns) * _NS
            )
            self._ring(self._legs, (cls, "stage")).append(
                max(0, js.t_launch_ns - js.t_dequeue_ns) * _NS
            )
            self._ring(self._legs, (cls, "launch")).append(
                max(0, now_ns - js.t_launch_ns) * _NS
            )
            self._ring(self._e2e, cls).append(max(0, now_ns - js.t_added_ns) * _NS)
            self._ring(self._slack, cls).append(slack_s)
            met = slack_s >= self.slack_floor_s
            self._total[cls] += 1
            if ok and met:
                self._good[cls] += 1
            if not met:
                self._miss[cls] += 1
        m = self.metrics
        if m is not None:
            m.slack_seconds.labels(cls.label, "verdict").observe(slack_s)
            m.sli_total.labels(cls.label).inc()
            if ok and met:
                m.sli_good.labels(cls.label).inc()
            if not met:
                m.deadline_miss.labels(cls.label).inc()

    # -- read side ------------------------------------------------------------

    def wait_budget(self) -> dict:
        """Per-class latency decomposition: quantiles for each leg and
        end-to-end, plus the SLI counters. The four legs share stamp
        pairs with end-to-end (buffer+queue+stage+launch telescopes to
        verdict-added), so a mean leg sum matches the mean end-to-end
        span up to ring-window skew."""
        model = self.model
        out: dict = {
            "enabled": model is not None,
            "slack_floor_ms": self.slack_floor_s * 1000.0,
            "deadline_model": None,
            "classes": {},
        }
        if model is not None:
            out["deadline_model"] = {
                "genesis_time": model.genesis_time,
                "seconds_per_slot": model.seconds_per_slot,
                "slots_per_epoch": model.slots_per_epoch,
                "deadline_fractions": {
                    c.label: DEADLINE_FRACTIONS[c] for c in PriorityClass
                },
            }
        with self._lock:
            for cls in PriorityClass:
                if self._total[cls] == 0 and cls not in self._e2e:
                    continue
                legs = {
                    leg: _quantiles(self._legs.get((cls, leg)))
                    for leg in WAIT_LEGS
                }
                out["classes"][cls.label] = {
                    "legs": legs,
                    "end_to_end": _quantiles(self._e2e.get(cls)),
                    "leg_sum_mean_ms": round(
                        sum(legs[leg]["mean_ms"] for leg in WAIT_LEGS), 4
                    ),
                    "slack": _quantiles(self._slack.get(cls), unit_ms=False),
                    "sli": {
                        "good": self._good[cls],
                        "total": self._total[cls],
                        "miss": self._miss[cls],
                    },
                }
        return out

    def slow_slot_slack(self) -> dict:
        """Per-class remaining slack right now — the snapshot a slow-slot
        dump embeds so 'did we still make the deadline' needs no
        metrics query."""
        model = self.model
        if model is None:
            return {}
        slot = model.current_slot
        now = model.now()
        return {
            "slot": slot,
            "slack_s": {
                c.label: round(model.slack_s(c, slot, now), 4) for c in PriorityClass
            },
        }


def _quantiles(ring: deque | None, unit_ms: bool = True) -> dict:
    scale = 1000.0 if unit_ms else 1.0
    suffix = "_ms" if unit_ms else "_s"
    if not ring:
        return {f"p50{suffix}": 0.0, f"p90{suffix}": 0.0, f"p99{suffix}": 0.0,
                f"mean{suffix}": 0.0, "count": 0}
    xs = sorted(ring)
    n = len(xs)

    def q(p: float) -> float:
        return round(xs[min(n - 1, int(p * n))] * scale, 4)

    return {
        f"p50{suffix}": q(0.50),
        f"p90{suffix}": q(0.90),
        f"p99{suffix}": q(0.99),
        f"mean{suffix}": round(sum(xs) / n * scale, 4),
        "count": n,
    }


_ACCT = _SloAccountant()


def configure_slo(
    *,
    enabled: bool = True,
    genesis_time: float | None = None,
    seconds_per_slot: int = 12,
    slots_per_epoch: int = 32,
    metrics=None,
    slack_floor_ms: float = 0.0,
    time_fn: Callable[[], float] = time.time,
    monotonic_ns_fn: Callable[[], int] = time.monotonic_ns,
) -> None:
    """(Re)configure the process-global accountant. `metrics` is a
    `SloMetrics` dataclass (or None to keep slack accounting local).
    Disabled or genesis-less: every job hook degrades to a single None
    check. `monotonic_ns_fn` pairs with `time_fn` when the caller runs
    on virtual time (chaos harness): wall-clock deadlines and job-leg
    stamps must advance together or leg durations go negative."""
    if enabled and genesis_time is not None:
        _ACCT.model = SlotDeadlineModel(
            genesis_time=genesis_time,
            seconds_per_slot=seconds_per_slot,
            slots_per_epoch=slots_per_epoch,
            time_fn=time_fn,
        )
    else:
        _ACCT.model = None
    _ACCT.metrics = metrics
    _ACCT.slack_floor_s = slack_floor_ms / 1000.0
    _ACCT.monotonic_ns = monotonic_ns_fn


def reset_slo() -> None:
    """Test isolation: drop the model, metrics binding, and all rings."""
    _ACCT.model = None
    _ACCT.metrics = None
    _ACCT.slack_floor_s = 0.0
    _ACCT.monotonic_ns = time.monotonic_ns
    with _ACCT._lock:
        _ACCT._legs.clear()
        _ACCT._e2e.clear()
        _ACCT._slack.clear()
        for c in PriorityClass:
            _ACCT._good[c] = 0
            _ACCT._total[c] = 0
            _ACCT._miss[c] = 0


def slo_active() -> bool:
    return _ACCT.model is not None


# -- per-job lifecycle hooks (pool-facing) ------------------------------------


def job_begin(priority: PriorityClass, slot: int | None = None) -> JobSlo | None:
    """Called at enqueue. Freezes the job's absolute deadline (anchored
    at the subject `slot` when the caller knows it) and records the
    enqueue-stage slack. Returns None when the accountant is inactive —
    the None is the whole disabled-path cost."""
    model = _ACCT.model
    if model is None:
        return None
    cls = PriorityClass(priority)
    deadline = model.deadline_for(cls, slot)
    js = JobSlo(cls, slot, deadline, _ACCT.monotonic_ns())
    _ACCT.observe_slack(cls, "enqueue", deadline - model.now())
    return js


def job_flushed(js: JobSlo | None) -> None:
    """Batchable job left the accumulation buffer for the queue."""
    if js is not None:
        js.t_flush_ns = _ACCT.monotonic_ns()


def job_dequeued(js: JobSlo | None, waited_ns: int = 0) -> None:
    """Scheduler handed the job to a worker: dispatch-stage slack."""
    if js is None:
        return
    js.t_dequeue_ns = _ACCT.monotonic_ns()
    js.queue_wait_ns = waited_ns
    model = _ACCT.model
    if model is not None:
        _ACCT.observe_slack(js.cls, "dispatch", js.deadline_s - model.now())


def job_launch(js: JobSlo | None) -> None:
    """Staging done, device launch starting."""
    if js is not None:
        js.t_launch_ns = _ACCT.monotonic_ns()


def job_verdict(js: JobSlo | None, ok: bool) -> None:
    """Job future resolved (exactly once per job — the caller hooks the
    future's done-callback, which fires once regardless of how many
    batch retries the verdict took). `ok=False` covers both invalid
    signatures and rejected jobs; cancellation should not reach here."""
    if js is None:
        return
    model = _ACCT.model
    slack = (js.deadline_s - model.now()) if model is not None else 0.0
    _ACCT.record_verdict(js, ok, _ACCT.monotonic_ns(), slack)


# -- span/dump helpers ---------------------------------------------------------


def slack_ms(priority: PriorityClass, slot: int | None = None) -> float | None:
    """Remaining slack in ms for span attributes; None when inactive."""
    model = _ACCT.model
    if model is None:
        return None
    return round(model.slack_s(PriorityClass(priority), slot) * 1000.0, 3)


def wait_budget() -> dict:
    """The machine-readable per-class wait-budget profile (see
    `_SloAccountant.wait_budget`)."""
    return _ACCT.wait_budget()


def debug_view() -> dict:
    """`GET /eth/v0/debug/slo` payload: the wait budget plus the live
    slack snapshot."""
    out = _ACCT.wait_budget()
    out["now"] = _ACCT.slow_slot_slack()
    return out


def slow_slot_slack() -> dict:
    """Per-class remaining slack at call time (slow-slot dump payload);
    empty dict when inactive."""
    return _ACCT.slow_slot_slack()
