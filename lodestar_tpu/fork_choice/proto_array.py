"""Proto-array LMD-GHOST fork choice.

Counterpart of the reference `fork-choice/src/protoArray/protoArray.ts`
(`applyScoreChanges` :83, `findHead` :447, `nodeIsViableForHead` :725) and
`computeDeltas.ts`. Same flat-array design — children always appear after
parents, so one backwards sweep both applies deltas and back-propagates
them, and a second sweep repairs best-child/best-descendant links.

TPU-first deviation: `compute_deltas` is vectorized. Votes live in numpy
arrays (per-validator interned root ids) and the per-validator loop the
reference runs over ~1M validators becomes two `np.bincount` scatter-adds
— the same O(V) work at C speed, and the natural stepping stone to a
device-resident version if head recomputation ever dominates.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "ExecutionStatus",
    "ProtoBlock",
    "ProtoNode",
    "ProtoArray",
    "VoteTracker",
    "compute_deltas",
    "HEX_ZERO_HASH",
]

HEX_ZERO_HASH = "0x" + "00" * 32
DEFAULT_PRUNE_THRESHOLD = 0


class ExecutionStatus(enum.Enum):
    PRE_MERGE = "PreMerge"
    SYNCING = "Syncing"
    VALID = "Valid"
    INVALID = "Invalid"


@dataclass
class ProtoBlock:
    """Summary of a block for fork choice (reference `interface.ts` ProtoBlock)."""

    slot: int
    block_root: str
    parent_root: str
    state_root: str
    target_root: str
    justified_epoch: int
    justified_root: str
    finalized_epoch: int
    finalized_root: str
    unrealized_justified_epoch: int = 0
    unrealized_justified_root: str = HEX_ZERO_HASH
    unrealized_finalized_epoch: int = 0
    unrealized_finalized_root: str = HEX_ZERO_HASH
    execution_payload_block_hash: str | None = None
    execution_status: ExecutionStatus = ExecutionStatus.PRE_MERGE


@dataclass
class ProtoNode(ProtoBlock):
    parent: int | None = None
    weight: int = 0
    best_child: int | None = None
    best_descendant: int | None = None


class ProtoArrayError(Exception):
    pass


class ProtoArray:
    def __init__(
        self,
        *,
        justified_epoch: int,
        justified_root: str,
        finalized_epoch: int,
        finalized_root: str,
        slots_per_epoch: int,
        prune_threshold: int = DEFAULT_PRUNE_THRESHOLD,
    ) -> None:
        self.prune_threshold = prune_threshold
        self.justified_epoch = justified_epoch
        self.justified_root = justified_root
        self.finalized_epoch = finalized_epoch
        self.finalized_root = finalized_root
        self.slots_per_epoch = slots_per_epoch
        self.nodes: list[ProtoNode] = []
        self.indices: dict[str, int] = {}
        self._previous_proposer_boost: tuple[str, int] | None = None

    # -- construction ---------------------------------------------------------

    @classmethod
    def initialize(cls, block: ProtoBlock, current_slot: int, slots_per_epoch: int) -> "ProtoArray":
        arr = cls(
            justified_epoch=block.justified_epoch,
            justified_root=block.justified_root,
            finalized_epoch=block.finalized_epoch,
            finalized_root=block.finalized_root,
            slots_per_epoch=slots_per_epoch,
        )
        anchor = ProtoNode(**{**vars(block), "target_root": block.block_root})
        arr.on_block(anchor, current_slot)
        return arr

    def on_block(self, block: ProtoBlock, current_slot: int) -> None:
        """Insert a block (reference `onBlock` :197). Ignores known roots;
        rejects Invalid execution status outright."""
        if block.block_root in self.indices:
            return
        if block.execution_status is ExecutionStatus.INVALID:
            raise ProtoArrayError(f"onBlock with invalid execution status: {block.block_root}")

        node = ProtoNode(**vars(block))
        node.parent = self.indices.get(block.parent_root)
        node.weight = 0
        node.best_child = None
        node.best_descendant = None

        node_index = len(self.nodes)
        self.indices[node.block_root] = node_index
        self.nodes.append(node)

        parent_index = node.parent
        if node.execution_status is ExecutionStatus.VALID and parent_index is not None:
            self._propagate_valid_execution(parent_index)

        idx = node_index
        while parent_index is not None:
            self._maybe_update_best_child_and_descendant(parent_index, idx, current_slot)
            idx = parent_index
            parent_index = self.nodes[idx].parent

    # -- scoring --------------------------------------------------------------

    def apply_score_changes(
        self,
        *,
        deltas: list[int],
        proposer_boost: tuple[str, int] | None,
        justified_epoch: int,
        justified_root: str,
        finalized_epoch: int,
        finalized_root: str,
        current_slot: int,
    ) -> None:
        """Reference `applyScoreChanges` (:83): one backwards sweep applies
        deltas + proposer boost and back-propagates into parent deltas; a
        second sweep repairs best-child/descendant links."""
        if len(deltas) != len(self.indices):
            raise ProtoArrayError(f"invalid delta length {len(deltas)} != {len(self.indices)}")

        self.justified_epoch = justified_epoch
        self.justified_root = justified_root
        self.finalized_epoch = finalized_epoch
        self.finalized_root = finalized_root

        for node_index in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[node_index]
            if node.block_root == HEX_ZERO_HASH:
                continue
            current_boost = (
                proposer_boost[1]
                if proposer_boost is not None and proposer_boost[0] == node.block_root
                else 0
            )
            previous_boost = (
                self._previous_proposer_boost[1]
                if self._previous_proposer_boost is not None
                and self._previous_proposer_boost[0] == node.block_root
                else 0
            )
            if node.execution_status is ExecutionStatus.INVALID:
                node_delta = -node.weight
            else:
                node_delta = deltas[node_index] + current_boost - previous_boost

            node.weight += node_delta
            if node.parent is not None:
                deltas[node.parent] += node_delta

        for node_index in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[node_index]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(node.parent, node_index, current_slot)

        self._previous_proposer_boost = proposer_boost

    # -- head -----------------------------------------------------------------

    def find_head(self, justified_root: str, current_slot: int) -> str:
        """Follow best-descendant from the justified node (reference :447)."""
        justified_index = self.indices.get(justified_root)
        if justified_index is None:
            raise ProtoArrayError(f"justified node unknown: {justified_root}")
        justified_node = self.nodes[justified_index]
        if justified_node.execution_status is ExecutionStatus.INVALID:
            raise ProtoArrayError("justified node has invalid execution status")

        best_descendant_index = (
            justified_node.best_descendant
            if justified_node.best_descendant is not None
            else justified_index
        )
        best_node = self.nodes[best_descendant_index]
        if best_descendant_index != justified_index and not self._node_is_viable_for_head(
            best_node, current_slot
        ):
            raise ProtoArrayError(
                f"invalid best node {best_node.block_root} from justified {justified_root}"
            )
        return best_node.block_root

    # -- pruning --------------------------------------------------------------

    def maybe_prune(self, finalized_root: str) -> list[ProtoNode]:
        """Drop all nodes before the finalized one (reference :511)."""
        finalized_index = self.indices.get(finalized_root)
        if finalized_index is None:
            raise ProtoArrayError(f"finalized node unknown: {finalized_root}")
        if finalized_index < self.prune_threshold:
            return []

        for node in self.nodes[:finalized_index]:
            del self.indices[node.block_root]
        removed = self.nodes[:finalized_index]
        self.nodes = self.nodes[finalized_index:]
        for key in self.indices:
            self.indices[key] -= finalized_index
        for node in self.nodes:
            if node.parent is not None:
                node.parent = None if node.parent < finalized_index else node.parent - finalized_index
            if node.best_child is not None:
                node.best_child -= finalized_index
            if node.best_descendant is not None:
                node.best_descendant -= finalized_index
        return removed

    # -- execution status -----------------------------------------------------

    def _propagate_valid_execution(self, start_index: int) -> None:
        idx: int | None = start_index
        while idx is not None:
            node = self.nodes[idx]
            if node.execution_status in (ExecutionStatus.PRE_MERGE, ExecutionStatus.VALID):
                break
            if node.execution_status is ExecutionStatus.INVALID:
                raise ProtoArrayError(
                    f"consensus failure: valid descendant of invalid block {node.block_root}"
                )
            node.execution_status = ExecutionStatus.VALID
            idx = node.parent

    def invalidate(self, block_root: str, current_slot: int) -> None:
        """Mark a node invalid; descendants become invalid via the
        -weight rule on the next apply_score_changes, and best-child links
        are repaired immediately."""
        idx = self.indices.get(block_root)
        if idx is None:
            raise ProtoArrayError(f"unknown block to invalidate: {block_root}")
        node = self.nodes[idx]
        if node.execution_status is ExecutionStatus.PRE_MERGE:
            raise ProtoArrayError("cannot invalidate a pre-merge block")
        node.execution_status = ExecutionStatus.INVALID
        node.best_child = None
        node.best_descendant = None
        # descendants of an invalid payload are invalid too
        for i in range(idx + 1, len(self.nodes)):
            n = self.nodes[i]
            p = n.parent
            if p is not None and self.nodes[p].execution_status is ExecutionStatus.INVALID:
                n.execution_status = ExecutionStatus.INVALID
                n.best_child = None
                n.best_descendant = None
        for i in range(len(self.nodes) - 1, -1, -1):
            n = self.nodes[i]
            if n.parent is not None:
                self._maybe_update_best_child_and_descendant(n.parent, i, current_slot)

    # -- internals ------------------------------------------------------------

    def _maybe_update_best_child_and_descendant(
        self, parent_index: int, child_index: int, current_slot: int
    ) -> None:
        child = self.nodes[child_index]
        parent = self.nodes[parent_index]
        child_viable = self._node_leads_to_viable_head(child, current_slot)

        change_to_child = (
            child_index,
            child.best_descendant if child.best_descendant is not None else child_index,
        )
        no_change = (parent.best_child, parent.best_descendant)

        best_child_index = parent.best_child
        if best_child_index is not None:
            if best_child_index == child_index and not child_viable:
                new = (None, None)
            elif best_child_index == child_index:
                new = change_to_child
            else:
                best_child = self.nodes[best_child_index]
                best_viable = self._node_leads_to_viable_head(best_child, current_slot)
                if child_viable and not best_viable:
                    new = change_to_child
                elif not child_viable and best_viable:
                    new = no_change
                elif child.weight == best_child.weight:
                    # equal-weight tie broken by root ordering (reference :668)
                    new = change_to_child if child.block_root >= best_child.block_root else no_change
                else:
                    new = change_to_child if child.weight >= best_child.weight else no_change
        elif child_viable:
            new = change_to_child
        else:
            new = no_change

        parent.best_child, parent.best_descendant = new

    def _node_leads_to_viable_head(self, node: ProtoNode, current_slot: int) -> bool:
        if node.best_descendant is not None:
            if self._node_is_viable_for_head(self.nodes[node.best_descendant], current_slot):
                return True
        return self._node_is_viable_for_head(node, current_slot)

    def _node_is_viable_for_head(self, node: ProtoNode, current_slot: int) -> bool:
        """`filter_block_tree` equivalent (reference :725): voting-source
        justification check (unrealized for previous-epoch blocks) +
        finalized-ancestor check."""
        if node.execution_status is ExecutionStatus.INVALID:
            return False
        current_epoch = current_slot // self.slots_per_epoch
        previous_epoch = current_epoch - 1
        is_from_prev_epoch = node.slot // self.slots_per_epoch < current_epoch
        voting_source_epoch = (
            node.unrealized_justified_epoch if is_from_prev_epoch else node.justified_epoch
        )
        correct_justified = voting_source_epoch == self.justified_epoch or self.justified_epoch == 0
        if not correct_justified and current_epoch > 0 and self.justified_epoch == previous_epoch:
            correct_justified = (
                node.unrealized_justified_epoch >= previous_epoch
                and voting_source_epoch + 2 >= current_epoch
            )
        finalized_slot = self.finalized_epoch * self.slots_per_epoch
        correct_finalized = (
            self.finalized_epoch == 0
            or self.finalized_root == self._ancestor_or_none(node.block_root, finalized_slot)
        )
        return correct_justified and correct_finalized

    def _ancestor_or_none(self, block_root: str, ancestor_slot: int) -> str | None:
        idx = self.indices.get(block_root)
        if idx is None:
            return None
        node = self.nodes[idx]
        while node.slot > ancestor_slot:
            if node.parent is None:
                return None
            node = self.nodes[node.parent]
        return node.block_root

    def get_ancestor(self, block_root: str, ancestor_slot: int) -> str:
        out = self._ancestor_or_none(block_root, ancestor_slot)
        if out is None:
            raise ProtoArrayError(f"ancestor of {block_root} at slot {ancestor_slot} unknown")
        return out

    def has_block(self, block_root: str) -> bool:
        return block_root in self.indices

    def get_block(self, block_root: str) -> ProtoNode | None:
        idx = self.indices.get(block_root)
        return self.nodes[idx] if idx is not None else None

    def __len__(self) -> int:
        return len(self.nodes)


class VoteTracker:
    """Per-validator LMD votes as numpy arrays of interned root ids.

    The reference keeps `VoteTracker[]` objects (`interface.ts:10-14`);
    here current/next root ids and next-vote epochs are flat int64 arrays
    so `compute_deltas` can scatter-add with bincount instead of looping
    validators in the interpreter.
    """

    def __init__(self) -> None:
        self._root_ids: dict[str, int] = {HEX_ZERO_HASH: 0}
        self._roots: list[str] = [HEX_ZERO_HASH]
        self.current = np.zeros(0, dtype=np.int64)  # root id voted (applied)
        self.next = np.zeros(0, dtype=np.int64)  # root id voted (pending)
        self.next_epoch = np.zeros(0, dtype=np.int64)
        self.equivocating = np.zeros(0, dtype=bool)

    def _intern(self, root: str) -> int:
        rid = self._root_ids.get(root)
        if rid is None:
            rid = len(self._roots)
            self._root_ids[root] = rid
            self._roots.append(root)
        return rid

    def _grow(self, n: int) -> None:
        if n <= len(self.current):
            return
        pad = n - len(self.current)
        self.current = np.concatenate([self.current, np.zeros(pad, dtype=np.int64)])
        self.next = np.concatenate([self.next, np.zeros(pad, dtype=np.int64)])
        self.next_epoch = np.concatenate([self.next_epoch, np.zeros(pad, dtype=np.int64)])
        self.equivocating = np.concatenate([self.equivocating, np.zeros(pad, dtype=bool)])

    def process_attestation(self, validator_index: int, block_root: str, target_epoch: int) -> None:
        """Update the pending vote if newer (reference forkChoice.ts
        onAttestation → votes[i].nextRoot/nextEpoch update)."""
        self._grow(validator_index + 1)
        if self.equivocating[validator_index]:
            return
        if target_epoch > self.next_epoch[validator_index] or self.next[validator_index] == 0:
            self.next[validator_index] = self._intern(block_root)
            self.next_epoch[validator_index] = target_epoch

    def mark_equivocation(self, validator_index: int) -> None:
        self._grow(validator_index + 1)
        self.equivocating[validator_index] = True

    def root_of(self, rid: int) -> str:
        return self._roots[rid]


def compute_deltas(
    indices: dict[str, int],
    votes: VoteTracker,
    old_balances: np.ndarray,
    new_balances: np.ndarray,
) -> list[int]:
    """Vectorized `computeDeltas.ts`: one delta per proto node.

    Two bincount scatter-adds replace the per-validator loop; vote state
    transitions (equivocation zeroing, current←next) are applied with
    boolean masks. Semantics match the reference exactly, including
    processing each equivocating validator only once.
    """
    n_nodes = len(indices)
    n = len(votes.current)
    deltas = np.zeros(n_nodes, dtype=np.int64)
    if n == 0:
        return deltas.tolist()

    # map interned root ids -> node indices (-1 = unknown/pruned)
    id_to_node = np.full(len(votes._roots), -1, dtype=np.int64)
    for root, node_idx in indices.items():
        rid = votes._root_ids.get(root)
        if rid is not None:
            id_to_node[rid] = node_idx

    old_b = np.zeros(n, dtype=np.int64)
    old_b[: min(n, len(old_balances))] = old_balances[: min(n, len(old_balances))]
    new_b = np.zeros(n, dtype=np.int64)
    new_b[: min(n, len(new_balances))] = new_balances[: min(n, len(new_balances))]

    cur, nxt = votes.current, votes.next
    active = ~((cur == 0) & (nxt == 0))

    # rid 0 is the zero-hash alias for genesis: never scored (reference
    # checks `currentRoot !== zeroHash` explicitly)
    id_to_node[0] = -1

    # equivocating validators: remove their current vote once, then zero it
    equiv = votes.equivocating & active
    eq_nodes = id_to_node[cur[equiv]]
    eq_known = eq_nodes >= 0
    np.subtract.at(deltas, eq_nodes[eq_known], old_b[equiv][eq_known])
    cur = cur.copy()
    cur[equiv] = 0

    # regular vote/balance changes
    changed = active & ~equiv & ((cur != nxt) | (old_b != new_b))
    c_nodes = id_to_node[cur[changed]]
    c_known = c_nodes >= 0
    np.subtract.at(deltas, c_nodes[c_known], old_b[changed][c_known])
    n_nodes_idx = id_to_node[nxt[changed]]
    n_known = n_nodes_idx >= 0
    np.add.at(deltas, n_nodes_idx[n_known], new_b[changed][n_known])

    # commit vote state: current <- next for all processed votes
    cur[changed] = nxt[changed]
    votes.current = cur
    return deltas.tolist()
