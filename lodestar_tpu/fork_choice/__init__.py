"""LMD-GHOST fork choice (reference `packages/fork-choice/src`).

`ForkChoice` wraps the proto-array with the store state the spec calls
`Store`: justified/finalized checkpoints + balances, per-validator votes,
queued future-slot attestations, equivocations, proposer boost
(reference `forkChoice/forkChoice.ts:67`). Head recomputation =
`compute_deltas` (vectorized) + `apply_score_changes` + `find_head`.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from lodestar_tpu import tracing

from .proto_array import (  # noqa: F401
    DEFAULT_PRUNE_THRESHOLD,
    ExecutionStatus,
    HEX_ZERO_HASH,
    ProtoArray,
    ProtoArrayError,
    ProtoBlock,
    ProtoNode,
    VoteTracker,
    compute_deltas,
)

__all__ = [
    "Checkpoint",
    "ForkChoice",
    "ForkChoiceError",
    "ProtoArray",
    "ProtoArrayError",
    "ProtoBlock",
    "ProtoNode",
    "ExecutionStatus",
    "VoteTracker",
    "compute_deltas",
    "HEX_ZERO_HASH",
]

# spec constant: proposer boost as % of the committee weight per slot
PROPOSER_SCORE_BOOST = 40


class ForkChoiceError(Exception):
    pass


@dataclass(frozen=True)
class Checkpoint:
    epoch: int
    root: str


@dataclass
class QueuedAttestation:
    slot: int
    attesting_indices: tuple[int, ...]
    block_root: str
    target_epoch: int


class ForkChoice:
    """Reference `ForkChoice` (`forkChoice.ts:67`), reduced to the store +
    vote machinery (the state-transition hooks land with the STF layer)."""

    def __init__(
        self,
        proto_array: ProtoArray,
        *,
        current_slot: int,
        justified_checkpoint: Checkpoint,
        finalized_checkpoint: Checkpoint,
        justified_balances: np.ndarray,
        slots_per_epoch: int,
    ) -> None:
        self.proto_array = proto_array
        self.votes = VoteTracker()
        self.queued_attestations: list[QueuedAttestation] = []
        self.current_slot = current_slot
        self.justified = justified_checkpoint
        self.finalized = finalized_checkpoint
        self.justified_balances = np.asarray(justified_balances, dtype=np.int64)
        self._old_balances = self.justified_balances
        self.slots_per_epoch = slots_per_epoch
        self.proposer_boost_root: str | None = None
        self._head: str | None = None

    @classmethod
    def from_anchor(
        cls,
        anchor: ProtoBlock,
        *,
        current_slot: int,
        justified_balances: np.ndarray,
        slots_per_epoch: int,
    ) -> "ForkChoice":
        arr = ProtoArray.initialize(anchor, current_slot, slots_per_epoch)
        return cls(
            arr,
            current_slot=current_slot,
            justified_checkpoint=Checkpoint(anchor.justified_epoch, anchor.justified_root),
            finalized_checkpoint=Checkpoint(anchor.finalized_epoch, anchor.finalized_root),
            justified_balances=justified_balances,
            slots_per_epoch=slots_per_epoch,
        )

    # -- clock ---------------------------------------------------------------

    def on_tick(self, slot: int) -> None:
        """Advance the store clock; drain queued attestations whose slot is
        now in the past; clear proposer boost at slot boundaries."""
        if slot < self.current_slot:
            raise ForkChoiceError("clock must not go backwards")
        if slot != self.current_slot:
            self.proposer_boost_root = None
        self.current_slot = slot
        ready = [a for a in self.queued_attestations if a.slot < slot]
        self.queued_attestations = [a for a in self.queued_attestations if a.slot >= slot]
        for att in ready:
            for vi in att.attesting_indices:
                self.votes.process_attestation(vi, att.block_root, att.target_epoch)

    # -- inputs ---------------------------------------------------------------

    def on_block(
        self,
        block: ProtoBlock,
        *,
        is_timely: bool = False,
        justified_checkpoint: Checkpoint | None = None,
        finalized_checkpoint: Checkpoint | None = None,
        justified_balances: np.ndarray | None = None,
    ) -> None:
        """Insert a (fully verified) block. Updates store checkpoints if
        the block's state advanced them (the STF supplies them)."""
        if not self.proto_array.has_block(block.parent_root):
            raise ForkChoiceError(f"unknown parent {block.parent_root}")
        self.proto_array.on_block(block, self.current_slot)
        if is_timely and block.slot == self.current_slot:
            self.proposer_boost_root = block.block_root
        if justified_checkpoint and justified_checkpoint.epoch > self.justified.epoch:
            self.justified = justified_checkpoint
            if justified_balances is not None:
                self._old_balances = self.justified_balances
                self.justified_balances = np.asarray(justified_balances, dtype=np.int64)
        if finalized_checkpoint and finalized_checkpoint.epoch > self.finalized.epoch:
            self.finalized = finalized_checkpoint

    def on_attestation(
        self, attesting_indices: list[int], block_root: str, target_epoch: int, slot: int
    ) -> None:
        """LMD vote registration (reference `onAttestation` :483); future-
        slot attestations queue until their slot passes."""
        if block_root == HEX_ZERO_HASH:
            return
        if slot < self.current_slot:
            for vi in attesting_indices:
                if not (vi < len(self.votes.equivocating) and self.votes.equivocating[vi]):
                    self.votes.process_attestation(vi, block_root, target_epoch)
        else:
            self.queued_attestations.append(
                QueuedAttestation(slot, tuple(attesting_indices), block_root, target_epoch)
            )

    def on_attester_slashing(self, attesting_indices_intersection: list[int]) -> None:
        for vi in attesting_indices_intersection:
            self.votes.mark_equivocation(vi)

    # -- head -----------------------------------------------------------------

    def update_head(self) -> str:
        """Recompute and return the canonical head root."""
        with tracing.span("find_head") as sp:
            boost = None
            if self.proposer_boost_root is not None:
                committee_weight = int(self.justified_balances.sum()) // self.slots_per_epoch
                boost = (self.proposer_boost_root, committee_weight * PROPOSER_SCORE_BOOST // 100)
            deltas = compute_deltas(
                self.proto_array.indices, self.votes, self._old_balances, self.justified_balances
            )
            self._old_balances = self.justified_balances
            self.proto_array.apply_score_changes(
                deltas=deltas,
                proposer_boost=boost,
                justified_epoch=self.justified.epoch,
                justified_root=self.justified.root,
                finalized_epoch=self.finalized.epoch,
                finalized_root=self.finalized.root,
                current_slot=self.current_slot,
            )
            self._head = self.proto_array.find_head(self.justified.root, self.current_slot)
            if sp:
                sp.set(nodes=len(self.proto_array.nodes))
            return self._head

    @property
    def head(self) -> str:
        if self._head is None:
            return self.update_head()
        return self._head

    def prune(self) -> list[ProtoNode]:
        return self.proto_array.maybe_prune(self.finalized.root)

    def get_all_ancestor_blocks(self, block_root: str) -> list[ProtoNode]:
        """The canonical chain ending at `block_root` (inclusive),
        ascending by slot — the blocks the archiver migrates to the cold
        db (reference forkChoice.getAllAncestorBlocks)."""
        pa = self.proto_array
        idx = pa.indices.get(block_root)
        out: list[ProtoNode] = []
        while idx is not None:
            node = pa.nodes[idx]
            out.append(node)
            idx = node.parent
        out.reverse()
        return out

    def get_all_non_ancestor_blocks(self, block_root: str) -> list[ProtoNode]:
        """Every known block NOT on the canonical chain to `block_root`
        — dead forks the archiver deletes from the hot db (reference
        forkChoice.getAllNonAncestorBlocks)."""
        canonical = {n.block_root for n in self.get_all_ancestor_blocks(block_root)}
        return [n for n in self.proto_array.nodes if n.block_root not in canonical]
