"""flare — the debug CLI (reference `packages/flare/src`, `cli.ts` +
`cmds/selfSlashProposer.ts` / `cmds/selfSlashAttester.ts`).

Testing-only tooling for exercising a running beacon node's slashing
pipeline: construct REAL (verifiable) ProposerSlashing /
AttesterSlashing objects for validators whose keys the operator holds,
and submit them over the Beacon API pool routes. The reference derives
keys from a mnemonic; this build's key scheme is the interop/keystore
index range, so keys come from `--interop-index/--count` (matching the
`dev` chain and the validator client's `--interop-keys`).

Usage:
  python -m lodestar_tpu.flare self-slash-proposer --server http://127.0.0.1:9596 \
      --interop-index 0 --count 2 [--slot 0]
  python -m lodestar_tpu.flare self-slash-attester ...same flags...

DANGER: submitting these against a chain where the validators are live
gets them slashed and ejected. That is the point of the tool.
"""

from __future__ import annotations

import argparse
import sys

from lodestar_tpu import params
from lodestar_tpu.api.client import BeaconApiClient
from lodestar_tpu.config import compute_domain, compute_signing_root
from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.ssz.json import to_json
from lodestar_tpu.state_transition.genesis import interop_secret_keys
from lodestar_tpu.types import ssz_types

__all__ = ["main"]


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="flare", description="lodestar-tpu debug CLI (reference packages/flare)"
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, help_ in (
        ("self-slash-proposer", "submit ProposerSlashings for own validators"),
        ("self-slash-attester", "submit AttesterSlashings for own validators"),
    ):
        c = sub.add_parser(name, help=help_)
        c.add_argument("--server", default="http://127.0.0.1:9596")
        c.add_argument("--interop-index", type=int, default=0, help="first interop key index")
        c.add_argument("--count", type=int, default=1, help="number of validators to slash")
        c.add_argument("--slot", type=int, default=0, help="slashing header/attestation slot")
        c.add_argument("--preset", default="minimal", choices=["minimal", "mainnet"])
    return ap


def _setup(args):
    params.set_active_preset(args.preset)
    t = ssz_types()
    client = BeaconApiClient(args.server)
    genesis = client.get_genesis()["data"]
    gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
    fork = client.get_state_fork("head")["data"]
    # the node verifies at the SLASHING's epoch: pick previous_version for
    # epochs before the head fork boundary (state_transition get_domain)
    p = params.active_preset()
    epoch = args.slot // p.SLOTS_PER_EPOCH
    key = "previous_version" if epoch < int(fork["epoch"]) else "current_version"
    fork_version = bytes.fromhex(fork[key][2:])

    # map our keys to on-chain validator indices by pubkey
    n_keys = args.interop_index + args.count
    sks = interop_secret_keys(n_keys)[args.interop_index :]
    validators = client.get_state_validators("head")["data"]
    index_by_pubkey = {v["validator"]["pubkey"]: int(v["index"]) for v in validators}
    pairs = []
    for sk in sks:
        pk_hex = "0x" + sk.to_pubkey().hex()
        if pk_hex not in index_by_pubkey:
            print(f"skip: pubkey {pk_hex[:18]}… not in the validator set", file=sys.stderr)
            continue
        pairs.append((index_by_pubkey[pk_hex], sk))
    if not pairs:
        raise RuntimeError("no provided keys are active validators on this chain")
    return t, client, gvr, fork_version, pairs


def self_slash_proposer(args) -> int:
    t, client, gvr, fork_version, pairs = _setup(args)
    domain = compute_domain(params.DOMAIN_BEACON_PROPOSER, fork_version, gvr)
    sent = 0
    for index, sk in pairs:
        def header(body_root: bytes):
            h = t.BeaconBlockHeader.default()
            h.slot = args.slot
            h.proposer_index = index
            h.parent_root = b"\xaa" * 32
            h.state_root = b"\xbb" * 32
            h.body_root = body_root
            return h

        slashing = t.ProposerSlashing.default()
        for slot_attr, root in (("signed_header_1", b"\xcc" * 32), ("signed_header_2", b"\xdd" * 32)):
            h = header(root)
            signed = t.SignedBeaconBlockHeader.default()
            signed.message = h
            signed.signature = bls.sign(
                sk, compute_signing_root(t.BeaconBlockHeader, h, domain)
            )
            setattr(slashing, slot_attr, signed)
        client.submit_pool_proposer_slashing(to_json(t.ProposerSlashing, slashing))
        sent += 1
        print(f"ProposerSlashing submitted for validator {index}")
    print(f"done: {sent}/{len(pairs)} proposer slashings accepted")
    return 0


def self_slash_attester(args) -> int:
    t, client, gvr, fork_version, pairs = _setup(args)
    p = params.active_preset()
    epoch = args.slot // p.SLOTS_PER_EPOCH
    domain = compute_domain(params.DOMAIN_BEACON_ATTESTER, fork_version, gvr)
    # one double-vote AttesterSlashing covering ALL provided validators
    indices = sorted(i for i, _ in pairs)
    by_index = dict(pairs)

    def indexed(beacon_root: bytes):
        data = t.AttestationData.default()
        data.slot = args.slot
        data.index = 0
        data.beacon_block_root = beacon_root
        data.source.epoch = 0
        data.source.root = b"\x00" * 32
        data.target.epoch = epoch
        data.target.root = beacon_root
        root = compute_signing_root(t.AttestationData, data, domain)
        sigs = [bls.sign(by_index[i], root) for i in indices]
        ia = t.IndexedAttestation.default()
        ia.attesting_indices = indices
        ia.data = data
        ia.signature = bls.aggregate_signatures(sigs)
        return ia

    slashing = t.AttesterSlashing.default()
    slashing.attestation_1 = indexed(b"\xaa" * 32)
    slashing.attestation_2 = indexed(b"\xbb" * 32)  # same target, different root
    client.submit_pool_attester_slashing(to_json(t.AttesterSlashing, slashing))
    print(f"AttesterSlashing submitted for validators {indices}")
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    try:
        if args.cmd == "self-slash-proposer":
            return self_slash_proposer(args)
        if args.cmd == "self-slash-attester":
            return self_slash_attester(args)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
