"""Directory-driven spec-test harness.

Counterpart of the reference `packages/spec-test-util/src/single.ts:93`
(`describeDirectorySpecTest`) and the exhaustive iterator
`beacon-node/test/spec/utils/specTestIterator.ts:23-40`, whose core
property this keeps: **unknown runners/handlers are errors, not skips** —
a vector directory that nothing claims fails the suite, so fixture trees
can never silently rot.

Layout (the official consensus-spec-tests structure):

    tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>/<files>

Each case directory's files are loaded by extension: `.yaml` via
yaml.safe_load, `.ssz` as raw bytes (official tarballs use ssz_snappy;
our committed fixtures are plain ssz — no snappy dependency in image).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable

import yaml

__all__ = ["SpecCase", "iterate_spec_tests", "run_spec_tests", "SkipOpts"]

_ARTIFACTS = {".DS_Store", "._.DS_Store", "version.txt"}


@dataclass
class SpecCase:
    """One test-case directory, files loaded lazily by stem."""

    config: str
    fork: str
    runner: str
    handler: str
    suite: str
    name: str
    path: str
    _cache: dict[str, Any] = field(default_factory=dict, repr=False)

    def files(self) -> list[str]:
        return sorted(f for f in os.listdir(self.path) if f not in _ARTIFACTS)

    def load(self, stem: str) -> Any:
        """Load `<stem>.yaml` (parsed) or `<stem>.ssz` (raw bytes)."""
        if stem in self._cache:
            return self._cache[stem]
        ypath = os.path.join(self.path, stem + ".yaml")
        spath = os.path.join(self.path, stem + ".ssz")
        if os.path.exists(ypath):
            with open(ypath) as f:
                out = yaml.safe_load(f)
        elif os.path.exists(spath):
            with open(spath, "rb") as f:
                out = f.read()
        else:
            raise FileNotFoundError(f"{self.path}: no {stem}.yaml / {stem}.ssz")
        self._cache[stem] = out
        return out

    @property
    def test_id(self) -> str:
        return f"{self.config}/{self.fork}/{self.runner}/{self.handler}/{self.suite}/{self.name}"


@dataclass
class SkipOpts:
    skipped_prefixes: tuple[str, ...] = ()
    skipped_forks: tuple[str, ...] = ()
    skipped_runners: tuple[str, ...] = ()
    skipped_handlers: tuple[str, ...] = ()


def _ls(path: str) -> list[str]:
    return sorted(e for e in os.listdir(path) if e not in _ARTIFACTS)


def iterate_spec_tests(root: str, skip: SkipOpts | None = None) -> list[SpecCase]:
    """Walk a `tests/` fixture tree into SpecCase leaves (no runners yet —
    matching happens in run_spec_tests so unknowns can error)."""
    skip = skip or SkipOpts()
    cases: list[SpecCase] = []
    for config in _ls(root):
        for fork in _ls(os.path.join(root, config)):
            if fork in skip.skipped_forks:
                continue
            for runner in _ls(os.path.join(root, config, fork)):
                if runner in skip.skipped_runners:
                    continue
                for handler in _ls(os.path.join(root, config, fork, runner)):
                    if handler in skip.skipped_handlers:
                        continue
                    hpath = os.path.join(root, config, fork, runner, handler)
                    for suite in _ls(hpath):
                        for case in _ls(os.path.join(hpath, suite)):
                            c = SpecCase(
                                config, fork, runner, handler, suite, case,
                                os.path.join(hpath, suite, case),
                            )
                            if any(c.test_id.startswith(p) for p in skip.skipped_prefixes):
                                continue
                            cases.append(c)
    return cases


def run_spec_tests(
    root: str,
    runners: dict[str, dict[str, Callable[[SpecCase], None]]],
    skip: SkipOpts | None = None,
) -> int:
    """Run every case through runners[runner][handler].

    Raises KeyError for an unknown runner or handler (the reference's
    exhaustiveness guarantee). Returns the number of cases run. Each
    handler fn asserts internally.
    """
    n = 0
    for case in iterate_spec_tests(root, skip):
        by_handler = runners.get(case.runner)
        if by_handler is None:
            raise KeyError(f"unknown spec-test runner: {case.test_id}")
        fn = by_handler.get(case.handler)
        if fn is None:
            raise KeyError(f"unknown spec-test handler: {case.test_id}")
        fn(case)
        n += 1
    return n
