from lodestar_tpu.cli import main

raise SystemExit(main())
