"""Per-slot pipeline span tracing: gossip → BLS → STF → fork choice.

Aggregate Prometheus metrics (`lodestar_tpu/metrics`) answer "how slow
is the pipeline on average"; this subsystem answers "why was slot N
slow". Explicit `Span` objects (monotonic-clock timed, parent/child
linked, attribute-carrying) are threaded through the block's life:

* gossip validation (`chain/validation.py`)
* BLS pool buffering / device launches / batch retries
  (`chain/bls/pool.py` — spans recorded from the executor thread with
  an explicitly captured parent, since `run_in_executor` does not
  propagate contextvars)
* offload RPCs (`offload/client.py` / `offload/server.py` — the trace
  context rides gRPC metadata out, server-side device spans ride the
  trailing metadata back and are grafted under the client's RPC span)
* state transition + hash-tree-root (`state_transition/`, chain STF)
* fork-choice head recompute (`fork_choice/`)

Design constraints:

* **near-zero overhead when disabled** — every instrumented call site
  costs one module-global flag check and returns a shared no-op
  singleton; no span object, dict, or clock read is allocated.
* **asyncio-safe** — the current span lives in a `contextvars.ContextVar`,
  so concurrent block imports / gossip handlers each see their own
  ancestry; `asyncio.ensure_future` snapshots the context, stitching
  child tasks (the parallel signature-verification task) automatically.
* **thread-safe** — spans complete from executor threads and the gRPC
  probe thread; traces guard their span list with a lock.

Completed root traces land in a ring buffer (`Tracer.ring`), queryable
per slot (debug API `/eth/v0/debug/traces/{slot}`). Traces slower than
`slow_slot_ms` are dumped once as a structured log line with the
critical path called out, optionally exported as Chrome `trace_event`
JSON into `export_dir` (open in chrome://tracing or Perfetto). Span
durations also feed the `lodestar_trace_*` Prometheus families so the
"block pipeline trace" Grafana dashboard renders without scraping the
debug API.

This is the event-level layer `utils/tracing.py` (env-gated XLA
profiler capture of device internals) composes with: XLA traces show
what the chip did inside one launch; these spans show where a slot's
wall-clock went across the host pipeline.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque

__all__ = [
    "Span",
    "Trace",
    "Tracer",
    "configure",
    "get_tracer",
    "reset",
    "span",
    "root",
    "current",
    "discard",
    "keep",
    "traced",
    "record",
    "context_header",
    "parse_context_header",
    "RemoteSpanRecorder",
    "remote_recorder",
    "graft_remote_spans",
    "critical_path",
    "current_log_ctx",
    "TRACE_CONTEXT_KEY",
    "TRACE_SPANS_KEY",
]

# gRPC metadata keys: context flows caller→callee, completed server
# spans flow back in trailing metadata ("-bin" keys carry raw bytes)
TRACE_CONTEXT_KEY = "x-lodestar-trace"
TRACE_SPANS_KEY = "x-lodestar-trace-spans-bin"

import contextvars

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "lodestar_trace_span", default=None
)
_trace_ids = itertools.count(1)  # CPython next() is atomic under the GIL


class Span:
    """One timed region. Also its own context manager: `with` pushes it
    as the current span (contextvar) and completes it on exit."""

    __slots__ = (
        "trace",
        "name",
        "span_id",
        "parent_id",
        "start_ns",
        "end_ns",
        "attrs",
        "tid",
        "_token",
    )

    def __init__(
        self,
        trace: "Trace",
        name: str,
        span_id: int,
        parent_id: int | None,
        start_ns: int | None = None,
    ):
        self.trace = trace
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns: int | None = None
        self.attrs: dict | None = None
        self.tid = threading.get_ident()
        self._token = None

    def set(self, **attrs) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    @property
    def duration_ms(self) -> float:
        if self.start_ns is None or self.end_ns is None:
            return 0.0
        return (self.end_ns - self.start_ns) / 1e6

    def __bool__(self) -> bool:  # noop spans are falsy; real spans truthy
        return True

    def __enter__(self) -> "Span":
        if self.start_ns is None:
            self.start_ns = time.monotonic_ns()
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end_ns = time.monotonic_ns()
        if exc is not None:
            self.set(error=f"{type(exc).__name__}: {exc}"[:200])
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        self.trace._complete_span(self)
        return False

    def __repr__(self) -> str:
        return f"Span({self.name!r}, id={self.span_id}, parent={self.parent_id}, {self.duration_ms:.3f}ms)"


class _NoopSpan:
    """Shared do-nothing span: the disabled fast path returns this one
    preallocated singleton, so instrumentation costs a flag check only."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self

    def __bool__(self) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Trace:
    """One stitched tree of spans, usually a slot's block import."""

    def __init__(self, trace_id: str, name: str, slot: int | None):
        self.trace_id = trace_id
        self.name = name
        self.slot = slot
        self.spans: list[Span] = []  # completion order
        self.root: Span | None = None
        self.discarded = False  # dropped on completion (no pipeline ran)
        # bulk traces (a range-sync batch over many blocks) are exempt
        # from the per-slot slow policy + pipeline histogram: a routine
        # 30-block batch is not a slow SLOT and must not spam warn logs,
        # export files, or the block-pipeline latency distribution
        self.bulk = False
        self.start_ns = time.monotonic_ns()
        self.end_ns: int | None = None
        self._lock = threading.Lock()
        self._next_span_id = 0

    def _new_span_id(self) -> int:
        with self._lock:
            self._next_span_id += 1
            return self._next_span_id

    def _complete_span(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    @property
    def duration_ms(self) -> float:
        end = self.end_ns if self.end_ns is not None else time.monotonic_ns()
        return (end - self.start_ns) / 1e6

    def to_dict(self) -> dict:
        """JSON-friendly view, span starts relative to the trace start."""
        with self._lock:
            spans = list(self.spans)
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "slot": self.slot,
            "duration_ms": round(self.duration_ms, 3),
            "spans": [
                {
                    "name": s.name,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    "start_ms": round((s.start_ns - self.start_ns) / 1e6, 3),
                    "duration_ms": round(s.duration_ms, 3),
                    "attrs": s.attrs or {},
                }
                for s in spans
            ],
        }


def critical_path(trace: Trace) -> list[Span]:
    """Root-to-leaf walk always descending into the longest child — the
    chain of spans that explains where the slot's wall-clock went."""
    with trace._lock:
        spans = list(trace.spans)
    if trace.root is None:
        return []
    children: dict[int | None, list[Span]] = {}
    for s in spans:
        if s is not trace.root:
            children.setdefault(s.parent_id, []).append(s)
    path = [trace.root]
    node = trace.root
    while True:
        kids = children.get(node.span_id)
        if not kids:
            return path
        node = max(kids, key=lambda s: s.end_ns - s.start_ns if s.end_ns else 0)
        path.append(node)


class Tracer:
    """Owns the enabled flag, the completed-trace ring buffer, the
    slow-slot policy, and the metric bridge. One module-global instance
    (`get_tracer()`) serves the whole process; tests may build their own."""

    def __init__(
        self,
        *,
        enabled: bool = False,
        slow_slot_ms: float = 2000.0,
        export_dir: str | None = None,
        export_max_files: int | None = 256,
        export_max_age_s: float | None = None,
        ring_size: int = 64,
        metrics=None,
        lag_ms_supplier=None,
        launches_supplier=None,
    ):
        self.enabled = enabled
        self.slow_slot_ms = slow_slot_ms
        self.export_dir = export_dir
        # retention for --tracing-export-dir: a long-running node's slow
        # slots must not grow the directory unbounded
        self.export_max_files = export_max_files
        self.export_max_age_s = export_max_age_s
        self.ring: deque[Trace] = deque(maxlen=ring_size)
        self.metrics = metrics  # metrics.TraceMetrics or None
        # () -> float|None: last event-loop lag sample in ms, surfaced in
        # slow-slot dumps (EventLoopLagSampler wires itself in here)
        self.lag_ms_supplier = lag_ms_supplier
        # () -> dict|None: recent device-launch ledger view
        # (telemetry.slow_slot_launches), folded into slow-slot dumps so
        # a slow slot names its launches (compile vs dispatch) inline
        self.launches_supplier = launches_supplier
        self.slow_slot_dumps = 0
        self.last_slow_dump: dict | None = None
        self._lock = threading.Lock()
        self._log = None  # lazy: logger imports tracing for %(trace_ctx)s

    # -- span creation --------------------------------------------------------

    def root(self, name: str, slot: int | None = None, bulk: bool = False):
        """Start a trace (becomes a plain child span if one is already
        active, so nested pipelines stitch instead of fragmenting).
        Exiting a fresh root completes the trace (ring + slow-slot
        policy + metrics). `bulk` marks many-block aggregate traces that
        skip the per-slot slow policy and pipeline histogram."""
        if not self.enabled:
            return NOOP_SPAN
        parent = _current_span.get()
        if parent is not None:
            return self._child(parent, name)
        trace = Trace(f"{next(_trace_ids):08x}", name, slot)
        trace.bulk = bulk
        # lint: allow(span-discipline) — tracer-internal construction: the returned _RootCtx is the context manager callers `with`
        span = Span(trace, name, trace._new_span_id(), None)
        trace.root = span
        return _RootCtx(self, span)

    def span(self, name: str, parent: Span | None = None):
        """Child span of `parent` (defaults to the contextvar's current
        span). No active trace → no-op: spans only exist inside a trace."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            parent = _current_span.get()
        if parent is None or isinstance(parent, _NoopSpan):
            return NOOP_SPAN
        return self._child(parent, name)

    def _child(self, parent: Span, name: str) -> Span:
        trace = parent.trace
        # lint: allow(span-discipline) — tracer-internal construction: span()/root() hand this out for the caller to `with`
        return Span(trace, name, trace._new_span_id(), parent.span_id)

    def record(
        self,
        parent: Span | None,
        name: str,
        start_ns: int,
        end_ns: int,
        attrs: dict | None = None,
    ) -> Span | None:
        """Attach an already-timed span under `parent` — the cross-thread
        path (BLS executor, offload RPC) where `with` blocks can't carry
        the contextvar."""
        if parent is None or isinstance(parent, _NoopSpan):
            return None
        trace = parent.trace
        # lint: allow(span-discipline) — record() is the documented pre-timed escape hatch: start/end are explicit, _complete_span closes it
        span = Span(trace, name, trace._new_span_id(), parent.span_id, start_ns)
        span.end_ns = end_ns
        if attrs:
            span.attrs = dict(attrs)
        trace._complete_span(span)
        return span

    # -- completion policy ----------------------------------------------------

    def on_trace_complete(self, trace: Trace) -> None:
        if trace.discarded:
            return  # e.g. gossip duplicates: no pipeline ran, keep the
            # ring + histograms for traces that measured real work
        trace.end_ns = trace.root.end_ns if trace.root is not None else time.monotonic_ns()
        with self._lock:
            self.ring.append(trace)
        m = self.metrics
        if m is not None:
            try:
                m.traces_completed.inc()
                if not trace.bulk:
                    m.block_pipeline_time.observe(trace.duration_ms / 1000.0)
                for s in trace.spans:
                    m.span_duration.labels(span=s.name).observe(
                        max(0.0, s.duration_ms / 1000.0)
                    )
            except Exception:
                pass  # metric bridge must never break the pipeline
        if trace.duration_ms > self.slow_slot_ms and not trace.bulk:
            self._dump_slow(trace)

    def _dump_slow(self, trace: Trace) -> None:
        """At most one dump per completed trace: structured log line with
        the critical path, plus an optional Chrome-trace file."""
        path = critical_path(trace)
        path_str = " > ".join(f"{s.name} {s.duration_ms:.1f}ms" for s in path)
        info = {
            "slot": trace.slot,
            "trace_id": trace.trace_id,
            "duration_ms": round(trace.duration_ms, 1),
            "threshold_ms": self.slow_slot_ms,
            "critical_path": path_str,
            "spans": len(trace.spans),
        }
        if self.lag_ms_supplier is not None:
            # loop starvation vs device slowness: the lag sample says which
            try:
                lag_ms = self.lag_ms_supplier()
                if lag_ms is not None:
                    info["event_loop_lag_ms"] = round(lag_ms, 3)
            except Exception:
                pass  # the dump must never fail on an optional probe
        if self.launches_supplier is not None:
            # the slot's device launches (program/size/wall/compile):
            # compile stall vs dispatch storm is readable from the dump
            try:
                launches = self.launches_supplier()
                if launches is not None:
                    info["device_launches"] = launches
            except Exception:
                pass  # the dump must never fail on an optional probe
        with self._lock:
            self.slow_slot_dumps += 1
            self.last_slow_dump = info
        if self.metrics is not None:
            try:
                self.metrics.slow_slots.inc()
            except Exception:
                pass
        if self._log is None:
            from lodestar_tpu.logger import get_logger

            self._log = get_logger(name="lodestar.tracing")
        self._log.warn(f"slow slot {trace.slot}", info)
        if self.export_dir:
            try:
                from .export import prune_export_dir, write_chrome_trace

                import os

                os.makedirs(self.export_dir, exist_ok=True)
                out = os.path.join(
                    self.export_dir, f"slot{trace.slot}_{trace.trace_id}.json"
                )
                write_chrome_trace(out, [trace])
                prune_export_dir(
                    self.export_dir,
                    max_files=self.export_max_files,
                    max_age_s=self.export_max_age_s,
                )
            except Exception:
                pass  # export failures must never fail the import pipeline

    # -- queries --------------------------------------------------------------

    def traces_for_slot(self, slot: int) -> list[Trace]:
        with self._lock:
            return [t for t in self.ring if t.slot == slot]

    def recent_traces(self, n: int = 16) -> list[Trace]:
        if n <= 0:
            return []  # [-0:] would return the whole ring
        with self._lock:
            return list(self.ring)[-n:]


# -- module-global tracer + thin fast-path functions ---------------------------

_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def configure(
    *,
    enabled: bool | None = None,
    slow_slot_ms: float | None = None,
    export_dir: str | None = None,
    export_max_files: int | None = None,
    export_max_age_s: float | None = None,
    ring_size: int | None = None,
    metrics=None,
    lag_ms_supplier=None,
    launches_supplier=None,
) -> Tracer:
    """Mutate the global tracer in place (callers hold no stale refs)."""
    t = _TRACER
    if enabled is not None:
        t.enabled = enabled
    if slow_slot_ms is not None:
        t.slow_slot_ms = slow_slot_ms
    if export_dir is not None:
        t.export_dir = export_dir
    if export_max_files is not None:
        t.export_max_files = export_max_files
    if export_max_age_s is not None:
        t.export_max_age_s = export_max_age_s
    if ring_size is not None:
        with t._lock:
            t.ring = deque(t.ring, maxlen=ring_size)
    if metrics is not None:
        t.metrics = metrics
    if lag_ms_supplier is not None:
        t.lag_ms_supplier = lag_ms_supplier
    if launches_supplier is not None:
        t.launches_supplier = launches_supplier
    return t


def reset() -> Tracer:
    """Fresh disabled global tracer (test isolation)."""
    global _TRACER
    _TRACER = Tracer()
    return _TRACER


def span(name: str, parent: Span | None = None):
    if not _TRACER.enabled:
        return NOOP_SPAN
    return _TRACER.span(name, parent)


def root(name: str, slot: int | None = None, bulk: bool = False):
    if not _TRACER.enabled:
        return NOOP_SPAN
    return _TRACER.root(name, slot, bulk=bulk)


class _RootCtx:
    """Wraps a root span so exiting it completes the whole trace."""

    __slots__ = ("tracer", "span")

    def __init__(self, tracer: Tracer, span: Span):
        self.tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        return self.span.__enter__()

    def __exit__(self, exc_type, exc, tb) -> bool:
        out = self.span.__exit__(exc_type, exc, tb)
        self.tracer.on_trace_complete(self.span.trace)
        return out

    def set(self, **attrs):
        self.span.set(**attrs)
        return self

    def __bool__(self) -> bool:
        return True


def traced(name: str):
    """Decorator form of `span(name)`: times the wrapped call when a
    trace is active, passes straight through (one flag check) otherwise."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _TRACER.enabled:
                return fn(*args, **kwargs)
            with _TRACER.span(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def current() -> Span | None:
    """The active span, or None (also None when tracing is disabled —
    callers capture this to parent cross-thread spans explicitly)."""
    if not _TRACER.enabled:
        return None
    return _current_span.get()


def discard() -> None:
    """Mark the active trace to be dropped on completion — for pipelines
    that turn out to be no-ops (gossip IGNORE/REJECT before import), so
    sub-millisecond non-traces don't flood the ring or skew the
    block-pipeline histograms."""
    if not _TRACER.enabled:
        return
    sp = _current_span.get()
    if sp is not None:
        sp.trace.discarded = True


def keep() -> None:
    """Clear a pending discard on the active trace. An outer root that
    aggregates nested pipelines (a range-sync batch over process_block
    calls) owns its own completion: one ALREADY_KNOWN duplicate mid-batch
    discards per the nested pipeline's policy, and the batch root calls
    keep() at the end so the batch trace still lands in the ring."""
    if not _TRACER.enabled:
        return
    sp = _current_span.get()
    if sp is not None:
        sp.trace.discarded = False


def record(
    parent: Span | None, name: str, start_ns: int, end_ns: int, attrs: dict | None = None
):
    return _TRACER.record(parent, name, start_ns, end_ns, attrs)


def current_log_ctx() -> str:
    """Log-format fragment for %(trace_ctx)s: ' [trace=<id>]' while a
    span is active, '' otherwise (and always '' when tracing is off)."""
    if not _TRACER.enabled:
        return ""
    sp = _current_span.get()
    if sp is None:
        return ""
    return f" [trace={sp.trace.trace_id}]"


# -- cross-process propagation (offload gRPC) ----------------------------------


def context_header() -> str | None:
    """Serialized trace context for gRPC metadata: 'trace_id:span_id:slot'."""
    if not _TRACER.enabled:
        return None
    sp = _current_span.get()
    if sp is None:
        return None
    slot = sp.trace.slot if sp.trace.slot is not None else ""
    return f"{sp.trace.trace_id}:{sp.span_id}:{slot}"


def parse_context_header(header: str) -> tuple[str, int, int | None] | None:
    try:
        trace_id, span_id, slot = header.split(":", 2)
        return trace_id, int(span_id), (int(slot) if slot else None)
    except (ValueError, AttributeError):
        return None


class RemoteSpanRecorder:
    """Server-side recorder: collects spans relative to its own creation
    and serializes them for the trailing-metadata trip home. Independent
    of the server process's global tracer — the caller's header is the
    enable signal."""

    __slots__ = ("origin_ns", "spans", "_lock", "_next_id")

    def __init__(self):
        self.origin_ns = time.monotonic_ns()
        self.spans: list[dict] = []
        self._lock = threading.Lock()
        self._next_id = itertools.count(1)

    def span(self, name: str, **attrs) -> "_RemoteSpanCtx":
        return _RemoteSpanCtx(self, name, attrs)

    def _add(self, name: str, start_ns: int, end_ns: int, attrs: dict) -> None:
        with self._lock:
            self.spans.append(
                {
                    "id": next(self._next_id),
                    "name": name,
                    "offset_ns": start_ns - self.origin_ns,
                    "dur_ns": end_ns - start_ns,
                    "attrs": attrs or {},
                }
            )

    def serialize(self) -> bytes:
        with self._lock:
            return json.dumps(self.spans, separators=(",", ":")).encode()


class _RemoteSpanCtx:
    __slots__ = ("rec", "name", "attrs", "start_ns")

    def __init__(self, rec: RemoteSpanRecorder, name: str, attrs: dict):
        self.rec = rec
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self.start_ns = time.monotonic_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.attrs["error"] = f"{type(exc).__name__}: {exc}"[:200]
        self.rec._add(self.name, self.start_ns, time.monotonic_ns(), self.attrs)
        return False

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self


class _NoopRemoteRecorder:
    __slots__ = ()

    def span(self, name: str, **attrs):
        return NOOP_SPAN

    def serialize(self) -> bytes | None:
        return None


_NOOP_REMOTE = _NoopRemoteRecorder()


def remote_recorder(header: str | None):
    """Server entry: a live recorder when the caller sent a trace
    context header, a shared no-op otherwise."""
    if header and parse_context_header(header) is not None:
        return RemoteSpanRecorder()
    return _NOOP_REMOTE


def graft_remote_spans(parent: Span | None, payload: bytes, anchor_start_ns: int) -> int:
    """Client side: rebase serialized server spans under the local RPC
    span. Server offsets are relative to its handling start; anchoring
    them at the client RPC start keeps ordering honest (network skew
    shows up as the gap between the RPC span and its children). Returns
    the number of grafted spans."""
    if parent is None or isinstance(parent, _NoopSpan) or not payload:
        return 0
    try:
        items = json.loads(payload.decode())
    except (ValueError, UnicodeDecodeError):
        return 0
    n = 0
    for item in items:
        try:
            start = anchor_start_ns + int(item["offset_ns"])
            attrs = dict(item.get("attrs") or {})
            attrs["remote"] = True
            _TRACER.record(parent, str(item["name"]), start, start + int(item["dur_ns"]), attrs)
            n += 1
        except (KeyError, TypeError, ValueError):
            continue
    return n
