"""Chrome `trace_event` export for completed pipeline traces.

The output is the Trace Event Format's JSON-object form ("traceEvents"
array of "ph":"X" complete events, microsecond timestamps) — load it in
chrome://tracing or https://ui.perfetto.dev unmodified. One process row
per trace (pid = slot when known), one thread row per originating
thread, so the BLS executor / offload spans render on their own tracks
under the slot they belong to.
"""

from __future__ import annotations

import json
from typing import Iterable

from . import Span, Trace

__all__ = ["to_chrome_trace", "write_chrome_trace"]


def _event(trace: Trace, span: Span, pid: int) -> dict:
    args = dict(span.attrs or {})
    args["span_id"] = span.span_id
    if span.parent_id is not None:
        args["parent_id"] = span.parent_id
    end_ns = span.end_ns if span.end_ns is not None else span.start_ns
    return {
        "name": span.name,
        "cat": "lodestar",
        "ph": "X",
        "ts": span.start_ns / 1e3,  # trace-event timestamps are in µs
        "dur": max(0.0, (end_ns - span.start_ns) / 1e3),
        "pid": pid,
        "tid": span.tid,
        "args": args,
    }


def to_chrome_trace(traces: Iterable[Trace]) -> dict:
    events: list[dict] = []
    seen_pids: set[int] = set()
    for i, trace in enumerate(traces):
        # one process row PER TRACE: competing blocks at the same slot
        # (short reorg / equivocation) must not merge into one track, so
        # colliding slots fall back to a synthetic distinct pid
        pid = trace.slot if trace.slot is not None else 0
        if pid in seen_pids:
            pid = 1_000_000 + i  # i is unique per call
            while pid in seen_pids:
                pid += 1_000_000
        seen_pids.add(pid)
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"slot {trace.slot} ({trace.name} {trace.trace_id})"},
            }
        )
        with trace._lock:
            spans = list(trace.spans)
        events.extend(
            _event(trace, s, pid) for s in spans if s.start_ns is not None
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, traces: Iterable[Trace]) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(traces), f, indent=1)
        f.write("\n")
    return path
