"""Chrome `trace_event` export for completed pipeline traces.

The output is the Trace Event Format's JSON-object form ("traceEvents"
array of "ph":"X" complete events, microsecond timestamps) — load it in
chrome://tracing or https://ui.perfetto.dev unmodified. One process row
per trace (pid = slot when known), one thread row per originating
thread, so the BLS executor / offload spans render on their own tracks
under the slot they belong to.
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterable

from . import Span, Trace

__all__ = ["to_chrome_trace", "write_chrome_trace", "prune_export_dir"]


def _event(trace: Trace, span: Span, pid: int) -> dict:
    args = dict(span.attrs or {})
    args["span_id"] = span.span_id
    if span.parent_id is not None:
        args["parent_id"] = span.parent_id
    end_ns = span.end_ns if span.end_ns is not None else span.start_ns
    return {
        "name": span.name,
        "cat": "lodestar",
        "ph": "X",
        "ts": span.start_ns / 1e3,  # trace-event timestamps are in µs
        "dur": max(0.0, (end_ns - span.start_ns) / 1e3),
        "pid": pid,
        "tid": span.tid,
        "args": args,
    }


def to_chrome_trace(traces: Iterable[Trace]) -> dict:
    events: list[dict] = []
    seen_pids: set[int] = set()
    for i, trace in enumerate(traces):
        # one process row PER TRACE: competing blocks at the same slot
        # (short reorg / equivocation) must not merge into one track, so
        # colliding slots fall back to a synthetic distinct pid
        pid = trace.slot if trace.slot is not None else 0
        if pid in seen_pids:
            pid = 1_000_000 + i  # i is unique per call
            while pid in seen_pids:
                pid += 1_000_000
        seen_pids.add(pid)
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"slot {trace.slot} ({trace.name} {trace.trace_id})"},
            }
        )
        with trace._lock:
            spans = list(trace.spans)
        events.extend(
            _event(trace, s, pid) for s in spans if s.start_ns is not None
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, traces: Iterable[Trace]) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(traces), f, indent=1)
        f.write("\n")
    return path


def prune_export_dir(
    path: str,
    *,
    max_files: int | None = None,
    max_age_s: float | None = None,
    now: float | None = None,
) -> list[str]:
    """Retention for a --tracing-export-dir: delete trace JSONs older
    than `max_age_s`, then the oldest (by mtime) beyond `max_files`, so a
    long-running node's slow slots can't grow the directory unbounded.
    Only touches the tracer's own `slot<N>_<trace_id>.json` output —
    unrelated JSON an operator keeps in the same directory is never
    pruned. Returns the removed paths; unlink races with an external
    cleaner are ignored. `max_files`/`max_age_s` of None or <= 0 mean
    unlimited (the usual CLI convention for 0)."""
    import fnmatch

    if max_files is not None and max_files <= 0:
        max_files = None
    if max_age_s is not None and max_age_s <= 0:
        max_age_s = None

    try:
        names = os.listdir(path)
    except OSError:
        return []
    entries: list[tuple[float, str]] = []
    for name in names:
        if not fnmatch.fnmatch(name, "slot*_*.json"):
            continue
        full = os.path.join(path, name)
        try:
            entries.append((os.path.getmtime(full), full))
        except OSError:
            continue
    entries.sort()  # oldest first
    now = time.time() if now is None else now
    removed: list[str] = []

    def _unlink(full: str) -> None:
        try:
            os.unlink(full)
            removed.append(full)
        except OSError:
            pass

    if max_age_s is not None:
        fresh = []
        for mtime, full in entries:
            if now - mtime > max_age_s:
                _unlink(full)
            else:
                fresh.append((mtime, full))
        entries = fresh
    if max_files is not None and len(entries) > max_files:
        for _mtime, full in entries[: len(entries) - max_files]:
            _unlink(full)
    return removed
