"""Req/Resp protocol library (reference `packages/reqresp/src`).

Protocol-agnostic request/response streams with eth2 ssz_snappy framing
(`encodingStrategies/sszSnappy/`): request = varint(ssz-length) +
snappy-framed payload; response = chunks of result-byte + varint +
snappy-framed payload. Transport is any asyncio duplex stream — the
libp2p negotiation layer sits above, exactly as the reference keeps
`ReqResp.ts:47` transport-agnostic.

Includes the token-bucket rate limiter (`rate_limiter/`) and the beacon
protocol table (status/goodbye/ping/metadata/blocksByRange/blocksByRoot,
reference `beacon-node/src/network/reqresp/protocols.ts`).
"""

from .encoding import read_request, read_response_chunks, write_request, write_response_chunk  # noqa: F401
from .protocols import BEACON_PROTOCOLS, Protocol, protocol_by_id  # noqa: F401
from .rate_limiter import RateLimiter, RateLimiterQuota  # noqa: F401
from .reqresp import ReqResp, ReqRespError, ResponseError, RespStatus  # noqa: F401
