"""ssz_snappy stream framing (reference
`reqresp/src/encodingStrategies/sszSnappy/`).

request  := varint(uncompressed ssz length) || snappy-frames(ssz)
resp-chunk := result_byte || varint(length) || snappy-frames(ssz)
"""

from __future__ import annotations

import asyncio

from lodestar_tpu.utils.snappy import decompress as _snappy_block_decompress
from lodestar_tpu.utils.snappy import frame_compress
from lodestar_tpu.utils.snappy import _masked_crc  # shared CRC32C masking
from lodestar_tpu.utils.snappy import SnappyError

__all__ = [
    "write_request",
    "read_request",
    "write_response_chunk",
    "read_response_chunks",
    "EncodingError",
]

MAX_VARINT_BYTES = 10


class EncodingError(Exception):
    pass


def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


async def _read_varint(reader: asyncio.StreamReader) -> int:
    out = 0
    for shift in range(0, 7 * MAX_VARINT_BYTES, 7):
        b = await reader.readexactly(1)
        out |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            return out
    raise EncodingError("varint too long")


async def _read_snappy_frames(reader: asyncio.StreamReader, uncompressed_len: int) -> bytes:
    """Read snappy frame chunks until `uncompressed_len` bytes decoded.

    Incremental: each frame chunk decodes independently (O(n) total, not
    O(chunks^2)), and exact chunk counts are consumed so back-to-back
    response chunks on one stream never desync. Zero-length payloads
    still carry their stream id + one empty data chunk (what
    frame_compress emits), so they are consumed exactly too.
    """
    stream_id = await reader.readexactly(10)
    if not stream_id.startswith(b"\xff\x06\x00\x00sNaPpY"):
        raise EncodingError("missing snappy stream identifier")
    out = bytearray()
    need_data_chunk = True  # even a 0-length payload carries one chunk
    while len(out) < uncompressed_len or need_data_chunk:
        hdr = await reader.readexactly(4)
        ctype = hdr[0]
        length = int.from_bytes(hdr[1:4], "little")
        body = await reader.readexactly(length)
        if ctype in (0x00, 0x01):
            crc = int.from_bytes(body[:4], "little")
            chunk = _snappy_block_decompress(body[4:]) if ctype == 0x00 else body[4:]
            if _masked_crc(chunk) != crc:
                raise EncodingError("bad snappy chunk checksum")
            out += chunk
            need_data_chunk = False
        elif ctype == 0xFF or 0x80 <= ctype <= 0xFD:
            continue  # repeated stream id / skippable padding
        else:
            raise EncodingError(f"unskippable chunk type {ctype:#x}")
    if len(out) != uncompressed_len:
        raise EncodingError(f"length mismatch {len(out)} != {uncompressed_len}")
    return bytes(out)


async def write_request(writer: asyncio.StreamWriter, ssz_bytes: bytes) -> None:
    writer.write(_encode_varint(len(ssz_bytes)) + frame_compress(ssz_bytes))
    await writer.drain()


async def read_request(reader: asyncio.StreamReader, max_len: int = 2**22) -> bytes:
    n = await _read_varint(reader)
    if n > max_len:
        raise EncodingError(f"request too large: {n}")
    return await _read_snappy_frames(reader, n)


async def write_response_chunk(
    writer: asyncio.StreamWriter, status: int, ssz_bytes: bytes,
    context: bytes = b"",
) -> None:
    """One response chunk. `context` (e.g. a 4-byte fork digest) rides
    between the result byte and the length varint on SUCCESS chunks —
    reference encodingStrategies ContextBytes placement."""
    head = bytes([status]) + (context if status == 0 else b"")
    writer.write(head + _encode_varint(len(ssz_bytes)) + frame_compress(ssz_bytes))
    await writer.drain()


async def read_response_chunks(
    reader: asyncio.StreamReader, max_len: int = 2**22, context_len: int = 0
):
    """Async iterator of (status, context, payload) until EOF.
    `context_len` bytes are read after SUCCESS result bytes only
    (error chunks carry a bare message)."""
    while True:
        try:
            status_b = await reader.readexactly(1)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return
        context = b""
        if status_b[0] == 0 and context_len:
            context = await reader.readexactly(context_len)
        n = await _read_varint(reader)
        if n > max_len:
            raise EncodingError(f"response chunk too large: {n}")
        payload = await _read_snappy_frames(reader, n)
        yield status_b[0], context, payload
