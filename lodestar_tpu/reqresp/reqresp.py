"""ReqResp engine: typed request/response over negotiated streams.

Reference `reqresp/src/ReqResp.ts:47`: the server side registers handlers
per protocol id and enforces rate limits; the client side opens a stream
(via an injected dial function), writes one request, and collects typed
response chunks. Stream negotiation here is a single length-prefixed
protocol-id line — the multistream-select stand-in for the asyncio
transport (the framing above it is byte-identical eth2 ssz_snappy).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Awaitable, Callable

from .encoding import read_request, read_response_chunks, write_request, write_response_chunk
from .protocols import CONTEXT_FORK_DIGEST, Protocol, protocol_by_id
from .rate_limiter import RateLimiter, RateLimiterQuota

__all__ = ["ReqResp", "RespStatus", "ReqRespError", "ResponseError"]


class RespStatus:
    SUCCESS = 0
    INVALID_REQUEST = 1
    SERVER_ERROR = 2
    RESOURCE_UNAVAILABLE = 3
    RATE_LIMITED = 139  # lodestar-specific code used for downscoring


class ReqRespError(Exception):
    pass


class ResponseError(ReqRespError):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"response status {status}: {message}")
        self.status = status


Handler = Callable[[object, str], AsyncIterator[object]]


class ReqResp:
    """Both halves of the protocol engine; transport injected."""

    def __init__(
        self,
        *,
        default_quota: RateLimiterQuota = RateLimiterQuota(50, 10.0),
        request_timeout_sec: float = 10.0,
    ) -> None:
        self._handlers: dict[str, Handler] = {}
        self._limiters: dict[str, RateLimiter] = {}
        self._default_quota = default_quota
        self._timeout = request_timeout_sec
        self._streams_served = 0
        # optional ReqRespMetrics (set by the node wiring); None = no-op
        self.metrics = None
        # fork-context resolvers (set_fork_context) for ForkDigest protocols
        self._fork_to_digest: Callable[[str], bytes] | None = None
        self._digest_to_fork: Callable[[bytes], str | None] | None = None

    def set_fork_context(
        self,
        fork_to_digest: Callable[[str], bytes],
        digest_to_fork: Callable[[bytes], str | None],
    ) -> None:
        """Install the fork digest mappings that ForkDigest-context
        protocols (blocks V2, blobs, light-client) resolve chunk types
        with (reference `ContextBytesType.ForkDigest`,
        `beacon-node/src/network/reqresp/protocols.ts:41`)."""
        self._fork_to_digest = fork_to_digest
        self._digest_to_fork = digest_to_fork

    # -- server side ----------------------------------------------------------

    def register_handler(
        self, protocol_id: str, handler: Handler, quota: RateLimiterQuota | None = None
    ) -> None:
        protocol_by_id(protocol_id)  # unknown protocol = programming error
        self._handlers[protocol_id] = handler
        self._limiters[protocol_id] = RateLimiter(quota or self._default_quota)

    async def handle_stream(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, peer_id: str = "?"
    ) -> None:
        """Serve one negotiated stream: read protocol id line, then the
        request, stream back chunks."""
        try:
            pid_len = int.from_bytes(await reader.readexactly(2), "big")
            protocol_id = (await reader.readexactly(pid_len)).decode()
            handler = self._handlers.get(protocol_id)
            if handler is None:
                await write_response_chunk(writer, RespStatus.INVALID_REQUEST, b"")
                return
            if self.metrics is not None:
                self.metrics.requests_received.labels(protocol=protocol_id).inc()
            limiter = self._limiters[protocol_id]
            if not limiter.allows(peer_id):
                if self.metrics is not None:
                    self.metrics.rate_limited.labels(protocol=protocol_id).inc()
                await write_response_chunk(writer, RespStatus.RATE_LIMITED, b"")
                return
            # bound per-peer bucket growth from untrusted peer-id churn
            self._streams_served += 1
            if self._streams_served % 1024 == 0:
                for lim in self._limiters.values():
                    lim.prune()
            proto = protocol_by_id(protocol_id)
            request = None
            if proto.request_type is not None:
                try:
                    raw = await asyncio.wait_for(read_request(reader), self._timeout)
                    request = proto.request_type().deserialize(raw)
                except Exception as e:  # malformed/slow request: tell the peer
                    await write_response_chunk(
                        writer, RespStatus.INVALID_REQUEST, repr(e).encode()[:256]
                    )
                    return
            count = 0
            fork_ctx = proto.context == CONTEXT_FORK_DIGEST
            try:
                async for item in handler(request, peer_id):
                    if count >= proto.max_response_chunks:
                        break
                    if fork_ctx:
                        # ForkDigest protocols: handlers yield (fork, item)
                        fork, item = item
                        if self._fork_to_digest is None:
                            raise ReqRespError("fork context not configured")
                        context = self._fork_to_digest(fork)
                        payload = proto.resolve_response_type(fork).serialize(item)
                    else:
                        context = b""
                        payload = proto.response_type().serialize(item)
                    await write_response_chunk(
                        writer, RespStatus.SUCCESS, payload, context=context
                    )
                    count += 1
            except ReqRespError as e:
                if self.metrics is not None:
                    self.metrics.request_errors.labels(protocol=protocol_id).inc()
                await write_response_chunk(writer, RespStatus.INVALID_REQUEST, str(e).encode()[:256])
            except Exception:
                if self.metrics is not None:
                    self.metrics.request_errors.labels(protocol=protocol_id).inc()
                await write_response_chunk(writer, RespStatus.SERVER_ERROR, b"")
            else:
                if self.metrics is not None:
                    self.metrics.response_chunks_sent.labels(protocol=protocol_id).inc(count)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass  # peer hung up mid-negotiation; nothing to answer
        finally:
            try:
                writer.write_eof()
                await writer.drain()
            except (ConnectionResetError, OSError):
                pass

    # -- client side ----------------------------------------------------------

    async def send_request(
        self,
        dial: Callable[[], Awaitable[tuple[asyncio.StreamReader, asyncio.StreamWriter]]],
        protocol_id: str,
        request,
        max_chunks: int | None = None,
    ) -> list:
        """Open a stream via `dial`, send `request`, return decoded chunks.
        Dial and the full response are bounded by request_timeout_sec each
        (TTFB/RESP timeouts in the reference) so a dead peer can never
        hang the caller."""
        proto = protocol_by_id(protocol_id)
        if self.metrics is not None:
            self.metrics.requests_sent.labels(protocol=protocol_id).inc()
        try:
            reader, writer = await asyncio.wait_for(dial(), self._timeout)
        except asyncio.TimeoutError:
            if self.metrics is not None:
                self.metrics.dial_timeouts.inc()
            raise
        try:
            pid = protocol_id.encode()
            writer.write(len(pid).to_bytes(2, "big") + pid)
            if proto.request_type is not None:
                await write_request(writer, proto.request_type().serialize(request))
            try:
                writer.write_eof()
            except (AttributeError, OSError):
                pass

            fork_ctx = proto.context == CONTEXT_FORK_DIGEST
            ctx_len = 4 if fork_ctx else 0

            async def collect() -> list:
                out = []
                limit = max_chunks if max_chunks is not None else proto.max_response_chunks
                async for status, context, payload in read_response_chunks(
                    reader, context_len=ctx_len
                ):
                    if status != RespStatus.SUCCESS:
                        raise ResponseError(status, payload.decode(errors="replace"))
                    if fork_ctx and self._digest_to_fork is not None:
                        fork = self._digest_to_fork(context)
                        if fork is None:
                            raise ReqRespError(
                                f"unknown fork digest {context.hex()}"
                            )
                        typ = proto.resolve_response_type(fork)
                    elif fork_ctx and not proto.fork_invariant:
                        # decoding a fork-variant chunk without a digest
                        # mapping would silently mis-deserialize: fail loud
                        raise ReqRespError(
                            "fork context not configured for "
                            f"{protocol_id}"
                        )
                    else:
                        typ = proto.response_type()
                    out.append(typ.deserialize(payload))
                    if len(out) >= limit:
                        break
                return out

            return await asyncio.wait_for(collect(), self._timeout)
        finally:
            writer.close()
    # one request per stream, as the spec demands
