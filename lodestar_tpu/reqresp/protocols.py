"""Beacon req/resp protocol table (reference
`beacon-node/src/network/reqresp/protocols.ts`): protocol ids, request/
response SSZ types, chunk limits, and per-chunk context-bytes mode. Types
resolve lazily from the registry so the table works under any preset.

Context bytes (reference `protocols.ts:41-66` ContextBytesType): V2 block
protocols, blob protocols and the light-client protocols prefix every
SUCCESS chunk with the 4-byte fork digest of the chunk's fork, and the
response SSZ type is resolved PER CHUNK from that digest — without this,
a post-phase0 block cannot cross the wire (VERDICT r4 missing #1).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from lodestar_tpu import ssz

__all__ = ["Protocol", "BEACON_PROTOCOLS", "protocol_by_id", "CONTEXT_NONE", "CONTEXT_FORK_DIGEST"]

CONTEXT_NONE = "empty"
CONTEXT_FORK_DIGEST = "fork_digest"


@dataclass(frozen=True)
class Protocol:
    protocol_id: str  # /eth2/beacon_chain/req/<name>/<version>/ssz_snappy
    request_type: Callable[[], object] | None  # () -> SSZType or None (no body)
    response_type: Callable[[], object]
    max_response_chunks: int
    # CONTEXT_NONE: bare chunks, response_type fixed.
    # CONTEXT_FORK_DIGEST: 4-byte fork digest per SUCCESS chunk;
    # response_type_by_fork resolves the chunk type from the fork name.
    context: str = CONTEXT_NONE
    response_type_by_fork: Callable[[str], object] | None = None
    # True when the chunk SSZ layout is the same for every fork (LC
    # containers, blob sidecars): a client without a digest mapping may
    # then decode with the static type; fork-VARIANT protocols (blocks
    # V2) must fail loudly instead of mis-deserializing
    fork_invariant: bool = False

    def resolve_response_type(self, fork: str | None):
        if self.context == CONTEXT_FORK_DIGEST and fork is not None:
            if self.response_type_by_fork is not None:
                return self.response_type_by_fork(fork)
        return self.response_type()


def _t():
    from lodestar_tpu.types import ssz_types

    return ssz_types()


def _signed_block_for_fork(fork: str):
    t = _t()
    ns = getattr(t, fork, None)
    typ = getattr(ns, "SignedBeaconBlock", None) if ns is not None else None
    if typ is None:
        raise KeyError(f"no SignedBeaconBlock for fork {fork!r}")
    return typ


def _pid(name: str, version: int = 1) -> str:
    return f"/eth2/beacon_chain/req/{name}/{version}/ssz_snappy"


BEACON_PROTOCOLS: dict[str, Protocol] = {
    p.protocol_id: p
    for p in [
        Protocol(_pid("status"), lambda: _t().Status, lambda: _t().Status, 1),
        Protocol(_pid("goodbye"), lambda: ssz.uint64, lambda: ssz.uint64, 1),
        Protocol(_pid("ping"), lambda: ssz.uint64, lambda: ssz.uint64, 1),
        Protocol(_pid("metadata"), None, lambda: _t().phase0.Metadata, 1),
        Protocol(_pid("metadata", 2), None, lambda: _t().altair.Metadata, 1),
        # V1 block protocols: context-free, phase0-typed chunks only
        # (reference protocols.ts BeaconBlocksByRange/Root V1)
        Protocol(
            _pid("beacon_blocks_by_range"),
            lambda: _t().BeaconBlocksByRangeRequest,
            lambda: _t().phase0.SignedBeaconBlock,
            1024,
        ),
        Protocol(
            _pid("beacon_blocks_by_root"),
            lambda: ssz.List(ssz.Bytes32, 1024),
            lambda: _t().phase0.SignedBeaconBlock,
            1024,
        ),
        # V2 block protocols: ForkDigest context per chunk, fork-resolved
        # type (reference protocols.ts:50,62 BeaconBlocksByRangeV2/RootV2)
        Protocol(
            _pid("beacon_blocks_by_range", 2),
            lambda: _t().BeaconBlocksByRangeRequest,
            lambda: _t().phase0.SignedBeaconBlock,
            1024,
            context=CONTEXT_FORK_DIGEST,
            response_type_by_fork=_signed_block_for_fork,
        ),
        Protocol(
            _pid("beacon_blocks_by_root", 2),
            lambda: ssz.List(ssz.Bytes32, 1024),
            lambda: _t().phase0.SignedBeaconBlock,
            1024,
            context=CONTEXT_FORK_DIGEST,
            response_type_by_fork=_signed_block_for_fork,
        ),
        Protocol(
            _pid("blobs_sidecars_by_range"),
            lambda: _t().deneb.BlobsSidecarsByRangeRequest,
            lambda: _t().deneb.BlobsSidecar,
            128,
            context=CONTEXT_FORK_DIGEST,
            response_type_by_fork=lambda fork: _t().deneb.BlobsSidecar,
            fork_invariant=True,
        ),
        # light-client protocols (reference protocols.ts LightClient* —
        # all carry ForkDigest context; our LC containers are
        # fork-invariant so the digest selects the same type)
        Protocol(
            _pid("light_client_bootstrap"),
            lambda: ssz.Bytes32,
            lambda: _t().LightClientBootstrap,
            1,
            context=CONTEXT_FORK_DIGEST,
            response_type_by_fork=lambda fork: _t().LightClientBootstrap,
            fork_invariant=True,
        ),
        Protocol(
            _pid("light_client_updates_by_range"),
            lambda: _t().LightClientUpdatesByRange,
            lambda: _t().LightClientUpdate,
            128,
            context=CONTEXT_FORK_DIGEST,
            response_type_by_fork=lambda fork: _t().LightClientUpdate,
            fork_invariant=True,
        ),
        Protocol(
            _pid("light_client_finality_update"),
            None,
            lambda: _t().LightClientFinalityUpdate,
            1,
            context=CONTEXT_FORK_DIGEST,
            response_type_by_fork=lambda fork: _t().LightClientFinalityUpdate,
            fork_invariant=True,
        ),
        Protocol(
            _pid("light_client_optimistic_update"),
            None,
            lambda: _t().LightClientOptimisticUpdate,
            1,
            context=CONTEXT_FORK_DIGEST,
            response_type_by_fork=lambda fork: _t().LightClientOptimisticUpdate,
            fork_invariant=True,
        ),
    ]
}


def protocol_by_id(protocol_id: str) -> Protocol:
    p = BEACON_PROTOCOLS.get(protocol_id)
    if p is None:
        raise KeyError(f"unknown protocol {protocol_id}")
    return p
