"""Beacon req/resp protocol table (reference
`beacon-node/src/network/reqresp/protocols.ts`): protocol ids, request/
response SSZ types, chunk limits. Types resolve lazily from the registry
so the table works under any preset.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from lodestar_tpu import ssz

__all__ = ["Protocol", "BEACON_PROTOCOLS", "protocol_by_id"]


@dataclass(frozen=True)
class Protocol:
    protocol_id: str  # /eth2/beacon_chain/req/<name>/<version>/ssz_snappy
    request_type: Callable[[], object] | None  # () -> SSZType or None (no body)
    response_type: Callable[[], object]
    max_response_chunks: int


def _t():
    from lodestar_tpu.types import ssz_types

    return ssz_types()


def _pid(name: str, version: int = 1) -> str:
    return f"/eth2/beacon_chain/req/{name}/{version}/ssz_snappy"


BEACON_PROTOCOLS: dict[str, Protocol] = {
    p.protocol_id: p
    for p in [
        Protocol(_pid("status"), lambda: _t().Status, lambda: _t().Status, 1),
        Protocol(_pid("goodbye"), lambda: ssz.uint64, lambda: ssz.uint64, 1),
        Protocol(_pid("ping"), lambda: ssz.uint64, lambda: ssz.uint64, 1),
        Protocol(_pid("metadata"), None, lambda: _t().phase0.Metadata, 1),
        Protocol(_pid("metadata", 2), None, lambda: _t().altair.Metadata, 1),
        Protocol(
            _pid("beacon_blocks_by_range"),
            lambda: _t().BeaconBlocksByRangeRequest,
            lambda: _t().phase0.SignedBeaconBlock,
            1024,
        ),
        Protocol(
            _pid("beacon_blocks_by_root"),
            lambda: ssz.List(ssz.Bytes32, 1024),
            lambda: _t().phase0.SignedBeaconBlock,
            1024,
        ),
        Protocol(
            _pid("blobs_sidecars_by_range"),
            lambda: _t().deneb.BlobsSidecarsByRangeRequest,
            lambda: _t().deneb.BlobsSidecar,
            128,
        ),
        # light-client protocols (reference protocols.ts LightClient*)
        Protocol(
            _pid("light_client_bootstrap"),
            lambda: ssz.Bytes32,
            lambda: _t().LightClientBootstrap,
            1,
        ),
        Protocol(
            _pid("light_client_updates_by_range"),
            lambda: _t().LightClientUpdatesByRange,
            lambda: _t().LightClientUpdate,
            128,
        ),
        Protocol(_pid("light_client_finality_update"), None, lambda: _t().LightClientFinalityUpdate, 1),
        Protocol(
            _pid("light_client_optimistic_update"), None, lambda: _t().LightClientOptimisticUpdate, 1
        ),
    ]
}


def protocol_by_id(protocol_id: str) -> Protocol:
    p = BEACON_PROTOCOLS.get(protocol_id)
    if p is None:
        raise KeyError(f"unknown protocol {protocol_id}")
    return p
