"""Per-peer token-bucket rate limiter (reference
`reqresp/src/rate_limiter/` — quota per protocol per peer + global)."""

from __future__ import annotations

import time
from dataclasses import dataclass

__all__ = ["RateLimiterQuota", "RateLimiter"]


@dataclass(frozen=True)
class RateLimiterQuota:
    quota: int  # tokens per period
    period_sec: float


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, quota: int):
        self.tokens = float(quota)
        self.last = time.monotonic()


class RateLimiter:
    def __init__(self, quota: RateLimiterQuota, *, time_fn=time.monotonic):
        self.quota = quota
        self._time = time_fn
        self._buckets: dict[str, _Bucket] = {}

    def allows(self, peer_id: str, cost: int = 1) -> bool:
        b = self._buckets.get(peer_id)
        now = self._time()
        if b is None:
            b = _Bucket(self.quota.quota)
            b.last = now
            self._buckets[peer_id] = b
        # refill
        b.tokens = min(
            float(self.quota.quota),
            b.tokens + (now - b.last) * self.quota.quota / self.quota.period_sec,
        )
        b.last = now
        if b.tokens >= cost:
            b.tokens -= cost
            return True
        return False

    def prune(self, older_than_sec: float = 600.0) -> None:
        now = self._time()
        for pid in [p for p, b in self._buckets.items() if now - b.last > older_than_sec]:
            del self._buckets[pid]
