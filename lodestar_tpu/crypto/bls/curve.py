"""BLS12-381 curve groups G1 (over Fp) and G2 (over Fp2, M-twist).

Pure-Python reference; affine coordinates with None = point at infinity.
Counterpart of the blst C library's G1/G2 layer that the reference consumes
through `@chainsafe/bls` (reference `packages/beacon-node/src/chain/bls/maybeBatch.ts:18`).

G1: y^2 = x^3 + 4           over Fp
G2: y^2 = x^3 + 4(u+1)      over Fp2  (sextic M-twist)
"""

from __future__ import annotations

from . import fields as F
from .fields import P, R, BLS_X

# --- Standard generators (IETF / ZCash BLS12-381 ciphersuite) --------------
# Verified below at import: on-curve and of order R.
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# G2 curve coefficient b' = 4 * (u + 1)
B_G2 = (4, 4)

# Cofactors from the BLS12 family polynomials (checked against the curve
# orders below; h1 formula also cross-checked against #E(Fp) = p + 1 - t).
H1 = (BLS_X - 1) ** 2 // 3
H2 = (BLS_X**8 - 4 * BLS_X**7 + 5 * BLS_X**6 - 4 * BLS_X**4 + 6 * BLS_X**3 - 4 * BLS_X**2 - 4 * BLS_X + 13) // 9
_TRACE = BLS_X + 1
assert H1 * R == P + 1 - _TRACE  # #E(Fp)


# --- G1 --------------------------------------------------------------------


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - 4) % P == 0


def g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], (-pt[1]) % P)


def g1_double(pt):
    if pt is None:
        return None
    x, y = pt
    if y == 0:
        return None
    lam = 3 * x * x * F.fp_inv(2 * y % P) % P
    x3 = (lam * lam - 2 * x) % P
    y3 = (lam * (x - x3) - y) % P
    return (x3, y3)


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        return g1_double(p1)
    lam = (y2 - y1) * F.fp_inv((x2 - x1) % P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_mul(pt, k: int):
    return g1_mul_raw(pt, k % R)


# -- Jacobian ladders ---------------------------------------------------------
# Scalar multiplication runs inversion-FREE in Jacobian coordinates with a
# single field inversion at the end: the affine double-and-add above costs
# one ~381-bit modexp inversion PER STEP (~0.3 ms), which made every
# hash-to-curve h_eff clearing (~900 steps) and subgroup check take ~0.3 s
# — the dominant host cost of batch-verify preparation. Formulas:
# dbl-2009-l and add-2007-bl for a=0 short Weierstrass curves.


def _jac_double(X, Y, Z, mul, sq, addf, subf, dbl):
    A = sq(X)
    B = sq(Y)
    C = sq(B)
    D = dbl(subf(subf(sq(addf(X, B)), A), C))
    E = addf(dbl(A), A)  # 3A
    F_ = sq(E)
    X3 = subf(F_, dbl(D))
    Y3 = subf(mul(E, subf(D, X3)), dbl(dbl(dbl(C))))  # E(D-X3) - 8C
    Z3 = dbl(mul(Y, Z))
    return X3, Y3, Z3


def _jac_add(P1, P2, mul, sq, addf, subf, dbl, is_zero):
    X1, Y1, Z1 = P1
    X2, Y2, Z2 = P2
    Z1Z1 = sq(Z1)
    Z2Z2 = sq(Z2)
    U1 = mul(X1, Z2Z2)
    U2 = mul(X2, Z1Z1)
    S1 = mul(Y1, mul(Z2, Z2Z2))
    S2 = mul(Y2, mul(Z1, Z1Z1))
    H = subf(U2, U1)
    r = dbl(subf(S2, S1))
    if is_zero(H):
        if is_zero(r):
            return _jac_double(X1, Y1, Z1, mul, sq, addf, subf, dbl)
        return None  # P + (-P) = infinity
    I = sq(dbl(H))
    J = mul(H, I)
    V = mul(U1, I)
    X3 = subf(subf(sq(r), J), dbl(V))
    Y3 = subf(mul(r, subf(V, X3)), dbl(mul(S1, J)))
    Z3 = mul(subf(subf(sq(addf(Z1, Z2)), Z1Z1), Z2Z2), H)
    return X3, Y3, Z3


def _jac_mul(pt_affine, k, one, mul, sq, addf, subf, dbl, is_zero, inv):
    """Affine point -> affine point*k via a Jacobian double-and-add with
    one inversion at the end. Returns None for infinity."""
    acc = None  # Jacobian accumulator, None = infinity
    add_pt = (pt_affine[0], pt_affine[1], one)
    while k:
        if k & 1:
            acc = add_pt if acc is None else _jac_add(acc, add_pt, mul, sq, addf, subf, dbl, is_zero)
        k >>= 1
        if k:
            add_pt = _jac_double(*add_pt, mul, sq, addf, subf, dbl)
    if acc is None or is_zero(acc[2]):
        return None
    X, Y, Z = acc
    zinv = inv(Z)
    zinv2 = sq(zinv)
    return mul(X, zinv2), mul(Y, mul(zinv, zinv2))


def g1_mul_raw(pt, k: int):
    """Scalar mul WITHOUT reducing k mod R (for cofactor clearing)."""
    if pt is None or k == 0:
        return None
    if k < 0:
        return g1_mul_raw(g1_neg(pt), -k)
    return _jac_mul(
        pt,
        k,
        1,
        lambda a, b: a * b % P,
        lambda a: a * a % P,
        lambda a, b: (a + b) % P,
        lambda a, b: (a - b) % P,
        lambda a: 2 * a % P,
        lambda a: a % P == 0,
        F.fp_inv,
    )


def g1_in_subgroup(pt) -> bool:
    """φ-eigenvalue subgroup membership (order-R ladder retained as
    g1_in_subgroup_order_check for differential tests)."""
    return g1_in_subgroup_fast(pt)


def g1_in_subgroup_order_check(pt) -> bool:
    return g1_is_on_curve(pt) and g1_mul_raw(pt, R) is None


def g1_eq(p1, p2) -> bool:
    if p1 is None or p2 is None:
        return p1 is None and p2 is None
    return p1[0] % P == p2[0] % P and p1[1] % P == p2[1] % P


# --- G2 --------------------------------------------------------------------


def g2_rhs(x):
    """Twist curve RHS: x^3 + 4(u+1)."""
    return F.fp2_add(F.fp2_mul(F.fp2_sq(x), x), B_G2)


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return F.fp2_eq(F.fp2_sq(y), g2_rhs(x))


def g2_neg(pt):
    if pt is None:
        return None
    return (pt[0], F.fp2_neg(pt[1]))


def g2_double(pt):
    if pt is None:
        return None
    x, y = pt
    if F.fp2_is_zero(y):
        return None
    lam = F.fp2_mul(F.fp2_mul_scalar(F.fp2_sq(x), 3), F.fp2_inv(F.fp2_mul_scalar(y, 2)))
    x3 = F.fp2_sub(F.fp2_sq(lam), F.fp2_mul_scalar(x, 2))
    y3 = F.fp2_sub(F.fp2_mul(lam, F.fp2_sub(x, x3)), y)
    return (x3, y3)


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if F.fp2_eq(x1, x2):
        if F.fp2_is_zero(F.fp2_add(y1, y2)):
            return None
        return g2_double(p1)
    lam = F.fp2_mul(F.fp2_sub(y2, y1), F.fp2_inv(F.fp2_sub(x2, x1)))
    x3 = F.fp2_sub(F.fp2_sub(F.fp2_sq(lam), x1), x2)
    y3 = F.fp2_sub(F.fp2_mul(lam, F.fp2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_mul_raw(pt, k: int):
    """Scalar mul WITHOUT reducing k mod R (Jacobian ladder, one fp2
    inversion total — see the G1 ladder note)."""
    if pt is None or k == 0:
        return None
    if k < 0:
        return g2_mul_raw(g2_neg(pt), -k)
    return _jac_mul(
        pt,
        k,
        F.FP2_ONE,
        F.fp2_mul,
        F.fp2_sq,
        F.fp2_add,
        F.fp2_sub,
        lambda a: F.fp2_add(a, a),
        F.fp2_is_zero,
        F.fp2_inv,
    )


def g2_mul(pt, k: int):
    return g2_mul_raw(pt, k % R)


def g2_in_subgroup(pt) -> bool:
    """ψ-eigenvalue subgroup membership (order-R ladder retained as
    g2_in_subgroup_order_check for differential tests)."""
    return g2_in_subgroup_fast(pt)


def g2_in_subgroup_order_check(pt) -> bool:
    return g2_is_on_curve(pt) and g2_mul_raw(pt, R) is None


def g2_eq(p1, p2) -> bool:
    if p1 is None or p2 is None:
        return p1 is None and p2 is None
    return F.fp2_eq(p1[0], p2[0]) and F.fp2_eq(p1[1], p2[1])


def g1_clear_cofactor(pt):
    return g1_mul_raw(pt, H1)


# --- import-time sanity checks --------------------------------------------
# --- psi endomorphism (G2) ----------------------------------------------------
# The untwist-Frobenius-twist endomorphism psi on the M-twist: psi(x, y) =
# (conj(x) * CX, conj(y) * CY) with CX = 1/(1+u)^((p-1)/3),
# CY = 1/(1+u)^((p-1)/2) — computed from the curve constants at import, no
# tabulated magic values. Powers the Budroni–Pintore fast cofactor
# clearing (RFC 9380 App. G.3) and the [x]-eigenvalue subgroup check,
# replacing 636/255-bit scalar ladders with 64-bit ones.

_PSI_CX = F.fp2_pow(F.fp2_inv((1, 1)), (P - 1) // 3)
_PSI_CY = F.fp2_pow(F.fp2_inv((1, 1)), (P - 1) // 2)


def g2_psi(pt):
    if pt is None:
        return None
    x, y = pt
    return (F.fp2_mul(F.fp2_conj(x), _PSI_CX), F.fp2_mul(F.fp2_conj(y), _PSI_CY))


def g2_psi2(pt):
    return g2_psi(g2_psi(pt))


def g2_clear_cofactor_fast(pt):
    """Budroni–Pintore clearing: [x^2-x-1]P + [x-1]psi(P) + psi^2([2]P),
    identical output to [h_eff]P (differentially pinned in
    tests/crypto test_psi_fast_paths_match_slow). c1 = -x = |BLS_X|
    since x < 0."""
    if pt is None:
        return None
    c1 = -BLS_X  # positive
    t1 = g2_neg(g2_mul_raw(pt, c1))  # [x]P
    t2 = g2_psi(pt)
    t3 = g2_psi2(g2_double(pt))  # psi^2([2]P)
    t3 = g2_add(t3, g2_neg(t2))  # psi^2(2P) - psi(P)
    t2 = g2_add(t1, t2)  # [x]P + psi(P)
    t2 = g2_neg(g2_mul_raw(t2, c1))  # [x]([x]P + psi(P))
    t3 = g2_add(t3, t2)
    t3 = g2_add(t3, g2_neg(t1))  # - [x]P
    return g2_add(t3, g2_neg(pt))  # - P


# --- phi endomorphism (G1) ---------------------------------------------------
# GLV endomorphism phi(x, y) = (beta*x, y) with beta a primitive cube root
# of unity in Fp. For THIS beta (2^((p-1)/3); the other root gives the
# conjugate eigenvalue x^2 - 1), phi acts on G1 as multiplication by
# lambda = -x^2 mod r — asserted against the generator below. Subgroup
# test per Scott (eprint 2021/1130, the check blst/zkcrypto ship): a point
# on the curve is in G1 iff phi(P) == -[x^2]P, replacing the 255-bit
# order ladder with a 127-bit one.

BETA_G1 = pow(2, (P - 1) // 3, P)
assert BETA_G1 != 1 and pow(BETA_G1, 3, P) == 1
BLS_X2 = BLS_X * BLS_X  # x^2 = |eigenvalue| of -phi (positive)


def g1_phi(pt):
    if pt is None:
        return None
    return (BETA_G1 * pt[0] % P, pt[1])


def g1_in_subgroup_fast(pt) -> bool:
    """phi-eigenvalue check: P on the curve is in G1 iff phi(P) == -[x^2]P
    (pinned against the order-R check in the differential tests; the
    eigenvalue itself is asserted at import)."""
    if pt is None:
        return True
    if not g1_is_on_curve(pt):
        return False
    return g1_eq(g1_phi(pt), g1_neg(g1_mul_raw(pt, BLS_X2)))


def g2_in_subgroup_fast(pt) -> bool:
    """[x]-eigenvalue check: P on the twist is in G2 iff psi(P) == [x]P
    (pinned against the order-R check in the differential tests; the
    eigenvalue itself is asserted at import)."""
    if pt is None:
        return True
    if not g2_is_on_curve(pt):
        return False
    return g2_eq(g2_psi(pt), g2_mul_raw(pt, BLS_X))


# import-time self-checks pinning the psi constants to the slow paths
assert g2_eq(g2_psi(G2_GEN), g2_mul_raw(G2_GEN, BLS_X))  # eigenvalue = x
assert g2_in_subgroup_fast(g2_mul_raw(G2_GEN, 12345))

# import-time self-checks pinning the phi eigenvalue and the fast G1 check
assert g1_eq(g1_phi(G1_GEN), g1_mul(G1_GEN, (-BLS_X2) % R))  # eigenvalue = -x^2
assert g1_in_subgroup_fast(g1_mul_raw(G1_GEN, 12345))


assert g1_is_on_curve(G1_GEN), "G1 generator not on curve"
assert g2_is_on_curve(G2_GEN), "G2 generator not on twist"
assert g1_in_subgroup(G1_GEN), "G1 generator wrong order"
assert g2_in_subgroup(G2_GEN), "G2 generator wrong order"
