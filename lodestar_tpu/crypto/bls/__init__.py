"""BLS12-381 pure-Python reference implementation (CPU oracle + fallback).

Device-side counterparts live in ``lodestar_tpu.ops`` (limb-vectorized field
arithmetic, batched Miller loops) and ``lodestar_tpu.models.batch_verify``
(the flagship batched verification pipeline).
"""

from .api import (
    G2_INFINITY,
    PointDecodeError,
    SecretKey,
    SignatureSet,
    aggregate_pubkeys,
    aggregate_signatures,
    aggregate_verify,
    eth_fast_aggregate_verify,
    fast_aggregate_verify,
    sign,
    sk_to_pk,
    verify,
    verify_signature_sets,
)

__all__ = [
    "G2_INFINITY",
    "PointDecodeError",
    "SecretKey",
    "SignatureSet",
    "aggregate_pubkeys",
    "aggregate_signatures",
    "aggregate_verify",
    "eth_fast_aggregate_verify",
    "fast_aggregate_verify",
    "sign",
    "sk_to_pk",
    "verify",
    "verify_signature_sets",
]
