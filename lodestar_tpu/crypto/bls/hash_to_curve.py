"""Hash-to-curve for BLS12-381 G2: BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_.

Implements the full RFC 9380 pipeline byte-exactly for the eth2 ciphersuite:
expand_message_xmd(SHA-256) → hash_to_field(Fp2) → simplified-SWU on the
3-isogenous curve E' (§6.6.3) → 3-isogeny map to the twist (Appendix E.3)
→ effective-cofactor clearing (§8.8.2 h_eff).

The isogeny coefficients and h_eff are the fixed public constants of the
ciphersuite (RFC 9380 Appendix E.3 / §8.8.2). They are validated at import
by a structural check: a sample point on E' must map onto the twist curve
y^2 = x^3 + 4(u+1), which any wrong coefficient breaks. Byte-exactness is
pinned by the RFC 9380 J.10.1 known-answer vectors in
tests/crypto/test_bls_reference.py.

Role in the system: this runs host-side per message while pairings run on
TPU — mirroring the reference where hashToCurve happens inside blst per
verify call (`packages/beacon-node/src/chain/bls/maybeBatch.ts`).
"""

from __future__ import annotations

import hashlib

from . import fields as F
from .curve import g2_add, g2_clear_cofactor_fast, g2_is_on_curve, g2_mul_raw
from .fields import P

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# RFC 9380 parameters for expand_message_xmd with SHA-256
_B_IN_BYTES = 32  # hash output size
_R_IN_BYTES = 64  # hash block size
_L = 64  # ceil((ceil(log2(p)) + k) / 8) = (381 + 128)/8 rounded up


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd parameter overflow")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        tmp = bytes(x ^ y for x, y in zip(b0, b[-1]))
        b.append(hashlib.sha256(tmp + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(b)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = DST_G2):
    """RFC 9380 §5.2 hash_to_field for Fp2 (m=2, L=64)."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = _L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off : off + _L], "big") % P)
        out.append(tuple(coords))
    return out


def _sgn0(a) -> int:
    """RFC 9380 §4.1 sgn0 for Fp2 elements (lexicographic sign-of-zero)."""
    sign_0 = a[0] % 2
    zero_0 = 1 if a[0] % P == 0 else 0
    sign_1 = a[1] % 2
    return sign_0 | (zero_0 & sign_1)


# --- Simplified SWU on the 3-isogenous curve E' (RFC 9380 §6.6.3) ----------
# E': y^2 = x^3 + A'x + B' over Fp2, with (RFC 9380 §8.8.2):
#   A' = 240 * I,  B' = 1012 * (1 + I),  Z = -(2 + I)

_ISO_A = (0, 240)
_ISO_B = (1012, 1012)
_Z = ((-2) % P, (-1) % P)
_NEG_B_OVER_A = F.fp2_neg(F.fp2_mul(_ISO_B, F.fp2_inv(_ISO_A)))
_B_OVER_ZA = F.fp2_mul(_ISO_B, F.fp2_inv(F.fp2_mul(_Z, _ISO_A)))


def _gp(x):
    """RHS of the isogenous curve: x^3 + A'x + B'."""
    return F.fp2_add(F.fp2_add(F.fp2_mul(F.fp2_sq(x), x), F.fp2_mul(_ISO_A, x)), _ISO_B)


def map_to_curve_sswu(u):
    """Simplified SWU map Fp2 -> E'(Fp2) (RFC 9380 §6.6.2)."""
    tv1 = F.fp2_mul(_Z, F.fp2_sq(u))  # Z * u^2
    tv2 = F.fp2_add(F.fp2_sq(tv1), tv1)  # Z^2 u^4 + Z u^2
    if F.fp2_is_zero(tv2):
        x1 = _B_OVER_ZA  # B / (Z*A)
    else:
        x1 = F.fp2_mul(_NEG_B_OVER_A, F.fp2_add(F.FP2_ONE, F.fp2_inv(tv2)))
    gx1 = _gp(x1)
    y1 = F.fp2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = F.fp2_mul(tv1, x1)  # Z * u^2 * x1
        gx2 = _gp(x2)
        y2 = F.fp2_sqrt(gx2)
        assert y2 is not None, "SSWU guarantees gx1 or gx2 is square"
        x, y = x2, y2
    if _sgn0(u) != _sgn0(y):
        y = F.fp2_neg(y)
    return (x, y)


# --- 3-isogeny E' -> E (RFC 9380 Appendix E.3) -----------------------------
# x = x_num(x') / x_den(x'),  y = y' * y_num(x') / y_den(x')
# Constants below are the ciphersuite's fixed isogeny coefficients
# (RFC 9380 E.3); each Fp2 element is written (c0, c1) for c0 + c1*I.

_K1 = (  # x_num, degree 3
    (
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
    ),
    (
        0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
    ),
    (
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
    ),
    (
        0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0,
    ),
)
_K2 = (  # x_den, monic degree 2: x'^2 + k21*x' + k20
    (
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
    ),
    (
        0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
    ),
    F.FP2_ONE,
)
_K3 = (  # y_num, degree 3
    (
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
    ),
    (
        0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
    ),
    (
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
    ),
    (
        0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0,
    ),
)
_K4 = (  # y_den, monic degree 3: x'^3 + k42*x'^2 + k41*x' + k40
    (
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
    ),
    (
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
    ),
    (
        0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
    ),
    F.FP2_ONE,
)


def _poly_eval(coeffs, x):
    """Evaluate sum_i coeffs[i] * x^i (Horner)."""
    acc = F.FP2_ZERO
    for c in reversed(coeffs):
        acc = F.fp2_add(F.fp2_mul(acc, x), c)
    return acc


def iso_map_g2(pt):
    """3-isogeny E'(Fp2) -> E(Fp2) (the twist). Infinity maps to infinity."""
    if pt is None:
        return None
    x, y = pt
    x_den = _poly_eval(_K2, x)
    y_den = _poly_eval(_K4, x)
    if F.fp2_is_zero(x_den) or F.fp2_is_zero(y_den):
        # x' is a pole of the isogeny: the image is the point at infinity.
        return None
    x_out = F.fp2_mul(_poly_eval(_K1, x), F.fp2_inv(x_den))
    y_out = F.fp2_mul(y, F.fp2_mul(_poly_eval(_K3, x), F.fp2_inv(y_den)))
    return (x_out, y_out)


# Effective cofactor for G2 cofactor clearing (RFC 9380 §8.8.2). NOT the
# actual curve cofactor h2 — the ciphersuite fixes this specific scalar so
# all implementations produce identical points (it encodes the
# Budroni-Pintore ψ-based fast clearing as a plain scalar).
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def clear_cofactor_g2(pt):
    """h_eff * P (RFC 9380 §7 clear_cofactor for the BLS12381G2 suites),
    via the Budroni–Pintore ψ-endomorphism method (App. G.3) — output
    identical to [h_eff]P (differentially pinned in tests), ~5x faster."""
    return g2_clear_cofactor_fast(pt)


# --- import-time structural validation of the isogeny constants ------------
# Find a deterministic sample point on E' and check its image lies on the
# twist; any wrong k-coefficient breaks this (byte-exactness is pinned by
# the RFC 9380 J.10.1 KATs in tests).
def _selfcheck() -> None:
    for k in range(1, 64):
        x = (k, 1)
        y = F.fp2_sqrt(_gp(x))
        if y is not None:
            img = iso_map_g2((x, y))
            assert img is not None and g2_is_on_curve(img), "isogeny constants invalid"
            return
    raise RuntimeError("no sample point found on isogenous curve")  # pragma: no cover


_selfcheck()


def map_to_curve_g2(u):
    """map_to_curve for the eth2 suite: SSWU on E' then 3-isogeny to E."""
    return iso_map_g2(map_to_curve_sswu(u))


def hash_to_g2(msg: bytes, dst: bytes = DST_G2):
    """hash_to_curve RO variant (RFC 9380 §3): eth2-byte-exact G2 hashing."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q = g2_add(map_to_curve_g2(u0), map_to_curve_g2(u1))
    return clear_cofactor_g2(q)
