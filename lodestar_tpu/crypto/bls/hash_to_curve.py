"""Hash-to-curve for BLS12-381 G2 (RFC 9380 structure).

Implements the full RFC 9380 pipeline — expand_message_xmd(SHA-256) →
hash_to_field(Fp2) → map_to_curve → clear_cofactor — with one documented
deviation: map_to_curve uses the Shallue–van de Woestijne map (RFC 9380
§6.6.1), whose constants are all *derivable at runtime* from the curve
equation, instead of the eth2 ciphersuite's SSWU-on-isogenous-curve map,
whose 3-isogeny coefficient tables are large literal constants. Every other
stage (domain separation, expansion, field hashing, cofactor clearing)
matches BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_. The map is a
deterministic encoding to the correct subgroup, so all protocol-level
properties (uniqueness of signatures, aggregation, proofs of possession)
hold; only cross-implementation signature bytes differ until the SSWU
isogeny tables are added (tracked as a parity TODO).

Role in the system: this runs host-side per message while pairings run on
TPU — mirroring the reference where hashToCurve happens inside blst per
verify call (`packages/beacon-node/src/chain/bls/maybeBatch.ts`).
"""

from __future__ import annotations

import hashlib

from . import fields as F
from .curve import g2_add, g2_clear_cofactor, g2_rhs
from .fields import P

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# RFC 9380 parameters for expand_message_xmd with SHA-256
_B_IN_BYTES = 32  # hash output size
_R_IN_BYTES = 64  # hash block size
_L = 64  # ceil((ceil(log2(p)) + k) / 8) = (381 + 128)/8 rounded up


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd parameter overflow")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        tmp = bytes(x ^ y for x, y in zip(b0, b[-1]))
        b.append(hashlib.sha256(tmp + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(b)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = DST_G2):
    """RFC 9380 §5.2 hash_to_field for Fp2 (m=2, L=64)."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = _L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off : off + _L], "big") % P)
        out.append(tuple(coords))
    return out


# --- Shallue-van de Woestijne map to the G2 twist --------------------------
# Curve: y^2 = g(x) = x^3 + B,  B = 4(u+1), A = 0.


_g = g2_rhs


def _sgn0(a) -> int:
    """RFC 9380 sgn0 for Fp2 (sign of 0 extension)."""
    sign_0 = a[0] % 2
    zero_0 = 1 if a[0] % P == 0 else 0
    sign_1 = a[1] % 2
    return sign_0 | (zero_0 & sign_1)


def _find_svdw_z():
    """Search for a Z meeting the RFC 9380 §6.6.1 criteria (A=0 curve)."""
    candidates = []
    for c1 in range(-4, 5):
        for c0 in range(-4, 5):
            candidates.append((c0 % P, c1 % P))
    for z in candidates:
        gz = _g(z)
        if F.fp2_is_zero(gz):
            continue
        three_z2 = F.fp2_mul_scalar(F.fp2_sq(z), 3)  # 3Z^2 + 4A, A=0
        if F.fp2_is_zero(three_z2):
            continue
        ratio = F.fp2_neg(F.fp2_mul(three_z2, F.fp2_inv(F.fp2_mul_scalar(gz, 4))))
        if F.fp2_legendre(ratio) != 1:
            continue
        g_neg_half_z = _g(F.fp2_mul(F.fp2_neg(z), F.fp2_inv((2, 0))))
        if F.fp2_legendre(gz) == 1 or F.fp2_legendre(g_neg_half_z) == 1:
            return z
    raise RuntimeError("no SvdW Z found")  # pragma: no cover


_Z = _find_svdw_z()
_C1 = _g(_Z)  # g(Z)
_C2 = F.fp2_mul(F.fp2_neg(_Z), F.fp2_inv((2, 0)))  # -Z/2
_3Z2 = F.fp2_mul_scalar(F.fp2_sq(_Z), 3)
_c3_sq = F.fp2_neg(F.fp2_mul(_C1, _3Z2))  # -g(Z)*(3Z^2)
_C3 = F.fp2_sqrt(_c3_sq)
assert _C3 is not None
if _sgn0(_C3) == 1:
    _C3 = F.fp2_neg(_C3)
_C4 = F.fp2_neg(F.fp2_mul(F.fp2_mul_scalar(_C1, 4), F.fp2_inv(_3Z2)))  # -4g(Z)/(3Z^2)


def map_to_curve_svdw(u):
    """SvdW map Fp2 -> E'(Fp2) (twist curve point, not yet in subgroup)."""
    tv1 = F.fp2_mul(F.fp2_sq(u), _C1)
    tv2 = F.fp2_add(F.FP2_ONE, tv1)
    tv1 = F.fp2_sub(F.FP2_ONE, tv1)
    tv3 = F.fp2_mul(tv1, tv2)
    tv3 = F.fp2_inv(tv3) if not F.fp2_is_zero(tv3) else F.FP2_ZERO  # inv0
    tv4 = F.fp2_mul(F.fp2_mul(F.fp2_mul(u, tv1), tv3), _C3)
    x1 = F.fp2_sub(_C2, tv4)
    x2 = F.fp2_add(_C2, tv4)
    x3 = F.fp2_add(_Z, F.fp2_mul(_C4, F.fp2_sq(F.fp2_mul(F.fp2_sq(tv2), tv3))))
    if F.fp2_legendre(_g(x1)) == 1:
        x = x1
    elif F.fp2_legendre(_g(x2)) == 1:
        x = x2
    else:
        x = x3
    y = F.fp2_sqrt(_g(x))
    assert y is not None, "SvdW guarantees a square g(x)"
    if _sgn0(u) != _sgn0(y):
        y = F.fp2_neg(y)
    return (x, y)


def hash_to_g2(msg: bytes, dst: bytes = DST_G2):
    """hash_to_curve (RO variant): two map evaluations + cofactor clearing."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q = g2_add(map_to_curve_svdw(u0), map_to_curve_svdw(u1))
    # cofactor clearing guarantees subgroup membership (tested in
    # tests/crypto: hash outputs satisfy g2_in_subgroup)
    return g2_clear_cofactor(q)
