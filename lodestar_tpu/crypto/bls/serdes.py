"""BLS12-381 point (de)serialization — ZCash compressed encoding.

48-byte G1 / 96-byte G2 compressed points with the standard flag bits in the
top byte: 0x80 = compressed, 0x40 = infinity, 0x20 = y is lexicographically
the larger root. This is the wire format of `BLSPubkey` (Bytes48) and
`BLSSignature` (Bytes96) used throughout the reference's SSZ types
(`packages/types/src/primitive/sszTypes.ts`) and the blst bindings.
"""

from __future__ import annotations

from . import fields as F
from .fields import P

_COMPRESSED = 0x80
_INFINITY = 0x40
_SIGN = 0x20
_HALF_P = (P - 1) // 2


class PointDecodeError(ValueError):
    pass


def _fp_is_larger(y: int) -> bool:
    return y > _HALF_P


def _fp2_is_larger(y) -> bool:
    """Lexicographic order on (c1, c0) per the ZCash convention."""
    if y[1] != 0:
        return y[1] > _HALF_P
    return y[0] > _HALF_P


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        return bytes([_COMPRESSED | _INFINITY]) + b"\x00" * 47
    x, y = pt
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= _COMPRESSED
    if _fp_is_larger(y):
        out[0] |= _SIGN
    return bytes(out)


def g1_from_bytes(data: bytes):
    """Decompress a G1 point. On-curve enforced; subgroup check is separate."""
    if len(data) != 48:
        raise PointDecodeError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _COMPRESSED:
        raise PointDecodeError("uncompressed G1 encoding not supported")
    if flags & _INFINITY:
        if any(data[1:]) or flags & ~( _COMPRESSED | _INFINITY):
            raise PointDecodeError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise PointDecodeError("G1 x coordinate >= p")
    y = F.fp_sqrt((x * x * x + 4) % P)
    if y is None:
        raise PointDecodeError("G1 x not on curve")
    if bool(flags & _SIGN) != _fp_is_larger(y):
        y = (-y) % P
    return (x, y)


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        return bytes([_COMPRESSED | _INFINITY]) + b"\x00" * 95
    (x0, x1), y = pt
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= _COMPRESSED
    if _fp2_is_larger(y):
        out[0] |= _SIGN
    return bytes(out)


def g2_from_bytes(data: bytes):
    if len(data) != 96:
        raise PointDecodeError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _COMPRESSED:
        raise PointDecodeError("uncompressed G2 encoding not supported")
    if flags & _INFINITY:
        if any(data[1:]) or flags & ~( _COMPRESSED | _INFINITY):
            raise PointDecodeError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise PointDecodeError("G2 x coordinate >= p")
    x = (x0, x1)
    from .curve import g2_rhs

    y = F.fp2_sqrt(g2_rhs(x))
    if y is None:
        raise PointDecodeError("G2 x not on twist curve")
    if bool(flags & _SIGN) != _fp2_is_larger(y):
        y = F.fp2_neg(y)
    return (x, y)
