"""High-level BLS signature API (eth2 flavor: minimal-pubkey-size).

Pure-Python CPU implementation of the same surface the reference gets from
`@chainsafe/bls`: sign / verify / aggregate / fastAggregateVerify /
aggregateVerify / verifyMultipleSignatures (random-linear-combination batch
verification — reference `packages/beacon-node/src/chain/bls/maybeBatch.ts:16-38`).

Pubkeys live in G1 (48B compressed), signatures in G2 (96B compressed),
messages hash to G2.  This module is the *oracle + fallback*; the production
path batches the same math onto TPU via ``lodestar_tpu.models.batch_verify``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import curve as C
from . import fields as F
from .curve import G1_GEN, g1_add, g1_mul, g1_neg
from .fields import R
from .hash_to_curve import hash_to_g2
from .pairing import miller_loop, final_exponentiation, pairings_are_one
from .serdes import (
    PointDecodeError,
    g1_from_bytes,
    g1_to_bytes,
    g2_from_bytes,
    g2_to_bytes,
)

__all__ = [
    "SecretKey",
    "sk_to_pk",
    "sign",
    "verify",
    "aggregate_pubkeys",
    "aggregate_signatures",
    "fast_aggregate_verify",
    "eth_fast_aggregate_verify",
    "G2_INFINITY",
    "aggregate_verify",
    "SignatureSet",
    "verify_signature_sets",
    "PointDecodeError",
]


@dataclass(frozen=True)
class SecretKey:
    scalar: int

    def __post_init__(self):
        # same range contract as from_bytes — the direct constructor must
        # not mint the identity-key footgun (sk=0 signs everything with
        # the infinity signature)
        if not 0 < self.scalar < R:
            raise ValueError("secret key out of range (must satisfy 0 < SK < r)")

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        """Strict IETF deserialization: 32 bytes, 0 < SK < r (no reduction)."""
        if len(data) != 32:
            raise ValueError("secret key must be 32 bytes")
        k = int.from_bytes(data, "big")
        if k == 0 or k >= R:
            raise ValueError("secret key out of range (must satisfy 0 < SK < r)")
        return cls(k)

    def to_pubkey_point(self):
        return g1_mul(G1_GEN, self.scalar)

    def to_pubkey(self) -> bytes:
        return g1_to_bytes(self.to_pubkey_point())


def sk_to_pk(sk: SecretKey) -> bytes:
    return sk.to_pubkey()


def sign(sk: SecretKey, message: bytes) -> bytes:
    h = hash_to_g2(message)
    return g2_to_bytes(C.g2_mul(h, sk.scalar))


def _decode_pubkey(pk: bytes):
    """KeyValidate: decompress, reject infinity, subgroup check."""
    pt = g1_from_bytes(pk)
    if pt is None:
        raise PointDecodeError("infinity pubkey rejected (KeyValidate)")
    if not C.g1_in_subgroup(pt):
        raise PointDecodeError("pubkey not in G1 subgroup")
    return pt


def _decode_signature(sig: bytes):
    pt = g2_from_bytes(sig)
    if pt is not None and not C.g2_in_subgroup(pt):
        raise PointDecodeError("signature not in G2 subgroup")
    return pt


def verify(pk: bytes, message: bytes, sig: bytes) -> bool:
    """Core verify: e(pk, H(m)) == e(g1, sig)."""
    try:
        pk_pt = _decode_pubkey(pk)
        sig_pt = _decode_signature(sig)
    except PointDecodeError:
        return False
    if sig_pt is None:
        return False
    h = hash_to_g2(message)
    return pairings_are_one([(g1_neg(G1_GEN), sig_pt), (pk_pt, h)])


def aggregate_pubkeys(pks: list[bytes]) -> bytes:
    if not pks:
        # An empty aggregate would encode the G1 infinity point — an invalid
        # pubkey per KeyValidate. Mirror aggregate_signatures and refuse.
        raise ValueError("cannot aggregate empty pubkey list")
    pts = [_decode_pubkey(pk) for pk in pks]
    acc = None
    for pt in pts:
        acc = g1_add(acc, pt)
    return g1_to_bytes(acc)


def aggregate_signatures(sigs: list[bytes]) -> bytes:
    if not sigs:
        raise ValueError("cannot aggregate empty signature list")
    acc = None
    for s in sigs:
        acc = C.g2_add(acc, g2_from_bytes(s))
    return g2_to_bytes(acc)


def fast_aggregate_verify(pks: list[bytes], message: bytes, sig: bytes) -> bool:
    """All pks signed the same message (sync-committee / aggregate path)."""
    if not pks:
        return False
    try:
        agg = None
        for pk in pks:
            agg = g1_add(agg, _decode_pubkey(pk))
        sig_pt = _decode_signature(sig)
    except PointDecodeError:
        return False
    if sig_pt is None or agg is None:
        return False
    h = hash_to_g2(message)
    return pairings_are_one([(g1_neg(G1_GEN), sig_pt), (agg, h)])


G2_INFINITY = b"\xc0" + b"\x00" * 95


def eth_fast_aggregate_verify(pks: list[bytes], message: bytes, sig: bytes) -> bool:
    """Altair eth_fast_aggregate_verify: empty participants + infinity sig is
    valid (sync-committee path, reference
    `packages/state-transition/src/signatureSets` sync committee sets).
    """
    if not pks and sig == G2_INFINITY:
        return True
    return fast_aggregate_verify(pks, message, sig)


def aggregate_verify(pks: list[bytes], messages: list[bytes], sig: bytes) -> bool:
    """Distinct messages, one aggregated signature."""
    if not pks or len(pks) != len(messages):
        return False
    try:
        pk_pts = [_decode_pubkey(pk) for pk in pks]
        sig_pt = _decode_signature(sig)
    except PointDecodeError:
        return False
    if sig_pt is None:
        return False
    pairs = [(g1_neg(G1_GEN), sig_pt)]
    pairs += [(pk, hash_to_g2(m)) for pk, m in zip(pk_pts, messages)]
    return pairings_are_one(pairs)


@dataclass(frozen=True)
class SignatureSet:
    """One verification work item: (aggregated) pubkey, signing root, signature.

    Mirrors ISignatureSet (reference
    `packages/state-transition/src/util/signatureSets.ts:10`) after pubkey
    aggregation has been applied — i.e. the exact wire shape shipped to the
    worker pool as SignatureSetsWorkerReq
    (`packages/beacon-node/src/chain/bls/multithread/types.ts:8-17`).
    """

    pubkey: bytes  # 48B compressed G1
    message: bytes  # 32B signing root
    signature: bytes  # 96B compressed G2


def _random_coeff() -> int:
    """Nonzero 64-bit blinding scalar for batch verification."""
    while True:
        k = int.from_bytes(os.urandom(8), "big")
        if k != 0:
            return k


def verify_signature_sets(sets: list[SignatureSet]) -> bool:
    """Random-linear-combination batch verification (always randomized).

    Checks e(-g1, sum_i r_i S_i) * prod_i e(r_i PK_i, H(m_i)) == 1 with one
    shared final exponentiation — the semantics of blst's
    verifyMultipleSignatures used by the reference worker
    (`packages/beacon-node/src/chain/bls/multithread/worker.ts:52-96`).
    The asymptotic ~2x win over one-by-one verification is the reference's
    own bound (`chain/bls/interface.ts:8`). There is deliberately no
    way to disable the blinding coefficients: an unrandomized batch is
    forgeable (defects in different sets can cancel).
    """
    if not sets:
        return False
    try:
        decoded = [
            (_decode_pubkey(s.pubkey), hash_to_g2(s.message), _decode_signature(s.signature))
            for s in sets
        ]
    except PointDecodeError:
        return False
    if any(sig is None for _, _, sig in decoded):
        return False
    coeffs = [1] + [_random_coeff() for _ in decoded[1:]]
    sig_acc = None
    f = F.FP12_ONE
    for (pk, h, sig), r_i in zip(decoded, coeffs):
        sig_acc = C.g2_add(sig_acc, C.g2_mul(sig, r_i))
        f = F.fp12_mul(f, miller_loop(g1_mul(pk, r_i), h))
    if sig_acc is None:
        return False
    f = F.fp12_mul(f, miller_loop(g1_neg(G1_GEN), sig_acc))
    return F.fp12_eq(final_exponentiation(f), F.FP12_ONE)
