"""Optimal ate pairing on BLS12-381 (pure-Python reference).

Algorithm notes (mirrored 1:1 by the batched TPU kernel in
``lodestar_tpu/ops/pairing.py``):

* Affine Miller loop over the twist. The G2 point stays in Fp2 twist
  coordinates; the untwist ψ(x,y) = (x·w^-2, y·w^-3) (w^6 = xi) is folded
  into a *sparse* line representation with three Fp2 coefficients in the
  w^0, w^3, w^5 slots. Lines are scaled by xi ∈ Fp2* — a proper-subfield
  factor killed by the final exponentiation. Vertical lines lie entirely in
  Fp6 and are dropped for the same reason.
* Final exponentiation computes f^(3·(p^12-1)/r) — the *cube* of the
  standard ate pairing — using the Hayashida–Hayasaka–Teruya hard-part
  decomposition 3(p^4-p^2+1)/r = (x-1)^2·(x+p)·(x^2+p^2-1) + 3 (identity
  asserted at import). Since gcd(3, r) = 1, cubing is a bijection on GT and
  all pairing-product equality checks are unaffected. This is what makes
  batch verification cheap: one shared final-exp per batch of Miller loops,
  the same trick as `verifyMultipleSignatures` in the reference
  (`packages/beacon-node/src/chain/bls/maybeBatch.ts:18`).

Affine + batch-inversion is the deliberate design point for the TPU port:
all signature sets in a device batch run the Miller loop in lockstep, so the
per-step Fp2 inversions amortize via Montgomery's batch-inversion trick
across the batch dimension.
"""

from __future__ import annotations

from . import fields as F
from .curve import G2_GEN  # noqa: F401  (re-export convenience)
from .fields import BLS_X, BLS_X_ABS, P, R, XI

# Bits of |x| below the most significant one, MSB first.
_X_BITS = [int(b) for b in bin(BLS_X_ABS)[3:]]

# HHT hard-part identity: 3*(p^4-p^2+1)/r == (x-1)^2 (x+p) (x^2+p^2-1) + 3
assert (P**4 - P**2 + 1) % R == 0
assert 3 * ((P**4 - P**2 + 1) // R) == (BLS_X - 1) ** 2 * (BLS_X + P) * (BLS_X**2 + P**2 - 1) + 3


def _sparse_line(c0, c3, c5):
    """Build the Fp12 element c0 + c3*w^3 + c5*w^5 (w^3 = v*w, w^5 = v^2*w)."""
    return ((c0, F.FP2_ZERO, F.FP2_ZERO), (F.FP2_ZERO, c3, c5))


def _line_eval(t, lam, p_g1):
    """Line through twist point t with twist-slope lam, evaluated at P in G1.

    Returns the xi-scaled sparse value: yP*xi - lam*xP*w^5 + (lam*xT - yT)*w^3.
    """
    xt, yt = t
    xp, yp = p_g1
    c0 = F.fp2_mul_scalar(XI, yp)
    c3 = F.fp2_sub(F.fp2_mul(lam, xt), yt)
    c5 = F.fp2_neg(F.fp2_mul_scalar(lam, xp))
    return _sparse_line(c0, c3, c5)


def miller_loop(p_g1, q_g2):
    """Miller loop f_{|x|,Q}(P), conjugated for the negative BLS parameter.

    p_g1: affine (x, y) in G1 over Fp. q_g2: affine (x, y) on the twist over
    Fp2. Neither may be infinity (callers handle identity separately).
    """
    t = q_g2
    f = F.FP12_ONE
    for bit in _X_BITS:
        # doubling step
        xt, yt = t
        lam = F.fp2_mul(
            F.fp2_mul_scalar(F.fp2_sq(xt), 3),
            F.fp2_inv(F.fp2_mul_scalar(yt, 2)),
        )
        f = F.fp12_mul(F.fp12_sq(f), _line_eval(t, lam, p_g1))
        x3 = F.fp2_sub(F.fp2_sq(lam), F.fp2_mul_scalar(xt, 2))
        y3 = F.fp2_sub(F.fp2_mul(lam, F.fp2_sub(xt, x3)), yt)
        t = (x3, y3)
        if bit:
            # addition step (T != +-Q throughout the ate loop: the running
            # multiple k of Q satisfies 1 < k < |x| << r)
            xt, yt = t
            xq, yq = q_g2
            lam = F.fp2_mul(F.fp2_sub(yt, yq), F.fp2_inv(F.fp2_sub(xt, xq)))
            f = F.fp12_mul(f, _line_eval(q_g2, lam, p_g1))
            x3 = F.fp2_sub(F.fp2_sub(F.fp2_sq(lam), xt), xq)
            y3 = F.fp2_sub(F.fp2_mul(lam, F.fp2_sub(xt, x3)), yt)
            t = (x3, y3)
    # x < 0: f_{x,Q} = conj(f_{|x|,Q})
    return F.fp12_conj(f)


def _pow_u(f):
    """f^|x| by square-and-multiply (|x| has Hamming weight 6)."""
    result = f
    for bit in _X_BITS:
        result = F.fp12_sq(result)
        if bit:
            result = F.fp12_mul(result, f)
    return result


def _pow_x(f):
    """f^x for the negative parameter x; valid in the cyclotomic subgroup."""
    return F.fp12_conj(_pow_u(f))


def _pow_xm1(f):
    """f^(x-1) = conj(f^(|x|+1)); cyclotomic subgroup only."""
    return F.fp12_conj(F.fp12_mul(_pow_u(f), f))


def final_exponentiation(f):
    """f^(3*(p^12-1)/r); see module docstring for the cubing caveat."""
    # easy part: f^((p^6-1)(p^2+1))
    f = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))
    f = F.fp12_mul(F.fp12_frobenius(f, 2), f)
    # hard part (cyclotomic from here; inverse == conjugate)
    y = _pow_xm1(f)  # f^(x-1)
    y = _pow_xm1(y)  # f^((x-1)^2)
    y = F.fp12_mul(_pow_x(y), F.fp12_frobenius(y, 1))  # ^(x+p)
    y = F.fp12_mul(
        F.fp12_mul(_pow_x(_pow_x(y)), F.fp12_frobenius(y, 2)),
        F.fp12_conj(y),
    )  # ^(x^2+p^2-1)
    f3 = F.fp12_mul(F.fp12_mul(f, f), f)
    return F.fp12_mul(y, f3)


def pairing(p_g1, q_g2):
    """Full (cubed) ate pairing e(P, Q)^3. Returns FP12_ONE for infinity inputs."""
    if p_g1 is None or q_g2 is None:
        return F.FP12_ONE
    return final_exponentiation(miller_loop(p_g1, q_g2))


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i)^3 with one shared final exponentiation."""
    f = F.FP12_ONE
    for p_g1, q_g2 in pairs:
        if p_g1 is None or q_g2 is None:
            continue
        f = F.fp12_mul(f, miller_loop(p_g1, q_g2))
    return final_exponentiation(f)


def pairings_are_one(pairs) -> bool:
    """Check prod_i e(P_i, Q_i) == 1 (the batch-verify core predicate)."""
    return F.fp12_eq(multi_pairing(pairs), F.FP12_ONE)
