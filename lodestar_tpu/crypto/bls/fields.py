"""BLS12-381 field tower arithmetic (pure-Python reference implementation).

This is the host-side CPU oracle for the TPU (JAX/Pallas) kernels in
``lodestar_tpu.ops`` and the fallback verifier used when no device is present —
the same role ``@chainsafe/bls`` herumi (WASM) plays in the reference
implementation (see reference `packages/beacon-node/src/chain/bls/multithread/index.ts:127-132`
impl switch, and `packages/light-client/src/index.ts:160` initBls fallback).

Functional style (plain ints / tuples) on purpose: every function here has a
1:1 vectorized counterpart in ``lodestar_tpu/ops`` operating on limb arrays,
which makes differential testing of intermediates trivial.

Tower construction (standard for BLS12-381):
  Fp2  = Fp[u]  / (u^2 + 1)
  Fp6  = Fp2[v] / (v^3 - (u + 1))
  Fp12 = Fp6[w] / (w^2 - v)

All Fp2 elements are (c0, c1) tuples, Fp6 are 3-tuples of Fp2, Fp12 are
2-tuples of Fp6.
"""

from __future__ import annotations

# --- Curve constants -------------------------------------------------------
# Base field modulus p, subgroup order r, and the BLS parameter x (negative).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X = -0xD201000000010000  # the BLS family parameter; negative for BLS12-381
BLS_X_ABS = -BLS_X

# Consistency checks of the family construction (these tie P, R, BLS_X
# together; if any memorized constant were wrong these would fail at import):
#   r = x^4 - x^2 + 1
#   p = (x - 1)^2 * r / 3 + x
assert R == BLS_X**4 - BLS_X**2 + 1
assert P == (BLS_X - 1) ** 2 * R // 3 + BLS_X
assert P % 4 == 3  # sqrt in Fp via a^((p+1)/4)

# G1 curve: y^2 = x^3 + 4.  G2 (M-twist): y^2 = x^3 + 4(u+1) over Fp2.
B_G1 = 4
XI = (1, 1)  # u + 1, the sextic-twist / Fp6 non-residue

# --- Fp --------------------------------------------------------------------


def fp_add(a: int, b: int) -> int:
    return (a + b) % P


def fp_sub(a: int, b: int) -> int:
    return (a - b) % P


def fp_mul(a: int, b: int) -> int:
    return (a * b) % P


def fp_neg(a: int) -> int:
    return (-a) % P


def fp_inv(a: int) -> int:
    if a % P == 0:
        raise ZeroDivisionError("inverse of 0 in Fp")
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> int | None:
    """Square root in Fp (p ≡ 3 mod 4), or None if a is a non-residue."""
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a % P else None


# --- Fp2 = Fp[u]/(u^2+1) ---------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_conj(a):
    """Conjugate c0 - c1*u == Frobenius (a^p), since u^p = -u for p ≡ 3 mod 4."""
    return (a[0], (-a[1]) % P)


def fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    # (a0+a1)(b0+b1) - t0 - t1 = a0*b1 + a1*b0 (Karatsuba)
    return ((t0 - t1) % P, ((a0 + a1) * (b0 + b1) - t0 - t1) % P)


def fp2_sq(a):
    a0, a1 = a
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fp2_mul_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def fp2_mul_xi(a):
    """Multiply by xi = u + 1: (c0 - c1) + (c0 + c1) u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fp2_inv(a):
    a0, a1 = a
    norm = (a0 * a0 + a1 * a1) % P  # a * conj(a) = a0^2 + a1^2
    ninv = fp_inv(norm)
    return (a0 * ninv % P, (-a1) * ninv % P)


def fp2_eq(a, b) -> bool:
    return a[0] % P == b[0] % P and a[1] % P == b[1] % P


def fp2_is_zero(a) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


def fp2_pow(a, e: int):
    result = FP2_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp2_mul(result, base)
        base = fp2_sq(base)
        e >>= 1
    return result


def fp2_legendre(a) -> int:
    """Euler criterion in Fp2: a^((p^2-1)/2) is 1 (QR), p^2-1≡-1 (QNR), or 0."""
    t = fp2_pow(a, (P * P - 1) // 2)
    if fp2_eq(t, FP2_ONE):
        return 1
    if fp2_is_zero(t):
        return 0
    return -1


def _find_fp2_nonresidue():
    # small search; (u + k) for small k quickly yields a QNR
    for k in range(1, 20):
        cand = (k, 1)
        if fp2_legendre(cand) == -1:
            return cand
    raise RuntimeError("no Fp2 non-residue found")  # pragma: no cover


_FP2_QNR = _find_fp2_nonresidue()
# Tonelli-Shanks precomputation for Fp2: p^2 - 1 = Q * 2^S with Q odd
_TS_S = 3  # v2(p-1)=1, v2(p+1)=2
_TS_Q = (P * P - 1) >> _TS_S
assert _TS_Q & 1 == 1
_TS_Z = fp2_pow(_FP2_QNR, _TS_Q)  # generator of the 2-Sylow subgroup


def fp2_sqrt(a):
    """Square root in Fp2 via Tonelli-Shanks (S=3), or None for non-residues."""
    if fp2_is_zero(a):
        return FP2_ZERO
    if fp2_legendre(a) != 1:
        return None
    m = _TS_S
    c = _TS_Z
    t = fp2_pow(a, _TS_Q)
    r_ = fp2_pow(a, (_TS_Q + 1) // 2)
    while not fp2_eq(t, FP2_ONE):
        # find least i with t^(2^i) == 1
        i = 0
        t2 = t
        while not fp2_eq(t2, FP2_ONE):
            t2 = fp2_sq(t2)
            i += 1
        b = c
        for _ in range(m - i - 1):
            b = fp2_sq(b)
        m = i
        c = fp2_sq(b)
        t = fp2_mul(t, c)
        r_ = fp2_mul(r_, b)
    return r_


# --- Fp6 = Fp2[v]/(v^3 - xi) ----------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    # c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    c0 = fp2_add(t0, fp2_mul_xi(fp2_sub(fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), t1), t2)))
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    c1 = fp2_add(fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), t0), t1), fp2_mul_xi(t2))
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    c2 = fp2_add(fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), t0), t2), t1)
    return (c0, c1, c2)


def fp6_sq(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    """Multiply by v: (a0, a1, a2) -> (xi*a2, a0, a1)."""
    return (fp2_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    # Standard: c0 = a0^2 - xi a1 a2, c1 = xi a2^2 - a0 a1, c2 = a1^2 - a0 a2
    c0 = fp2_sub(fp2_sq(a0), fp2_mul_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul_xi(fp2_sq(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sq(a1), fp2_mul(a0, a2))
    # t = a0 c0 + xi (a2 c1 + a1 c2)
    t = fp2_add(fp2_mul(a0, c0), fp2_mul_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))))
    tinv = fp2_inv(t)
    return (fp2_mul(c0, tinv), fp2_mul(c1, tinv), fp2_mul(c2, tinv))


def fp6_eq(a, b) -> bool:
    return all(fp2_eq(x, y) for x, y in zip(a, b))


# --- Fp12 = Fp6[w]/(w^2 - v) -----------------------------------------------

FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sq(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    """Conjugation over Fp6 (i.e. a^(p^6)): (a0, -a1).

    For elements in the cyclotomic subgroup (post easy-part of the final
    exponentiation) this equals the inverse.
    """
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    # 1/(a0 + a1 w) = (a0 - a1 w) / (a0^2 - v a1^2)
    t = fp6_sub(fp6_sq(a0), fp6_mul_by_v(fp6_sq(a1)))
    tinv = fp6_inv(t)
    return (fp6_mul(a0, tinv), fp6_neg(fp6_mul(a1, tinv)))


def fp12_eq(a, b) -> bool:
    return fp6_eq(a[0], b[0]) and fp6_eq(a[1], b[1])


def fp12_pow(a, e: int):
    if e < 0:
        return fp12_pow(fp12_inv(a), -e)
    result = FP12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sq(base)
        e >>= 1
    return result


# --- Frobenius endomorphism on Fp12 ---------------------------------------
# a^p computed coefficient-wise. For a = sum_{i<6} c_i * w^i with c_i in Fp2
# (w^2 = v, v^3 = xi, w^6 = xi), Frobenius maps c_i -> conj(c_i) * g_i where
# g_i = xi^(i*(p-1)/6) -- all computable at runtime, no magic tables.

_FROB_COEFF = tuple(fp2_pow(XI, i * (P - 1) // 6) for i in range(6))


def _fp12_to_w_coeffs(a):
    """Fp12 as ((c0,c2,c4),(c1,c3,c5)) over w-powers: a = sum c_i w^i."""
    (a00, a01, a02), (a10, a11, a12) = a
    return (a00, a10, a01, a11, a02, a12)


def _fp12_from_w_coeffs(c):
    return ((c[0], c[2], c[4]), (c[1], c[3], c[5]))


def fp12_frobenius(a, power: int = 1):
    """a^(p^power) for 1 <= power < 12."""
    out = a
    for _ in range(power % 12):
        coeffs = _fp12_to_w_coeffs(out)
        new = tuple(fp2_mul(fp2_conj(c), _FROB_COEFF[i]) for i, c in enumerate(coeffs))
        out = _fp12_from_w_coeffs(new)
    return out
