"""KZG commitments for EIP-4844 blobs (replaces the reference's c-kzg
C binding, `beacon-node/src/util/kzg.ts` + `chain/validation/blobsSidecar.ts`).

Written from the public polynomial-commitments spec over this repo's own
pairing stack: commitments are MSMs over the MONOMIAL trusted setup
(device `ops.msm` for the 4096-point blob commitment, after an inverse
NTT takes the blob from evaluation to coefficient form), proof
verification is two pairings through the byte-exact CPU oracle.

`trusted_setup.bin` is the public KZG ceremony output, MONOMIAL form:
4096 G1 points [tau^i]G1 + 65 G2 points [tau^i]G2 (verified here by the
pairing identity e([tau]1, G2) == e(G1, [tau]2); format header
u32be(4096) u32be(96) then compressed points — same file the reference
ships at `beacon-node/trusted_setup.bin`). Blob commitments therefore go
evaluation form -> coefficients (inverse NTT over Fr) -> monomial MSM.
"""

from __future__ import annotations

import hashlib
import os
from functools import lru_cache

import numpy as np

from . import bls  # noqa: F401  (package marker)
from .bls import curve as C
from .bls.fields import R
from .bls.pairing import pairings_are_one
from .bls.serdes import PointDecodeError, g1_from_bytes, g1_to_bytes, g2_from_bytes

__all__ = [
    "load_trusted_setup",
    "blob_to_kzg_commitment",
    "verify_kzg_proof",
    "verify_blob_kzg_proof",
    "compute_roots_of_unity",
    "KzgError",
    "FIELD_ELEMENTS_PER_BLOB_MAINNET",
]

FIELD_ELEMENTS_PER_BLOB_MAINNET = 4096
_SETUP_PATH = os.path.join(os.path.dirname(__file__), "trusted_setup.bin")
_GENERATOR = 7  # Fr multiplicative generator (c-kzg GENERATOR)
BYTES_PER_FIELD_ELEMENT = 32
# Early-4844 wire convention, pinned by the reference's `c-kzg: ^1.0.9`
# (`packages/beacon-node/package.json:136`): 16-byte domain string,
# field-element bytes LITTLE-endian. (The final mainnet-deneb spec later
# switched to big-endian; v1.8.0's coupled BlobsSidecar flow predates
# that.)
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
KZG_ENDIANNESS = "little"


class KzgError(Exception):
    pass


@lru_cache(maxsize=1)
def load_trusted_setup(path: str = _SETUP_PATH):
    """-> (g1_monomial: [tau^i]G1 oracle affine points,
    g2_monomial: [tau^i]G2 oracle G2 affine points)."""
    with open(path, "rb") as f:
        data = f.read()
    n_g1 = int.from_bytes(data[0:4], "big")
    g2_bytes = int.from_bytes(data[4:8], "big")
    assert g2_bytes == 96
    pos = 8
    g1 = []
    for _ in range(n_g1):
        pt = g1_from_bytes(data[pos : pos + 48])
        if pt is None:
            raise KzgError("invalid G1 point in trusted setup")
        g1.append(pt)
        pos += 48
    g2 = []
    while pos + 96 <= len(data):
        pt = g2_from_bytes(data[pos : pos + 96])
        if pt is None:
            raise KzgError("invalid G2 point in trusted setup")
        g2.append(pt)
        pos += 96
    return g1, g2


# --- field / domain helpers --------------------------------------------------


def _bit_reverse(n: int, order: int) -> int:
    bits = order.bit_length() - 1
    out = 0
    for i in range(bits):
        out = (out << 1) | ((n >> i) & 1)
    return out


@lru_cache(maxsize=4)
def compute_roots_of_unity(order: int, bit_reversed: bool = True) -> tuple[int, ...]:
    """Primitive `order`-th roots of unity in Fr, in the bit-reversed
    permutation c-kzg uses for the Lagrange setup."""
    assert (R - 1) % order == 0
    omega = pow(_GENERATOR, (R - 1) // order, R)
    roots = [1] * order
    for i in range(1, order):
        roots[i] = roots[i - 1] * omega % R
    if bit_reversed:
        roots = [roots[_bit_reverse(i, order)] for i in range(order)]
    return tuple(roots)


def _blob_to_scalars(blob: bytes) -> list[int]:
    if len(blob) % BYTES_PER_FIELD_ELEMENT:
        raise KzgError("blob length not a multiple of 32")
    out = []
    for i in range(0, len(blob), BYTES_PER_FIELD_ELEMENT):
        v = int.from_bytes(blob[i : i + 32], KZG_ENDIANNESS)
        if v >= R:
            raise KzgError("blob element out of field range")
        out.append(v)
    return out


# --- commitments -------------------------------------------------------------


def _inverse_ntt(evals_natural: list[int]) -> list[int]:
    """Inverse radix-2 NTT over Fr: evaluations at the natural-order
    domain -> monomial coefficients."""
    n = len(evals_natural)
    if n & (n - 1):
        raise KzgError("domain size must be a power of two")
    # forward NTT with the inverse root, then scale by n^-1
    w_inv = pow(pow(_GENERATOR, (R - 1) // n, R), R - 2, R)
    out = _ntt(evals_natural, w_inv)
    n_inv = pow(n, R - 2, R)
    return [v * n_inv % R for v in out]


def _ntt(values: list[int], omega: int) -> list[int]:
    n = len(values)
    if n == 1:
        return list(values)
    # iterative Cooley-Tukey, decimation in time
    a = [values[_bit_reverse(i, n)] for i in range(n)]
    length = 2
    while length <= n:
        w_len = pow(omega, n // length, R)
        for start in range(0, n, length):
            w = 1
            half = length // 2
            for i in range(start, start + half):
                u, v = a[i], a[i + half] * w % R
                a[i] = (u + v) % R
                a[i + half] = (u - v) % R
                w = w * w_len % R
        length <<= 1
    return a


def blob_to_kzg_commitment(blob: bytes, *, device: bool = True) -> bytes:
    """Blob (evaluation form over the bit-reversed domain) -> monomial
    coefficients (inverse NTT) -> MSM over the monomial setup
    (device=True routes through ops.msm — the 4096-point G1 MSM is the
    KZG hot loop BASELINE's plan earmarked for the device)."""
    g1, _ = load_trusted_setup()
    scalars = _blob_to_scalars(blob)
    if len(scalars) != len(g1):
        raise KzgError(f"blob has {len(scalars)} elements, setup {len(g1)}")
    n = len(scalars)
    # undo the bit-reversal storage order, then interpolate
    evals_natural = [0] * n
    for i, v in enumerate(scalars):
        evals_natural[_bit_reverse(i, n)] = v
    coeffs = _inverse_ntt(evals_natural)
    return _commit_msm(g1, coeffs, device)


def _commit_msm(g1, scalars, device: bool) -> bytes:
    if device:
        from lodestar_tpu.ops import curve as cv
        from lodestar_tpu.ops import fp as fpo
        from lodestar_tpu.ops import msm
        from lodestar_tpu.ops import prep as dp
        from lodestar_tpu.ops import tower as tw  # noqa: F401

        # every device launch on this path rides the counted seam: the
        # MSM itself counts inside ops/msm; the boundary conversions and
        # the affine conversion are counted here
        xs = np.asarray(
            dp._dispatch(fpo.to_mont, fpo.limbs_from_ints([p[0] for p in g1]))
        )
        ys = np.asarray(
            dp._dispatch(fpo.to_mont, fpo.limbs_from_ints([p[1] for p in g1]))
        )
        bits = msm.bits_msb(scalars, 255)
        out = msm.msm_g1((xs, ys), bits)
        aff = dp._dispatch(
            cv.jac_to_affine_batch, cv.F1, tuple(np.asarray(c)[None] for c in out)
        )
        z_zero = bool(np.all(np.asarray(out[2]) == 0))
        if z_zero:
            return g1_to_bytes(None)
        x = fpo.int_from_limbs(
            np.asarray(dp._dispatch(fpo.from_mont, np.asarray(aff[0])[0]))
        )
        y = fpo.int_from_limbs(
            np.asarray(dp._dispatch(fpo.from_mont, np.asarray(aff[1])[0]))
        )
        return g1_to_bytes((x, y))
    acc = None
    for pt, s in zip(g1, scalars):
        if s:
            acc = C.g1_add(acc, C.g1_mul(pt, s))
    return g1_to_bytes(acc)


# --- verification ------------------------------------------------------------

_kzg_fallback_counter = None  # guarded by: GIL (prometheus Counter slot, set at node init)
_kzg_fallbacks_total = 0  # guarded by: GIL (monotonic int; += under the GIL, test reads)


def configure_kzg_fallback_counter(counter) -> None:
    """Install the `lodestar_kzg_device_fallback_total` Counter (node
    init); None leaves the process-local count only."""
    global _kzg_fallback_counter
    _kzg_fallback_counter = counter


def kzg_device_fallbacks_total() -> int:
    """Process-local count of device-pairing failures served by the CPU
    oracle — the number the degradation tests assert against."""
    return _kzg_fallbacks_total


def _note_kzg_device_fallback(err: Exception) -> None:
    global _kzg_fallbacks_total
    _kzg_fallbacks_total += 1
    c = _kzg_fallback_counter
    if c is not None:
        c.inc()
    from lodestar_tpu.logger import get_logger

    get_logger(name="lodestar.kzg").warn(
        "device pairing check failed, serving the CPU oracle verdict",
        {"error": str(err)[:120]},
    )


def _pairs_are_one_device(pairs) -> bool | None:
    """Run a pairing-product == 1 check on the DEVICE kernels
    (ops/pairing.multi_pairing_is_one); None = device unavailable (no
    ops stack on this host), caller falls back to the CPU oracle. A
    RUNTIME device failure is a degradation, not an absence: it ticks
    `lodestar_kzg_device_fallback_total` and serves the oracle verdict
    directly. Infinity entries are masked (pair contributes the neutral
    element, same as the oracle's skip-None); the batch axis is padded
    to a power of two with masked-out generator rows so the pairing
    program compiles per size class, not per pair count."""
    try:
        import numpy as np

        from lodestar_tpu.ops import fp
        from lodestar_tpu.ops import pairing as prg
        from lodestar_tpu.ops import prep as dp
        from lodestar_tpu.ops import tower as tw
    except Exception:
        return None
    mask, px, py, qx, qy = [], [], [], [], []
    for p1, q2 in pairs:
        live = p1 is not None and q2 is not None
        mask.append(live)
        pp = p1 if p1 is not None else C.G1_GEN
        qq = q2 if q2 is not None else C.G2_GEN
        px.append(fp.mont_limbs_from_int(pp[0]))
        py.append(fp.mont_limbs_from_int(pp[1]))
        qx.append(tw._fp2_mont_limbs_host(*qq[0]))
        qy.append(tw._fp2_mont_limbs_host(*qq[1]))
    size = dp.pad_pow2(len(mask), floor=2)
    for _ in range(size - len(pairs)):
        mask.append(False)  # padding rows: valid points, masked to one
        px.append(px[0])
        py.append(py[0])
        qx.append(qx[0])
        qy.append(qy[0])
    try:
        ok = dp._dispatch(
            prg.multi_pairing_is_one,
            (np.stack(px), np.stack(py)),
            (np.stack(qx), np.stack(qy)),
            mask=np.asarray(mask),
        )
        return bool(np.asarray(ok))
    except Exception as e:
        _note_kzg_device_fallback(e)
        return pairings_are_one(pairs)


def verify_kzg_proof(
    commitment: bytes, z: int, y: int, proof: bytes, *, device: bool = True
) -> bool:
    """Pairing check e(P - [y]G1, -G2) * e(proof, [tau]G2 - [z]G2) == 1,
    run through the DEVICE pairing by default (the r3 verdict's Deneb
    blob-validation throughput gap; CPU oracle as fallback anchor).
    Malformed or out-of-subgroup points fail verification (spec
    validate_kzg_g1) rather than raising."""
    _, g2 = load_trusted_setup()
    try:
        c_pt = g1_from_bytes(commitment)
        proof_pt = g1_from_bytes(proof)
    except PointDecodeError:
        return False
    for pt in (c_pt, proof_pt):
        if pt is not None and not C.g1_in_subgroup(pt):
            return False

    # X - [z] in G2: tau_g2 - z*g2_gen
    tau_g2 = g2[1]
    z_g2 = C.g2_mul(C.G2_GEN, z % R) if z % R else None
    x_minus_z = C.g2_add(tau_g2, C.g2_neg(z_g2) if z_g2 else None)
    # P - [y] in G1
    y_g1 = C.g1_mul(C.G1_GEN, y % R) if y % R else None
    p_minus_y = C.g1_add(c_pt, C.g1_neg(y_g1) if y_g1 else None)

    pairs = [
        (p_minus_y, C.g2_neg(C.G2_GEN)),
        (proof_pt, x_minus_z),
    ]
    if device:
        out = _pairs_are_one_device(pairs)
        if out is not None:
            return out
    return pairings_are_one(pairs)


def _evaluate_blob_at(blob_scalars: list[int], z: int) -> int:
    """Barycentric evaluation of the (bit-reversed) evaluation-form
    polynomial at z (spec evaluate_polynomial_in_evaluation_form)."""
    n = len(blob_scalars)
    roots = compute_roots_of_unity(n)
    z %= R
    for i, w in enumerate(roots):
        if z == w:
            return blob_scalars[i]
    # p(z) = (z^n - 1)/n * sum_i p_i * w_i / (z - w_i)
    total = 0
    for p_i, w in zip(blob_scalars, roots):
        total = (total + p_i * w % R * pow((z - w) % R, R - 2, R)) % R
    zn = (pow(z, n, R) - 1) % R
    return total * zn % R * pow(n, R - 2, R) % R


def _hash_to_bls_field(data: bytes) -> int:
    """Spec hash_to_bls_field: sha256 reduced to Fr, module endianness."""
    return int.from_bytes(hashlib.sha256(data).digest(), KZG_ENDIANNESS) % R


def _compute_challenge(blob: bytes, commitment: bytes) -> int:
    """Fiat-Shamir challenge for the per-blob (decoupled, later-deneb API)
    proof: domain || uint128(FIELD_ELEMENTS_PER_BLOB) || blob ||
    commitment hashed to a field element. Endianness follows the module's
    early-4844 convention."""
    n = len(blob) // BYTES_PER_FIELD_ELEMENT
    data = (
        FIAT_SHAMIR_PROTOCOL_DOMAIN + n.to_bytes(16, KZG_ENDIANNESS) + blob + commitment
    )
    return _hash_to_bls_field(data)


def verify_blob_kzg_proof(blob: bytes, commitment: bytes, proof: bytes) -> bool:
    """Spec verify_blob_kzg_proof: evaluate at the Fiat-Shamir challenge
    and verify the opening."""
    scalars = _blob_to_scalars(blob)
    z = _compute_challenge(blob, commitment)
    y = _evaluate_blob_at(scalars, z)
    return verify_kzg_proof(commitment, z, y, proof)


# --- early-4844 aggregate proofs (coupled BlobsSidecar) -----------------------
# The reference v1.8.0 ships the EARLY EIP-4844 p2p design: one coupled
# `BlobsSidecar` per block carrying ALL blobs + ONE aggregated proof,
# verified by `validate_blobs_sidecar` (c-kzg verifyAggregateKzgProof —
# reference `chain/validation/blobsSidecar.ts:68`). Aggregation follows
# the early spec: blobs/commitments are folded with powers of one
# Fiat-Shamir scalar, then a single opening at a second challenge.

G1_INFINITY_BYTES = bytes([0xC0]) + bytes(47)


def _commit_evals(scalars: list[int], device: bool) -> bytes:
    """Commit an evaluation-form (bit-reversed domain) polynomial."""
    g1, _ = load_trusted_setup()
    n = len(scalars)
    evals_natural = [0] * n
    for i, v in enumerate(scalars):
        evals_natural[_bit_reverse(i, n)] = v
    return _commit_msm(g1, _inverse_ntt(evals_natural), device)


def _compute_challenges(blobs: list[bytes], commitments: list[bytes]) -> tuple[int, int]:
    """(folding challenge r, evaluation challenge x) for the aggregate
    proof, mirroring c-kzg 1.0.x `compute_challenges` (eip4844.c — the
    implementation the reference links, `package.json:136` c-kzg ^1.0.9):

        transcript = domain(16B) || uint64le(FIELD_ELEMENTS_PER_BLOB)
                   || uint64le(n) || blob bytes || commitment bytes
        hashed    = sha256(transcript)
        r         = hash_to_bls_field(sha256(hashed || 0x00))
        x         = hash_to_bls_field(sha256(hashed || 0x01))

    Both challenges squeeze from ONE transcript over the raw wire bytes;
    in particular x does NOT depend on the aggregated commitment. Field
    elements reduce little-endian (KZG_ENDIANNESS). Reconstructed from
    the c-kzg source of that era — the official vectors are unreachable
    from this build environment, so byte-for-byte interop is asserted by
    construction, not fixtures.
    """
    n = len(blobs)
    width = len(blobs[0]) // BYTES_PER_FIELD_ELEMENT
    h = hashlib.sha256()
    h.update(FIAT_SHAMIR_PROTOCOL_DOMAIN)
    h.update(width.to_bytes(8, KZG_ENDIANNESS))
    h.update(n.to_bytes(8, KZG_ENDIANNESS))
    for b in blobs:
        h.update(bytes(b))
    for c in commitments:
        h.update(bytes(c))
    hashed = h.digest()
    r = _hash_to_bls_field(hashed + b"\x00")
    x = _hash_to_bls_field(hashed + b"\x01")
    return r, x


def _aggregate(blob_scalar_lists: list[list[int]], commitments: list[bytes], r: int):
    """(aggregated eval-form scalars, aggregated commitment point) via
    powers of the folding challenge r (early spec
    compute_aggregated_poly_and_commitment)."""
    n = len(blob_scalar_lists)
    powers = [pow(r, i, R) for i in range(n)]
    width = len(blob_scalar_lists[0])
    agg = [0] * width
    for coeff, scalars in zip(powers, blob_scalar_lists):
        for i, s in enumerate(scalars):
            agg[i] = (agg[i] + coeff * s) % R
    agg_commitment = None
    for coeff, c in zip(powers, commitments):
        try:
            pt = g1_from_bytes(bytes(c))
        except PointDecodeError as e:
            raise KzgError(f"malformed commitment: {e}") from e
        if pt is not None and not C.g1_in_subgroup(pt):
            raise KzgError("commitment outside the G1 subgroup")
        if pt is not None and coeff:
            agg_commitment = C.g1_add(agg_commitment, C.g1_mul(pt, coeff))
    return agg, agg_commitment


def compute_aggregate_kzg_proof(blobs: list[bytes], *, device: bool = True) -> bytes:
    """One proof for all of a block's blobs (early spec
    compute_aggregate_kzg_proof; c-kzg computeAggregateKzgProof)."""
    if not blobs:
        return G1_INFINITY_BYTES
    blob_scalars = [_blob_to_scalars(b) for b in blobs]
    commitments = [blob_to_kzg_commitment(b, device=device) for b in blobs]
    r, x = _compute_challenges([bytes(b) for b in blobs], commitments)
    agg, _agg_pt = _aggregate(blob_scalars, commitments, r)
    y = _evaluate_blob_at(agg, x)
    # quotient in evaluation form: q_i = (p_i - y) / (w_i - x)
    roots = compute_roots_of_unity(len(agg))
    q = [
        (p_i - y) % R * pow((w - x) % R, R - 2, R) % R
        for p_i, w in zip(agg, roots)
    ]
    return _commit_evals(q, device)


def verify_aggregate_kzg_proof(
    blobs: list[bytes], commitments: list[bytes], proof: bytes
) -> bool:
    """Early spec verify_aggregate_kzg_proof (the check inside
    validate_blobs_sidecar)."""
    if len(blobs) != len(commitments):
        return False
    if not blobs:
        return bytes(proof) == G1_INFINITY_BYTES
    try:
        blob_scalars = [_blob_to_scalars(b) for b in blobs]
        commitment_bytes = [bytes(c) for c in commitments]
        r, x = _compute_challenges([bytes(b) for b in blobs], commitment_bytes)
        agg, agg_pt = _aggregate(blob_scalars, commitment_bytes, r)
        y = _evaluate_blob_at(agg, x)
        return verify_kzg_proof(g1_to_bytes(agg_pt), x, y, bytes(proof))
    except (KzgError, PointDecodeError):
        return False


def validate_blobs_sidecar(
    slot: int, beacon_block_root: bytes, expected_kzg_commitments: list[bytes], sidecar
) -> None:
    """Spec validate_blobs_sidecar (reference blobsSidecar.ts:73): slot
    and root binding, blob count, aggregate proof. Raises KzgError."""
    if int(sidecar.beacon_block_slot) != int(slot):
        raise KzgError("sidecar slot mismatch")
    if bytes(sidecar.beacon_block_root) != bytes(beacon_block_root):
        raise KzgError("sidecar block root mismatch")
    blobs = [bytes(b) for b in sidecar.blobs]
    if len(blobs) != len(expected_kzg_commitments):
        raise KzgError(
            f"{len(blobs)} blobs vs {len(expected_kzg_commitments)} commitments"
        )
    if not verify_aggregate_kzg_proof(
        blobs, [bytes(c) for c in expected_kzg_commitments], bytes(sidecar.kzg_aggregated_proof)
    ):
        raise KzgError("aggregate KZG proof failed verification")
