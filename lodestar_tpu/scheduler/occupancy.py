"""Occupancy accounting + graded admission for one device backend.

`OccupancyTracker` answers the ROADMAP question "busy-ns per wall-ns":
launches bracket themselves with `with tracker.launch():`; between
transitions the tracker folds the interval's busy fraction (1.0 while
any launch is active, overlaps don't double-count) into an exponentially
weighted moving average with time constant `tau_s`. Thread-safe — the
BLS pool launches from executor threads, the offload server from gRPC
worker threads, and Status RPCs read concurrently.

`AdmissionController` turns occupancy + queue depth + an optional
can-accept callable into the three-state admission signal the offload
Status frame carries: ACCEPT (all work), SHED_BULK (urgent classes only
— bulk should go to a less-loaded host), REJECT (nothing).
"""

from __future__ import annotations

import enum
import math
import threading
import time
from contextlib import contextmanager

from .core import BULK_CLASSES, PriorityClass

__all__ = ["OccupancyTracker", "AdmissionController", "AdmissionState"]

DEFAULT_TAU_S = 10.0
DEFAULT_SHED_BULK_AT = 0.75  # EWMA occupancy fraction
DEFAULT_REJECT_AT = 0.95


class AdmissionState(enum.IntEnum):
    ACCEPT = 0
    SHED_BULK = 1
    REJECT = 2

    @property
    def label(self) -> str:
        return self.name.lower()


class OccupancyTracker:
    """EWMA busy-fraction of one device pipeline (0.0 idle .. 1.0 pinned)."""

    def __init__(self, *, tau_s: float = DEFAULT_TAU_S, time_fn=time.monotonic_ns) -> None:
        self._tau_ns = tau_s * 1e9
        self._time_fn = time_fn
        self._lock = threading.Lock()
        self._active = 0  # guarded by: _lock
        self._ewma = 0.0  # guarded by: _lock
        self._last_ns = time_fn()  # guarded by: _lock
        self.busy_ns_total = 0  # guarded by: _lock — lifetime busy integral (debug/tests)

    def _advance(self, now_ns: int) -> None:  # lint: allow(lock-discipline) — internal fold step; every caller (begin/end/occupancy) holds _lock
        dt = now_ns - self._last_ns
        if dt <= 0:
            return
        busy = 1.0 if self._active > 0 else 0.0
        if busy:
            self.busy_ns_total += dt
        keep = math.exp(-dt / self._tau_ns)
        self._ewma = self._ewma * keep + busy * (1.0 - keep)
        self._last_ns = now_ns

    def begin(self) -> None:
        with self._lock:
            self._advance(self._time_fn())
            self._active += 1

    def end(self) -> None:
        with self._lock:
            self._advance(self._time_fn())
            self._active = max(0, self._active - 1)

    @contextmanager
    def launch(self):
        self.begin()
        try:
            yield self
        finally:
            self.end()

    def occupancy(self) -> float:
        with self._lock:
            self._advance(self._time_fn())
            return self._ewma

    def occupancy_permille(self) -> int:
        return max(0, min(1000, int(round(self.occupancy() * 1000.0))))


class AdmissionController:
    """Graded admission from occupancy + depth (+ a hard veto callable).

    REJECT: the veto says no, occupancy >= reject_at, or depth >=
    reject_depth. SHED_BULK: occupancy >= shed_bulk_at or depth >=
    shed_bulk_depth. ACCEPT otherwise.
    """

    def __init__(
        self,
        occupancy: OccupancyTracker,
        *,
        shed_bulk_at: float = DEFAULT_SHED_BULK_AT,
        reject_at: float = DEFAULT_REJECT_AT,
        depth_fn=None,
        shed_bulk_depth: int = 256,
        reject_depth: int = 1024,
        can_accept=None,
    ) -> None:
        self.occupancy = occupancy
        self.shed_bulk_at = shed_bulk_at
        self.reject_at = reject_at
        self._depth_fn = depth_fn or (lambda: 0)
        self.shed_bulk_depth = shed_bulk_depth
        self.reject_depth = reject_depth
        self._can_accept = can_accept or (lambda: True)

    def state(self) -> AdmissionState:
        if not self._can_accept():
            return AdmissionState.REJECT
        occ = self.occupancy.occupancy()
        depth = self._depth_fn()
        if occ >= self.reject_at or depth >= self.reject_depth:
            return AdmissionState.REJECT
        if occ >= self.shed_bulk_at or depth >= self.shed_bulk_depth:
            return AdmissionState.SHED_BULK
        return AdmissionState.ACCEPT

    def admits(self, cls: PriorityClass) -> bool:
        state = self.state()
        if state is AdmissionState.REJECT:
            return False
        if state is AdmissionState.SHED_BULK:
            return PriorityClass(cls) not in BULK_CLASSES
        return True
