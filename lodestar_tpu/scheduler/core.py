"""Priority classes + the weighted-fair launch queue.

Scheduling scheme: stride scheduling (a deterministic weighted-fair
policy — Waldspurger & Weihl, OSDI '95). Each class keeps a virtual
"pass"; serving one job advances the class's pass by `STRIDE_SCALE /
weight`. Dequeue picks the non-empty class with the smallest pass,
priority order breaking ties, so with weights 64:16:8:2:1 a saturated
queue serves gossip blocks ~64x as often as backfill without ever
parking backfill forever. A class waking from idle joins at the current
service frontier (min pass over non-empty classes) so idle time earns no
burst credit. On top of fairness, starvation aging: any head-of-line job
that has waited longer than `aging_ms` is served immediately, oldest
first — the hard bound on bulk-class latency.

Asyncio-native and single-loop like the pool it feeds: `put_nowait` /
`get_nowait` run on the event loop; `get` parks on an Event. The
injectable `time_fn` keeps aging deterministic under test.
"""

from __future__ import annotations

import asyncio
import enum
import time
from collections import deque

__all__ = [
    "PriorityClass",
    "PriorityWorkQueue",
    "BULK_CLASSES",
    "DEFAULT_WEIGHTS",
    "DEFAULT_AGING_MS",
]


class PriorityClass(enum.IntEnum):
    """Launch classes, most- to least-urgent. Lower value wins ties."""

    GOSSIP_BLOCK = 0  # slot-deadline block import (gossip, is_timely)
    GOSSIP_ATTESTATION = 1  # gossip attestations/aggregates/sync messages
    API = 2  # REST submissions + direct imports
    RANGE_SYNC = 3  # forward sync segments
    BACKFILL = 4  # historical backfill batches

    @property
    def label(self) -> str:
        return self.name.lower()


#: classes a SHED_BULK admission state turns away
BULK_CLASSES = frozenset({PriorityClass.RANGE_SYNC, PriorityClass.BACKFILL})

#: service shares under saturation (stride = STRIDE_SCALE / weight)
DEFAULT_WEIGHTS: dict[PriorityClass, int] = {
    PriorityClass.GOSSIP_BLOCK: 64,
    PriorityClass.GOSSIP_ATTESTATION: 16,
    PriorityClass.API: 8,
    PriorityClass.RANGE_SYNC: 2,
    PriorityClass.BACKFILL: 1,
}

DEFAULT_AGING_MS = 2000.0  # bulk head-of-line jobs older than this jump the fair order

_STRIDE_SCALE = 1 << 20


class PriorityWorkQueue:
    """Multi-class work queue with stride-fair dequeue and aging.

    Items are opaque; the caller owns result futures / tracing parents.
    With `fifo=True` classes are ignored and arrival order rules — the
    pre-scheduler behavior, kept as the measurable control arm.

    `metrics` (a `SchedulerMetrics` dataclass) is optional; when present
    the queue maintains the `lodestar_sched_queue_*` families itself so
    every consumer (BLS pool today) reports identically.
    """

    def __init__(
        self,
        *,
        weights: dict[PriorityClass, int] | None = None,
        aging_ms: float = DEFAULT_AGING_MS,
        fifo: bool = False,
        metrics=None,
        time_fn=time.monotonic_ns,
    ) -> None:
        self.fifo = fifo
        self.metrics = metrics
        self._time_fn = time_fn
        self._aging_ns = aging_ms * 1e6
        w = dict(DEFAULT_WEIGHTS)
        if weights:
            w.update(weights)
        self._strides = {c: _STRIDE_SCALE // max(1, w[c]) for c in PriorityClass}
        self._pass = {c: 0 for c in PriorityClass}
        self._vtime = 0  # service frontier, survives the queue draining empty
        self._queues: dict[PriorityClass, deque] = {c: deque() for c in PriorityClass}
        self._size = 0
        self._event = asyncio.Event()
        self.starvation_promotions = 0
        self._last_was_promotion = False

    # -- ingress ---------------------------------------------------------------

    def put_nowait(self, item, cls: PriorityClass = PriorityClass.API) -> None:
        cls = PriorityClass(cls)
        q = self._queues[cls]
        if not q and not self.fifo:
            # waking from idle: join at the service frontier, no burst
            # credit — min over active passes, or the persisted frontier
            # when the whole queue had drained
            active = [self._pass[c] for c in PriorityClass if self._queues[c]]
            floor = min(active) if active else self._vtime
            self._pass[cls] = max(self._pass[cls], floor)
        q.append((item, self._time_fn()))
        self._size += 1
        self._event.set()
        if self.metrics is not None:
            self.metrics.queue_depth.labels(cls.label).set(len(q))

    # -- egress ----------------------------------------------------------------

    def _select_class(self) -> PriorityClass | None:
        nonempty = [c for c in PriorityClass if self._queues[c]]
        if not nonempty:
            return None
        if self.fifo:
            return min(nonempty, key=lambda c: self._queues[c][0][1])
        now = self._time_fn()
        fair = min(nonempty, key=lambda c: (self._pass[c], c))
        aged = [c for c in nonempty if now - self._queues[c][0][1] >= self._aging_ns]
        if aged:
            chosen = min(aged, key=lambda c: self._queues[c][0][1])
            # aging alternates with the fair pick: a fully-aged bulk
            # backlog under sustained saturation must not degenerate the
            # queue to global FIFO — an arriving urgent job waits out at
            # most ONE promotion before the fair order serves it
            if chosen is not fair and self._last_was_promotion:
                chosen = fair
            if chosen is not fair:
                self._last_was_promotion = True
                self.starvation_promotions += 1
                if self.metrics is not None:
                    self.metrics.starvation_promotions.inc()
                return chosen
        self._last_was_promotion = False
        return fair

    def get_nowait(
        self, cls: PriorityClass | None = None
    ) -> tuple[object, PriorityClass, int] | None:
        """Pop one item -> (item, class, waited_ns); None when empty.

        With `cls` given, pop from that class only (the pool's same-class
        package drain) — fairness accounting still advances."""
        if cls is not None:
            cls = PriorityClass(cls) if self._queues[PriorityClass(cls)] else None
            if cls is None:
                return None
        else:
            cls = self._select_class()
            if cls is None:
                return None
        item, enq_ns = self._queues[cls].popleft()
        self._size -= 1
        if self._size == 0:
            self._event.clear()
        if not self.fifo:
            self._pass[cls] += self._strides[cls]
            self._vtime = max(self._vtime, self._pass[cls])
        waited_ns = max(0, self._time_fn() - enq_ns)
        if self.metrics is not None:
            self.metrics.queue_depth.labels(cls.label).set(len(self._queues[cls]))
            self.metrics.queue_wait.labels(cls.label).observe(waited_ns / 1e9)
            self.metrics.jobs_dequeued.labels(cls.label).inc()
        return item, cls, waited_ns

    async def get(self) -> tuple[object, PriorityClass, int]:
        while True:
            out = self.get_nowait()
            if out is not None:
                return out
            self._event.clear()
            await self._event.wait()

    def drain(self) -> list[tuple[object, PriorityClass, int]]:
        """Pop everything (shutdown path) in plain class order."""
        out = []
        for c in PriorityClass:
            while self._queues[c]:
                item, enq_ns = self._queues[c].popleft()
                self._size -= 1
                out.append((item, c, max(0, self._time_fn() - enq_ns)))
        self._event.clear()
        return out

    # -- introspection ---------------------------------------------------------

    def __len__(self) -> int:
        return self._size

    def depth(self, cls: PriorityClass | None = None) -> int:
        if cls is None:
            return self._size
        return len(self._queues[PriorityClass(cls)])

    def depths(self) -> dict[str, int]:
        return {c.label: len(self._queues[c]) for c in PriorityClass}

    def stats(self) -> dict:
        """One-shot scheduler snapshot (chaos-harness ledger / debug):
        per-class depths plus the fairness counters that summarize how
        contended the queue has been so far."""
        return {
            "depths": self.depths(),
            "size": self._size,
            "starvation_promotions": self.starvation_promotions,
            "vtime": self._vtime,
        }
