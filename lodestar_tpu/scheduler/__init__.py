"""Device work scheduler: priority-aware launch queue + occupancy + admission.

The accelerator is one shared pipeline fed by workloads with wildly
different deadlines: a gossip block must verify inside its slot, a
range-sync backfill batch merely needs to finish eventually. A FIFO
launch queue lets the second starve the first (head-of-line blocking the
committee-consensus measurements in PAPERS.md call the dominant tail
term once verification is outsourced). This package is the seam every
device launch routes through:

* `PriorityClass` — the five launch classes, most- to least-urgent:
  gossip block > gossip attestation/aggregate > API > range sync >
  backfill. Call sites tag work via `VerifySignatureOpts.priority`.
* `PriorityWorkQueue` — weighted-fair dequeue (stride scheduling: each
  class holds a virtual "pass" advancing by 1/weight per served job, the
  smallest pass wins) so bulk classes keep a trickle of service under
  gossip pressure, plus starvation aging: any head-of-line job older
  than `aging_ms` is served outright. `fifo=True` degrades to the old
  arrival-order queue (the control arm for the saturation tests).
* `OccupancyTracker` — EWMA busy-ns per wall-ns around device launches;
  the ROADMAP's "can this host absorb another beacon node" number.
* `AdmissionController` — grades the binary can-accept gate into
  ACCEPT / SHED_BULK / REJECT from occupancy + queue depth, the frame
  `BlsOffloadServer.Status` ships to clients for load-aware routing.

Dependency-free by design: `chain/bls`, `offload` and the call sites all
import from here, never the reverse.
"""

from .core import (  # noqa: F401
    BULK_CLASSES,
    DEFAULT_AGING_MS,
    DEFAULT_WEIGHTS,
    PriorityClass,
    PriorityWorkQueue,
)
from .occupancy import (  # noqa: F401
    AdmissionController,
    AdmissionState,
    OccupancyTracker,
)

__all__ = [
    "PriorityClass",
    "PriorityWorkQueue",
    "BULK_CLASSES",
    "DEFAULT_WEIGHTS",
    "DEFAULT_AGING_MS",
    "OccupancyTracker",
    "AdmissionController",
    "AdmissionState",
]
