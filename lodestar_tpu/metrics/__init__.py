"""Metrics registry + beacon metric taxonomy + scrape server.

Reference `beacon-node/src/metrics/` — `RegistryMetricCreator`
(`utils/registryMetricCreator.ts`), the lodestar metric groups
(`metrics/lodestar.ts`, incl. the blsThreadPool.* latency decomposition
at :358-430 and the state-transition timers at :279,302), and the HTTP
scrape server (`server/http.ts:14`). Built on prometheus_client (in
image); metric names keep the reference's so existing Grafana dashboards
(`dashboards/lodestar_bls_thread_pool.json`, ...) read unmodified.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Sequence

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from .validator_monitor import ValidatorMonitor

__all__ = [
    "RegistryMetricCreator",
    "BeaconMetrics",
    "BlsPrepMetrics",
    "BlsPipelineMetrics",
    "DeviceLaunchMetrics",
    "TraceMetrics",
    "SloMetrics",
    "SchedulerMetrics",
    "ResilienceMetrics",
    "AuditMetrics",
    "TenantMetrics",
    "create_tenant_metrics",
    "create_metrics",
    "MetricsServer",
    "ValidatorMonitor",
]


class RegistryMetricCreator:
    """Typed factory bound to one registry (reference
    `registryMetricCreator.ts`)."""

    def __init__(self) -> None:
        self.registry = CollectorRegistry()

    def gauge(self, name: str, help_: str, labels: Sequence[str] = ()) -> Gauge:
        return Gauge(name, help_, labelnames=list(labels), registry=self.registry)

    def counter(self, name: str, help_: str, labels: Sequence[str] = ()) -> Counter:
        return Counter(name, help_, labelnames=list(labels), registry=self.registry)

    def histogram(
        self, name: str, help_: str, buckets: Sequence[float], labels: Sequence[str] = ()
    ) -> Histogram:
        return Histogram(
            name, help_, labelnames=list(labels), buckets=list(buckets), registry=self.registry
        )

    def scrape(self) -> bytes:
        return generate_latest(self.registry)


@dataclass
class BlsPoolMetrics:
    """blsThreadPool.* (reference `metrics/lodestar.ts:358-430`) — the
    worker-pool latency decomposition retargeted at the device pipeline."""

    job_wait_time: Histogram
    jobs_started: Counter
    sig_sets_started: Counter
    success_sets: Counter
    error_sets: Counter
    batch_retries: Counter
    batch_sigs_success: Counter
    time_per_sig_set: Histogram
    latency_to_device: Histogram
    latency_from_device: Histogram


@dataclass
class StateTransitionMetrics:
    epoch_transition_time: Histogram
    process_block_time: Histogram
    state_hash_tree_root_time: Histogram


@dataclass
class GossipMetrics:
    queue_length: Gauge
    queue_dropped: Counter
    accepted: Counter
    rejected: Counter


@dataclass
class ForkChoiceMetrics:
    find_head_time: Histogram
    requests: Counter
    errors: Counter
    reorgs: Counter


@dataclass
class NetworkMetrics:
    peers_by_direction: Gauge
    peer_disconnects: Counter
    gossip_mesh_peers: Gauge
    gossip_received: Counter
    gossip_duplicates: Counter


@dataclass
class SyncMetrics:
    range_sync_batches: Counter
    range_sync_blocks: Counter
    range_sync_errors: Counter
    backfill_blocks: Counter
    unknown_block_requests: Counter


@dataclass
class DbMetrics:
    reads: Counter
    writes: Counter
    size_bytes: Gauge


@dataclass
class RegenMetrics:
    state_cache_hits: Counter
    state_cache_misses: Counter
    checkpoint_cache_hits: Counter
    regen_queue_length: Gauge
    regen_time: Histogram


@dataclass
class OpPoolMetrics:
    attestation_pool_size: Gauge
    aggregated_pool_size: Gauge
    exits: Gauge
    proposer_slashings: Gauge
    attester_slashings: Gauge
    sync_messages: Gauge


@dataclass
class ApiMetrics:
    rest_requests: Counter
    rest_errors: Counter
    rest_response_time: Histogram


@dataclass
class ReqRespMetrics:
    """beacon_reqresp_* detail (reference metrics/lodestar.ts reqresp
    family): per-protocol streams, bytes, timing and rate limiting."""

    requests_sent: Counter
    requests_received: Counter
    request_errors: Counter
    response_time: Histogram
    response_chunks_sent: Counter
    response_chunks_received: Counter
    rate_limited: Counter
    dial_timeouts: Counter
    streams_reset: Counter


@dataclass
class PeerMetrics:
    """lodestar_peers_* detail (reference peerManager metrics)."""

    peer_count: Gauge
    peers_by_client: Gauge
    peer_score: Histogram
    peer_action_count: Counter
    goodbye_sent: Counter
    goodbye_received: Counter
    dials_attempted: Counter
    dials_succeeded: Counter
    long_lived_subnets: Gauge
    discv5_sessions: Gauge
    discv5_findnode_sent: Counter
    discv5_enrs_discovered: Counter


@dataclass
class GossipDetailMetrics:
    """gossipsub router internals (reference gossipsub metrics)."""

    mesh_grafts: Counter
    mesh_prunes: Counter
    ihave_sent: Counter
    iwant_received: Counter
    iwant_served: Counter
    mcache_size: Gauge
    peer_score_by_topic: Gauge
    flood_publishes: Counter
    backoff_violations: Counter


@dataclass
class SyncDetailMetrics:
    """lodestar_sync_* detail (reference sync metrics)."""

    status: Gauge
    peers_by_status: Gauge
    batch_download_time: Histogram
    batch_processing_time: Histogram
    batches_downloaded: Counter
    batch_download_retries: Counter
    head_distance: Gauge
    backfill_earliest_slot: Gauge
    unknown_block_queue_length: Gauge


@dataclass
class DbDetailMetrics:
    read_items: Counter
    write_items: Counter
    batch_write_time: Histogram
    wal_size_bytes: Gauge
    archived_states: Counter
    archived_blocks: Counter
    pruned_blocks: Counter


@dataclass
class ChainDetailMetrics:
    """block pipeline + caches (reference chain metrics)."""

    block_import_time: Histogram
    block_production_time: Histogram
    blocks_imported: Counter
    blocks_rejected: Counter
    attestations_imported: Counter
    seen_attesters_size: Gauge
    seen_aggregators_size: Gauge
    checkpoint_state_cache_size: Gauge
    state_cache_size: Gauge
    light_client_updates_served: Counter
    light_client_bootstraps_served: Counter
    eth1_block_height: Gauge
    eth1_deposits_fetched: Counter
    eth1_requests: Counter
    engine_api_requests: Counter
    engine_api_time: Histogram
    builder_requests: Counter
    builder_circuit_open: Gauge


@dataclass
class ProcessMetrics:
    event_loop_lag: Histogram
    start_time: Gauge
    offload_outstanding: Gauge
    offload_healthy: Gauge


@dataclass
class SchedulerMetrics:
    """lodestar_sched_* — the device work scheduler
    (`lodestar_tpu/scheduler`): per-class launch queue depth/wait/serve
    counts, starvation-aging promotions, EWMA device occupancy and the
    graded admission state backing the occupancy dashboard."""

    queue_depth: Gauge  # labeled by launch class
    queue_wait: Histogram  # labeled by launch class
    jobs_dequeued: Counter  # labeled by launch class
    starvation_promotions: Counter
    occupancy_permille: Gauge  # mesh aggregate over available lanes
    admission_state: Gauge  # 0 accept / 1 shed_bulk / 2 reject
    shed_total: Counter  # labeled by launch class
    lane_occupancy: Gauge  # per-device EWMA occupancy, labeled by device
    lane_launches: Counter  # device launches, labeled by device + mode (single/sharded)
    lane_wedge_trips: Counter  # per-chip wedge-breaker trips, labeled by device
    mesh_lanes: Gauge  # non-wedged lanes currently serving


@dataclass
class ResilienceMetrics:
    """lodestar_resilience_* — the offload resilience layer
    (`offload/resilience.py`, `chain/bls/fallback.py`): per-endpoint
    routing/failover/hedge counts, circuit-breaker states, and the
    degradation-chain fallback counters."""

    routed: Counter  # verify RPCs issued, labeled by endpoint
    shed: Counter  # client-side admission sheds, labeled by reason
    failovers: Counter  # failed attempts per endpoint (breaker input)
    hedges: Counter  # hedged retries issued, labeled by launch class
    hedge_wins: Counter  # hedged retries that returned the verdict
    breaker_state: Gauge  # 0 closed / 1 half-open / 2 open, per endpoint
    breaker_transitions: Counter  # labeled by endpoint and new state
    fallback_verifications: Counter  # degraded verifications served, by layer
    fallback_skipped: Counter  # layers skipped (not accepting), by layer
    fallback_active: Gauge  # 1 while a non-primary layer served last
    outage_unscored: Counter  # outage-caused rejections spared from peer scoring


@dataclass
class AuditMetrics:
    """lodestar_offload_audit_* — the Byzantine audit subsystem
    (`offload/audit.py`): sampled/re-verified verdict counts, audit CPU
    spend against its budget, per-endpoint trust EWMA, Byzantine events
    and quarantine states."""

    sampled: Counter  # verdicts picked for re-verification, by launch class
    verified: Counter  # completed re-verifications, by outcome agree/disagree
    dropped: Counter  # sampled-but-not-audited, by reason (queue_full/queue_bytes/audit_error)
    byzantine: Counter  # Byzantine events (re-check contradicted), by endpoint
    trust_score: Gauge  # audit trust EWMA per endpoint (1.0 = never contradicted)
    quarantined: Gauge  # 1 while the endpoint is quarantined
    queue_depth: Gauge  # audit queue backlog
    cpu_seconds: Counter  # audit re-verification CPU time (budget accounting)


@dataclass
class TenantMetrics:
    """lodestar_offload_tenant_* — the offload server's multi-tenant
    front-end (`offload/tenancy.py`): per-tenant admitted/served work,
    quota sheds by reason, in-flight grants and configured stride
    weights. Registered by the serving host (`create_tenant_metrics`),
    not the beacon node — the node is a tenant, the server meters them."""

    served_sets: Counter  # signature sets served, labeled by tenant
    shed: Counter  # admission sheds, labeled by tenant + reason (quota/slot_timeout)
    inflight: Gauge  # granted service slots, labeled by tenant
    quota_weight: Gauge  # configured stride weight, labeled by tenant
    slack: Histogram  # remaining slot-deadline slack at verdict, by tenant + class


def create_tenant_metrics(creator: "RegistryMetricCreator | None" = None) -> TenantMetrics:
    """Tenant families for an offload serving host (its own registry by
    default — the server runs in its own process)."""
    c = creator or RegistryMetricCreator()
    return TenantMetrics(
        served_sets=c.counter(
            "lodestar_offload_tenant_served_sets_total",
            "Signature sets served per tenant",
            ["tenant"],
        ),
        shed=c.counter(
            "lodestar_offload_tenant_shed_total",
            "Admission sheds per tenant (quota = depth grading, "
            "slot_timeout = stride queue wait expired)",
            ["tenant", "reason"],
        ),
        inflight=c.gauge(
            "lodestar_offload_tenant_inflight",
            "Granted service slots per tenant",
            ["tenant"],
        ),
        quota_weight=c.gauge(
            "lodestar_offload_tenant_quota_weight",
            "Configured stride-fair service weight per tenant",
            ["tenant"],
        ),
        slack=c.histogram(
            "lodestar_offload_tenant_slack_seconds",
            "Remaining slot-deadline slack at verdict per tenant and "
            "priority class (negative = the verdict landed past the "
            "class deadline) — requires the server to be launched with "
            "--genesis-time so it shares the tenants' slot clock",
            _SEC_SLACK,
            ["tenant", "class"],
        ),
    )


@dataclass
class BlsPrepMetrics:
    """lodestar_bls_prep_* — batch-verify input preparation
    (`models/batch_verify.py` prep modes, `ops/prep.py` device stages):
    sets prepared per layer (device on-chip pipeline vs host
    native/python), prep wall time, device→host fallbacks and
    structurally-rejected batches."""

    sets: Counter  # sets prepared, labeled by layer (device/host/single_launch)
    seconds: Histogram  # per-call prep wall time, labeled by layer
    fallbacks: Counter  # device-prep errors degraded to host prep
    single_launch_fallbacks: Counter  # single-launch errors degraded to the split schedule
    rejected: Counter  # prep calls that rejected a structurally invalid batch
    launches: Counter  # ALL dispatches at ops/prep.py's seam (prep legs AND single-launch verifies)


@dataclass
class BlsPipelineMetrics:
    """lodestar_bls_pipeline_* — the prep→verify double buffer
    (`chain/bls/pool.py` `_OverlapTracker`/`pipeline_stats()`): live
    gauges over the pool's pipeline accounting, evaluated at scrape
    time via `set_function` (the same pattern as the occupancy gauges)
    so the previously process-trapped `pipeline_stats()` numbers are
    dashboard-readable during a run, not only from bench harnesses."""

    overlap_occupancy_pct: Gauge  # % of verify busy time with a prep stage in flight
    staged_packages: Gauge  # packages staged through the double buffer (cumulative)
    prep_seconds: Gauge  # cumulative prep-stage busy seconds
    verify_seconds: Gauge  # cumulative verify-stage busy seconds


@dataclass
class DeviceLaunchMetrics:
    """lodestar_device_launch_* / lodestar_device_compile_* — the launch
    telemetry layer (`lodestar_tpu/telemetry.py`): per-dispatch wall
    time by program and size class at the counted dispatch seams
    (ops/prep `_dispatch`, ssz/device_htr `_device_level`, mesh lane
    launches, the batch-verify jit-cache seams), plus first-call
    compile-detection counters — the compile-vs-dispatch decomposition
    the hardware measurement campaign reads."""

    launch_seconds: Histogram  # dispatch wall time, labeled by program + size_class
    compile_seconds: Counter  # wall time of first-call (trace+compile) dispatches
    compile_hits: Counter  # dispatches whose (program, size_class) was already compiled
    compile_misses: Counter  # first-call dispatches per (program, size_class) key


@dataclass
class SszHtrMetrics:
    """lodestar_ssz_htr_* — device hashTreeRoot (`ssz/device_htr.py`
    collector, `state_transition/htr.py` tracker): dirty-subtree
    flushes per backend, dirty chunk volume, batched hash launches
    (the one-per-level invariant's observable), flush wall time, and
    device→CPU degradations."""

    flushes: Counter  # collector flushes served, labeled by backend (device/cpu)
    dirty_chunks: Counter  # dirty leaf chunks re-hashed across flushes
    launches: Counter  # ALL device hash_pairs dispatches (collector flush levels + shared-hook batch levels)
    seconds: Histogram  # per-flush wall time, labeled by backend
    fallbacks: Counter  # degradations, by leg (flush: device err → CPU hasher; tracker: bug → value path)


@dataclass
class KzgMetrics:
    """lodestar_kzg_* — KZG blob verification (`crypto/kzg.py`): the
    degrade-and-count observable for the device pairing check (device
    error → CPU oracle verdict, counted where the degradation is
    served)."""

    device_fallbacks: Counter  # device pairing errors served by the CPU oracle


@dataclass
class TraceMetrics:
    """lodestar_trace_* — span-duration summaries derived from the
    per-slot pipeline tracer (`lodestar_tpu/tracing`): every completed
    trace feeds its spans here so the block-pipeline-trace dashboard
    renders from Prometheus without scraping the debug trace API."""

    span_duration: Histogram  # labeled by span name
    block_pipeline_time: Histogram  # root-trace (block import) duration
    traces_completed: Counter
    slow_slots: Counter


@dataclass
class SloMetrics:
    """lodestar_slo_* — slot-deadline SLO accounting (`lodestar_tpu/slo`):
    remaining-slack histograms per priority class at each lifecycle
    stage (enqueue/dispatch/verdict), deadline-miss counters, and the
    good/total SLI pair the generated multi-window burn-rate alerts
    (`tools/gen_alerts.py`) consume as numerator/denominator."""

    slack_seconds: Histogram  # remaining slack (negative = past deadline), by class + stage
    deadline_miss: Counter  # verdicts that landed under the slack floor, by class
    sli_good: Counter  # SLI numerator: ok verdicts inside the deadline, by class
    sli_total: Counter  # SLI denominator: all verdicts, by class


@dataclass
class BeaconMetrics:
    creator: RegistryMetricCreator
    bls_pool: BlsPoolMetrics
    bls_prep: "BlsPrepMetrics"
    bls_pipeline: "BlsPipelineMetrics"
    device_launch: "DeviceLaunchMetrics"
    ssz_htr: "SszHtrMetrics"
    kzg: "KzgMetrics"
    state_transition: StateTransitionMetrics
    gossip: GossipMetrics
    fork_choice: ForkChoiceMetrics
    network: "NetworkMetrics"
    sync: "SyncMetrics"
    db: "DbMetrics"
    regen: "RegenMetrics"
    op_pool: "OpPoolMetrics"
    api: "ApiMetrics"
    reqresp: "ReqRespMetrics"
    peer: "PeerMetrics"
    gossip_detail: "GossipDetailMetrics"
    sync_detail: "SyncDetailMetrics"
    db_detail: "DbDetailMetrics"
    chain: "ChainDetailMetrics"
    process: "ProcessMetrics"
    trace: "TraceMetrics"
    slo: "SloMetrics"
    sched: "SchedulerMetrics"
    resilience: "ResilienceMetrics"
    audit: "AuditMetrics"
    head_slot: Gauge
    finalized_epoch: Gauge
    justified_epoch: Gauge
    clock_slot: Gauge
    peers: Gauge
    validator_monitor: "ValidatorMonitor"

    def scrape(self) -> bytes:
        return self.creator.scrape()


_SEC_SMALL = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5)
_SEC_TINY = (0.0001, 0.001, 0.01, 0.1, 1)
#: launch-latency ladder: dense below 5 ms (steady-state dispatches all
#: land there — the old ladder jumped 1→5→50 ms and folded every
#: healthy launch into two buckets), then stretching to slot length and
#: the worst trace+compile stall
_SEC_LAUNCH = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1, 2, 5, 12, 30, 120,
)
#: slack ladder: symmetric around the deadline — negative buckets size
#: the miss (how late), positive buckets the margin, bounded at ±slot
#: lengths (a backfill job can hold multi-slot slack)
_SEC_SLACK = (
    -12, -4, -1, -0.25, -0.05, 0, 0.05, 0.1, 0.25, 0.5, 1, 2, 4, 8, 12, 48, 384,
)


def create_metrics() -> BeaconMetrics:
    """Reference `createMetrics` (`metrics/metrics.ts:14`)."""
    c = RegistryMetricCreator()
    bls = BlsPoolMetrics(
        job_wait_time=c.histogram(
            "lodestar_bls_thread_pool_queue_job_wait_time_seconds",
            "Time a job waited in queue before execution", _SEC_SMALL,
        ),
        jobs_started=c.counter(
            "lodestar_bls_thread_pool_jobs_started_total", "Jobs started"
        ),
        sig_sets_started=c.counter(
            "lodestar_bls_thread_pool_sig_sets_started_total", "Signature sets started"
        ),
        success_sets=c.counter(
            "lodestar_bls_thread_pool_success_jobs_signature_sets_count", "Successful sets"
        ),
        error_sets=c.counter(
            "lodestar_bls_thread_pool_error_jobs_signature_sets_count", "Errored sets"
        ),
        batch_retries=c.counter(
            "lodestar_bls_thread_pool_batch_retries_total", "Invalid batches retried individually"
        ),
        batch_sigs_success=c.counter(
            "lodestar_bls_thread_pool_batch_sigs_success_total", "Sets verified in successful batches"
        ),
        time_per_sig_set=c.histogram(
            "lodestar_bls_thread_pool_time_per_sig_set_seconds", "Device time per set", _SEC_TINY,
        ),
        latency_to_device=c.histogram(
            "lodestar_bls_thread_pool_latency_to_worker", "Dispatch latency", _SEC_TINY,
        ),
        latency_from_device=c.histogram(
            "lodestar_bls_thread_pool_latency_from_worker", "Result latency", _SEC_TINY,
        ),
    )
    bls_prep = BlsPrepMetrics(
        sets=c.counter(
            "lodestar_bls_prep_sets_total",
            "Signature sets prepared (decompress + subgroup + hash-to-G2), by layer",
            ["layer"],
        ),
        seconds=c.histogram(
            "lodestar_bls_prep_seconds",
            "Input-prep wall time per batch, by layer (device/host)",
            _SEC_SMALL,
            ["layer"],
        ),
        fallbacks=c.counter(
            "lodestar_bls_prep_fallback_total",
            "Device input-prep errors degraded to the host prep path",
        ),
        single_launch_fallbacks=c.counter(
            "lodestar_bls_single_launch_fallback_total",
            "Single-launch verify errors (device fault or verdict-shape "
            "anomaly) degraded to the split prep-then-verify schedule",
        ),
        rejected=c.counter(
            "lodestar_bls_prep_rejected_total",
            "Prep calls that rejected a structurally invalid batch",
        ),
        launches=c.counter(
            "lodestar_bls_prep_launches_total",
            "Device program dispatches at the ops/prep.py launch seam "
            "(plain dispatch counter: fused-stage, per-leg, hash-to-G2 "
            "AND single-launch verify dispatches all count — per-schedule "
            "rates come from lodestar_device_launch_seconds{program}; the "
            "per-batch budget invariant is asserted in tests against the "
            "same seam)",
        ),
    )
    bls_pipeline = BlsPipelineMetrics(
        overlap_occupancy_pct=c.gauge(
            "lodestar_bls_pipeline_overlap_occupancy_pct",
            "Percent of verify-stage busy time with a prep stage in flight "
            "(the pool's pipeline_stats overlap accounting, scrape-time)",
        ),
        staged_packages=c.gauge(
            "lodestar_bls_pipeline_staged_packages",
            "Packages staged through the prep→verify double buffer "
            "(cumulative; 0 = the pipeline never engaged)",
        ),
        prep_seconds=c.gauge(
            "lodestar_bls_pipeline_prep_seconds_total",
            "Cumulative wall seconds some prep stage was in flight",
        ),
        verify_seconds=c.gauge(
            "lodestar_bls_pipeline_verify_seconds_total",
            "Cumulative wall seconds some verify stage was in flight",
        ),
    )
    device_launch = DeviceLaunchMetrics(
        launch_seconds=c.histogram(
            "lodestar_device_launch_seconds",
            "Device dispatch wall time at the counted launch seams, by "
            "program and pow-2 size class (host-observed: includes device "
            "execution on synchronous backends and trace+compile on the "
            "first call per class)",
            _SEC_LAUNCH,
            ["program", "size_class"],
        ),
        compile_seconds=c.counter(
            "lodestar_device_compile_seconds_total",
            "Wall seconds spent in first-call-per-(program,size_class) "
            "dispatches — the trace+compile (or persistent-cache load) tax",
        ),
        compile_hits=c.counter(
            "lodestar_device_compile_hits_total",
            "Dispatches whose (program, size_class) executable was already "
            "compiled in this process",
            ["program"],
        ),
        compile_misses=c.counter(
            "lodestar_device_compile_misses_total",
            "First-call dispatches per (program, size_class) — each paid "
            "trace+compile or a persistent-cache load",
            ["program"],
        ),
    )
    ssz_htr = SszHtrMetrics(
        flushes=c.counter(
            "lodestar_ssz_htr_flushes_total",
            "Dirty-subtree collector flushes, by backend (device/cpu)",
            ["backend"],
        ),
        dirty_chunks=c.counter(
            "lodestar_ssz_htr_dirty_chunks_total",
            "Dirty leaf chunks re-hashed by collector flushes",
        ),
        launches=c.counter(
            "lodestar_ssz_htr_launches_total",
            "Device hash_pairs dispatches issued, counted at the dispatch site "
            "(collector flush levels plus shared-hook batch levels; the per-flush "
            "launch-count invariant itself is asserted by tests)",
        ),
        seconds=c.histogram(
            "lodestar_ssz_htr_seconds",
            "Collector flush wall time, by backend",
            _SEC_SMALL,
            ["backend"],
        ),
        fallbacks=c.counter(
            "lodestar_ssz_htr_fallback_total",
            "HTR degradations, by leg (flush: device error to CPU hasher; tracker: tracker error to value path)",
            ["leg"],
        ),
    )
    kzg = KzgMetrics(
        device_fallbacks=c.counter(
            "lodestar_kzg_device_fallback_total",
            "KZG device pairing failures served by the CPU oracle verdict "
            "(counted where the degradation is served, crypto/kzg.py)",
        ),
    )
    st = StateTransitionMetrics(
        epoch_transition_time=c.histogram(
            "lodestar_stfn_epoch_transition_seconds", "Epoch transition time", _SEC_SMALL
        ),
        process_block_time=c.histogram(
            "lodestar_stfn_process_block_seconds", "Block processing time", _SEC_SMALL
        ),
        state_hash_tree_root_time=c.histogram(
            "lodestar_stfn_hash_tree_root_seconds", "State hashTreeRoot time", _SEC_SMALL
        ),
    )
    gossip = GossipMetrics(
        queue_length=c.gauge(
            "lodestar_gossip_validation_queue_length", "Gossip queue length", ["topic"]
        ),
        queue_dropped=c.counter(
            "lodestar_gossip_validation_queue_dropped_jobs_total", "Dropped gossip jobs", ["topic"]
        ),
        accepted=c.counter(
            "lodestar_gossip_validation_accept_total", "Accepted gossip objects", ["topic"]
        ),
        rejected=c.counter(
            "lodestar_gossip_validation_reject_total", "Rejected gossip objects", ["topic"]
        ),
    )
    fc = ForkChoiceMetrics(
        find_head_time=c.histogram(
            "lodestar_fork_choice_find_head_seconds", "findHead time", _SEC_TINY
        ),
        requests=c.counter("lodestar_fork_choice_requests_total", "findHead calls"),
        errors=c.counter("lodestar_fork_choice_errors_total", "fork choice errors"),
        reorgs=c.counter("lodestar_fork_choice_reorg_events_total", "reorg events"),
    )
    network = NetworkMetrics(
        peers_by_direction=c.gauge(
            "lodestar_peers_by_direction_count", "Connected peers by direction", ["direction"]
        ),
        peer_disconnects=c.counter(
            "lodestar_peer_disconnects_total", "Peer disconnects", ["reason"]
        ),
        gossip_mesh_peers=c.gauge(
            "lodestar_gossip_mesh_peers_by_type_count", "Gossip mesh peers", ["type"]
        ),
        gossip_received=c.counter(
            "lodestar_gossip_peer_received_messages_total", "Gossip messages received"
        ),
        gossip_duplicates=c.counter(
            "lodestar_gossipsub_seen_cache_duplicates_total", "Duplicate gossip messages"
        ),
    )
    sync = SyncMetrics(
        range_sync_batches=c.counter(
            "lodestar_sync_range_batches_total", "Range-sync batches processed", ["status"]
        ),
        range_sync_blocks=c.counter(
            "lodestar_sync_range_blocks_total", "Blocks imported by range sync"
        ),
        range_sync_errors=c.counter(
            "lodestar_sync_range_errors_total", "Range sync batch failures"
        ),
        backfill_blocks=c.counter(
            "lodestar_backfill_sync_blocks_total", "Blocks verified by backfill"
        ),
        unknown_block_requests=c.counter(
            "lodestar_sync_unknown_block_requests_total", "Unknown-block sync triggers"
        ),
    )
    db = DbMetrics(
        reads=c.counter("lodestar_db_read_req_total", "DB read requests", ["bucket"]),
        writes=c.counter("lodestar_db_write_req_total", "DB write requests", ["bucket"]),
        size_bytes=c.gauge("lodestar_db_size_bytes", "Approximate DB size"),
    )
    regen = RegenMetrics(
        state_cache_hits=c.counter("lodestar_state_cache_hits_total", "State cache hits"),
        state_cache_misses=c.counter(
            "lodestar_state_cache_misses_total", "State cache misses"
        ),
        checkpoint_cache_hits=c.counter(
            "lodestar_cp_state_cache_hits_total", "Checkpoint state cache hits"
        ),
        regen_queue_length=c.gauge(
            "lodestar_regen_queue_length", "Queued regen requests"
        ),
        regen_time=c.histogram(
            "lodestar_regen_fn_call_duration_seconds", "State regen time", _SEC_SMALL
        ),
    )
    op_pool = OpPoolMetrics(
        attestation_pool_size=c.gauge(
            "lodestar_op_pool_attestation_pool_size", "Unaggregated attestation pool size"
        ),
        aggregated_pool_size=c.gauge(
            "lodestar_op_pool_aggregated_attestation_pool_size", "Aggregated pool size"
        ),
        exits=c.gauge("lodestar_op_pool_voluntary_exit_pool_size", "Voluntary exits pooled"),
        proposer_slashings=c.gauge(
            "lodestar_op_pool_proposer_slashing_pool_size", "Proposer slashings pooled"
        ),
        attester_slashings=c.gauge(
            "lodestar_op_pool_attester_slashing_pool_size", "Attester slashings pooled"
        ),
        sync_messages=c.gauge(
            "lodestar_op_pool_sync_committee_message_pool_size", "Sync messages pooled"
        ),
    )
    api = ApiMetrics(
        rest_requests=c.counter(
            "lodestar_api_rest_requests_total", "REST API requests", ["method", "status"]
        ),
        rest_errors=c.counter("lodestar_api_rest_errors_total", "REST API 5xx errors"),
        rest_response_time=c.histogram(
            "lodestar_api_rest_response_time_seconds", "REST response time", _SEC_SMALL
        ),
    )
    reqresp = ReqRespMetrics(
        requests_sent=c.counter(
            "beacon_reqresp_outgoing_requests_total", "Outgoing requests", ["protocol"]
        ),
        requests_received=c.counter(
            "beacon_reqresp_incoming_requests_total", "Incoming requests", ["protocol"]
        ),
        request_errors=c.counter(
            "beacon_reqresp_incoming_errors_total", "Incoming request errors", ["protocol"]
        ),
        response_time=c.histogram(
            "beacon_reqresp_response_time_seconds", "Full response time", _SEC_SMALL, ["protocol"]
        ),
        response_chunks_sent=c.counter(
            "beacon_reqresp_outgoing_response_chunks_total", "Response chunks sent", ["protocol"]
        ),
        response_chunks_received=c.counter(
            "beacon_reqresp_incoming_response_chunks_total", "Response chunks received", ["protocol"]
        ),
        rate_limited=c.counter(
            "beacon_reqresp_rate_limited_total", "Rate-limited requests", ["protocol"]
        ),
        dial_timeouts=c.counter("beacon_reqresp_dial_timeouts_total", "Dial timeouts"),
        streams_reset=c.counter("beacon_reqresp_streams_reset_total", "Streams reset"),
    )
    peer = PeerMetrics(
        peer_count=c.gauge("lodestar_peers_count", "Connected peer count"),
        peers_by_client=c.gauge("lodestar_peers_by_client_count", "Peers by client", ["client"]),
        peer_score=c.histogram(
            "lodestar_app_peer_score", "Application peer scores", (-100, -50, -10, 0, 10, 50, 100)
        ),
        peer_action_count=c.counter(
            "lodestar_peers_report_peer_count_total", "Peer score actions", ["action"]
        ),
        goodbye_sent=c.counter("lodestar_peer_goodbye_sent_total", "Goodbyes sent", ["reason"]),
        goodbye_received=c.counter(
            "lodestar_peer_goodbye_received_total", "Goodbyes received", ["reason"]
        ),
        dials_attempted=c.counter("lodestar_peers_dial_attempts_total", "Dial attempts"),
        dials_succeeded=c.counter("lodestar_peers_dial_success_total", "Successful dials"),
        long_lived_subnets=c.gauge(
            "lodestar_peers_long_lived_attnets_count", "Long-lived attnet subscriptions"
        ),
        discv5_sessions=c.gauge("lodestar_discv5_active_sessions_count", "discv5 sessions"),
        discv5_findnode_sent=c.counter(
            "lodestar_discv5_findnode_sent_total", "FINDNODE queries sent"
        ),
        discv5_enrs_discovered=c.counter(
            "lodestar_discv5_discovered_enrs_total", "ENRs discovered"
        ),
    )
    gossip_detail = GossipDetailMetrics(
        mesh_grafts=c.counter("lodestar_gossip_mesh_graft_total", "Mesh grafts", ["topic"]),
        mesh_prunes=c.counter("lodestar_gossip_mesh_prune_total", "Mesh prunes", ["topic"]),
        ihave_sent=c.counter("lodestar_gossip_ihave_sent_total", "IHAVE control messages sent"),
        iwant_received=c.counter("lodestar_gossip_iwant_received_total", "IWANT requests received"),
        iwant_served=c.counter("lodestar_gossip_iwant_served_total", "IWANT messages served"),
        mcache_size=c.gauge("lodestar_gossip_mcache_size", "Message cache entries"),
        peer_score_by_topic=c.gauge(
            "lodestar_gossip_score_by_topic", "Mean peer score per topic", ["topic"]
        ),
        flood_publishes=c.counter("lodestar_gossip_flood_publish_total", "Flood publishes"),
        backoff_violations=c.counter(
            "lodestar_gossip_graft_backoff_violations_total", "Grafts inside backoff"
        ),
    )
    sync_detail = SyncDetailMetrics(
        status=c.gauge("lodestar_sync_status", "0=stalled 1=syncing 2=synced"),
        peers_by_status=c.gauge(
            "lodestar_sync_peers_by_status_count", "Peers by sync usefulness", ["status"]
        ),
        batch_download_time=c.histogram(
            "lodestar_sync_range_batch_download_seconds", "Batch download time", _SEC_SMALL
        ),
        batch_processing_time=c.histogram(
            "lodestar_sync_range_batch_processing_seconds", "Batch processing time", _SEC_SMALL
        ),
        batches_downloaded=c.counter(
            "lodestar_sync_range_batches_downloaded_total", "Batches downloaded"
        ),
        batch_download_retries=c.counter(
            "lodestar_sync_range_download_retries_total", "Batch download retries"
        ),
        head_distance=c.gauge("lodestar_sync_head_distance_slots", "Slots behind the clock"),
        backfill_earliest_slot=c.gauge(
            "lodestar_backfill_earliest_slot", "Earliest backfilled slot"
        ),
        unknown_block_queue_length=c.gauge(
            "lodestar_sync_unknown_block_pending_count", "Pending unknown-block roots"
        ),
    )
    db_detail = DbDetailMetrics(
        read_items=c.counter("lodestar_db_read_items_total", "Items read", ["bucket"]),
        write_items=c.counter("lodestar_db_write_items_total", "Items written", ["bucket"]),
        batch_write_time=c.histogram(
            "lodestar_db_batch_write_seconds", "Batch write latency", _SEC_TINY
        ),
        wal_size_bytes=c.gauge("lodestar_db_wal_size_bytes", "Write-ahead log size"),
        archived_states=c.counter("lodestar_db_archived_states_total", "States archived"),
        archived_blocks=c.counter("lodestar_db_archived_blocks_total", "Blocks archived"),
        pruned_blocks=c.counter("lodestar_db_pruned_blocks_total", "Hot blocks pruned"),
    )
    chain = ChainDetailMetrics(
        block_import_time=c.histogram(
            "lodestar_block_processor_import_seconds", "Full block import time", _SEC_SMALL
        ),
        block_production_time=c.histogram(
            "lodestar_block_production_seconds", "Block production time", _SEC_SMALL
        ),
        blocks_imported=c.counter("lodestar_blocks_imported_total", "Blocks imported", ["source"]),
        blocks_rejected=c.counter("lodestar_blocks_rejected_total", "Blocks rejected", ["reason"]),
        attestations_imported=c.counter(
            "lodestar_attestations_imported_total", "Attestations applied to fork choice"
        ),
        seen_attesters_size=c.gauge("lodestar_seen_cache_attesters_size", "Seen attesters"),
        seen_aggregators_size=c.gauge("lodestar_seen_cache_aggregators_size", "Seen aggregators"),
        checkpoint_state_cache_size=c.gauge(
            "lodestar_cp_state_cache_size", "Checkpoint state cache entries"
        ),
        state_cache_size=c.gauge("lodestar_state_cache_size", "Hot state cache entries"),
        light_client_updates_served=c.counter(
            "lodestar_light_client_updates_served_total", "LC updates served"
        ),
        light_client_bootstraps_served=c.counter(
            "lodestar_light_client_bootstraps_served_total", "LC bootstraps served"
        ),
        eth1_block_height=c.gauge("lodestar_eth1_latest_block_number", "Latest eth1 block seen"),
        eth1_deposits_fetched=c.counter("lodestar_eth1_deposit_events_total", "Deposit logs fetched"),
        eth1_requests=c.counter("lodestar_eth1_requests_total", "Eth1 JSON-RPC requests", ["method"]),
        engine_api_requests=c.counter(
            "lodestar_execution_engine_requests_total", "Engine API requests", ["method"]
        ),
        engine_api_time=c.histogram(
            "lodestar_execution_engine_request_seconds", "Engine API latency", _SEC_SMALL
        ),
        builder_requests=c.counter(
            "lodestar_builder_requests_total", "Builder API requests", ["method", "status"]
        ),
        builder_circuit_open=c.gauge(
            "lodestar_builder_circuit_breaker_open", "Builder circuit breaker state"
        ),
    )
    process = ProcessMetrics(
        event_loop_lag=c.histogram(
            "lodestar_event_loop_lag_seconds", "Event loop scheduling lag", _SEC_TINY
        ),
        start_time=c.gauge("process_start_time_seconds", "Process start unix time"),
        offload_outstanding=c.gauge(
            "lodestar_offload_outstanding_jobs", "Offload jobs in flight"
        ),
        offload_healthy=c.gauge("lodestar_offload_healthy", "Offload channel health bit"),
    )
    trace = TraceMetrics(
        span_duration=c.histogram(
            "lodestar_trace_span_duration_seconds",
            "Pipeline trace span duration by span name",
            _SEC_SMALL,
            ["span"],
        ),
        block_pipeline_time=c.histogram(
            "lodestar_trace_block_pipeline_seconds",
            "Root block-pipeline trace duration",
            _SEC_SMALL,
        ),
        traces_completed=c.counter(
            "lodestar_trace_completed_total", "Completed pipeline traces"
        ),
        slow_slots=c.counter(
            "lodestar_trace_slow_slot_total", "Slow-slot trace dumps emitted"
        ),
    )
    resilience = ResilienceMetrics(
        routed=c.counter(
            "lodestar_resilience_routed_total",
            "Offload verify RPCs issued per endpoint",
            ["endpoint"],
        ),
        shed=c.counter(
            "lodestar_resilience_shed_total",
            "Gossip work deferred because the offload verifier refused admission",
            ["reason"],
        ),
        failovers=c.counter(
            "lodestar_resilience_failover_total",
            "Failed offload attempts per endpoint (feeds the breaker)",
            ["endpoint"],
        ),
        hedges=c.counter(
            "lodestar_resilience_hedge_total",
            "Hedged retries issued to a second endpoint, by launch class",
            ["class"],
        ),
        hedge_wins=c.counter(
            "lodestar_resilience_hedge_win_total",
            "Hedged retries that returned the verdict, by launch class",
            ["class"],
        ),
        breaker_state=c.gauge(
            "lodestar_resilience_breaker_state",
            "Offload circuit breaker per endpoint: 0 closed / 1 half-open / 2 open",
            ["endpoint"],
        ),
        breaker_transitions=c.counter(
            "lodestar_resilience_breaker_transitions_total",
            "Breaker state transitions per endpoint and new state",
            ["endpoint", "state"],
        ),
        fallback_verifications=c.counter(
            "lodestar_resilience_fallback_total",
            "Verifications served after degrading to this layer",
            ["layer"],
        ),
        fallback_skipped=c.counter(
            "lodestar_resilience_fallback_skipped_total",
            "Verifier layers skipped because they refused work",
            ["layer"],
        ),
        fallback_active=c.gauge(
            "lodestar_resilience_fallback_active",
            "1 while the most recent verification was served by a non-primary layer",
        ),
        outage_unscored=c.counter(
            "lodestar_resilience_outage_unscored_total",
            "Gossip rejections caused by a local verifier outage, spared from peer downscoring",
        ),
    )
    audit = AuditMetrics(
        sampled=c.counter(
            "lodestar_offload_audit_sampled_total",
            "Offload verdicts sampled for independent re-verification, by class",
            ["class"],
        ),
        verified=c.counter(
            "lodestar_offload_audit_verified_total",
            "Completed audit re-verifications by outcome (agree/disagree)",
            ["outcome"],
        ),
        dropped=c.counter(
            "lodestar_offload_audit_dropped_total",
            "Sampled verdicts not audited (queue_full/queue_bytes/audit_error)",
            ["reason"],
        ),
        byzantine=c.counter(
            "lodestar_offload_audit_byzantine_total",
            "Byzantine events: helper verdicts contradicted by re-verification",
            ["endpoint"],
        ),
        trust_score=c.gauge(
            "lodestar_offload_audit_trust_score",
            "Per-endpoint audit trust EWMA (1.0 = never contradicted)",
            ["endpoint"],
        ),
        quarantined=c.gauge(
            "lodestar_offload_audit_quarantined",
            "1 while the endpoint is quarantined for a Byzantine event",
            ["endpoint"],
        ),
        queue_depth=c.gauge(
            "lodestar_offload_audit_queue_depth", "Audit re-verification backlog"
        ),
        cpu_seconds=c.counter(
            "lodestar_offload_audit_cpu_seconds_total",
            "CPU time spent re-verifying sampled verdicts (budget accounting)",
        ),
    )
    slo = SloMetrics(
        slack_seconds=c.histogram(
            "lodestar_slo_slack_seconds",
            "Remaining slot-deadline slack per priority class at each "
            "lifecycle stage (enqueue/dispatch/verdict); negative = the "
            "stage happened past the class deadline",
            _SEC_SLACK,
            ["class", "stage"],
        ),
        deadline_miss=c.counter(
            "lodestar_slo_deadline_miss_total",
            "Verdicts that landed with less slack than the configured "
            "floor (--slo-slack-floor-ms), counted once per job",
            ["class"],
        ),
        sli_good=c.counter(
            "lodestar_slo_sli_good_total",
            "SLI numerator: verdicts that were ok AND inside the class "
            "deadline (pairs with lodestar_slo_sli_total for burn rates)",
            ["class"],
        ),
        sli_total=c.counter(
            "lodestar_slo_sli_total",
            "SLI denominator: all verdicts, counted once per job",
            ["class"],
        ),
    )
    sched = SchedulerMetrics(
        queue_depth=c.gauge(
            "lodestar_sched_queue_depth", "Device scheduler queue depth", ["class"]
        ),
        queue_wait=c.histogram(
            "lodestar_sched_queue_wait_seconds",
            "Launch-queue wait (enqueue to dequeue) by class",
            _SEC_SMALL,
            ["class"],
        ),
        jobs_dequeued=c.counter(
            "lodestar_sched_jobs_dequeued_total", "Jobs dequeued for launch", ["class"]
        ),
        starvation_promotions=c.counter(
            "lodestar_sched_starvation_promotions_total",
            "Jobs served by aging ahead of the fair order",
        ),
        occupancy_permille=c.gauge(
            "lodestar_sched_occupancy_permille", "EWMA device busy-ns per wall-ns (0-1000)"
        ),
        admission_state=c.gauge(
            "lodestar_sched_admission_state", "0 accept / 1 shed bulk / 2 reject"
        ),
        shed_total=c.counter(
            "lodestar_sched_shed_total", "Work deferred by backpressure/admission", ["class"]
        ),
        lane_occupancy=c.gauge(
            "lodestar_sched_lane_occupancy_permille",
            "Per-chip EWMA busy-ns per wall-ns (0-1000)",
            ["device"],
        ),
        lane_launches=c.counter(
            "lodestar_sched_lane_launches_total",
            "Device launches per mesh lane (mode: single or sharded collective)",
            ["device", "mode"],
        ),
        lane_wedge_trips=c.counter(
            "lodestar_sched_lane_wedge_trips_total",
            "Per-chip wedge-breaker trips (lane degraded out of the mesh)",
            ["device"],
        ),
        mesh_lanes=c.gauge(
            "lodestar_sched_mesh_lanes_available",
            "Mesh lanes currently serving (non-wedged)",
        ),
    )
    return BeaconMetrics(
        creator=c,
        bls_pool=bls,
        bls_prep=bls_prep,
        bls_pipeline=bls_pipeline,
        device_launch=device_launch,
        ssz_htr=ssz_htr,
        kzg=kzg,
        state_transition=st,
        gossip=gossip,
        fork_choice=fc,
        network=network,
        sync=sync,
        db=db,
        regen=regen,
        op_pool=op_pool,
        api=api,
        reqresp=reqresp,
        peer=peer,
        gossip_detail=gossip_detail,
        sync_detail=sync_detail,
        db_detail=db_detail,
        chain=chain,
        process=process,
        trace=trace,
        slo=slo,
        sched=sched,
        resilience=resilience,
        audit=audit,
        head_slot=c.gauge("beacon_head_slot", "Current head slot"),
        finalized_epoch=c.gauge("beacon_finalized_epoch", "Finalized epoch"),
        justified_epoch=c.gauge("beacon_current_justified_epoch", "Justified epoch"),
        clock_slot=c.gauge("beacon_clock_slot", "Current wall-clock slot"),
        peers=c.gauge("libp2p_peers", "Connected peers"),
        validator_monitor=ValidatorMonitor(c),
    )


class MetricsServer:
    """Minimal /metrics scrape endpoint (reference `server/http.ts:14`)."""

    def __init__(self, metrics: BeaconMetrics, port: int = 8008, host: str = "127.0.0.1"):
        self.metrics = metrics
        self.port = port
        self.host = host
        self._httpd = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        import http.server

        metrics = self.metrics

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    body = metrics.scrape()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/healthz":
                    # liveness probe (k8s-style): the scrape server being
                    # able to answer at all is the signal
                    body = b'{"status":"ok"}'
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
