"""Metrics registry + beacon metric taxonomy + scrape server.

Reference `beacon-node/src/metrics/` — `RegistryMetricCreator`
(`utils/registryMetricCreator.ts`), the lodestar metric groups
(`metrics/lodestar.ts`, incl. the blsThreadPool.* latency decomposition
at :358-430 and the state-transition timers at :279,302), and the HTTP
scrape server (`server/http.ts:14`). Built on prometheus_client (in
image); metric names keep the reference's so existing Grafana dashboards
(`dashboards/lodestar_bls_thread_pool.json`, ...) read unmodified.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Sequence

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from .validator_monitor import ValidatorMonitor

__all__ = [
    "RegistryMetricCreator",
    "BeaconMetrics",
    "create_metrics",
    "MetricsServer",
    "ValidatorMonitor",
]


class RegistryMetricCreator:
    """Typed factory bound to one registry (reference
    `registryMetricCreator.ts`)."""

    def __init__(self) -> None:
        self.registry = CollectorRegistry()

    def gauge(self, name: str, help_: str, labels: Sequence[str] = ()) -> Gauge:
        return Gauge(name, help_, labelnames=list(labels), registry=self.registry)

    def counter(self, name: str, help_: str, labels: Sequence[str] = ()) -> Counter:
        return Counter(name, help_, labelnames=list(labels), registry=self.registry)

    def histogram(
        self, name: str, help_: str, buckets: Sequence[float], labels: Sequence[str] = ()
    ) -> Histogram:
        return Histogram(
            name, help_, labelnames=list(labels), buckets=list(buckets), registry=self.registry
        )

    def scrape(self) -> bytes:
        return generate_latest(self.registry)


@dataclass
class BlsPoolMetrics:
    """blsThreadPool.* (reference `metrics/lodestar.ts:358-430`) — the
    worker-pool latency decomposition retargeted at the device pipeline."""

    job_wait_time: Histogram
    jobs_started: Counter
    sig_sets_started: Counter
    success_sets: Counter
    error_sets: Counter
    batch_retries: Counter
    batch_sigs_success: Counter
    time_per_sig_set: Histogram
    latency_to_device: Histogram
    latency_from_device: Histogram


@dataclass
class StateTransitionMetrics:
    epoch_transition_time: Histogram
    process_block_time: Histogram
    state_hash_tree_root_time: Histogram


@dataclass
class GossipMetrics:
    queue_length: Gauge
    queue_dropped: Counter
    accepted: Counter
    rejected: Counter


@dataclass
class ForkChoiceMetrics:
    find_head_time: Histogram
    requests: Counter
    errors: Counter
    reorgs: Counter


@dataclass
class NetworkMetrics:
    peers_by_direction: Gauge
    peer_disconnects: Counter
    gossip_mesh_peers: Gauge
    gossip_received: Counter
    gossip_duplicates: Counter
    reqresp_requests_sent: Counter
    reqresp_requests_received: Counter
    reqresp_errors: Counter


@dataclass
class SyncMetrics:
    range_sync_batches: Counter
    range_sync_blocks: Counter
    range_sync_errors: Counter
    backfill_blocks: Counter
    unknown_block_requests: Counter


@dataclass
class DbMetrics:
    reads: Counter
    writes: Counter
    size_bytes: Gauge


@dataclass
class RegenMetrics:
    state_cache_hits: Counter
    state_cache_misses: Counter
    checkpoint_cache_hits: Counter
    regen_queue_length: Gauge
    regen_time: Histogram


@dataclass
class OpPoolMetrics:
    attestation_pool_size: Gauge
    aggregated_pool_size: Gauge
    exits: Gauge
    proposer_slashings: Gauge
    attester_slashings: Gauge
    sync_messages: Gauge


@dataclass
class ApiMetrics:
    rest_requests: Counter
    rest_errors: Counter
    rest_response_time: Histogram


@dataclass
class BeaconMetrics:
    creator: RegistryMetricCreator
    bls_pool: BlsPoolMetrics
    state_transition: StateTransitionMetrics
    gossip: GossipMetrics
    fork_choice: ForkChoiceMetrics
    network: "NetworkMetrics"
    sync: "SyncMetrics"
    db: "DbMetrics"
    regen: "RegenMetrics"
    op_pool: "OpPoolMetrics"
    api: "ApiMetrics"
    head_slot: Gauge
    finalized_epoch: Gauge
    justified_epoch: Gauge
    clock_slot: Gauge
    peers: Gauge
    validator_monitor: "ValidatorMonitor"

    def scrape(self) -> bytes:
        return self.creator.scrape()


_SEC_SMALL = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5)
_SEC_TINY = (0.0001, 0.001, 0.01, 0.1, 1)


def create_metrics() -> BeaconMetrics:
    """Reference `createMetrics` (`metrics/metrics.ts:14`)."""
    c = RegistryMetricCreator()
    bls = BlsPoolMetrics(
        job_wait_time=c.histogram(
            "lodestar_bls_thread_pool_queue_job_wait_time_seconds",
            "Time a job waited in queue before execution", _SEC_SMALL,
        ),
        jobs_started=c.counter(
            "lodestar_bls_thread_pool_jobs_started_total", "Jobs started"
        ),
        sig_sets_started=c.counter(
            "lodestar_bls_thread_pool_sig_sets_started_total", "Signature sets started"
        ),
        success_sets=c.counter(
            "lodestar_bls_thread_pool_success_jobs_signature_sets_count", "Successful sets"
        ),
        error_sets=c.counter(
            "lodestar_bls_thread_pool_error_jobs_signature_sets_count", "Errored sets"
        ),
        batch_retries=c.counter(
            "lodestar_bls_thread_pool_batch_retries_total", "Invalid batches retried individually"
        ),
        batch_sigs_success=c.counter(
            "lodestar_bls_thread_pool_batch_sigs_success_total", "Sets verified in successful batches"
        ),
        time_per_sig_set=c.histogram(
            "lodestar_bls_thread_pool_time_per_sig_set_seconds", "Device time per set", _SEC_TINY,
        ),
        latency_to_device=c.histogram(
            "lodestar_bls_thread_pool_latency_to_worker", "Dispatch latency", _SEC_TINY,
        ),
        latency_from_device=c.histogram(
            "lodestar_bls_thread_pool_latency_from_worker", "Result latency", _SEC_TINY,
        ),
    )
    st = StateTransitionMetrics(
        epoch_transition_time=c.histogram(
            "lodestar_stfn_epoch_transition_seconds", "Epoch transition time", _SEC_SMALL
        ),
        process_block_time=c.histogram(
            "lodestar_stfn_process_block_seconds", "Block processing time", _SEC_SMALL
        ),
        state_hash_tree_root_time=c.histogram(
            "lodestar_stfn_hash_tree_root_seconds", "State hashTreeRoot time", _SEC_SMALL
        ),
    )
    gossip = GossipMetrics(
        queue_length=c.gauge(
            "lodestar_gossip_validation_queue_length", "Gossip queue length", ["topic"]
        ),
        queue_dropped=c.counter(
            "lodestar_gossip_validation_queue_dropped_jobs_total", "Dropped gossip jobs", ["topic"]
        ),
        accepted=c.counter(
            "lodestar_gossip_validation_accept_total", "Accepted gossip objects", ["topic"]
        ),
        rejected=c.counter(
            "lodestar_gossip_validation_reject_total", "Rejected gossip objects", ["topic"]
        ),
    )
    fc = ForkChoiceMetrics(
        find_head_time=c.histogram(
            "lodestar_fork_choice_find_head_seconds", "findHead time", _SEC_TINY
        ),
        requests=c.counter("lodestar_fork_choice_requests_total", "findHead calls"),
        errors=c.counter("lodestar_fork_choice_errors_total", "fork choice errors"),
        reorgs=c.counter("lodestar_fork_choice_reorg_events_total", "reorg events"),
    )
    network = NetworkMetrics(
        peers_by_direction=c.gauge(
            "lodestar_peers_by_direction_count", "Connected peers by direction", ["direction"]
        ),
        peer_disconnects=c.counter(
            "lodestar_peer_disconnects_total", "Peer disconnects", ["reason"]
        ),
        gossip_mesh_peers=c.gauge(
            "lodestar_gossip_mesh_peers_by_type_count", "Gossip mesh peers", ["type"]
        ),
        gossip_received=c.counter(
            "lodestar_gossip_peer_received_messages_total", "Gossip messages received"
        ),
        gossip_duplicates=c.counter(
            "lodestar_gossipsub_seen_cache_duplicates_total", "Duplicate gossip messages"
        ),
        reqresp_requests_sent=c.counter(
            "beacon_reqresp_outgoing_requests_total", "Outgoing reqresp requests", ["method"]
        ),
        reqresp_requests_received=c.counter(
            "beacon_reqresp_incoming_requests_total", "Incoming reqresp requests", ["method"]
        ),
        reqresp_errors=c.counter(
            "beacon_reqresp_outgoing_errors_total", "Reqresp errors", ["method"]
        ),
    )
    sync = SyncMetrics(
        range_sync_batches=c.counter(
            "lodestar_sync_range_batches_total", "Range-sync batches processed", ["status"]
        ),
        range_sync_blocks=c.counter(
            "lodestar_sync_range_blocks_total", "Blocks imported by range sync"
        ),
        range_sync_errors=c.counter(
            "lodestar_sync_range_errors_total", "Range sync batch failures"
        ),
        backfill_blocks=c.counter(
            "lodestar_backfill_sync_blocks_total", "Blocks verified by backfill"
        ),
        unknown_block_requests=c.counter(
            "lodestar_sync_unknown_block_requests_total", "Unknown-block sync triggers"
        ),
    )
    db = DbMetrics(
        reads=c.counter("lodestar_db_read_req_total", "DB read requests", ["bucket"]),
        writes=c.counter("lodestar_db_write_req_total", "DB write requests", ["bucket"]),
        size_bytes=c.gauge("lodestar_db_size_bytes", "Approximate DB size"),
    )
    regen = RegenMetrics(
        state_cache_hits=c.counter("lodestar_state_cache_hits_total", "State cache hits"),
        state_cache_misses=c.counter(
            "lodestar_state_cache_misses_total", "State cache misses"
        ),
        checkpoint_cache_hits=c.counter(
            "lodestar_cp_state_cache_hits_total", "Checkpoint state cache hits"
        ),
        regen_queue_length=c.gauge(
            "lodestar_regen_queue_length", "Queued regen requests"
        ),
        regen_time=c.histogram(
            "lodestar_regen_fn_call_duration_seconds", "State regen time", _SEC_SMALL
        ),
    )
    op_pool = OpPoolMetrics(
        attestation_pool_size=c.gauge(
            "lodestar_op_pool_attestation_pool_size", "Unaggregated attestation pool size"
        ),
        aggregated_pool_size=c.gauge(
            "lodestar_op_pool_aggregated_attestation_pool_size", "Aggregated pool size"
        ),
        exits=c.gauge("lodestar_op_pool_voluntary_exit_pool_size", "Voluntary exits pooled"),
        proposer_slashings=c.gauge(
            "lodestar_op_pool_proposer_slashing_pool_size", "Proposer slashings pooled"
        ),
        attester_slashings=c.gauge(
            "lodestar_op_pool_attester_slashing_pool_size", "Attester slashings pooled"
        ),
        sync_messages=c.gauge(
            "lodestar_op_pool_sync_committee_message_pool_size", "Sync messages pooled"
        ),
    )
    api = ApiMetrics(
        rest_requests=c.counter(
            "lodestar_api_rest_requests_total", "REST API requests", ["method", "status"]
        ),
        rest_errors=c.counter("lodestar_api_rest_errors_total", "REST API 5xx errors"),
        rest_response_time=c.histogram(
            "lodestar_api_rest_response_time_seconds", "REST response time", _SEC_SMALL
        ),
    )
    return BeaconMetrics(
        creator=c,
        bls_pool=bls,
        state_transition=st,
        gossip=gossip,
        fork_choice=fc,
        network=network,
        sync=sync,
        db=db,
        regen=regen,
        op_pool=op_pool,
        api=api,
        head_slot=c.gauge("beacon_head_slot", "Current head slot"),
        finalized_epoch=c.gauge("beacon_finalized_epoch", "Finalized epoch"),
        justified_epoch=c.gauge("beacon_current_justified_epoch", "Justified epoch"),
        clock_slot=c.gauge("beacon_clock_slot", "Current wall-clock slot"),
        peers=c.gauge("libp2p_peers", "Connected peers"),
        validator_monitor=ValidatorMonitor(c),
    )


class MetricsServer:
    """Minimal /metrics scrape endpoint (reference `server/http.ts:14`)."""

    def __init__(self, metrics: BeaconMetrics, port: int = 8008, host: str = "127.0.0.1"):
        self.metrics = metrics
        self.port = port
        self.host = host
        self._httpd = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        import http.server

        metrics = self.metrics

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") == "/metrics":
                    body = metrics.scrape()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
