"""Per-validator performance monitor (reference
`metrics/validatorMonitor.ts`): track registered local validators'
block proposals and attestation life-cycle (seen on gossip, included in
blocks, inclusion distance), and summarize per epoch.

Wire-in points (the same seams the reference hooks):
* `register_local_validator(index)` — from the validator/keymanager
* `on_block_imported(slot, proposer_index)` — chain import
* `on_attestation_in_block(epoch, indices, inclusion_distance)` — STF
  block-ops processing
* `on_gossip_attestation(epoch, indices)` — gossip validation accept
* `on_epoch(epoch)` — clock epoch boundary: flush the previous epoch's
  summaries into the prometheus series
"""

from __future__ import annotations

from collections import defaultdict

__all__ = ["ValidatorMonitor"]


class ValidatorMonitor:
    def __init__(self, creator):
        self._validators: set[int] = set()
        self._first_observed_epoch: int | None = None
        # epoch -> index -> status
        self._gossip_seen: dict[int, set[int]] = defaultdict(set)
        self._included: dict[int, set[int]] = defaultdict(set)
        self._distances: dict[int, dict[int, int]] = defaultdict(dict)
        self._blocks: dict[int, int] = defaultdict(int)  # index -> proposals

        self.validators_total = creator.gauge(
            "validator_monitor_validators_total", "Registered local validators"
        )
        self.prev_epoch_attestations = creator.counter(
            "validator_monitor_prev_epoch_attestations_total",
            "Local validators attesting in the previous epoch",
        )
        self.prev_epoch_attestation_misses = creator.counter(
            "validator_monitor_prev_epoch_attestations_missed_total",
            "Local validators that missed the previous epoch",
        )
        self.prev_epoch_inclusion_distance = creator.histogram(
            "validator_monitor_prev_epoch_attestation_inclusion_distance",
            "Inclusion distance of local attestations",
            (1, 2, 3, 4, 8, 16, 32),
        )
        self.blocks_total = creator.counter(
            "validator_monitor_beacon_block_total", "Blocks proposed by local validators"
        )
        self.gossip_attestations = creator.counter(
            "validator_monitor_unaggregated_attestation_total",
            "Local attestations seen on gossip",
        )

    # -- registration ----------------------------------------------------------

    def register_local_validator(self, index: int) -> None:
        self._validators.add(int(index))
        self.validators_total.set(len(self._validators))

    @property
    def count(self) -> int:
        return len(self._validators)

    # -- observation hooks -----------------------------------------------------

    def on_block_imported(self, slot: int, proposer_index: int) -> None:
        if int(proposer_index) in self._validators:
            self._blocks[int(proposer_index)] += 1
            self.blocks_total.inc()

    def on_gossip_attestation(self, epoch: int, indices) -> None:
        if self._first_observed_epoch is None:
            self._first_observed_epoch = int(epoch)
        for i in indices:
            if int(i) in self._validators:
                self._gossip_seen[int(epoch)].add(int(i))
                self.gossip_attestations.inc()

    def on_attestation_in_block(self, epoch: int, indices, inclusion_distance: int) -> None:
        if self._first_observed_epoch is None:
            self._first_observed_epoch = int(epoch)
        dist = max(1, int(inclusion_distance))
        for i in indices:
            i = int(i)
            if i in self._validators:
                self._included[int(epoch)].add(i)
                prev = self._distances[int(epoch)].get(i)
                if prev is None or dist < prev:
                    self._distances[int(epoch)][i] = dist

    # -- epoch summary ---------------------------------------------------------

    def on_epoch(self, epoch: int) -> dict:
        """Flush epoch-2 (attestations for epoch e land up to e+1) and
        prune. Returns the summary dict for logging."""
        target = int(epoch) - 2
        if target < 0 or not self._validators:
            return {}
        included = self._included.pop(target, set())
        self._gossip_seen.pop(target, None)
        distances = self._distances.pop(target, {})
        # prune anything older than the flush target too (historical
        # range-sync epochs and clock jumps would otherwise accumulate
        # per-epoch sets for the process lifetime)
        for store in (self._included, self._gossip_seen, self._distances):
            for old in [e for e in store if e < target]:
                del store[old]
        # epochs before monitoring began have no observations by
        # construction: judging them would report a spurious 100% miss on
        # every restart
        if self._first_observed_epoch is None or target < self._first_observed_epoch:
            return {}
        hit = len(included & self._validators)
        miss = len(self._validators) - hit
        self.prev_epoch_attestations.inc(hit)
        self.prev_epoch_attestation_misses.inc(miss)
        for d in distances.values():
            self.prev_epoch_inclusion_distance.observe(d)
        return {
            "epoch": target,
            "attested": hit,
            "missed": miss,
            "avg_inclusion_distance": (
                sum(distances.values()) / len(distances) if distances else 0.0
            ),
        }
