"""Remote monitoring service (reference
`beacon-node/src/monitoring/service.ts:31-33,123-150`): periodically push
beaconcha.in-style client stats (process + beacon-node records) to a
remote endpoint. Transport injected for testability; scheduling via
asyncio like the reference's setTimeout loop."""

from __future__ import annotations

import asyncio
import json
import os
import time
import urllib.request

from lodestar_tpu.logger import get_logger

__all__ = ["MonitoringService", "EventLoopLagSampler"]

VERSION = "lodestar-tpu/0.3.0"


class EventLoopLagSampler:
    """Clock-drift sampler behind `ProcessMetrics.event_loop_lag`
    (reference nodeJsUtil monitorEventLoopDelay analogue): sleep a fixed
    interval on the loop and observe how late the wakeup lands — the
    overshoot is exactly the scheduling lag other tasks inflicted. The
    last sample is also surfaced into slow-slot trace dumps (via
    `Tracer.lag_ms_supplier`) so a dump distinguishes an event loop
    starved by Python work from a genuinely slow device pipeline."""

    def __init__(self, histogram=None, *, interval_s: float = 0.5) -> None:
        self.histogram = histogram
        self.interval = interval_s
        self.last_lag_s: float | None = None
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval)
            lag = max(0.0, loop.time() - t0 - self.interval)
            self.last_lag_s = lag
            if self.histogram is not None:
                try:
                    self.histogram.observe(lag)
                except Exception:
                    pass  # metric bridge must never kill the sampler

    def last_lag_ms(self) -> float | None:
        return None if self.last_lag_s is None else self.last_lag_s * 1000.0


class MonitoringService:
    def __init__(
        self,
        *,
        endpoint: str,
        chain=None,
        interval_sec: float = 60.0,
        send_fn=None,
    ) -> None:
        self.endpoint = endpoint
        self.chain = chain
        self.interval = interval_sec
        self._send = send_fn or self._http_send
        self._task: asyncio.Task | None = None
        self._start_time = time.time()
        self.log = get_logger(name="lodestar.monitoring")

    # -- stats records (service.ts collectData shape) -------------------------

    def collect(self) -> list[dict]:
        now_ms = int(time.time() * 1000)
        process = {
            "version": 1,
            "timestamp": now_ms,
            "process": "beaconnode",
            "client_name": "lodestar-tpu",
            "client_version": VERSION,
            "cpu_process_seconds_total": int(time.process_time()),
            "memory_process_bytes": _rss_bytes(),
            "sync_eth2_synced": True,
        }
        if self.chain is not None:
            head = self.chain.fork_choice.proto_array.get_block(self.chain.fork_choice.head)
            process.update(
                {
                    "sync_beacon_head_slot": head.slot if head else 0,
                    "slasher_active": False,
                }
            )
        return [process]

    # -- loop -----------------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                # the HTTP push is blocking urllib: keep it off the loop
                await loop.run_in_executor(None, self._send, self.collect())
            except Exception as e:
                self.log.warn(f"monitoring push failed: {e!r}")
            await asyncio.sleep(self.interval)

    def _http_send(self, records: list[dict]) -> None:
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(records).encode(),
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=10).read()


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0
