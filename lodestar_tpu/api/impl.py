"""Beacon API implementation over BeaconChain.

Reference `beacon-node/src/api/impl/` — each method returns plain JSON-
ready dicts ({"data": ...} envelopes per the Eth Beacon API spec), using
the generic eth2-JSON codecs over the registry types.
"""

from __future__ import annotations

import asyncio

from lodestar_tpu.chain.bls import VerifySignatureOpts
from lodestar_tpu.scheduler import PriorityClass
from lodestar_tpu.ssz.json import from_json, to_json
from lodestar_tpu.state_transition import EpochContext, compute_epoch_at_slot, process_slots
from lodestar_tpu.types import ssz_types

# REST-submitted objects verify under the API launch class: behind
# gossip work, ahead of sync bulk
_API_VERIFY_OPTS = VerifySignatureOpts(priority=PriorityClass.API)

__all__ = ["BeaconApiImpl", "ApiError"]

VERSION = "lodestar-tpu/0.3.0"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class BeaconApiImpl:
    def __init__(self, chain):
        self.chain = chain
        self.p = chain.p
        self.t = ssz_types(chain.p)

    def _run_async(self, coro):
        """Run a chain-mutating coroutine on the NODE's event loop when
        one is attached (chain.loop, set by BeaconNode.init). REST
        handler threads must not drive loop-bound machinery (the device
        BLS pool's queues/timers live on the main loop) nor mutate chain
        structures concurrently with the gossip drain; routing through
        the loop restores the reference's single-threaded semantics.
        Library users without a node fall back to a private loop."""
        loop = getattr(self.chain, "loop", None)
        if loop is not None and loop.is_running():
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not loop:
                return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=120)
        return asyncio.run(coro)

    # -- events namespace (SSE) -----------------------------------------------

    def stream_events(self, topics: list[str]) -> "EventStream":
        import queue as _queue

        for t in topics:
            if t not in EVENT_TOPICS:
                raise ApiError(400, f"unknown event topic {t!r}")
        if not topics:
            topics = list(EVENT_TOPICS)
        q: "_queue.Queue" = _queue.Queue(maxsize=1024)
        chain = self.chain

        def _put(event_type: str, payload: dict) -> None:
            try:
                q.put_nowait((event_type, payload))
            except _queue.Full:
                pass  # slow consumer: drop rather than stall the chain

        handlers = []
        if "block" in topics:

            def on_block(root, signed):
                _put(
                    "block",
                    {
                        "slot": str(int(signed.message.slot)),
                        "block": "0x" + bytes(root).hex(),
                        "execution_optimistic": False,
                    },
                )

            chain.on("block", on_block)
            handlers.append(("block", on_block))
        if "head" in topics:
            # baseline from the CURRENT HEAD's slot, not the wall clock: a
            # syncing node's clock epoch is far ahead of its head epoch and
            # would fire a spurious epoch_transition on the first event
            head_node = chain.fork_choice.proto_array.get_block(chain.fork_choice.head)
            prev_epoch = [(head_node.slot if head_node else 0) // chain.p.SLOTS_PER_EPOCH]

            def on_head(head_hex):
                node = chain.fork_choice.proto_array.get_block(head_hex)
                epoch = (node.slot if node else 0) // chain.p.SLOTS_PER_EPOCH
                transition = epoch != prev_epoch[0]
                prev_epoch[0] = epoch
                _put(
                    "head",
                    {
                        "slot": str(node.slot if node else 0),
                        "block": head_hex,
                        "state": node.state_root if node else "0x" + "00" * 32,
                        "epoch_transition": transition,
                        "execution_optimistic": False,
                    },
                )

            chain.on("head", on_head)
            handlers.append(("head", on_head))
        if "finalized_checkpoint" in topics:

            def on_finalized(cp):
                node = chain.fork_choice.proto_array.get_block("0x" + bytes(cp.root).hex())
                _put(
                    "finalized_checkpoint",
                    {
                        "block": "0x" + bytes(cp.root).hex(),
                        "state": node.state_root if node else "0x" + "00" * 32,
                        "epoch": str(int(cp.epoch)),
                        "execution_optimistic": False,
                    },
                )

            chain.on("finalized", on_finalized)
            handlers.append(("finalized", on_finalized))

        def unsubscribe():
            for event, fn in handlers:
                chain.off(event, fn)

        return EventStream(q, unsubscribe)



    # -- state resolution -----------------------------------------------------

    def _state_at(self, state_id: str):
        """Beacon API stateId: head | finalized | <slot> | 0x<state root>."""
        chain = self.chain
        if state_id == "head":
            return chain.get_head_state()
        if state_id == "genesis":
            raise ApiError(501, "genesis state queries need the archive")
        if state_id == "finalized":
            st = chain.get_finalized_state()
            if st is None:
                raise ApiError(404, "finalized state not found")
            return st
        if state_id.startswith("0x"):
            # hex stateId is a STATE root: fork choice nodes record their
            # block's state_root, so resolve through them to the block root
            for node in chain.fork_choice.proto_array.nodes:
                if node.state_root == state_id:
                    return chain.get_state_by_block_root(bytes.fromhex(node.block_root[2:]))
            raise ApiError(404, f"state {state_id} not found")
        if state_id.isdigit():
            return chain.get_state_by_block_root(self._block_root(state_id))
        raise ApiError(400, f"unsupported state id {state_id}")

    # -- beacon namespace -----------------------------------------------------

    def get_genesis(self) -> dict:
        st = self.chain.get_head_state()
        # fork version from the chain config when bound: the head state's
        # previous_version stops being the genesis version after any fork
        if self.chain.cfg is not None:
            version = self.chain.cfg.GENESIS_FORK_VERSION
        else:
            version = bytes(st.fork.previous_version)
        return {
            "data": {
                "genesis_time": str(st.genesis_time),
                "genesis_validators_root": "0x" + bytes(st.genesis_validators_root).hex(),
                "genesis_fork_version": "0x" + version.hex(),
            }
        }

    def get_block_header(self, block_id: str) -> dict:
        root = self._block_root(block_id)
        signed = self.chain.get_block_by_root(root)
        if signed is None:
            raise ApiError(404, f"block {block_id} not found")
        header = self.t.BeaconBlockHeader.default()
        msg = signed.message
        header.slot = msg.slot
        header.proposer_index = msg.proposer_index
        header.parent_root = bytes(msg.parent_root)
        header.state_root = bytes(msg.state_root)
        header.body_root = self.t.phase0.BeaconBlockBody.hash_tree_root(msg.body)
        return {
            "data": {
                "root": "0x" + root.hex(),
                "canonical": True,
                "header": {
                    "message": to_json(self.t.BeaconBlockHeader, header),
                    "signature": "0x" + bytes(signed.signature).hex(),
                },
            }
        }

    def _block_root(self, block_id: str) -> bytes:
        if block_id == "head":
            return self.chain.head_root
        if block_id.startswith("0x"):
            return bytes.fromhex(block_id[2:])
        # numeric slot: resolve through fork choice chain from head
        slot = int(block_id)
        node = self.chain.fork_choice.proto_array.get_block(self.chain.fork_choice.head)
        while node is not None and node.slot > slot:
            parent = node.parent
            node = self.chain.fork_choice.proto_array.nodes[parent] if parent is not None else None
        if node is None or node.slot != slot:
            raise ApiError(404, f"no canonical block at slot {slot}")
        return bytes.fromhex(node.block_root[2:])

    def get_block_v2(self, block_id: str) -> dict:
        from lodestar_tpu.state_transition.block import fork_of

        root = self._block_root(block_id)
        signed = self.chain.get_block_by_root(root)
        if signed is None:
            raise ApiError(404, f"block {block_id} not found")
        fork = fork_of(signed.message)
        return {
            "version": fork,
            "execution_optimistic": False,
            "data": to_json(getattr(self.t, fork).SignedBeaconBlock, signed),
        }

    def publish_block(self, body: dict) -> dict:
        # decode with the fork active at the block's slot (the standard
        # API sends the version in a header the stdlib router doesn't
        # surface; the slot determines it just as well)
        try:
            slot = int(body["message"]["slot"])
        except (KeyError, TypeError, ValueError) as e:
            raise ApiError(400, f"malformed block body: {e}") from e
        fork = self.chain.fork_name_at_slot(slot)
        try:
            signed = from_json(getattr(self.t, fork).SignedBeaconBlock, body)
        except (KeyError, TypeError, ValueError) as e:
            raise ApiError(400, f"cannot decode {fork} block: {e}") from e
        from lodestar_tpu.chain.chain import BlockError

        try:
            # the node's OWN proposal published over REST is the most
            # deadline-critical block it ever imports — it verifies at
            # gossip-block priority, not the API bulk class
            self._run_async(
                self.chain.process_block(signed, priority=PriorityClass.GOSSIP_BLOCK)
            )
        except BlockError as e:
            raise ApiError(400, str(e)) from e
        return {}

    def get_state_finality_checkpoints(self, state_id: str) -> dict:
        st = self._state_at(state_id)
        return {
            "data": {
                "previous_justified": to_json(self.t.Checkpoint, st.previous_justified_checkpoint),
                "current_justified": to_json(self.t.Checkpoint, st.current_justified_checkpoint),
                "finalized": to_json(self.t.Checkpoint, st.finalized_checkpoint),
            }
        }

    def get_state_fork(self, state_id: str) -> dict:
        st = self._state_at(state_id)
        return {"data": to_json(self.t.Fork, st.fork)}

    def get_state_validators(self, state_id: str) -> dict:
        st = self._state_at(state_id)
        epoch = compute_epoch_at_slot(st.slot, self.p)
        out = []
        for i, v in enumerate(st.validators):
            status = _validator_status(v, epoch)
            out.append(
                {
                    "index": str(i),
                    "balance": str(st.balances[i]),
                    "status": status,
                    "validator": to_json(self.t.Validator, v),
                }
            )
        return {"data": out}

    def submit_pool_attestations(self, body: list) -> dict:
        from lodestar_tpu.chain.validation import GossipValidationError, validate_gossip_attestation

        from lodestar_tpu.network.processor import import_verified_attestation

        errors = []

        async def run_batch():
            for i, att_json in enumerate(body):
                att = from_json(self.t.Attestation, att_json)
                try:
                    res = validate_gossip_attestation(self.chain, att)
                except GossipValidationError as e:
                    errors.append({"index": i, "message": str(e)})
                    continue
                if not await self.chain.bls.verify_signature_sets(res.signature_sets, _API_VERIFY_OPTS):
                    errors.append({"index": i, "message": "invalid attestation signature"})
                    continue
                import_verified_attestation(self.chain, res, att)

        self._run_async(run_batch())
        if errors:
            raise ApiError(400, f"some attestations failed: {errors}")
        return {}

    # -- validator namespace --------------------------------------------------

    def get_proposer_duties(self, epoch: int) -> dict:
        from lodestar_tpu.chain.produce_block import dial_to_slot

        st = self.chain.get_head_state()
        target_slot = epoch * self.p.SLOTS_PER_EPOCH
        work, ctx = dial_to_slot(st, max(target_slot, st.slot), self.p, self.chain.cfg)
        if ctx.current_epoch != epoch:
            raise ApiError(400, f"cannot compute duties for epoch {epoch}")
        duties = []
        for i, proposer in enumerate(ctx.proposers):
            duties.append(
                {
                    "pubkey": "0x" + bytes(work.validators[proposer].pubkey).hex(),
                    "validator_index": str(proposer),
                    "slot": str(target_slot + i),
                }
            )
        return {"data": duties, "dependent_root": self.chain.fork_choice.head}

    def get_attester_duties(self, epoch: int, indices: list[int]) -> dict:
        from lodestar_tpu.chain.produce_block import dial_to_slot

        st = self.chain.get_head_state()
        work, ctx = dial_to_slot(
            st, max(epoch * self.p.SLOTS_PER_EPOCH, st.slot), self.p, self.chain.cfg
        )
        want = set(indices)
        duties = []
        sh = ctx._shuffling_at(epoch)
        for slot_i in range(self.p.SLOTS_PER_EPOCH):
            for c_idx, committee in enumerate(sh.committees[slot_i]):
                for pos, vi in enumerate(committee):
                    if int(vi) in want:
                        duties.append(
                            {
                                "pubkey": "0x" + bytes(work.validators[int(vi)].pubkey).hex(),
                                "validator_index": str(int(vi)),
                                "committee_index": str(c_idx),
                                "committee_length": str(len(committee)),
                                "committees_at_slot": str(sh.committees_per_slot),
                                "validator_committee_index": str(pos),
                                "slot": str(epoch * self.p.SLOTS_PER_EPOCH + slot_i),
                            }
                        )
        return {"data": duties, "dependent_root": self.chain.fork_choice.head}

    def produce_block_v2(self, slot: int, randao_reveal: str, graffiti: str = "") -> dict:
        from lodestar_tpu.chain.produce_block import produce_block

        block = produce_block(
            self.chain,
            slot=slot,
            randao_reveal=bytes.fromhex(randao_reveal[2:]),
            graffiti=bytes.fromhex(graffiti[2:]) if graffiti.startswith("0x") else graffiti.encode(),
        )
        from lodestar_tpu.state_transition.block import fork_of

        fork = fork_of(block)
        return {"version": fork, "data": to_json(getattr(self.t, fork).BeaconBlock, block)}

    def produce_attestation_data(self, slot: int, committee_index: int) -> dict:
        from lodestar_tpu.chain.produce_block import make_attestation_data

        data = make_attestation_data(self.chain, slot, committee_index)
        return {"data": to_json(self.t.AttestationData, data)}

    def get_validator_liveness(self, epoch: int, indices: list[int]) -> dict:
        """POST /eth/v1/validator/liveness/{epoch}: whether each index
        showed on-chain activity in the epoch (doppelganger data source;
        the reference reads its validator monitor — here the seen-attester
        cache carries the same signal)."""
        chain = self.chain

        def is_live(i: int) -> bool:
            return (
                chain.seen_attesters.is_known(int(epoch), i)
                or chain.seen_block_attesters.is_known(int(epoch), i)
                or chain.seen_aggregators.is_known(int(epoch), i)
                or chain.seen_block_proposers.is_known(int(epoch), i)
            )

        return {
            "data": [
                {"index": str(int(i)), "is_live": bool(is_live(int(i)))} for i in indices
            ]
        }

    # -- node namespace -------------------------------------------------------

    def get_health(self) -> int:
        return 200

    def get_version(self) -> dict:
        return {"data": {"version": VERSION}}

    def get_syncing_status(self) -> dict:
        head = self.chain.fork_choice.proto_array.get_block(self.chain.fork_choice.head)
        head_slot = head.slot if head else 0
        current = self.chain.fork_choice.current_slot
        return {
            "data": {
                "head_slot": str(head_slot),
                "sync_distance": str(max(0, current - head_slot)),
                "is_syncing": current - head_slot > 3,
                "is_optimistic": False,
            }
        }

    # -- debug / config -------------------------------------------------------

    def get_debug_state_v2(self, state_id: str) -> dict:
        from lodestar_tpu.state_transition.block import fork_of

        st = self._state_at(state_id)
        return {"version": fork_of(st), "data": to_json(st.type, st)}

    def get_spec(self) -> dict:
        """Preset constants PLUS the chain config's fork schedule and
        timing — validator clients derive their signing domains from
        this (reference config/spec includes *_FORK_VERSION/_EPOCH)."""
        p = self.p
        fields = {
            name: str(getattr(p, name))
            for name in type(p).__dataclass_fields__  # type: ignore[attr-defined]
        }
        cfg = self.chain.cfg
        if cfg is not None:
            for name in type(cfg).__dataclass_fields__:  # type: ignore[attr-defined]
                value = getattr(cfg, name)
                fields[name] = "0x" + value.hex() if isinstance(value, bytes) else str(value)
        return {"data": fields}

    def get_fork_schedule(self) -> dict:
        from lodestar_tpu.config import FORK_ORDER

        cfg = self.chain.cfg
        if cfg is None:
            raise ApiError(501, "no chain config bound")
        out = []
        prev = cfg.GENESIS_FORK_VERSION
        for fork in FORK_ORDER:
            epoch = cfg.fork_epoch(fork)
            version = cfg.fork_version(fork)
            out.append(
                {
                    "previous_version": "0x" + prev.hex(),
                    "current_version": "0x" + version.hex(),
                    "epoch": str(epoch),
                }
            )
            prev = version
        return {"data": out}

    def get_deposit_contract(self) -> dict:
        cfg = self.chain.cfg
        chain_id = getattr(cfg, "DEPOSIT_CHAIN_ID", 0) if cfg else 0
        address = getattr(cfg, "DEPOSIT_CONTRACT_ADDRESS", b"\x00" * 20) if cfg else b"\x00" * 20
        if isinstance(address, bytes):
            address = "0x" + address.hex()
        return {"data": {"chain_id": str(chain_id), "address": address}}

    # -- beacon/state extras ---------------------------------------------------

    def get_state_root(self, state_id: str) -> dict:
        st = self._state_at(state_id)
        return {"data": {"root": "0x" + st.type.hash_tree_root(st).hex()}}

    def get_epoch_committees(self, state_id: str, query: dict) -> dict:
        from lodestar_tpu.state_transition import EpochContext

        st = self._state_at(state_id)
        ctx = EpochContext(st, self.p)
        epoch = int(query.get("epoch", ctx.current_epoch))
        if epoch not in (ctx.current_epoch, ctx.previous_epoch, ctx.current_epoch + 1):
            raise ApiError(400, f"epoch {epoch} out of shuffling range")
        want_index = query.get("index")
        want_slot = query.get("slot")
        try:
            sh = ctx._shuffling_at(epoch)
        except ValueError as e:
            raise ApiError(400, f"no shuffling cached for epoch {epoch}: {e}") from e
        out = []
        for slot_i in range(self.p.SLOTS_PER_EPOCH):
            slot = epoch * self.p.SLOTS_PER_EPOCH + slot_i
            if want_slot is not None and int(want_slot) != slot:
                continue
            for c_idx, committee in enumerate(sh.committees[slot_i]):
                if want_index is not None and int(want_index) != c_idx:
                    continue
                out.append(
                    {
                        "index": str(c_idx),
                        "slot": str(slot),
                        "validators": [str(int(v)) for v in committee],
                    }
                )
        return {"data": out, "execution_optimistic": False}

    def get_epoch_sync_committees(self, state_id: str, query: dict) -> dict:
        from lodestar_tpu.state_transition import EpochContext

        st = self._state_at(state_id)
        if not hasattr(st, "current_sync_committee"):
            raise ApiError(400, "state has no sync committees (pre-altair)")
        from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT

        idx_map = EpochContext(st, self.p).pubkey_to_index(st)
        indices = []
        for pk in st.current_sync_committee.pubkeys:
            vi = idx_map.get(bytes(pk))
            if vi is None:
                raise ApiError(500, "sync committee pubkey not in validator set")
            indices.append(str(vi))
        sub = self.p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        return {
            "data": {
                "validators": indices,
                "validator_aggregates": [
                    indices[i : i + sub] for i in range(0, len(indices), sub)
                ],
            },
            "execution_optimistic": False,
        }

    def get_state_validator(self, state_id: str, validator_id: str) -> dict:
        st = self._state_at(state_id)
        epoch = compute_epoch_at_slot(st.slot, self.p)
        if validator_id.startswith("0x"):
            pk = bytes.fromhex(validator_id[2:])
            index = next(
                (i for i, v in enumerate(st.validators) if bytes(v.pubkey) == pk), None
            )
        elif validator_id.isdigit():
            index = int(validator_id)
            if index >= len(st.validators):
                index = None
        else:
            raise ApiError(400, f"bad validator id {validator_id!r}")
        if index is None:
            raise ApiError(404, f"validator {validator_id} not found")
        v = st.validators[index]
        return {
            "data": {
                "index": str(index),
                "balance": str(st.balances[index]),
                "status": _validator_status(v, epoch),
                "validator": to_json(self.t.Validator, v),
            },
            "execution_optimistic": False,
        }

    def get_state_validator_balances(self, state_id: str, query: dict) -> dict:
        st = self._state_at(state_id)
        want = query.get("id")
        if want:
            ids = []
            for token in want.split(","):
                if token.startswith("0x"):
                    pk = bytes.fromhex(token[2:])
                    idx = next(
                        (i for i, v in enumerate(st.validators) if bytes(v.pubkey) == pk),
                        None,
                    )
                    if idx is not None:
                        ids.append(idx)
                elif token.isdigit():
                    ids.append(int(token))
                else:
                    raise ApiError(400, f"bad validator id {token!r}")
            ids = sorted(set(ids))
        else:
            ids = range(len(st.validators))
        return {
            "data": [
                {"index": str(i), "balance": str(st.balances[i])}
                for i in ids
                if i < len(st.validators)
            ]
        }

    # -- beacon/block extras ---------------------------------------------------

    def get_block_root(self, block_id: str) -> dict:
        return {
            "data": {"root": "0x" + self._block_root(block_id).hex()},
            "execution_optimistic": False,
        }

    def get_block_attestations(self, block_id: str) -> dict:
        signed = self.chain.get_block_by_root(self._block_root(block_id))
        if signed is None:
            raise ApiError(404, f"block {block_id} not found")
        return {
            "data": [
                to_json(self.t.Attestation, a) for a in signed.message.body.attestations
            ],
            "execution_optimistic": False,
        }

    def get_block_headers(self, query: dict) -> dict:
        """GET /eth/v1/beacon/headers?slot=&parent_root= — canonical chain
        walk filtered by the query (reference block.ts getBlockHeaders)."""
        slot = query.get("slot")
        parent_root = query.get("parent_root")
        fc = self.chain.fork_choice.proto_array
        node = fc.get_block(self.chain.fork_choice.head)
        out = []
        while node is not None:
            keep = True
            if slot is not None and node.slot != int(slot):
                keep = False
            if parent_root is not None and node.parent_root != parent_root:
                keep = False
            if keep:
                try:
                    out.append(self.get_block_header(node.block_root)["data"])
                except ApiError:
                    pass  # anchor node: no stored block behind the root
            if slot is not None and node.slot < int(slot):
                break
            node = fc.nodes[node.parent] if node.parent is not None else None
        return {"data": out, "execution_optimistic": False}

    # -- beacon/pool full surface ----------------------------------------------

    def get_pool_attestations(self) -> dict:
        pool = self.chain.attestation_pool
        out = []
        for slot, by_root in pool._by_slot.items():
            for root in by_root:
                agg = pool.get_aggregate(slot, root)
                if agg is not None:
                    out.append(to_json(self.t.Attestation, agg))
        return {"data": out}

    def get_pool_attester_slashings(self) -> dict:
        return {
            "data": [
                to_json(self.t.AttesterSlashing, s)
                for s in self.chain.op_pool._attester_slashings.values()
            ]
        }

    def get_pool_proposer_slashings(self) -> dict:
        return {
            "data": [
                to_json(self.t.ProposerSlashing, s)
                for s in self.chain.op_pool._proposer_slashings.values()
            ]
        }

    def get_pool_voluntary_exits(self) -> dict:
        return {
            "data": [
                to_json(self.t.SignedVoluntaryExit, e)
                for e in self.chain.op_pool._exits.values()
            ]
        }

    def get_pool_bls_changes(self) -> dict:
        return {
            "data": [
                to_json(self.t.SignedBLSToExecutionChange, c)
                for c in self.chain.op_pool._bls_changes.values()
            ]
        }

    def _submit_pool_op(self, body, type_name: str, apply_fn, insert) -> dict:
        """Decode, validate by applying the operation (with signature
        verification) to a COPY of the head state — the reference's pool
        routes run the same state-transition checks — then insert."""
        t = getattr(self.t, type_name, None)
        if t is None:
            raise ApiError(400, f"{type_name} not supported by the active fork set")
        try:
            op = from_json(t, body)
        except (KeyError, TypeError, ValueError) as e:
            raise ApiError(400, f"malformed {type_name}: {e}") from e
        from lodestar_tpu.state_transition import EpochContext

        st = self.chain.get_head_state().copy()
        try:
            apply_fn(st, op, EpochContext(st, self.p))
        except Exception as e:
            raise ApiError(400, f"invalid {type_name}: {e}") from e
        insert(op)
        return {}

    def submit_pool_attester_slashing(self, body) -> dict:
        from lodestar_tpu.state_transition.block import process_attester_slashing

        def insert(op):
            root = self.t.AttesterSlashing.hash_tree_root(op)
            self.chain.op_pool.insert_attester_slashing(op, root)

        return self._submit_pool_op(
            body, "AttesterSlashing",
            lambda s, op, ctx: process_attester_slashing(
                s, op, ctx, verify_signatures=True, cfg=self.chain.cfg
            ),
            insert,
        )

    def submit_pool_proposer_slashing(self, body) -> dict:
        from lodestar_tpu.state_transition.block import process_proposer_slashing

        return self._submit_pool_op(
            body, "ProposerSlashing",
            lambda s, op, ctx: process_proposer_slashing(
                s, op, ctx, verify_signatures=True, cfg=self.chain.cfg
            ),
            self.chain.op_pool.insert_proposer_slashing,
        )

    def submit_pool_voluntary_exit(self, body) -> dict:
        from lodestar_tpu.state_transition.block import process_voluntary_exit

        return self._submit_pool_op(
            body, "SignedVoluntaryExit",
            lambda s, op, ctx: process_voluntary_exit(
                s, op, ctx, verify_signatures=True, cfg=self.chain.cfg
            ),
            self.chain.op_pool.insert_voluntary_exit,
        )

    def submit_pool_bls_change(self, body) -> dict:
        from lodestar_tpu.state_transition.capella import process_bls_to_execution_change

        return self._submit_pool_op(
            body, "SignedBLSToExecutionChange",
            lambda s, op, ctx: process_bls_to_execution_change(
                s, op, ctx, cfg=self.chain.cfg
            ),
            self.chain.op_pool.insert_bls_to_execution_change,
        )

    def submit_pool_sync_committees(self, body: list) -> dict:
        """POST /eth/v1/beacon/pool/sync_committees (validator client
        submits SyncCommitteeMessages). Subnet is derived from the
        validator's subcommittee membership."""
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_sync_committee_message,
        )
        from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT

        errors = []

        async def run():
            for i, msg_json in enumerate(body):
                try:
                    msg = from_json(self.t.SyncCommitteeMessage, msg_json)
                except (KeyError, TypeError, ValueError) as e:
                    errors.append({"index": i, "message": f"malformed message: {e}"})
                    continue
                # a validator can hold seats in SEVERAL subcommittees
                # (sampled with replacement): record every subnet it
                # belongs to; duplicate submissions dedupe via the
                # seen-cache and are not errors
                accepted = seen_dup = False
                last_err = "validator not in any subcommittee"
                for subnet in range(SYNC_COMMITTEE_SUBNET_COUNT):
                    try:
                        res = validate_sync_committee_message(self.chain, msg, subnet)
                    except GossipValidationError as e:
                        if "already seen" in str(e):
                            seen_dup = True
                        else:
                            last_err = str(e)
                        continue
                    if not await self.chain.bls.verify_signature_sets(res.signature_sets, _API_VERIFY_OPTS):
                        last_err = "invalid signature"
                        break
                    res.register_seen()
                    for pos in res.indices_in_subcommittee:
                        self.chain.sync_committee_message_pool.add(subnet, msg, pos)
                    accepted = True
                if not accepted and not seen_dup:
                    errors.append({"index": i, "message": last_err})

        self._run_async(run())
        if errors:
            raise ApiError(400, f"some messages failed: {errors}")
        return {}

    # -- node namespace extras -------------------------------------------------

    def _network(self):
        return getattr(self.chain, "network", None)

    def get_node_identity(self) -> dict:
        net = self._network()
        peer_id = net.peer_id if net else "unknown"
        addrs = (
            [f"/ip4/127.0.0.1/tcp/{net.host.listen_port}/p2p/{peer_id}"] if net else []
        )
        return {
            "data": {
                "peer_id": peer_id,
                "enr": "",
                "p2p_addresses": addrs,
                "discovery_addresses": [],
                "metadata": {"seq_number": "0", "attnets": "0x" + "00" * 8},
            }
        }

    def get_node_peers(self, query: dict) -> dict:
        net = self._network()
        peers = []
        if net is not None:
            for pid, conn in net.host.connections.items():
                peers.append(
                    {
                        "peer_id": pid,
                        "enr": "",
                        "last_seen_p2p_address": f"/ip4/{conn.addr[0]}/tcp/{conn.addr[1]}"
                        if conn.addr
                        else "",
                        "state": "connected",
                        "direction": "outbound" if conn.mux._initiator else "inbound",
                    }
                )
        return {"data": peers, "meta": {"count": len(peers)}}

    def get_node_peer(self, peer_id: str) -> dict:
        peers = self.get_node_peers({})["data"]
        for p in peers:
            if p["peer_id"] == peer_id:
                return {"data": p}
        raise ApiError(404, f"peer {peer_id} not known")

    def get_node_peer_count(self) -> dict:
        net = self._network()
        n = len(net.host.connections) if net else 0
        return {
            "data": {
                "disconnected": "0",
                "connecting": "0",
                "connected": str(n),
                "disconnecting": "0",
            }
        }

    # -- light-client REST (reference routes/lightclient.ts) -------------------

    def _lc(self):
        server = self.chain.light_client_server
        if server is None:
            raise ApiError(404, "light-client server not enabled")
        return server

    def get_lc_bootstrap(self, block_root: str) -> dict:
        bootstrap = self._lc().get_bootstrap(bytes.fromhex(block_root[2:]))
        if bootstrap is None:
            raise ApiError(404, "bootstrap unavailable for that root")
        return {"data": to_json(self.t.LightClientBootstrap, bootstrap)}

    def get_lc_updates(self, query: dict) -> dict:
        start = int(query.get("start_period", 0))
        count = min(int(query.get("count", 1)), 128)
        updates = self._lc().get_updates(start, count)
        return {
            "data": [
                {"version": "altair", "data": to_json(self.t.LightClientUpdate, u)}
                for u in updates
            ]
        }

    def get_lc_optimistic_update(self) -> dict:
        u = self._lc().get_optimistic_update()
        if u is None:
            raise ApiError(404, "no optimistic update")
        return {"version": "altair", "data": to_json(self.t.LightClientOptimisticUpdate, u)}

    def get_lc_finality_update(self) -> dict:
        u = self._lc().get_finality_update()
        if u is None:
            raise ApiError(404, "no finality update")
        return {"version": "altair", "data": to_json(self.t.LightClientFinalityUpdate, u)}

    # -- proof namespace (reference routes/proof.ts, v0) -----------------------

    def get_state_proof(self, state_id: str, query: dict) -> dict:
        """Single-leaf merkle proofs by generalized index
        (?gindex=N[,N...]), from the state's merkle tree."""
        from lodestar_tpu.ssz.tree import merkle_proof

        st = self._state_at(state_id)
        gindices = [int(g) for g in str(query.get("gindex", "")).split(",") if g]
        if not gindices:
            raise ApiError(400, "gindex query parameter required")
        proofs = []
        for g in gindices:
            leaf, branch = merkle_proof(st.type, st, g)
            proofs.append(
                {
                    "gindex": str(g),
                    "leaf": "0x" + leaf.hex(),
                    "branch": ["0x" + b.hex() for b in branch],
                }
            )
        return {"data": {"root": "0x" + st.type.hash_tree_root(st).hex(), "proofs": proofs}}

    # -- validator namespace extras --------------------------------------------

    def get_sync_committee_duties(self, epoch: int, indices: list[int]) -> dict:
        """POST /eth/v1/validator/duties/sync/{epoch} — one entry per
        validator carrying ALL its committee positions. An epoch in the
        NEXT sync-committee period serves from next_sync_committee (the
        lookahead clients use to subscribe subnets before the boundary)."""
        from lodestar_tpu.state_transition import EpochContext

        st = self.chain.get_head_state()
        if not hasattr(st, "current_sync_committee"):
            return {"data": []}
        period_epochs = self.p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        head_period = (int(st.slot) // self.p.SLOTS_PER_EPOCH) // period_epochs
        req_period = int(epoch) // period_epochs
        if req_period == head_period:
            committee = st.current_sync_committee
        elif req_period == head_period + 1:
            committee = st.next_sync_committee
        else:
            raise ApiError(400, f"epoch {epoch} outside the known committee periods")
        want = set(int(i) for i in indices)
        positions: dict[int, list[int]] = {}
        pk_of: dict[int, bytes] = {}
        idx_map = EpochContext(st, self.p).pubkey_to_index(st)
        for pos, pk in enumerate(bytes(p) for p in committee.pubkeys):
            vi = idx_map.get(pk)
            if vi is not None and vi in want:
                positions.setdefault(vi, []).append(pos)
                pk_of[vi] = pk
        return {
            "data": [
                {
                    "pubkey": "0x" + pk_of[vi].hex(),
                    "validator_index": str(vi),
                    "validator_sync_committee_indices": [str(p) for p in poss],
                }
                for vi, poss in sorted(positions.items())
            ]
        }

    def get_aggregated_attestation(self, query: dict) -> dict:
        slot = int(query["slot"])
        root = bytes.fromhex(str(query["attestation_data_root"])[2:])
        agg = self.chain.attestation_pool.get_aggregate(slot, root)
        if agg is None:
            raise ApiError(404, "no aggregate for that attestation data")
        return {"data": to_json(self.t.Attestation, agg)}

    def publish_aggregate_and_proofs(self, body: list) -> dict:
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_gossip_aggregate_and_proof,
        )
        from lodestar_tpu.network.processor import import_verified_attestation

        errors = []

        async def run():
            for i, item in enumerate(body):
                agg = from_json(self.t.SignedAggregateAndProof, item)
                try:
                    res = validate_gossip_aggregate_and_proof(self.chain, agg)
                except GossipValidationError as e:
                    errors.append({"index": i, "message": str(e)})
                    continue
                if not await self.chain.bls.verify_signature_sets(res.signature_sets, _API_VERIFY_OPTS):
                    errors.append({"index": i, "message": "invalid signatures"})
                    continue
                import_verified_attestation(
                    self.chain, res, agg.message.aggregate, aggregated=True
                )

        self._run_async(run())
        if errors:
            raise ApiError(400, f"some aggregates failed: {errors}")
        return {}

    def produce_sync_committee_contribution(self, query: dict) -> dict:
        slot = int(query["slot"])
        subnet = int(query["subcommittee_index"])
        root = bytes.fromhex(str(query["beacon_block_root"])[2:])
        contribution = self.chain.sync_committee_message_pool.get_contribution(
            subnet, slot, root
        )
        if contribution is None:
            raise ApiError(404, "no contribution available")
        return {"data": to_json(self.t.SyncCommitteeContribution, contribution)}

    def publish_contribution_and_proofs(self, body: list) -> dict:
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_sync_committee_contribution,
        )

        errors = []

        async def run():
            for i, item in enumerate(body):
                signed = from_json(self.t.SignedContributionAndProof, item)
                try:
                    res = validate_sync_committee_contribution(self.chain, signed)
                except GossipValidationError as e:
                    errors.append({"index": i, "message": str(e)})
                    continue
                if not await self.chain.bls.verify_signature_sets(res.signature_sets, _API_VERIFY_OPTS):
                    errors.append({"index": i, "message": "invalid signatures"})
                    continue
                res.register_seen()
                self.chain.sync_contribution_pool.add(signed.message)

        self._run_async(run())
        if errors:
            raise ApiError(400, f"some contributions failed: {errors}")
        return {}

    def prepare_beacon_committee_subnet(self, body: list) -> dict:
        subnets = getattr(self.chain, "attnets", None)
        if subnets is not None:
            for sub in body:
                try:
                    subnets.subscribe_committee_subnet(
                        int(sub["committee_index"]),
                        int(sub["slot"]),
                        bool(sub.get("is_aggregator", False)),
                    )
                except (AttributeError, KeyError, TypeError):
                    pass
        return {}

    def prepare_sync_committee_subnets(self, body: list) -> dict:
        return {}

    def prepare_beacon_proposer(self, body: list) -> dict:
        store = getattr(self.chain, "proposer_preparation", None)
        if store is None:
            store = self.chain.proposer_preparation = {}
        for item in body:
            store[int(item["validator_index"])] = item["fee_recipient"]
        return {}

    def register_validator(self, body: list) -> dict:
        store = getattr(self.chain, "validator_registrations", None)
        if store is None:
            store = self.chain.validator_registrations = {}
        for item in body:
            pk = item.get("message", {}).get("pubkey")
            if pk:
                store[pk] = item
        return {}

    # -- debug extras ----------------------------------------------------------

    def get_debug_chain_heads(self) -> dict:
        fc = self.chain.fork_choice.proto_array
        heads = []
        children = {n.parent for n in fc.nodes if n.parent is not None}
        for i, node in enumerate(fc.nodes):
            if i not in children:
                heads.append(
                    {"root": node.block_root, "slot": str(node.slot),
                     "execution_optimistic": False}
                )
        return {"data": heads}

    def get_slot_traces(self, slot: str, fmt: str = "json") -> dict:
        """Completed pipeline traces for a slot from the tracer's ring
        buffer (`lodestar_tpu/tracing`). fmt="chrome" returns one Chrome
        `trace_event` document — UNWRAPPED (no {"data"} envelope), so a
        curl'd response loads in chrome://tracing/Perfetto as-is."""
        from lodestar_tpu import tracing

        traces = tracing.get_tracer().traces_for_slot(int(slot))
        if fmt == "chrome":
            from lodestar_tpu.tracing.export import to_chrome_trace

            return to_chrome_trace(traces)
        return {"data": [t.to_dict() for t in traces]}

    def get_recent_traces(self, count: int = 16) -> dict:
        """The newest completed traces in the ring, oldest first."""
        from lodestar_tpu import tracing

        traces = tracing.get_tracer().recent_traces(count)
        return {"data": [t.to_dict() for t in traces]}

    def get_debug_launches(self, count: int = 64, program: str | None = None) -> dict:
        """The device launch ledger (`lodestar_tpu/telemetry.py`): the
        trailing `count` dispatches at the counted launch seams, plus
        the cumulative totals — a slow slot's launches by name without
        waiting for a Prometheus scrape. `program` narrows the ledger
        view to one dispatch seam (chip-run triage of a single program);
        an unknown name is a 400, not an empty list — a typo'd filter
        must not read as 'that program never launched'."""
        from lodestar_tpu import telemetry

        entries = telemetry.launch_ledger(max(0, count))
        if program is not None:
            known = telemetry.known_programs()
            if program not in known:
                raise ApiError(
                    400,
                    f"unknown program {program!r}; launched so far: "
                    f"{sorted(known) or '(none)'}",
                )
            entries = [e for e in entries if e["program"] == program]
        return {
            "data": {
                "mode_active": telemetry.launch_telemetry_active(),
                "totals": telemetry.launch_totals(),
                "launches": entries,
            }
        }

    def get_debug_slo(self) -> dict:
        """The slot-deadline SLO view (`lodestar_tpu/slo`): per-class
        wait-budget decomposition (buffer/queue/stage/launch quantiles
        whose legs partition the end-to-end span), SLI counters, and
        the live per-class slack snapshot — the machine-readable
        wait-budget profile the batch former consumes
        (`tools/wait_budget_profile.py`)."""
        from lodestar_tpu import slo

        return {"data": slo.debug_view()}

    def get_fork_choice_nodes(self) -> dict:
        fc = self.chain.fork_choice.proto_array
        return {
            "data": [
                {
                    "slot": str(n.slot),
                    "block_root": n.block_root,
                    "parent_root": fc.nodes[n.parent].block_root
                    if n.parent is not None
                    else None,
                    "justified_epoch": str(n.justified_epoch),
                    "finalized_epoch": str(n.finalized_epoch),
                    "weight": str(getattr(n, "weight", 0)),
                    "best_child": None,
                    "best_descendant": None,
                }
                for n in fc.nodes
            ]
        }


def _validator_status(v, epoch: int) -> str:
    from lodestar_tpu.params import FAR_FUTURE_EPOCH

    if v.activation_epoch > epoch:
        return "pending_queued" if v.activation_eligibility_epoch != FAR_FUTURE_EPOCH else "pending_initialized"
    if epoch < v.exit_epoch:
        return "active_slashed" if v.slashed else "active_ongoing"
    if epoch < v.withdrawable_epoch:
        return "exited_slashed" if v.slashed else "exited_unslashed"
    return "withdrawal_possible"


# --- events namespace (SSE) ---------------------------------------------------
# Reference `beacon-node/src/api/impl/events/index.ts`: subscribe chain
# emitter topics, forward as Server-Sent Events. The REST server streams
# an EventStream return value instead of JSON-encoding it.

EVENT_TOPICS = ("head", "block", "finalized_checkpoint")


class EventStream:
    """Thread-safe queue of (event_type, payload_dict) fed by chain
    events; the HTTP handler drains it as an SSE body. `close()`
    detaches the chain subscriptions."""

    def __init__(self, queue, unsubscribe):
        self.queue = queue
        self._unsubscribe = unsubscribe

    def close(self) -> None:
        self._unsubscribe()

