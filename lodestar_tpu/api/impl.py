"""Beacon API implementation over BeaconChain.

Reference `beacon-node/src/api/impl/` — each method returns plain JSON-
ready dicts ({"data": ...} envelopes per the Eth Beacon API spec), using
the generic eth2-JSON codecs over the registry types.
"""

from __future__ import annotations

import asyncio

from lodestar_tpu.ssz.json import from_json, to_json
from lodestar_tpu.state_transition import EpochContext, compute_epoch_at_slot, process_slots
from lodestar_tpu.types import ssz_types

__all__ = ["BeaconApiImpl", "ApiError"]

VERSION = "lodestar-tpu/0.3.0"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class BeaconApiImpl:
    def __init__(self, chain):
        self.chain = chain
        self.p = chain.p
        self.t = ssz_types(chain.p)

    def _run_async(self, coro):
        """Run a chain-mutating coroutine on the NODE's event loop when
        one is attached (chain.loop, set by BeaconNode.init). REST
        handler threads must not drive loop-bound machinery (the device
        BLS pool's queues/timers live on the main loop) nor mutate chain
        structures concurrently with the gossip drain; routing through
        the loop restores the reference's single-threaded semantics.
        Library users without a node fall back to a private loop."""
        loop = getattr(self.chain, "loop", None)
        if loop is not None and loop.is_running():
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not loop:
                return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=120)
        return asyncio.run(coro)

    # -- events namespace (SSE) -----------------------------------------------

    def stream_events(self, topics: list[str]) -> "EventStream":
        import queue as _queue

        for t in topics:
            if t not in EVENT_TOPICS:
                raise ApiError(400, f"unknown event topic {t!r}")
        if not topics:
            topics = list(EVENT_TOPICS)
        q: "_queue.Queue" = _queue.Queue(maxsize=1024)
        chain = self.chain

        def _put(event_type: str, payload: dict) -> None:
            try:
                q.put_nowait((event_type, payload))
            except _queue.Full:
                pass  # slow consumer: drop rather than stall the chain

        handlers = []
        if "block" in topics:

            def on_block(root, signed):
                _put(
                    "block",
                    {
                        "slot": str(int(signed.message.slot)),
                        "block": "0x" + bytes(root).hex(),
                        "execution_optimistic": False,
                    },
                )

            chain.on("block", on_block)
            handlers.append(("block", on_block))
        if "head" in topics:
            # baseline from the CURRENT HEAD's slot, not the wall clock: a
            # syncing node's clock epoch is far ahead of its head epoch and
            # would fire a spurious epoch_transition on the first event
            head_node = chain.fork_choice.proto_array.get_block(chain.fork_choice.head)
            prev_epoch = [(head_node.slot if head_node else 0) // chain.p.SLOTS_PER_EPOCH]

            def on_head(head_hex):
                node = chain.fork_choice.proto_array.get_block(head_hex)
                epoch = (node.slot if node else 0) // chain.p.SLOTS_PER_EPOCH
                transition = epoch != prev_epoch[0]
                prev_epoch[0] = epoch
                _put(
                    "head",
                    {
                        "slot": str(node.slot if node else 0),
                        "block": head_hex,
                        "state": node.state_root if node else "0x" + "00" * 32,
                        "epoch_transition": transition,
                        "execution_optimistic": False,
                    },
                )

            chain.on("head", on_head)
            handlers.append(("head", on_head))
        if "finalized_checkpoint" in topics:

            def on_finalized(cp):
                node = chain.fork_choice.proto_array.get_block("0x" + bytes(cp.root).hex())
                _put(
                    "finalized_checkpoint",
                    {
                        "block": "0x" + bytes(cp.root).hex(),
                        "state": node.state_root if node else "0x" + "00" * 32,
                        "epoch": str(int(cp.epoch)),
                        "execution_optimistic": False,
                    },
                )

            chain.on("finalized", on_finalized)
            handlers.append(("finalized", on_finalized))

        def unsubscribe():
            for event, fn in handlers:
                chain.off(event, fn)

        return EventStream(q, unsubscribe)



    # -- state resolution -----------------------------------------------------

    def _state_at(self, state_id: str):
        """Beacon API stateId: head | finalized | <slot> | 0x<state root>."""
        chain = self.chain
        if state_id == "head":
            return chain.get_head_state()
        if state_id == "genesis":
            raise ApiError(501, "genesis state queries need the archive")
        if state_id == "finalized":
            st = chain.get_finalized_state()
            if st is None:
                raise ApiError(404, "finalized state not found")
            return st
        if state_id.startswith("0x"):
            # hex stateId is a STATE root: fork choice nodes record their
            # block's state_root, so resolve through them to the block root
            for node in chain.fork_choice.proto_array.nodes:
                if node.state_root == state_id:
                    return chain.get_state_by_block_root(bytes.fromhex(node.block_root[2:]))
            raise ApiError(404, f"state {state_id} not found")
        if state_id.isdigit():
            return chain.get_state_by_block_root(self._block_root(state_id))
        raise ApiError(400, f"unsupported state id {state_id}")

    # -- beacon namespace -----------------------------------------------------

    def get_genesis(self) -> dict:
        st = self.chain.get_head_state()
        # fork version from the chain config when bound: the head state's
        # previous_version stops being the genesis version after any fork
        if self.chain.cfg is not None:
            version = self.chain.cfg.GENESIS_FORK_VERSION
        else:
            version = bytes(st.fork.previous_version)
        return {
            "data": {
                "genesis_time": str(st.genesis_time),
                "genesis_validators_root": "0x" + bytes(st.genesis_validators_root).hex(),
                "genesis_fork_version": "0x" + version.hex(),
            }
        }

    def get_block_header(self, block_id: str) -> dict:
        root = self._block_root(block_id)
        signed = self.chain.get_block_by_root(root)
        if signed is None:
            raise ApiError(404, f"block {block_id} not found")
        header = self.t.BeaconBlockHeader.default()
        msg = signed.message
        header.slot = msg.slot
        header.proposer_index = msg.proposer_index
        header.parent_root = bytes(msg.parent_root)
        header.state_root = bytes(msg.state_root)
        header.body_root = self.t.phase0.BeaconBlockBody.hash_tree_root(msg.body)
        return {
            "data": {
                "root": "0x" + root.hex(),
                "canonical": True,
                "header": {
                    "message": to_json(self.t.BeaconBlockHeader, header),
                    "signature": "0x" + bytes(signed.signature).hex(),
                },
            }
        }

    def _block_root(self, block_id: str) -> bytes:
        if block_id == "head":
            return self.chain.head_root
        if block_id.startswith("0x"):
            return bytes.fromhex(block_id[2:])
        # numeric slot: resolve through fork choice chain from head
        slot = int(block_id)
        node = self.chain.fork_choice.proto_array.get_block(self.chain.fork_choice.head)
        while node is not None and node.slot > slot:
            parent = node.parent
            node = self.chain.fork_choice.proto_array.nodes[parent] if parent is not None else None
        if node is None or node.slot != slot:
            raise ApiError(404, f"no canonical block at slot {slot}")
        return bytes.fromhex(node.block_root[2:])

    def get_block_v2(self, block_id: str) -> dict:
        from lodestar_tpu.state_transition.block import fork_of

        root = self._block_root(block_id)
        signed = self.chain.get_block_by_root(root)
        if signed is None:
            raise ApiError(404, f"block {block_id} not found")
        fork = fork_of(signed.message)
        return {
            "version": fork,
            "execution_optimistic": False,
            "data": to_json(getattr(self.t, fork).SignedBeaconBlock, signed),
        }

    def publish_block(self, body: dict) -> dict:
        # decode with the fork active at the block's slot (the standard
        # API sends the version in a header the stdlib router doesn't
        # surface; the slot determines it just as well)
        try:
            slot = int(body["message"]["slot"])
        except (KeyError, TypeError, ValueError) as e:
            raise ApiError(400, f"malformed block body: {e}") from e
        fork = self.chain.fork_name_at_slot(slot)
        try:
            signed = from_json(getattr(self.t, fork).SignedBeaconBlock, body)
        except (KeyError, TypeError, ValueError) as e:
            raise ApiError(400, f"cannot decode {fork} block: {e}") from e
        from lodestar_tpu.chain.chain import BlockError

        try:
            self._run_async(self.chain.process_block(signed))
        except BlockError as e:
            raise ApiError(400, str(e)) from e
        return {}

    def get_state_finality_checkpoints(self, state_id: str) -> dict:
        st = self._state_at(state_id)
        return {
            "data": {
                "previous_justified": to_json(self.t.Checkpoint, st.previous_justified_checkpoint),
                "current_justified": to_json(self.t.Checkpoint, st.current_justified_checkpoint),
                "finalized": to_json(self.t.Checkpoint, st.finalized_checkpoint),
            }
        }

    def get_state_fork(self, state_id: str) -> dict:
        st = self._state_at(state_id)
        return {"data": to_json(self.t.Fork, st.fork)}

    def get_state_validators(self, state_id: str) -> dict:
        st = self._state_at(state_id)
        epoch = compute_epoch_at_slot(st.slot, self.p)
        out = []
        for i, v in enumerate(st.validators):
            status = _validator_status(v, epoch)
            out.append(
                {
                    "index": str(i),
                    "balance": str(st.balances[i]),
                    "status": status,
                    "validator": to_json(self.t.Validator, v),
                }
            )
        return {"data": out}

    def submit_pool_attestations(self, body: list) -> dict:
        from lodestar_tpu.chain.validation import GossipValidationError, validate_gossip_attestation

        from lodestar_tpu.network.processor import import_verified_attestation

        errors = []

        async def run_batch():
            for i, att_json in enumerate(body):
                att = from_json(self.t.Attestation, att_json)
                try:
                    res = validate_gossip_attestation(self.chain, att)
                except GossipValidationError as e:
                    errors.append({"index": i, "message": str(e)})
                    continue
                if not await self.chain.bls.verify_signature_sets(res.signature_sets):
                    errors.append({"index": i, "message": "invalid attestation signature"})
                    continue
                import_verified_attestation(self.chain, res, att)

        self._run_async(run_batch())
        if errors:
            raise ApiError(400, f"some attestations failed: {errors}")
        return {}

    # -- validator namespace --------------------------------------------------

    def get_proposer_duties(self, epoch: int) -> dict:
        from lodestar_tpu.chain.produce_block import dial_to_slot

        st = self.chain.get_head_state()
        target_slot = epoch * self.p.SLOTS_PER_EPOCH
        work, ctx = dial_to_slot(st, max(target_slot, st.slot), self.p, self.chain.cfg)
        if ctx.current_epoch != epoch:
            raise ApiError(400, f"cannot compute duties for epoch {epoch}")
        duties = []
        for i, proposer in enumerate(ctx.proposers):
            duties.append(
                {
                    "pubkey": "0x" + bytes(work.validators[proposer].pubkey).hex(),
                    "validator_index": str(proposer),
                    "slot": str(target_slot + i),
                }
            )
        return {"data": duties, "dependent_root": self.chain.fork_choice.head}

    def get_attester_duties(self, epoch: int, indices: list[int]) -> dict:
        from lodestar_tpu.chain.produce_block import dial_to_slot

        st = self.chain.get_head_state()
        work, ctx = dial_to_slot(
            st, max(epoch * self.p.SLOTS_PER_EPOCH, st.slot), self.p, self.chain.cfg
        )
        want = set(indices)
        duties = []
        sh = ctx._shuffling_at(epoch)
        for slot_i in range(self.p.SLOTS_PER_EPOCH):
            for c_idx, committee in enumerate(sh.committees[slot_i]):
                for pos, vi in enumerate(committee):
                    if int(vi) in want:
                        duties.append(
                            {
                                "pubkey": "0x" + bytes(work.validators[int(vi)].pubkey).hex(),
                                "validator_index": str(int(vi)),
                                "committee_index": str(c_idx),
                                "committee_length": str(len(committee)),
                                "committees_at_slot": str(sh.committees_per_slot),
                                "validator_committee_index": str(pos),
                                "slot": str(epoch * self.p.SLOTS_PER_EPOCH + slot_i),
                            }
                        )
        return {"data": duties, "dependent_root": self.chain.fork_choice.head}

    def produce_block_v2(self, slot: int, randao_reveal: str, graffiti: str = "") -> dict:
        from lodestar_tpu.chain.produce_block import produce_block

        block = produce_block(
            self.chain,
            slot=slot,
            randao_reveal=bytes.fromhex(randao_reveal[2:]),
            graffiti=bytes.fromhex(graffiti[2:]) if graffiti.startswith("0x") else graffiti.encode(),
        )
        from lodestar_tpu.state_transition.block import fork_of

        fork = fork_of(block)
        return {"version": fork, "data": to_json(getattr(self.t, fork).BeaconBlock, block)}

    def produce_attestation_data(self, slot: int, committee_index: int) -> dict:
        from lodestar_tpu.chain.produce_block import make_attestation_data

        data = make_attestation_data(self.chain, slot, committee_index)
        return {"data": to_json(self.t.AttestationData, data)}

    def get_validator_liveness(self, epoch: int, indices: list[int]) -> dict:
        """POST /eth/v1/validator/liveness/{epoch}: whether each index
        showed on-chain activity in the epoch (doppelganger data source;
        the reference reads its validator monitor — here the seen-attester
        cache carries the same signal)."""
        chain = self.chain

        def is_live(i: int) -> bool:
            return (
                chain.seen_attesters.is_known(int(epoch), i)
                or chain.seen_block_attesters.is_known(int(epoch), i)
                or chain.seen_aggregators.is_known(int(epoch), i)
                or chain.seen_block_proposers.is_known(int(epoch), i)
            )

        return {
            "data": [
                {"index": str(int(i)), "is_live": bool(is_live(int(i)))} for i in indices
            ]
        }

    # -- node namespace -------------------------------------------------------

    def get_health(self) -> int:
        return 200

    def get_version(self) -> dict:
        return {"data": {"version": VERSION}}

    def get_syncing_status(self) -> dict:
        head = self.chain.fork_choice.proto_array.get_block(self.chain.fork_choice.head)
        head_slot = head.slot if head else 0
        current = self.chain.fork_choice.current_slot
        return {
            "data": {
                "head_slot": str(head_slot),
                "sync_distance": str(max(0, current - head_slot)),
                "is_syncing": current - head_slot > 3,
                "is_optimistic": False,
            }
        }

    # -- debug / config -------------------------------------------------------

    def get_debug_state_v2(self, state_id: str) -> dict:
        from lodestar_tpu.state_transition.block import fork_of

        st = self._state_at(state_id)
        return {"version": fork_of(st), "data": to_json(st.type, st)}

    def get_spec(self) -> dict:
        """Preset constants PLUS the chain config's fork schedule and
        timing — validator clients derive their signing domains from
        this (reference config/spec includes *_FORK_VERSION/_EPOCH)."""
        p = self.p
        fields = {
            name: str(getattr(p, name))
            for name in type(p).__dataclass_fields__  # type: ignore[attr-defined]
        }
        cfg = self.chain.cfg
        if cfg is not None:
            for name in type(cfg).__dataclass_fields__:  # type: ignore[attr-defined]
                value = getattr(cfg, name)
                fields[name] = "0x" + value.hex() if isinstance(value, bytes) else str(value)
        return {"data": fields}


def _validator_status(v, epoch: int) -> str:
    from lodestar_tpu.params import FAR_FUTURE_EPOCH

    if v.activation_epoch > epoch:
        return "pending_queued" if v.activation_eligibility_epoch != FAR_FUTURE_EPOCH else "pending_initialized"
    if epoch < v.exit_epoch:
        return "active_slashed" if v.slashed else "active_ongoing"
    if epoch < v.withdrawable_epoch:
        return "exited_slashed" if v.slashed else "exited_unslashed"
    return "withdrawal_possible"


# --- events namespace (SSE) ---------------------------------------------------
# Reference `beacon-node/src/api/impl/events/index.ts`: subscribe chain
# emitter topics, forward as Server-Sent Events. The REST server streams
# an EventStream return value instead of JSON-encoding it.

EVENT_TOPICS = ("head", "block", "finalized_checkpoint")


class EventStream:
    """Thread-safe queue of (event_type, payload_dict) fed by chain
    events; the HTTP handler drains it as an SSE body. `close()`
    detaches the chain subscriptions."""

    def __init__(self, queue, unsubscribe):
        self.queue = queue
        self._unsubscribe = unsubscribe

    def close(self) -> None:
        self._unsubscribe()

