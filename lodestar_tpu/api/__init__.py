"""Eth Beacon API: route definitions + server + client.

Reference `packages/api/src` (route schemas shared by client and server,
`beacon/routes/*`) and `beacon-node/src/api/` (fastify impl,
`rest/base.ts:39`). Namespaces implemented: beacon (genesis, headers,
blocks, state info, pool), validator (duties, block/attestation
production), node (health/version/syncing), debug (state), config
(spec), events (SSE).
"""

from .impl import BeaconApiImpl  # noqa: F401
from .server import BeaconRestApiServer  # noqa: F401
from .client import BeaconApiClient  # noqa: F401
