"""REST server: Eth Beacon API routes over the impl.

Reference `beacon-node/src/api/rest/base.ts:39` (fastify) — here a
threaded stdlib HTTP server with a declarative route table, the same
path shapes (`/eth/v1/...`, `/eth/v2/...`) so standard beacon clients
interoperate.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Callable

from .impl import ApiError, BeaconApiImpl, EventStream

__all__ = ["BeaconRestApiServer", "ROUTES"]

# (method, path regex with named groups, handler name, kind)
ROUTES: list[tuple[str, str, str]] = [
    ("GET", r"/eth/v1/beacon/genesis", "r_genesis"),
    ("GET", r"/eth/v1/beacon/headers", "r_block_headers"),
    ("GET", r"/eth/v1/beacon/headers/(?P<block_id>[^/]+)", "r_block_header"),
    ("GET", r"/eth/v2/beacon/blocks/(?P<block_id>[^/]+)", "r_block_v2"),
    ("GET", r"/eth/v1/beacon/blocks/(?P<block_id>[^/]+)/root", "r_block_root"),
    ("GET", r"/eth/v1/beacon/blocks/(?P<block_id>[^/]+)/attestations", "r_block_attestations"),
    ("POST", r"/eth/v1/beacon/blocks", "r_publish_block"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/finality_checkpoints", "r_finality"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/fork", "r_fork"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/root", "r_state_root"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/committees", "r_committees"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/sync_committees", "r_sync_committees"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/validators", "r_validators"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/validators/(?P<validator_id>[^/]+)", "r_state_validator"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/validator_balances", "r_validator_balances"),
    ("GET", r"/eth/v1/beacon/pool/attestations", "r_get_pool_attestations"),
    ("POST", r"/eth/v1/beacon/pool/attestations", "r_pool_attestations"),
    ("GET", r"/eth/v1/beacon/pool/attester_slashings", "r_get_pool_attester_slashings"),
    ("POST", r"/eth/v1/beacon/pool/attester_slashings", "r_post_pool_attester_slashing"),
    ("GET", r"/eth/v1/beacon/pool/proposer_slashings", "r_get_pool_proposer_slashings"),
    ("POST", r"/eth/v1/beacon/pool/proposer_slashings", "r_post_pool_proposer_slashing"),
    ("GET", r"/eth/v1/beacon/pool/voluntary_exits", "r_get_pool_voluntary_exits"),
    ("POST", r"/eth/v1/beacon/pool/voluntary_exits", "r_post_pool_voluntary_exit"),
    ("GET", r"/eth/v1/beacon/pool/bls_to_execution_changes", "r_get_pool_bls_changes"),
    ("POST", r"/eth/v1/beacon/pool/bls_to_execution_changes", "r_post_pool_bls_change"),
    ("POST", r"/eth/v1/beacon/pool/sync_committees", "r_post_pool_sync_committees"),
    ("GET", r"/eth/v1/beacon/light_client/bootstrap/(?P<block_root>0x[0-9a-fA-F]+)", "r_lc_bootstrap"),
    ("GET", r"/eth/v1/beacon/light_client/updates", "r_lc_updates"),
    ("GET", r"/eth/v1/beacon/light_client/optimistic_update", "r_lc_optimistic"),
    ("GET", r"/eth/v1/beacon/light_client/finality_update", "r_lc_finality"),
    ("GET", r"/eth/v0/beacon/proof/state/(?P<state_id>[^/]+)", "r_state_proof"),
    ("GET", r"/eth/v1/validator/duties/proposer/(?P<epoch>\d+)", "r_proposer_duties"),
    ("POST", r"/eth/v1/validator/duties/attester/(?P<epoch>\d+)", "r_attester_duties"),
    ("POST", r"/eth/v1/validator/duties/sync/(?P<epoch>\d+)", "r_sync_duties"),
    ("GET", r"/eth/v2/validator/blocks/(?P<slot>\d+)", "r_produce_block"),
    ("GET", r"/eth/v1/validator/attestation_data", "r_attestation_data"),
    ("GET", r"/eth/v1/validator/aggregate_attestation", "r_aggregate_attestation"),
    ("POST", r"/eth/v1/validator/aggregate_and_proofs", "r_aggregate_and_proofs"),
    ("GET", r"/eth/v1/validator/sync_committee_contribution", "r_sync_contribution"),
    ("POST", r"/eth/v1/validator/contribution_and_proofs", "r_contribution_and_proofs"),
    ("POST", r"/eth/v1/validator/beacon_committee_subscriptions", "r_committee_subscriptions"),
    ("POST", r"/eth/v1/validator/sync_committee_subscriptions", "r_sync_subscriptions"),
    ("POST", r"/eth/v1/validator/prepare_beacon_proposer", "r_prepare_proposer"),
    ("POST", r"/eth/v1/validator/register_validator", "r_register_validator"),
    ("POST", r"/eth/v1/validator/liveness/(?P<epoch>\d+)", "r_liveness"),
    ("GET", r"/eth/v1/events", "r_events"),
    ("GET", r"/eth/v1/node/health", "r_health"),
    ("GET", r"/eth/v1/node/version", "r_version"),
    ("GET", r"/eth/v1/node/syncing", "r_syncing"),
    ("GET", r"/eth/v1/node/identity", "r_node_identity"),
    ("GET", r"/eth/v1/node/peers", "r_node_peers"),
    ("GET", r"/eth/v1/node/peers/(?P<peer_id>[^/]+)", "r_node_peer"),
    ("GET", r"/eth/v1/node/peer_count", "r_node_peer_count"),
    ("GET", r"/eth/v2/debug/beacon/states/(?P<state_id>[^/]+)", "r_debug_state"),
    ("GET", r"/eth/v1/debug/beacon/heads", "r_debug_heads"),
    ("GET", r"/eth/v2/debug/beacon/heads", "r_debug_heads"),
    ("GET", r"/eth/v0/debug/forkchoice", "r_debug_forkchoice"),
    ("GET", r"/eth/v0/debug/traces", "r_debug_traces_recent"),
    ("GET", r"/eth/v0/debug/traces/(?P<slot>\d+)", "r_debug_traces"),
    ("GET", r"/eth/v0/debug/launches", "r_debug_launches"),
    ("GET", r"/eth/v0/debug/slo", "r_debug_slo"),
    ("GET", r"/eth/v1/config/spec", "r_spec"),
    ("GET", r"/eth/v1/config/fork_schedule", "r_fork_schedule"),
    ("GET", r"/eth/v1/config/deposit_contract", "r_deposit_contract"),
]


class _Router:
    def __init__(self, api: BeaconApiImpl):
        self.api = api
        self.table = [
            (method, re.compile("^" + pattern + "$"), getattr(self, handler))
            for method, pattern, handler in ROUTES
        ]

    def dispatch(self, method: str, path: str, query: dict, body):
        for m, rx, fn in self.table:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                return fn(query=query, body=body, **match.groupdict())
        raise ApiError(404, f"route not found: {method} {path}")

    # handlers — translate path/query/body into impl calls
    def r_genesis(self, **kw):
        return self.api.get_genesis()

    def r_block_header(self, block_id, **kw):
        return self.api.get_block_header(block_id)

    def r_block_v2(self, block_id, **kw):
        return self.api.get_block_v2(block_id)

    def r_publish_block(self, body, **kw):
        return self.api.publish_block(body)

    def r_finality(self, state_id, **kw):
        return self.api.get_state_finality_checkpoints(state_id)

    def r_fork(self, state_id, **kw):
        return self.api.get_state_fork(state_id)

    def r_validators(self, state_id, **kw):
        return self.api.get_state_validators(state_id)

    def r_pool_attestations(self, body, **kw):
        return self.api.submit_pool_attestations(body)

    def r_proposer_duties(self, epoch, **kw):
        return self.api.get_proposer_duties(int(epoch))

    def r_attester_duties(self, epoch, body, **kw):
        return self.api.get_attester_duties(int(epoch), [int(i) for i in body])

    def r_produce_block(self, slot, query, **kw):
        reveal = query.get("randao_reveal")
        if not reveal:
            raise ApiError(400, "missing required parameter: randao_reveal")
        return self.api.produce_block_v2(int(slot), reveal, query.get("graffiti", ""))

    def r_attestation_data(self, query, **kw):
        return self.api.produce_attestation_data(
            int(query["slot"]), int(query["committee_index"])
        )

    def r_liveness(self, epoch, body, **kw):
        return self.api.get_validator_liveness(int(epoch), [int(i) for i in (body or [])])

    def r_events(self, query, **kw):
        topics = [t for t in (query.get("topics") or "").split(",") if t]
        return self.api.stream_events(topics)

    def r_health(self, **kw):
        return self.api.get_health()

    def r_version(self, **kw):
        return self.api.get_version()

    def r_syncing(self, **kw):
        return self.api.get_syncing_status()

    def r_debug_state(self, state_id, **kw):
        return self.api.get_debug_state_v2(state_id)

    def r_spec(self, **kw):
        return self.api.get_spec()

    # -- expanded surface (beacon/state, pools, node, lightclient, proof,
    # sync-committee validator flows, debug, config) --------------------------

    def r_block_headers(self, query, **kw):
        return self.api.get_block_headers(query)

    def r_block_root(self, block_id, **kw):
        return self.api.get_block_root(block_id)

    def r_block_attestations(self, block_id, **kw):
        return self.api.get_block_attestations(block_id)

    def r_state_root(self, state_id, **kw):
        return self.api.get_state_root(state_id)

    def r_committees(self, state_id, query, **kw):
        return self.api.get_epoch_committees(state_id, query)

    def r_sync_committees(self, state_id, query, **kw):
        return self.api.get_epoch_sync_committees(state_id, query)

    def r_state_validator(self, state_id, validator_id, **kw):
        return self.api.get_state_validator(state_id, validator_id)

    def r_validator_balances(self, state_id, query, **kw):
        return self.api.get_state_validator_balances(state_id, query)

    def r_get_pool_attestations(self, **kw):
        return self.api.get_pool_attestations()

    def r_get_pool_attester_slashings(self, **kw):
        return self.api.get_pool_attester_slashings()

    def r_post_pool_attester_slashing(self, body, **kw):
        return self.api.submit_pool_attester_slashing(body)

    def r_get_pool_proposer_slashings(self, **kw):
        return self.api.get_pool_proposer_slashings()

    def r_post_pool_proposer_slashing(self, body, **kw):
        return self.api.submit_pool_proposer_slashing(body)

    def r_get_pool_voluntary_exits(self, **kw):
        return self.api.get_pool_voluntary_exits()

    def r_post_pool_voluntary_exit(self, body, **kw):
        return self.api.submit_pool_voluntary_exit(body)

    def r_get_pool_bls_changes(self, **kw):
        return self.api.get_pool_bls_changes()

    def r_post_pool_bls_change(self, body, **kw):
        return self.api.submit_pool_bls_change(body)

    def r_post_pool_sync_committees(self, body, **kw):
        return self.api.submit_pool_sync_committees(body or [])

    def r_lc_bootstrap(self, block_root, **kw):
        return self.api.get_lc_bootstrap(block_root)

    def r_lc_updates(self, query, **kw):
        return self.api.get_lc_updates(query)

    def r_lc_optimistic(self, **kw):
        return self.api.get_lc_optimistic_update()

    def r_lc_finality(self, **kw):
        return self.api.get_lc_finality_update()

    def r_state_proof(self, state_id, query, **kw):
        return self.api.get_state_proof(state_id, query)

    def r_sync_duties(self, epoch, body, **kw):
        return self.api.get_sync_committee_duties(int(epoch), [int(i) for i in (body or [])])

    def r_aggregate_attestation(self, query, **kw):
        return self.api.get_aggregated_attestation(query)

    def r_aggregate_and_proofs(self, body, **kw):
        return self.api.publish_aggregate_and_proofs(body or [])

    def r_sync_contribution(self, query, **kw):
        return self.api.produce_sync_committee_contribution(query)

    def r_contribution_and_proofs(self, body, **kw):
        return self.api.publish_contribution_and_proofs(body or [])

    def r_committee_subscriptions(self, body, **kw):
        return self.api.prepare_beacon_committee_subnet(body or [])

    def r_sync_subscriptions(self, body, **kw):
        return self.api.prepare_sync_committee_subnets(body or [])

    def r_prepare_proposer(self, body, **kw):
        return self.api.prepare_beacon_proposer(body or [])

    def r_register_validator(self, body, **kw):
        return self.api.register_validator(body or [])

    def r_node_identity(self, **kw):
        return self.api.get_node_identity()

    def r_node_peers(self, query, **kw):
        return self.api.get_node_peers(query)

    def r_node_peer(self, peer_id, **kw):
        return self.api.get_node_peer(peer_id)

    def r_node_peer_count(self, **kw):
        return self.api.get_node_peer_count()

    def r_debug_heads(self, **kw):
        return self.api.get_debug_chain_heads()

    def r_debug_forkchoice(self, **kw):
        return self.api.get_fork_choice_nodes()

    def r_debug_traces(self, slot, query=None, **kw):
        return self.api.get_slot_traces(slot, fmt=(query or {}).get("format", "json"))

    def r_debug_traces_recent(self, query=None, **kw):
        raw = (query or {}).get("count", "16")
        try:
            count = int(raw)
        except ValueError:
            raise ApiError(400, f"count must be an integer, got {raw!r}") from None
        return self.api.get_recent_traces(count)

    def r_debug_launches(self, query=None, **kw):
        raw = (query or {}).get("count", "64")
        try:
            count = int(raw)
        except ValueError:
            raise ApiError(400, f"count must be an integer, got {raw!r}") from None
        return self.api.get_debug_launches(count, program=(query or {}).get("program"))

    def r_debug_slo(self, **kw):
        return self.api.get_debug_slo()

    def r_fork_schedule(self, **kw):
        return self.api.get_fork_schedule()

    def r_deposit_contract(self, **kw):
        return self.api.get_deposit_contract()


class RestServer:
    """Threaded stdlib HTTP server over any router exposing
    `dispatch(method, path, query, body)` (the Beacon API and the
    validator keymanager API share this host)."""

    def __init__(
        self,
        router,
        *,
        host: str = "127.0.0.1",
        port: int = 9596,
        auth_token: str | None = None,
    ):
        self.router = router
        self.host = host
        self.port = port
        # When set, every request must carry `Authorization: Bearer <token>`
        # (the keymanager API's api-token.txt scheme, reference
        # `keymanager/server/index.ts` bearer auth).
        self.auth_token = auth_token
        self._httpd = None
        self._thread: threading.Thread | None = None
        self._sse_streams: set = set()  # live EventStreams, closed on stop()
        self._closing = False

    def start(self) -> None:
        import http.server
        from urllib.parse import parse_qsl, urlsplit

        router = self.router
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _run(self, method):
                parts = urlsplit(self.path)
                query = dict(parse_qsl(parts.query))
                if outer.auth_token is not None:
                    import hmac

                    # compare as bytes: compare_digest raises TypeError on
                    # non-ASCII str (headers arrive latin-1 decoded)
                    presented = (self.headers.get("Authorization") or "").encode(
                        "utf-8", "surrogateescape"
                    )
                    expected = f"Bearer {outer.auth_token}".encode()
                    if not hmac.compare_digest(presented, expected):
                        payload = json.dumps(
                            {"code": 401, "message": "missing or invalid bearer token"}
                        ).encode()
                        self._reply(401, payload)
                        return
                try:
                    body = None
                    if method in ("POST", "DELETE"):
                        length = int(self.headers.get("Content-Length") or 0)
                        raw = self.rfile.read(length) if length else b""
                        try:
                            body = json.loads(raw) if raw else None
                        except json.JSONDecodeError as e:
                            raise ApiError(400, f"malformed JSON body: {e}") from e
                    out = router.dispatch(method, parts.path, query, body)
                except ApiError as e:
                    payload = json.dumps({"code": e.status, "message": e.message}).encode()
                    self._reply(e.status, payload)
                    return
                except Exception as e:  # internal error fail-safe
                    payload = json.dumps({"code": 500, "message": repr(e)}).encode()
                    self._reply(500, payload)
                    return
                if isinstance(out, int):  # health-style status-only
                    self.send_response(out)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if isinstance(out, EventStream):
                    self._stream_sse(out)
                    return
                self._reply(200, json.dumps(out).encode())

            def _stream_sse(self, stream):
                """Server-Sent Events: drain the stream's queue until the
                client disconnects or the server shuts down (None
                sentinel); periodic keepalive comments."""
                import queue as _queue

                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                outer._sse_streams.add(stream)
                try:
                    while not outer._closing:
                        try:
                            item = stream.queue.get(timeout=10.0)
                        except _queue.Empty:
                            self.wfile.write(b": keepalive\n\n")
                            self.wfile.flush()
                            continue
                        if item is None:  # shutdown sentinel from stop()
                            break
                        event_type, payload = item
                        frame = (
                            f"event: {event_type}\ndata: {json.dumps(payload)}\n\n".encode()
                        )
                        self.wfile.write(frame)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away
                finally:
                    outer._sse_streams.discard(stream)
                    stream.close()

            def _reply(self, status, payload: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._run("GET")

            def do_POST(self):  # noqa: N802
                self._run("POST")

            def do_DELETE(self):  # noqa: N802
                self._run("DELETE")

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # unblock live SSE handlers: the closing flag covers handlers the
        # sentinel can't reach (race before _sse_streams.add, full queue)
        # within one keepalive interval; the sentinel covers the rest now
        self._closing = True
        for stream in list(self._sse_streams):
            stream.close()
            try:
                stream.queue.put_nowait(None)
            except Exception:
                pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class BeaconRestApiServer(RestServer):
    def __init__(self, api: BeaconApiImpl, *, host: str = "127.0.0.1", port: int = 9596):
        super().__init__(_Router(api), host=host, port=port)
