"""REST server: Eth Beacon API routes over the impl.

Reference `beacon-node/src/api/rest/base.ts:39` (fastify) — here a
threaded stdlib HTTP server with a declarative route table, the same
path shapes (`/eth/v1/...`, `/eth/v2/...`) so standard beacon clients
interoperate.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Callable

from .impl import ApiError, BeaconApiImpl, EventStream

__all__ = ["BeaconRestApiServer", "ROUTES"]

# (method, path regex with named groups, handler name, kind)
ROUTES: list[tuple[str, str, str]] = [
    ("GET", r"/eth/v1/beacon/genesis", "r_genesis"),
    ("GET", r"/eth/v1/beacon/headers/(?P<block_id>[^/]+)", "r_block_header"),
    ("GET", r"/eth/v2/beacon/blocks/(?P<block_id>[^/]+)", "r_block_v2"),
    ("POST", r"/eth/v1/beacon/blocks", "r_publish_block"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/finality_checkpoints", "r_finality"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/fork", "r_fork"),
    ("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/validators", "r_validators"),
    ("POST", r"/eth/v1/beacon/pool/attestations", "r_pool_attestations"),
    ("GET", r"/eth/v1/validator/duties/proposer/(?P<epoch>\d+)", "r_proposer_duties"),
    ("POST", r"/eth/v1/validator/duties/attester/(?P<epoch>\d+)", "r_attester_duties"),
    ("GET", r"/eth/v2/validator/blocks/(?P<slot>\d+)", "r_produce_block"),
    ("GET", r"/eth/v1/validator/attestation_data", "r_attestation_data"),
    ("POST", r"/eth/v1/validator/liveness/(?P<epoch>\d+)", "r_liveness"),
    ("GET", r"/eth/v1/events", "r_events"),
    ("GET", r"/eth/v1/node/health", "r_health"),
    ("GET", r"/eth/v1/node/version", "r_version"),
    ("GET", r"/eth/v1/node/syncing", "r_syncing"),
    ("GET", r"/eth/v2/debug/beacon/states/(?P<state_id>[^/]+)", "r_debug_state"),
    ("GET", r"/eth/v1/config/spec", "r_spec"),
]


class _Router:
    def __init__(self, api: BeaconApiImpl):
        self.api = api
        self.table = [
            (method, re.compile("^" + pattern + "$"), getattr(self, handler))
            for method, pattern, handler in ROUTES
        ]

    def dispatch(self, method: str, path: str, query: dict, body):
        for m, rx, fn in self.table:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                return fn(query=query, body=body, **match.groupdict())
        raise ApiError(404, f"route not found: {method} {path}")

    # handlers — translate path/query/body into impl calls
    def r_genesis(self, **kw):
        return self.api.get_genesis()

    def r_block_header(self, block_id, **kw):
        return self.api.get_block_header(block_id)

    def r_block_v2(self, block_id, **kw):
        return self.api.get_block_v2(block_id)

    def r_publish_block(self, body, **kw):
        return self.api.publish_block(body)

    def r_finality(self, state_id, **kw):
        return self.api.get_state_finality_checkpoints(state_id)

    def r_fork(self, state_id, **kw):
        return self.api.get_state_fork(state_id)

    def r_validators(self, state_id, **kw):
        return self.api.get_state_validators(state_id)

    def r_pool_attestations(self, body, **kw):
        return self.api.submit_pool_attestations(body)

    def r_proposer_duties(self, epoch, **kw):
        return self.api.get_proposer_duties(int(epoch))

    def r_attester_duties(self, epoch, body, **kw):
        return self.api.get_attester_duties(int(epoch), [int(i) for i in body])

    def r_produce_block(self, slot, query, **kw):
        reveal = query.get("randao_reveal")
        if not reveal:
            raise ApiError(400, "missing required parameter: randao_reveal")
        return self.api.produce_block_v2(int(slot), reveal, query.get("graffiti", ""))

    def r_attestation_data(self, query, **kw):
        return self.api.produce_attestation_data(
            int(query["slot"]), int(query["committee_index"])
        )

    def r_liveness(self, epoch, body, **kw):
        return self.api.get_validator_liveness(int(epoch), [int(i) for i in (body or [])])

    def r_events(self, query, **kw):
        topics = [t for t in (query.get("topics") or "").split(",") if t]
        return self.api.stream_events(topics)

    def r_health(self, **kw):
        return self.api.get_health()

    def r_version(self, **kw):
        return self.api.get_version()

    def r_syncing(self, **kw):
        return self.api.get_syncing_status()

    def r_debug_state(self, state_id, **kw):
        return self.api.get_debug_state_v2(state_id)

    def r_spec(self, **kw):
        return self.api.get_spec()


class RestServer:
    """Threaded stdlib HTTP server over any router exposing
    `dispatch(method, path, query, body)` (the Beacon API and the
    validator keymanager API share this host)."""

    def __init__(
        self,
        router,
        *,
        host: str = "127.0.0.1",
        port: int = 9596,
        auth_token: str | None = None,
    ):
        self.router = router
        self.host = host
        self.port = port
        # When set, every request must carry `Authorization: Bearer <token>`
        # (the keymanager API's api-token.txt scheme, reference
        # `keymanager/server/index.ts` bearer auth).
        self.auth_token = auth_token
        self._httpd = None
        self._thread: threading.Thread | None = None
        self._sse_streams: set = set()  # live EventStreams, closed on stop()
        self._closing = False

    def start(self) -> None:
        import http.server
        from urllib.parse import parse_qsl, urlsplit

        router = self.router
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _run(self, method):
                parts = urlsplit(self.path)
                query = dict(parse_qsl(parts.query))
                if outer.auth_token is not None:
                    import hmac

                    # compare as bytes: compare_digest raises TypeError on
                    # non-ASCII str (headers arrive latin-1 decoded)
                    presented = (self.headers.get("Authorization") or "").encode(
                        "utf-8", "surrogateescape"
                    )
                    expected = f"Bearer {outer.auth_token}".encode()
                    if not hmac.compare_digest(presented, expected):
                        payload = json.dumps(
                            {"code": 401, "message": "missing or invalid bearer token"}
                        ).encode()
                        self._reply(401, payload)
                        return
                try:
                    body = None
                    if method in ("POST", "DELETE"):
                        length = int(self.headers.get("Content-Length") or 0)
                        raw = self.rfile.read(length) if length else b""
                        try:
                            body = json.loads(raw) if raw else None
                        except json.JSONDecodeError as e:
                            raise ApiError(400, f"malformed JSON body: {e}") from e
                    out = router.dispatch(method, parts.path, query, body)
                except ApiError as e:
                    payload = json.dumps({"code": e.status, "message": e.message}).encode()
                    self._reply(e.status, payload)
                    return
                except Exception as e:  # internal error fail-safe
                    payload = json.dumps({"code": 500, "message": repr(e)}).encode()
                    self._reply(500, payload)
                    return
                if isinstance(out, int):  # health-style status-only
                    self.send_response(out)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if isinstance(out, EventStream):
                    self._stream_sse(out)
                    return
                self._reply(200, json.dumps(out).encode())

            def _stream_sse(self, stream):
                """Server-Sent Events: drain the stream's queue until the
                client disconnects or the server shuts down (None
                sentinel); periodic keepalive comments."""
                import queue as _queue

                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                outer._sse_streams.add(stream)
                try:
                    while not outer._closing:
                        try:
                            item = stream.queue.get(timeout=10.0)
                        except _queue.Empty:
                            self.wfile.write(b": keepalive\n\n")
                            self.wfile.flush()
                            continue
                        if item is None:  # shutdown sentinel from stop()
                            break
                        event_type, payload = item
                        frame = (
                            f"event: {event_type}\ndata: {json.dumps(payload)}\n\n".encode()
                        )
                        self.wfile.write(frame)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away
                finally:
                    outer._sse_streams.discard(stream)
                    stream.close()

            def _reply(self, status, payload: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._run("GET")

            def do_POST(self):  # noqa: N802
                self._run("POST")

            def do_DELETE(self):  # noqa: N802
                self._run("DELETE")

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # unblock live SSE handlers: the closing flag covers handlers the
        # sentinel can't reach (race before _sse_streams.add, full queue)
        # within one keepalive interval; the sentinel covers the rest now
        self._closing = True
        for stream in list(self._sse_streams):
            stream.close()
            try:
                stream.queue.put_nowait(None)
            except Exception:
                pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class BeaconRestApiServer(RestServer):
    def __init__(self, api: BeaconApiImpl, *, host: str = "127.0.0.1", port: int = 9596):
        super().__init__(_Router(api), host=host, port=port)
