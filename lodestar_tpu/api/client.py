"""Typed REST client over the same routes (reference
`api/src/beacon/client/` getClient — the validator process talks to the
node exclusively through this)."""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request

__all__ = ["BeaconApiClient", "ApiClientError"]


class ApiClientError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status


class BeaconApiClient:
    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")

    def _req(self, method: str, path: str, query: dict | None = None, body=None):
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        if data is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req) as r:
                raw = r.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("message", "")
            except Exception:
                msg = ""
            raise ApiClientError(e.code, msg) from e

    # beacon
    def get_genesis(self):
        return self._req("GET", "/eth/v1/beacon/genesis")

    def get_block_header(self, block_id: str):
        return self._req("GET", f"/eth/v1/beacon/headers/{block_id}")

    def get_block_v2(self, block_id: str):
        return self._req("GET", f"/eth/v2/beacon/blocks/{block_id}")

    def publish_block(self, signed_block_json: dict):
        return self._req("POST", "/eth/v1/beacon/blocks", body=signed_block_json)

    def get_state_finality_checkpoints(self, state_id: str):
        return self._req("GET", f"/eth/v1/beacon/states/{state_id}/finality_checkpoints")

    def get_state_fork(self, state_id: str):
        return self._req("GET", f"/eth/v1/beacon/states/{state_id}/fork")

    def get_state_validators(self, state_id: str):
        return self._req("GET", f"/eth/v1/beacon/states/{state_id}/validators")

    def submit_pool_proposer_slashing(self, slashing_json: dict):
        return self._req("POST", "/eth/v1/beacon/pool/proposer_slashings", body=slashing_json)

    def submit_pool_attester_slashing(self, slashing_json: dict):
        return self._req("POST", "/eth/v1/beacon/pool/attester_slashings", body=slashing_json)

    def submit_pool_attestations(self, attestations_json: list):
        return self._req("POST", "/eth/v1/beacon/pool/attestations", body=attestations_json)

    # validator
    def get_proposer_duties(self, epoch: int):
        return self._req("GET", f"/eth/v1/validator/duties/proposer/{epoch}")

    def get_attester_duties(self, epoch: int, indices: list[int]):
        return self._req(
            "POST", f"/eth/v1/validator/duties/attester/{epoch}", body=[str(i) for i in indices]
        )

    def produce_block_v2(self, slot: int, randao_reveal: bytes, graffiti: str = ""):
        return self._req(
            "GET",
            f"/eth/v2/validator/blocks/{slot}",
            query={"randao_reveal": "0x" + randao_reveal.hex(), "graffiti": graffiti},
        )

    def produce_attestation_data(self, slot: int, committee_index: int):
        return self._req(
            "GET",
            "/eth/v1/validator/attestation_data",
            query={"slot": slot, "committee_index": committee_index},
        )

    # node
    # sync-committee validator flows
    def get_sync_committee_duties(self, epoch: int, indices: list[int]):
        return self._req(
            "POST", f"/eth/v1/validator/duties/sync/{epoch}", body=[int(i) for i in indices]
        )

    def submit_pool_sync_committees(self, messages_json: list):
        return self._req("POST", "/eth/v1/beacon/pool/sync_committees", body=messages_json)

    def produce_sync_committee_contribution(
        self, slot: int, subcommittee_index: int, beacon_block_root: str
    ):
        return self._req(
            "GET",
            "/eth/v1/validator/sync_committee_contribution",
            {
                "slot": str(slot),
                "subcommittee_index": str(subcommittee_index),
                "beacon_block_root": beacon_block_root,
            },
        )

    def publish_contribution_and_proofs(self, signed_json: list):
        return self._req(
            "POST", "/eth/v1/validator/contribution_and_proofs", body=signed_json
        )

    def get_block_root(self, block_id: str):
        return self._req("GET", f"/eth/v1/beacon/blocks/{block_id}/root")

    # light-client namespace
    def get_lc_bootstrap(self, block_root_hex: str):
        return self._req(
            "GET", f"/eth/v1/beacon/light_client/bootstrap/{block_root_hex}"
        )

    def get_lc_updates(self, start_period: int, count: int):
        return self._req(
            "GET",
            "/eth/v1/beacon/light_client/updates",
            {"start_period": str(start_period), "count": str(count)},
        )

    def get_lc_finality_update(self):
        return self._req("GET", "/eth/v1/beacon/light_client/finality_update")

    def get_lc_optimistic_update(self):
        return self._req("GET", "/eth/v1/beacon/light_client/optimistic_update")

    def get_health(self) -> int:
        try:
            self._req("GET", "/eth/v1/node/health")
            return 200
        except ApiClientError as e:
            return e.status

    def get_version(self):
        return self._req("GET", "/eth/v1/node/version")

    def get_syncing_status(self):
        return self._req("GET", "/eth/v1/node/syncing")

    # debug / config
    def get_debug_state_v2(self, state_id: str):
        return self._req("GET", f"/eth/v2/debug/beacon/states/{state_id}")

    def get_spec(self):
        return self._req("GET", "/eth/v1/config/spec")
