"""Engine API seam: HTTP client + in-memory mock EL.

Reference `execution/engine/http.ts:83` (engine_newPayloadV1/
forkchoiceUpdatedV1/getPayloadV1 over JSON-RPC with JWT) and `mock.ts`
(the in-memory EL used by the `dev` command and sim tests).
"""

from __future__ import annotations

import base64
import enum
import hashlib
import hmac
import json
import os
import time
import urllib.request
from dataclasses import dataclass, field

__all__ = [
    "ExecutePayloadStatus",
    "PayloadAttributes",
    "IExecutionEngine",
    "ExecutionEngineMock",
    "ExecutionEngineHttp",
]


class ExecutePayloadStatus(enum.Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"
    INVALID_BLOCK_HASH = "INVALID_BLOCK_HASH"
    ELERROR = "ELERROR"
    UNAVAILABLE = "UNAVAILABLE"


@dataclass
class PayloadAttributes:
    timestamp: int
    prev_randao: bytes
    suggested_fee_recipient: bytes


class IExecutionEngine:
    async def notify_new_payload(self, payload) -> tuple[ExecutePayloadStatus, bytes | None]:
        """-> (status, latest_valid_hash)."""
        raise NotImplementedError

    async def notify_forkchoice_update(
        self, head_block_hash: bytes, safe_block_hash: bytes, finalized_block_hash: bytes,
        payload_attributes: PayloadAttributes | None = None,
    ) -> str | None:
        """-> payload_id when attributes were supplied."""
        raise NotImplementedError

    async def get_payload(self, payload_id: str):
        raise NotImplementedError


@dataclass
class _MockBlock:
    block_hash: bytes
    parent_hash: bytes
    block_number: int
    timestamp: int
    prev_randao: bytes


class ExecutionEngineMock(IExecutionEngine):
    """In-memory EL: tracks a hash-linked payload chain, builds payloads
    on request (reference `mock.ts`); scriptable validity for fault
    injection (the invalid-payload test path)."""

    def __init__(self, genesis_block_hash: bytes = b"\x00" * 32):
        self.head_hash = genesis_block_hash
        self.blocks: dict[bytes, _MockBlock] = {
            genesis_block_hash: _MockBlock(genesis_block_hash, b"\x00" * 32, 0, 0, b"\x00" * 32)
        }
        self.invalid_hashes: set[bytes] = set()  # scripted INVALID responses
        self._payloads: dict[str, _MockBlock] = {}
        self._payload_seq = 0

    async def notify_new_payload(self, payload):
        block_hash = bytes(payload.block_hash)
        parent_hash = bytes(payload.parent_hash)
        if block_hash in self.invalid_hashes:
            parent = self.blocks.get(parent_hash)
            lvh = parent.block_hash if parent else None
            return ExecutePayloadStatus.INVALID, lvh
        if parent_hash not in self.blocks:
            return ExecutePayloadStatus.SYNCING, None
        self.blocks[block_hash] = _MockBlock(
            block_hash, parent_hash, payload.block_number, payload.timestamp,
            bytes(payload.prev_randao),
        )
        return ExecutePayloadStatus.VALID, block_hash

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash, payload_attributes=None
    ):
        if bytes(head_block_hash) not in self.blocks:
            return None
        self.head_hash = bytes(head_block_hash)
        if payload_attributes is None:
            return None
        self._payload_seq += 1
        pid = f"0x{self._payload_seq:016x}"
        parent = self.blocks[self.head_hash]
        body = parent.block_hash + payload_attributes.prev_randao + payload_attributes.timestamp.to_bytes(8, "little")
        self._payloads[pid] = _MockBlock(
            hashlib.sha256(body).digest(),
            parent.block_hash,
            parent.block_number + 1,
            payload_attributes.timestamp,
            payload_attributes.prev_randao,
        )
        return pid

    async def get_payload(self, payload_id: str):
        blk = self._payloads.get(payload_id)
        if blk is None:
            raise ValueError(f"unknown payload id {payload_id}")
        return blk


class ExecutionEngineHttp(IExecutionEngine):
    """Engine API over JSON-RPC with JWT bearer auth (http.ts:83).
    Offline-testable: the transport is one overridable `_post` method."""

    def __init__(self, url: str, jwt_secret: bytes, timeout_sec: float = 5.0):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout_sec
        self._id = 0

    # -- jwt ------------------------------------------------------------------

    def _jwt_token(self) -> str:
        header = base64.urlsafe_b64encode(json.dumps({"alg": "HS256", "typ": "JWT"}).encode()).rstrip(b"=")
        claims = base64.urlsafe_b64encode(json.dumps({"iat": int(time.time())}).encode()).rstrip(b"=")
        signing_input = header + b"." + claims
        sig = hmac.new(self.jwt_secret, signing_input, hashlib.sha256).digest()
        return (signing_input + b"." + base64.urlsafe_b64encode(sig).rstrip(b"=")).decode()

    def _post(self, body: dict) -> dict:
        req = urllib.request.Request(
            self.url,
            data=json.dumps(body).encode(),
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {self._jwt_token()}",
            },
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def _rpc_sync(self, method: str, params: list) -> dict:
        self._id += 1
        out = self._post({"jsonrpc": "2.0", "id": self._id, "method": method, "params": params})
        if "error" in out:
            raise RuntimeError(f"{method}: {out['error']}")
        return out["result"]

    async def _rpc(self, method: str, params: list) -> dict:
        """Blocking urllib transport stays off the event loop — a slow EL
        must only stall the awaiting caller, not the whole node."""
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(
            None, self._rpc_sync, method, params
        )

    # -- engine api -----------------------------------------------------------

    async def notify_new_payload(self, payload):
        from lodestar_tpu.ssz.json import to_json
        from lodestar_tpu.types import ssz_types

        t = ssz_types()
        result = await self._rpc("engine_newPayloadV1", [to_json(t.bellatrix.ExecutionPayload, payload)])
        status = ExecutePayloadStatus(result["status"])
        lvh = result.get("latestValidHash")
        return status, bytes.fromhex(lvh[2:]) if lvh else None

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash, payload_attributes=None
    ):
        state = {
            "headBlockHash": "0x" + bytes(head_block_hash).hex(),
            "safeBlockHash": "0x" + bytes(safe_block_hash).hex(),
            "finalizedBlockHash": "0x" + bytes(finalized_block_hash).hex(),
        }
        attrs = None
        if payload_attributes is not None:
            attrs = {
                "timestamp": hex(payload_attributes.timestamp),
                "prevRandao": "0x" + payload_attributes.prev_randao.hex(),
                "suggestedFeeRecipient": "0x" + payload_attributes.suggested_fee_recipient.hex(),
            }
        result = await self._rpc("engine_forkchoiceUpdatedV1", [state, attrs])
        return (result.get("payloadId")) if result else None

    async def get_payload(self, payload_id: str):
        return await self._rpc("engine_getPayloadV1", [payload_id])
