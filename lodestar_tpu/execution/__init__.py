"""Execution layer clients (reference `beacon-node/src/execution/`).

`ExecutionEngineHttp` speaks the Engine API over JSON-RPC with JWT auth
(`engine/http.ts:83`); `ExecutionEngineMock` is the in-memory EL that
ships in src/ so dev/sim runs need no external client
(`engine/mock.ts`). Both implement the same 3-method seam the block
pipeline consumes: notify_new_payload / notify_forkchoice_update /
get_payload.
"""

from .engine import (  # noqa: F401
    ExecutePayloadStatus,
    ExecutionEngineHttp,
    ExecutionEngineMock,
    IExecutionEngine,
    PayloadAttributes,
)
from .eth1 import Eth1ForBlockProductionDisabled, Eth1MemoryProvider  # noqa: F401
