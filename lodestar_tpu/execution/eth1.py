"""Eth1 data for block production.

Reference `eth1/eth1DepositDataTracker.ts:115` (getEth1DataAndDeposits:
deposit-log ingestion + eth1Data voting) and `eth1/index.ts:108`
(Eth1ForBlockProductionDisabled — the no-op provider dev nodes use).
`Eth1MemoryProvider` implements the voting rule over an in-memory block
feed: follow-distance window, majority vote continuation, deposit-count
monotonicity.
"""

from __future__ import annotations

from dataclasses import dataclass

from lodestar_tpu.types import ssz_types

__all__ = ["Eth1ForBlockProductionDisabled", "Eth1MemoryProvider", "Eth1Block"]


@dataclass(frozen=True)
class Eth1Block:
    number: int
    timestamp: int
    block_hash: bytes
    deposit_root: bytes
    deposit_count: int


class Eth1ForBlockProductionDisabled:
    """Reuse the state's existing eth1Data (reference `index.ts:108`)."""

    def get_eth1_data_and_deposits(self, state):
        return state.eth1_data, []


class Eth1MemoryProvider:
    """Voting over a fed eth1 chain (the tracker logic minus JSON-RPC).

    Deposit EVENTS must be fed too (`feed_deposit`): the STF requires a
    block to carry min(MAX_DEPOSITS, eth1_data.deposit_count -
    eth1_deposit_index) deposits, so the provider never votes a
    deposit_count beyond what it can actually serve — otherwise block
    production wedges on the deposit-count check
    (`state_transition/block.py` process_operations)."""

    def __init__(self, *, follow_distance_sec: int = 0, cfg=None):
        if cfg is not None:
            follow_distance_sec = cfg.SECONDS_PER_ETH1_BLOCK * cfg.ETH1_FOLLOW_DISTANCE
        self.follow_distance_sec = follow_distance_sec
        self.blocks: list[Eth1Block] = []
        self.deposits: dict[int, object] = {}  # deposit index -> Deposit (with proof)

    def feed_block(self, block: Eth1Block) -> None:
        if self.blocks and block.deposit_count < self.blocks[-1].deposit_count:
            raise ValueError("deposit count must be monotonic")
        self.blocks.append(block)

    def feed_deposit(self, index: int, deposit) -> None:
        self.deposits[index] = deposit

    def _servable_count(self, from_index: int) -> int:
        """Highest deposit_count we can prove contiguously from from_index."""
        count = from_index
        while count in self.deposits:
            count += 1
        return count

    def get_eth1_data_and_deposits(self, state, *, current_time: int | None = None):
        """Spec get_eth1_vote: among candidate blocks inside the follow-
        distance window, vote with the existing majority if any candidate
        matches, else the latest candidate; never decrease deposit_count
        and never exceed the servable deposit horizon."""
        t = ssz_types()
        if not self.blocks:
            return state.eth1_data, []
        now = current_time if current_time is not None else self.blocks[-1].timestamp
        servable = self._servable_count(state.eth1_deposit_index)
        candidates = [
            b
            for b in self.blocks
            if b.timestamp + self.follow_distance_sec <= now
            and state.eth1_data.deposit_count <= b.deposit_count <= servable
        ]
        if not candidates:
            return state.eth1_data, []

        def to_data(b: Eth1Block):
            d = t.Eth1Data.default()
            d.deposit_root = b.deposit_root
            d.deposit_count = b.deposit_count
            d.block_hash = b.block_hash
            return d

        # count existing votes among candidates
        cand_by_hash = {b.block_hash: b for b in candidates}
        tally: dict[bytes, int] = {}
        for vote in state.eth1_data_votes:
            h = bytes(vote.block_hash)
            if h in cand_by_hash:
                tally[h] = tally.get(h, 0) + 1
        if tally:
            best = max(tally.items(), key=lambda kv: (kv[1], cand_by_hash[kv[0]].number))[0]
            chosen = cand_by_hash[best]
        else:
            chosen = candidates[-1]
        deposits = [
            self.deposits[i]
            for i in range(state.eth1_deposit_index, chosen.deposit_count)
        ]
        return to_data(chosen), deposits
