"""Eth1 deposit tracking over JSON-RPC (reference
`eth1/eth1DepositDataTracker.ts:52`, `eth1/eth1MergeBlockTracker.ts`,
`eth1/provider/`).

Components:

* `Eth1JsonRpcProvider` — the thin JSON-RPC client (eth_blockNumber,
  eth_getBlockByNumber, eth_getLogs filtered on the DepositEvent topic).
* `DepositTree` — incremental depth-32 sparse merkle tree of DepositData
  roots with the length mix-in and branch extraction (the
  `@chainsafe/persistent-merkle-tree` role for deposits).
* `Eth1DepositDataTracker` — polls the provider, parses DepositEvent ABI
  logs into DepositData, maintains the deposits + eth1Data caches, and
  serves `get_eth1_data_and_deposits(state)`: spec eth1-data voting
  (follow distance + voting-period majority) and deposit inclusion with
  proofs against the state's eth1_data root.
* `Eth1MergeBlockTracker` — scans for the first block whose
  total_difficulty crosses TERMINAL_TOTAL_DIFFICULTY (bellatrix merge
  readiness; reference eth1MergeBlockTracker.ts).
* `MockEth1Node` — an in-process HTTP JSON-RPC execution-layer stub with
  a simulated deposit contract: `submit_deposit` appends a DepositEvent
  log and advances blocks, giving dev chains real deposit ingestion.

DepositEvent ABI layout (deposit contract): five dynamic `bytes` fields
(pubkey, withdrawal_credentials, amount[8 LE], signature, index[8 LE])
— parsed with plain offset arithmetic, no ABI library.
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.request

from lodestar_tpu.logger import get_logger
from lodestar_tpu.types import ssz_types

__all__ = [
    "DEPOSIT_EVENT_TOPIC",
    "DepositTree",
    "Eth1JsonRpcProvider",
    "Eth1DepositDataTracker",
    "Eth1MergeBlockTracker",
    "MockEth1Node",
    "encode_deposit_log_data",
    "parse_deposit_log",
]

# keccak256("DepositEvent(bytes,bytes,bytes,bytes,bytes)") — the fixed
# public topic of the deposit contract. Precomputed constant (no keccak
# dependency at runtime; pinned in tests against the known value).
DEPOSIT_EVENT_TOPIC = "0x649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0889aa790803be39038c5"

DEPOSIT_CONTRACT_TREE_DEPTH = 32


def _sha256(x: bytes) -> bytes:
    return hashlib.sha256(x).digest()


# --- incremental deposit tree -------------------------------------------------


class DepositTree:
    """Incremental sparse merkle tree of DepositData roots, depth 32 with
    uint64 length mix-in (spec get_deposit_root)."""

    def __init__(self) -> None:
        self._zeros = [b"\x00" * 32]
        for _ in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            self._zeros.append(_sha256(self._zeros[-1] + self._zeros[-1]))
        self._leaves: list[bytes] = []

    def push(self, leaf: bytes) -> None:
        self._leaves.append(bytes(leaf))

    def __len__(self) -> int:
        return len(self._leaves)

    def _layer(self, depth: int, count: int) -> list[bytes]:
        """Nodes of `depth` covering the first `count` leaves."""
        nodes = self._leaves[:count]
        for d in range(depth):
            if len(nodes) % 2:
                nodes.append(self._zeros[d])
            nodes = [_sha256(nodes[i] + nodes[i + 1]) for i in range(0, len(nodes), 2)]
        return nodes

    def root_at(self, count: int) -> bytes:
        """Deposit root with only the first `count` leaves (historic
        roots for eth1 voting)."""
        node = self._layer(DEPOSIT_CONTRACT_TREE_DEPTH, count)
        top = node[0] if node else self._zeros[DEPOSIT_CONTRACT_TREE_DEPTH]
        return _sha256(top + count.to_bytes(32, "little"))

    def root(self) -> bytes:
        return self.root_at(len(self._leaves))

    def proof(self, index: int, count: int) -> list[bytes]:
        """Branch for leaf `index` in the `count`-leaf tree, plus the
        length mix-in — the 33-element proof process_deposit verifies."""
        if not 0 <= index < count <= len(self._leaves):
            raise IndexError("deposit proof out of range")
        branch = []
        nodes = self._leaves[:count]
        idx = index
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if len(nodes) % 2:
                nodes.append(self._zeros[d])
            sibling = nodes[idx ^ 1]
            branch.append(sibling)
            nodes = [_sha256(nodes[i] + nodes[i + 1]) for i in range(0, len(nodes), 2)]
            idx //= 2
        branch.append(count.to_bytes(32, "little"))
        return branch


# --- DepositEvent ABI codec ---------------------------------------------------


def _abi_bytes(data: bytes) -> bytes:
    padded_len = (len(data) + 31) // 32 * 32
    return len(data).to_bytes(32, "big") + data.ljust(padded_len, b"\x00")


def encode_deposit_log_data(
    pubkey: bytes, withdrawal_credentials: bytes, amount_gwei: int, signature: bytes, index: int
) -> bytes:
    """ABI-encode the DepositEvent's five dynamic bytes fields."""
    fields = [
        pubkey,
        withdrawal_credentials,
        amount_gwei.to_bytes(8, "little"),
        signature,
        index.to_bytes(8, "little"),
    ]
    head = b""
    tail = b""
    offset = 32 * 5
    for f in fields:
        head += offset.to_bytes(32, "big")
        enc = _abi_bytes(f)
        tail += enc
        offset += len(enc)
    return head + tail


def parse_deposit_log(data: bytes) -> tuple[object, int]:
    """ABI log data -> (DepositData, deposit index)."""
    t = ssz_types()

    def read_bytes(field_i: int) -> bytes:
        offset = int.from_bytes(data[32 * field_i : 32 * field_i + 32], "big")
        ln = int.from_bytes(data[offset : offset + 32], "big")
        return data[offset + 32 : offset + 32 + ln]

    dd = t.DepositData.default()
    dd.pubkey = read_bytes(0)
    dd.withdrawal_credentials = read_bytes(1)
    dd.amount = int.from_bytes(read_bytes(2), "little")
    dd.signature = read_bytes(3)
    index = int.from_bytes(read_bytes(4), "little")
    return dd, index


# --- JSON-RPC provider --------------------------------------------------------


class Eth1JsonRpcProvider:
    def __init__(self, url: str, *, timeout_sec: float = 5.0):
        self.url = url
        self.timeout = timeout_sec
        self._id = 0

    def _call(self, method: str, params: list):
        self._id += 1
        payload = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        req = urllib.request.Request(
            self.url, data=payload, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            out = json.loads(r.read())
        if "error" in out:
            raise RuntimeError(f"eth1 rpc error: {out['error']}")
        return out["result"]

    def block_number(self) -> int:
        return int(self._call("eth_blockNumber", []), 16)

    def chain_id(self) -> int:
        return int(self._call("eth_chainId", []), 16)

    def get_block_by_number(self, number: int | str) -> dict | None:
        tag = hex(number) if isinstance(number, int) else number
        return self._call("eth_getBlockByNumber", [tag, False])

    def get_deposit_logs(self, from_block: int, to_block: int, address: str) -> list[dict]:
        return self._call(
            "eth_getLogs",
            [
                {
                    "fromBlock": hex(from_block),
                    "toBlock": hex(to_block),
                    "address": address,
                    "topics": [DEPOSIT_EVENT_TOPIC],
                }
            ],
        )


# --- deposit tracker ----------------------------------------------------------

MAX_BLOCKS_PER_LOG_QUERY = 1000


class Eth1DepositDataTracker:
    """Deposit-log ingestion + eth1Data voting + deposit inclusion
    (reference eth1DepositDataTracker.ts). Drive with `update()` (poll)
    from the node's slot loop or a background task."""

    def __init__(
        self,
        provider: Eth1JsonRpcProvider,
        *,
        deposit_contract_address: str,
        cfg=None,
        follow_distance_blocks: int = 16,
        seconds_per_eth1_block: int = 14,
    ):
        self.provider = provider
        self.address = deposit_contract_address
        self.cfg = cfg
        self.follow_distance = follow_distance_blocks
        self.seconds_per_eth1_block = seconds_per_eth1_block
        self.tree = DepositTree()
        self.deposits: list = []  # DepositData by index
        self.eth1_blocks: list[dict] = []  # {number, hash, timestamp, deposit_count, deposit_root}
        self._last_processed_block = -1
        self.log = get_logger(name="lodestar.eth1")

    # -- ingestion ------------------------------------------------------------

    def update(self) -> int:
        """Fetch new deposit logs + block metadata up to head-follow.
        Returns the number of new deposits ingested."""
        head = self.provider.block_number()
        target = head - self.follow_distance
        if target <= self._last_processed_block:
            return 0
        new = 0
        frm = self._last_processed_block + 1
        while frm <= target:
            to = min(frm + MAX_BLOCKS_PER_LOG_QUERY - 1, target)
            for log_entry in self.provider.get_deposit_logs(frm, to, self.address):
                data = bytes.fromhex(log_entry["data"][2:])
                dd, index = parse_deposit_log(data)
                if index != len(self.deposits):
                    raise RuntimeError(
                        f"non-consecutive deposit index {index} (have {len(self.deposits)})"
                    )
                t = ssz_types()
                self.deposits.append(dd)
                self.tree.push(t.DepositData.hash_tree_root(dd))
                new += 1
            frm = to + 1
        # block metadata for voting (batched head range; dev scale keeps
        # this simple — the reference dynamically adjusts batch sizes)
        for n in range(max(0, self._last_processed_block + 1), target + 1):
            blk = self.provider.get_block_by_number(n)
            if blk is None:
                continue
            self.eth1_blocks.append(
                {
                    "number": int(blk["number"], 16),
                    "hash": bytes.fromhex(blk["hash"][2:]),
                    "timestamp": int(blk["timestamp"], 16),
                    "deposit_count": len(self.deposits),
                    "deposit_root": self.tree.root_at(len(self.deposits)),
                }
            )
        self._last_processed_block = target
        return new

    # -- voting + inclusion (spec get_eth1_vote / getEth1DataAndDeposits) ------

    def _votes_to_consider(self, state) -> list[dict]:
        from lodestar_tpu.params import active_preset

        pr = active_preset()
        period_start = self._voting_period_start_time(state, pr)
        follow_sec = self.follow_distance * self.seconds_per_eth1_block
        return [
            b
            for b in self.eth1_blocks
            if period_start - 2 * follow_sec <= b["timestamp"] <= period_start - follow_sec
            and b["deposit_count"] >= int(state.eth1_data.deposit_count)
        ]

    def _voting_period_start_time(self, state, pr) -> int:
        seconds_per_slot = self.cfg.SECONDS_PER_SLOT if self.cfg else 12
        period_slots = pr.EPOCHS_PER_ETH1_VOTING_PERIOD * pr.SLOTS_PER_EPOCH
        start_slot = int(state.slot) - int(state.slot) % period_slots
        return int(state.genesis_time) + start_slot * seconds_per_slot

    def get_eth1_data_and_deposits(self, state):
        """(eth1_data vote, deposits for inclusion) — the produce-block
        seam (reference IEth1ForBlockProduction)."""
        t = ssz_types()
        votes = self._votes_to_consider(state)
        if votes:
            # majority among existing state votes restricted to valid
            # candidates, else the most recent candidate
            counts: dict[bytes, int] = {}
            by_hash = {v["hash"]: v for v in votes}
            for vote in state.eth1_data_votes:
                h = bytes(vote.block_hash)
                if h in by_hash:
                    counts[h] = counts.get(h, 0) + 1
            if counts:
                best = max(counts.items(), key=lambda kv: kv[1])[0]
                chosen = by_hash[best]
            else:
                chosen = max(votes, key=lambda b: b["number"])
            eth1_data = t.Eth1Data.default()
            eth1_data.deposit_root = chosen["deposit_root"]
            eth1_data.deposit_count = chosen["deposit_count"]
            eth1_data.block_hash = chosen["hash"]
        else:
            eth1_data = state.eth1_data

        deposits = self._deposits_for_inclusion(state, eth1_data)
        return eth1_data, deposits

    def _deposits_for_inclusion(self, state, eth1_data) -> list:
        from lodestar_tpu.params import active_preset

        pr = active_preset()
        t = ssz_types()
        # if the vote would win this block, deposits verify against ITS
        # root; conservatively include only up to the CURRENT state's
        # eth1_data (the reference does the same: deposits are proven
        # against state.eth1_data at processing time)
        count = int(state.eth1_data.deposit_count)
        start = int(state.eth1_deposit_index)
        if start >= count or start >= len(self.deposits):
            return []
        n = min(count - start, pr.MAX_DEPOSITS, len(self.deposits) - start)
        out = []
        for i in range(start, start + n):
            dep = t.Deposit.default()
            dep.proof = self.tree.proof(i, count)
            dep.data = self.deposits[i]
            out.append(dep)
        return out


# --- merge block tracker ------------------------------------------------------


class Eth1MergeBlockTracker:
    """Find the terminal PoW block: first block with
    total_difficulty >= TTD whose parent is below (reference
    eth1MergeBlockTracker.ts getTerminalPowBlock)."""

    def __init__(self, provider: Eth1JsonRpcProvider, *, ttd: int):
        self.provider = provider
        self.ttd = ttd
        self._terminal: dict | None = None

    def get_terminal_pow_block(self) -> dict | None:
        if self._terminal is not None:
            return self._terminal
        head = self.provider.block_number()
        # walk back from head to find the crossing block
        candidate = None
        for n in range(head, -1, -1):
            blk = self.provider.get_block_by_number(n)
            if blk is None:
                break
            td = int(blk.get("totalDifficulty", "0x0"), 16)
            if td >= self.ttd:
                candidate = blk
            else:
                break
        if candidate is not None:
            self._terminal = {
                "block_hash": bytes.fromhex(candidate["hash"][2:]),
                "number": int(candidate["number"], 16),
                "total_difficulty": int(candidate.get("totalDifficulty", "0x0"), 16),
            }
        return self._terminal


# --- mock execution layer -----------------------------------------------------


class MockEth1Node:
    """In-process HTTP JSON-RPC EL with a simulated deposit contract.

    `submit_deposit(DepositData)` mines a block carrying the
    DepositEvent log; `mine_blocks(n)` advances empty blocks (so the
    follow distance can be satisfied in tests/dev chains)."""

    CONTRACT = "0x" + "42" * 20

    def __init__(self, *, start_difficulty_per_block: int = 1):
        self._blocks: list[dict] = []
        self._logs: list[dict] = []  # {blockNumber, data}
        self._deposit_count = 0
        self._difficulty = start_difficulty_per_block
        self._httpd = None
        self._thread = None
        self.port = 0
        self._lock = threading.Lock()
        self._mine(b"")  # genesis

    # -- chain building --------------------------------------------------------

    def _mine(self, extra: bytes) -> dict:
        n = len(self._blocks)
        prev_td = self._blocks[-1]["td"] if self._blocks else 0
        h = _sha256(b"mock-eth1" + n.to_bytes(8, "big") + extra)
        blk = {
            "number": n,
            "hash": h,
            "timestamp": 1_600_000_000 + n * 14,
            "td": prev_td + self._difficulty,
        }
        self._blocks.append(blk)
        return blk

    def mine_blocks(self, n: int) -> None:
        with self._lock:
            for _ in range(n):
                self._mine(b"")

    def submit_deposit(self, deposit_data) -> int:
        """Append a DepositEvent in a fresh block; returns the index."""
        t = ssz_types()
        with self._lock:
            index = self._deposit_count
            self._deposit_count += 1
            data = encode_deposit_log_data(
                bytes(deposit_data.pubkey),
                bytes(deposit_data.withdrawal_credentials),
                int(deposit_data.amount),
                bytes(deposit_data.signature),
                index,
            )
            blk = self._mine(data)
            self._logs.append({"blockNumber": blk["number"], "data": data})
            return index

    # -- JSON-RPC server -------------------------------------------------------

    def _rpc(self, method: str, params: list):
        with self._lock:
            if method == "eth_blockNumber":
                return hex(len(self._blocks) - 1)
            if method == "eth_chainId":
                return "0x1"
            if method == "eth_getBlockByNumber":
                tag = params[0]
                if tag in ("latest", "pending"):
                    n = len(self._blocks) - 1
                else:
                    n = int(tag, 16)
                if not 0 <= n < len(self._blocks):
                    return None
                b = self._blocks[n]
                return {
                    "number": hex(b["number"]),
                    "hash": "0x" + b["hash"].hex(),
                    "parentHash": "0x"
                    + (self._blocks[n - 1]["hash"].hex() if n else "00" * 32),
                    "timestamp": hex(b["timestamp"]),
                    "totalDifficulty": hex(b["td"]),
                }
            if method == "eth_getLogs":
                flt = params[0]
                frm = int(flt["fromBlock"], 16)
                to = int(flt["toBlock"], 16)
                if flt.get("topics") and flt["topics"][0] != DEPOSIT_EVENT_TOPIC:
                    return []
                return [
                    {
                        "blockNumber": hex(lg["blockNumber"]),
                        "data": "0x" + lg["data"].hex(),
                        "topics": [DEPOSIT_EVENT_TOPIC],
                        "address": self.CONTRACT,
                    }
                    for lg in self._logs
                    if frm <= lg["blockNumber"] <= to
                ]
            raise ValueError(f"mock eth1: unsupported method {method}")

    def start(self) -> None:
        import http.server

        node = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(length))
                try:
                    result = node._rpc(req["method"], req.get("params", []))
                    payload = {"jsonrpc": "2.0", "id": req.get("id"), "result": result}
                except Exception as e:  # mock-level error frame
                    payload = {
                        "jsonrpc": "2.0",
                        "id": req.get("id"),
                        "error": {"code": -32601, "message": str(e)},
                    }
                raw = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def log_message(self, *a):  # quiet
                pass

        import socketserver

        class Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True

        self._httpd = Server(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
