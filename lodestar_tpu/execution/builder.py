"""MEV-boost builder API client (blinded block flow).

Reference `beacon-node/src/execution/builder/http.ts:30`
(ExecutionBuilderHttp): registerValidator / getHeader /
submitBlindedBlock over the builder REST API, with the spec'd
circuit-breaker — the builder is disabled when more than
`allowed_faults` of the last `fault_inspection_window` slots missed
blocks, re-enabled once the window clears.

Transport is a pluggable callable `transport(method, path, json_body)
-> dict` so tests (and the zero-egress environment) inject fakes; a
urllib transport is provided for real deployments.
"""

from __future__ import annotations

import json as _json
import random
from typing import Callable

from lodestar_tpu.logger import get_logger
from lodestar_tpu.params import BeaconPreset, active_preset
from lodestar_tpu.ssz.json import from_json, to_json
from lodestar_tpu.types import ssz_types

__all__ = ["ExecutionBuilderHttp", "BuilderError", "http_transport"]


class BuilderError(Exception):
    pass


def http_transport(base_url: str, timeout: float = 12.0) -> Callable:
    """urllib JSON transport (reference getClient baseUrl binding)."""
    import urllib.request

    def transport(method: str, path: str, body=None):
        req = urllib.request.Request(
            base_url.rstrip("/") + path,
            method=method,
            data=None if body is None else _json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:  # noqa: S310
            data = resp.read()
            return _json.loads(data) if data else None

    return transport


class ExecutionBuilderHttp:
    """Builder circuit-breaker state machine + the three endpoints
    (reference http.ts: updateStatus/checkStatus/registerValidator/
    getHeader/submitBlindedBlock)."""

    def __init__(
        self,
        transport: Callable,
        p: BeaconPreset | None = None,
        cfg=None,
        *,
        fault_inspection_window: int | None = None,
        allowed_faults: int | None = None,
        rand_fn=random.randint,
    ) -> None:
        self.transport = transport
        self.p = p or active_preset()
        self.cfg = cfg
        self.log = get_logger(name="lodestar.builder")
        self.status = False  # enabled only via update_status (reference :74)
        spe = self.p.SLOTS_PER_EPOCH
        # randomized per boot within the spec'd ranges (reference :55-70)
        window = fault_inspection_window
        if window is None:
            window = spe + rand_fn(0, spe)
        self.fault_inspection_window = max(window, spe)
        cap = self.fault_inspection_window // 2
        self.allowed_faults = min(allowed_faults if allowed_faults is not None else cap, cap)
        self._faults: list[int] = []  # slots with missed builder blocks

    # -- circuit breaker -------------------------------------------------------

    def update_status(self, should_enable: bool) -> None:
        self.status = should_enable

    def check_status(self) -> None:
        """Probe /eth/v1/builder/status; a failure disables the builder
        until the next explicit update_status(True)."""
        try:
            self.transport("GET", "/eth/v1/builder/status")
        except Exception as e:
            if self.status:
                self.log.warn("builder status check failed, disabling", {"error": str(e)})
            self.status = False

    def register_fault(self, slot: int) -> None:
        """A slot whose builder block was missed/failed."""
        self._faults.append(int(slot))
        self._gc_faults(int(slot))

    def _gc_faults(self, current_slot: int) -> None:
        floor = current_slot - self.fault_inspection_window
        self._faults = [s for s in self._faults if s > floor]

    def is_circuit_broken(self, current_slot: int) -> bool:
        self._gc_faults(int(current_slot))
        return len(self._faults) > self.allowed_faults

    # -- endpoints -------------------------------------------------------------

    def register_validator(self, signed_registrations: list) -> None:
        t = ssz_types(self.p)
        body = [
            to_json(t.SignedValidatorRegistrationV1, r) for r in signed_registrations
        ]
        self.transport("POST", "/eth/v1/builder/validators", body)

    def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes, fork: str = "capella"):
        """SignedBuilderBid for (slot, parent, proposer) or None when the
        builder has no bid (204)."""
        path = (
            f"/eth/v1/builder/header/{int(slot)}/0x{bytes(parent_hash).hex()}"
            f"/0x{bytes(pubkey).hex()}"
        )
        res = self.transport("GET", path)
        if res is None:
            return None
        if "data" not in res:
            raise BuilderError(f"builder header response missing data: {res!r}")
        t = ssz_types(self.p)
        bid_type = getattr(t, fork).SignedBuilderBid
        return from_json(bid_type, res["data"])

    def submit_blinded_block(self, signed_blinded_block, fork: str = "capella"):
        """SignedBlindedBeaconBlock -> the unblinded ExecutionPayload
        (reference submitBlindedBlock)."""
        t = ssz_types(self.p)
        blinded_type = getattr(t, fork).SignedBlindedBeaconBlock
        res = self.transport(
            "POST", "/eth/v1/builder/blinded_blocks", to_json(blinded_type, signed_blinded_block)
        )
        if res is None or "data" not in res:
            raise BuilderError("builder returned no payload for blinded block")
        payload_type = getattr(t, fork).ExecutionPayload
        payload = from_json(payload_type, res["data"])
        # the unblinded payload MUST match the header the proposer signed
        header = signed_blinded_block.message.body.execution_payload_header
        if bytes(payload.block_hash) != bytes(header.block_hash):
            raise BuilderError("unblinded payload block_hash != signed header block_hash")
        return payload
