"""Wait-budget profiler: render the per-class latency decomposition
behind `GET /eth/v0/debug/slo` as an operator-readable table.

The SLO accountant (lodestar_tpu/slo) partitions every verification
job's added→verdict wall time into four telescoping monotonic legs —

    buffer  (added → batch-former flush)
    queue   (flush → scheduler dequeue)
    stage   (dequeue → device launch)
    launch  (launch → verdict)

— so the legs SUM to the measured end-to-end by construction, and the
profile answers "which leg is eating the slot budget" per priority
class, next to the remaining-slack distribution and the SLI good/total
pair.

Sources (exactly one):

  --url http://127.0.0.1:9596   fetch the live node's debug route
  --in dump.json                a saved response (or its "data" value)

Options: --out FILE writes the raw decomposition JSON next to the
table (for diffing two runs); exit status is nonzero when any class's
leg sum disagrees with the measured end-to-end mean by more than
--tolerance (default 10%) — the accountant's partition invariant,
checkable from the outside.

Stdlib-only (urllib), same doctrine as the module it profiles.
"""

import argparse
import json
import sys
import urllib.request

LEGS = ("buffer", "queue", "stage", "launch")


def fetch(url: str) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/eth/v0/debug/slo", timeout=10) as r:
        return json.loads(r.read().decode("utf-8"))


def load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _fmt_ms(v) -> str:
    return f"{v:9.3f}" if isinstance(v, (int, float)) else f"{'-':>9}"


def render(budget: dict, tolerance: float) -> tuple[str, list]:
    """(table text, list of classes violating the partition tolerance)."""
    lines = []
    violations = []
    if not budget.get("enabled"):
        lines.append("SLO accounting is disabled on this node (--slo-disable,")
        lines.append("or no genesis time yet) — no decomposition to profile.")
        return "\n".join(lines) + "\n", violations
    dm = budget.get("deadline_model") or {}
    lines.append(
        "deadline model: genesis={g} seconds_per_slot={s} slack_floor={f}ms".format(
            g=dm.get("genesis_time"), s=dm.get("seconds_per_slot"),
            f=budget.get("slack_floor_ms"),
        )
    )
    classes = budget.get("classes") or {}
    if not classes:
        lines.append("no verification jobs observed yet")
        return "\n".join(lines) + "\n", violations
    hdr = f"{'class':<20}{'leg':<8}{'p50 ms':>9}{'p90 ms':>9}{'p99 ms':>9}{'mean ms':>9}{'n':>7}"
    for cls in sorted(classes):
        c = classes[cls]
        lines.append("")
        lines.append(hdr)
        for leg in LEGS:
            q = (c.get("legs") or {}).get(leg) or {}
            lines.append(
                f"{cls:<20}{leg:<8}"
                f"{_fmt_ms(q.get('p50_ms'))}{_fmt_ms(q.get('p90_ms'))}"
                f"{_fmt_ms(q.get('p99_ms'))}{_fmt_ms(q.get('mean_ms'))}"
                f"{q.get('count', 0):>7}"
            )
        e2e = c.get("end_to_end") or {}
        lines.append(
            f"{cls:<20}{'e2e':<8}"
            f"{_fmt_ms(e2e.get('p50_ms'))}{_fmt_ms(e2e.get('p90_ms'))}"
            f"{_fmt_ms(e2e.get('p99_ms'))}{_fmt_ms(e2e.get('mean_ms'))}"
            f"{e2e.get('count', 0):>7}"
        )
        # recompute the sum from the per-leg means — trusting the
        # server's leg_sum_mean_ms would make the partition check a
        # tautology, not an outside verification
        leg_means = [((c.get("legs") or {}).get(leg) or {}).get("mean_ms") for leg in LEGS]
        if all(isinstance(v, (int, float)) for v in leg_means):
            leg_sum = sum(leg_means)
        else:
            leg_sum = c.get("leg_sum_mean_ms")
        e2e_mean = e2e.get("mean_ms")
        if isinstance(leg_sum, (int, float)) and isinstance(e2e_mean, (int, float)):
            drift = abs(leg_sum - e2e_mean) / e2e_mean if e2e_mean else 0.0
            flag = ""
            if drift > tolerance:
                violations.append(cls)
                flag = f"  << legs do not partition e2e (>{tolerance:.0%})"
            lines.append(
                f"{cls:<20}{'sum':<8}{'':>27}{_fmt_ms(leg_sum)}"
                f"{'':>7}  (drift {drift:.1%}){flag}"
            )
        sli = c.get("sli") or {}
        lines.append(
            f"{cls:<20}sli     good={sli.get('good', 0)} "
            f"total={sli.get('total', 0)} miss={sli.get('miss', 0)}"
        )
    return "\n".join(lines) + "\n", violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="beacon REST base, e.g. http://127.0.0.1:9596")
    src.add_argument("--in", dest="infile", help="saved /eth/v0/debug/slo response")
    ap.add_argument("--out", help="write the raw decomposition JSON here")
    ap.add_argument(
        "--tolerance", type=float, default=0.10,
        help="max |leg sum - e2e mean| / e2e mean before nonzero exit (0.10)",
    )
    args = ap.parse_args(argv)

    doc = fetch(args.url) if args.url else load(args.infile)
    budget = doc.get("data", doc)  # accept the route envelope or the bare value
    text, violations = render(budget, args.tolerance)
    sys.stdout.write(text)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(budget, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
