"""Seeded chaos experiments over the in-process fleet harness.

Drives `lodestar_tpu.testing.fleet` — N beacon-node verification
stacks against M offload hosts with per-edge fault injectors — through
the named scenario matrix (partition_storm, lying_helper,
latency_ramp, chip_wedge, tenant_flood, plus the tier-1 smoke), checks
the fleet invariants after every run, and exits nonzero on any
violation:

* zero wrong verdicts, ever, under every fault class;
* block import stays alive within the slot deadline under a full
  offload partition (CPU fallback, not an error);
* SLI misses are counted exactly once per job (ledger-reconciled).

Modes::

    python tools/chaos_experiment.py --scenario smoke --seed 7
    python tools/chaos_experiment.py --matrix --seed 7
    python tools/chaos_experiment.py --sweep hedge_delay_ms=10,30,120 \
        --scenario latency_ramp --seeds 3 --write-tuning

``--sweep knob=v1,v2,...`` re-runs one scenario with each candidate
value of one `FleetConfig` field across ``--seeds`` seeds and scores
candidates lexicographically: invariant violations (must be zero),
then degraded-throughput retention (higher), then SLI misses (lower),
then recovery slots (lower), then mean verdict latency (lower). List
the shipped default as the FIRST candidate — a full tie keeps it, so
a TUNING.md row only moves off the shipped value when a candidate
measurably beats it. ``--write-tuning`` records the winner in
``TUNING.md`` with a stable experiment ID (``exp-<scenario>-<knob>``)
so every tuned constant in the tree carries provenance — the
``tuning-provenance`` analysis rule statically checks that each
constant named there still exists where the table says it lives.

Bench wiring: every run emits the two chaos trajectory lines below via
``_line`` (the same JSON-lines shape the baseline bench uses), so
``tools/bench_trajectory.py`` gates them round-over-round and the
``bench-wiring`` rule cross-checks the names against ``THRESHOLDS``.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lodestar_tpu.testing.fleet import (  # noqa: E402
    SCENARIOS,
    build_scenario,
    check_invariants,
    run_fleet,
)

TUNING_PATH = os.path.join(REPO, "TUNING.md")

#: sweepable FleetConfig knobs that shadow a shipped constant — the
#: mapping the TUNING.md provenance rows are written from. Knobs not
#: listed here still sweep fine; they just cannot --write-tuning.
KNOB_CONSTANTS: dict[str, tuple[str, str]] = {
    "hedge_delay_ms": ("DEFAULT_HEDGE_DELAY_MS", "lodestar_tpu/offload/resilience.py"),
    "tenant_quota_depth": ("DEFAULT_TENANT_SHED_DEPTH", "lodestar_tpu/offload/tenancy.py"),
    "audit_rate": ("DEFAULT_AUDIT_RATE", "lodestar_tpu/offload/audit.py"),
    "timeout_s": ("DEFAULT_TIMEOUT_S", "lodestar_tpu/offload/client.py"),
}


def _line(metric: str, value, **extra) -> None:
    """One JSON bench line on stdout (same shape bench.py emits)."""
    doc = {"metric": metric, "value": value}
    doc.update(extra)
    print(json.dumps(doc), flush=True)


def _parse_value(text: str):
    """A sweep candidate: int, float, none/null, or bare string."""
    t = text.strip()
    if t.lower() in ("none", "null"):
        return None
    for cast in (int, float):
        try:
            return cast(t)
        except ValueError:
            continue
    return t


def _run_one(name: str, seed: int, **overrides):
    """(result, violations) for one seeded scenario run."""
    cfg = build_scenario(name, seed=seed, **overrides)
    result = run_fleet(cfg)
    return result, check_invariants(result)


def _print_summary_table(rows: list[dict]) -> None:
    cols = [
        "scenario", "seed", "total_jobs", "wrong_verdicts", "sli_misses",
        "throughput_retention_pct", "recovery_slots", "mean_latency_ms",
        "hedges", "hedge_wins", "failovers", "sheds", "byzantine_events",
        "violations",
    ]
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))


def _summary_row(name: str, seed: int, result, violations: list[str]) -> dict:
    s = dict(result.summary)
    s["scenario"] = name
    s["seed"] = seed
    s["violations"] = len(violations)
    return s


def _emit_chaos_lines(rows: list[dict]) -> None:
    """The two gated trajectory lines, aggregated worst-case over the
    runs just made: retention takes the MIN (the weakest degraded
    scenario is the one the gate must hold), recovery the MAX."""
    retention = min(float(r["throughput_retention_pct"]) for r in rows)
    recovery = max(int(r["recovery_slots"]) for r in rows)
    scenarios = ",".join(sorted({r["scenario"] for r in rows}))
    _line("chaos_degraded_throughput_retention_pct", retention, scenarios=scenarios)
    _line("chaos_recovery_slots", recovery, scenarios=scenarios)


# -- TUNING.md provenance ------------------------------------------------------

_ROW_RE = re.compile(r"^\|\s*`(?P<constant>[A-Za-z_][A-Za-z0-9_]*)`\s*\|")


def write_tuning_row(
    path: str,
    constant: str,
    value,
    defined_in: str,
    experiment: str,
    scenario: str,
    seeds: list[int],
    metric: str,
) -> None:
    """Insert or replace the provenance row for `constant` in the
    TUNING.md table (rows are keyed by constant name)."""
    row = (
        f"| `{constant}` | {value} | `{defined_in}` | {experiment} "
        f"| {scenario} | {','.join(str(s) for s in seeds)} | {metric} |"
    )
    with open(path) as f:
        lines = f.read().splitlines()
    replaced = False
    for i, ln in enumerate(lines):
        m = _ROW_RE.match(ln)
        if m and m.group("constant") == constant:
            lines[i] = row
            replaced = True
            break
    if not replaced:
        # append after the last table row (the file always ends with
        # the provenance table; see TUNING.md schema section)
        last = max(i for i, ln in enumerate(lines) if ln.startswith("|"))
        lines.insert(last + 1, row)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"TUNING.md: recorded {constant} = {value} ({experiment})")


# -- modes ---------------------------------------------------------------------

def run_matrix(names: list[str], seed: int) -> int:
    rows = []
    all_violations: list[str] = []
    for name in names:
        result, violations = _run_one(name, seed)
        rows.append(_summary_row(name, seed, result, violations))
        for v in violations:
            all_violations.append(f"{name}: {v}")
    _print_summary_table(rows)
    _emit_chaos_lines(rows)
    for v in all_violations:
        print(f"INVARIANT VIOLATION: {v}", file=sys.stderr)
    if all_violations:
        print(f"FAIL: {len(all_violations)} invariant violation(s)", file=sys.stderr)
        return 1
    print(f"ok: {len(rows)} scenario run(s), all invariants held")
    return 0


def run_sweep(
    knob: str,
    candidates: list,
    scenario: str,
    seeds: list[int],
    write_tuning: bool,
) -> int:
    rows = []
    scored = []
    for value in candidates:
        per_seed = []
        for seed in seeds:
            result, violations = _run_one(scenario, seed, **{knob: value})
            row = _summary_row(scenario, seed, result, violations)
            row["candidate"] = value
            rows.append(row)
            per_seed.append(row)
        score = (
            sum(r["violations"] for r in per_seed),
            -min(float(r["throughput_retention_pct"]) for r in per_seed),
            sum(int(r["sli_misses"]) for r in per_seed),
            max(int(r["recovery_slots"]) for r in per_seed),
            # final tie-break: mean verdict latency (real-time scenarios
            # — hedge_race — separate here; virtual-time runs tie at the
            # injected costs and fall through unchanged)
            round(sum(float(r["mean_latency_ms"]) for r in per_seed), 3),
        )
        scored.append((score, value, per_seed))
    _print_summary_table(rows)
    _emit_chaos_lines(rows)

    scored.sort(key=lambda t: t[0])
    best_score, best_value, best_rows = scored[0]
    experiment = f"exp-{scenario}-{knob}"
    print(
        f"winner: {knob}={best_value} "
        f"(violations={best_score[0]}, retention={-best_score[1]:.1f}%, "
        f"sli_misses={best_score[2]}, recovery_slots={best_score[3]}, "
        f"mean_latency_ms={best_score[4]}) [{experiment}]"
    )
    if best_score[0]:
        print("FAIL: even the winning candidate violated invariants", file=sys.stderr)
        return 1
    if write_tuning:
        if knob not in KNOB_CONSTANTS:
            print(
                f"error: knob '{knob}' has no constant mapping; cannot "
                "--write-tuning (add it to KNOB_CONSTANTS)",
                file=sys.stderr,
            )
            return 2
        constant, defined_in = KNOB_CONSTANTS[knob]
        write_tuning_row(
            TUNING_PATH,
            constant,
            best_value,
            defined_in,
            experiment,
            scenario,
            seeds,
            metric=f"retention={-best_score[1]:.1f}%",
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos-experiment",
        description="seeded fleet chaos scenarios: invariants, sweeps, "
        "and TUNING.md provenance",
    )
    ap.add_argument("--scenario", default=None, choices=sorted(SCENARIOS),
                    help="run one named scenario (default with --sweep: the sweep's scenario)")
    ap.add_argument("--matrix", action="store_true",
                    help="run the full scenario matrix")
    ap.add_argument("--seed", type=int, default=0, help="base seed (default 0)")
    ap.add_argument("--seeds", type=int, default=1, metavar="N",
                    help="number of consecutive seeds per sweep candidate")
    ap.add_argument("--sweep", default=None, metavar="KNOB=V1,V2,...",
                    help="sweep one FleetConfig field over candidate values")
    ap.add_argument("--write-tuning", action="store_true",
                    help="record the sweep winner in TUNING.md with its experiment ID")
    args = ap.parse_args(argv)

    if args.sweep is not None:
        if "=" not in args.sweep:
            ap.error("--sweep wants KNOB=V1,V2,...")
        knob, _, raw = args.sweep.partition("=")
        knob = knob.strip()
        candidates = [_parse_value(v) for v in raw.split(",") if v.strip()]
        if not candidates:
            ap.error("--sweep carried no candidate values")
        # hedge tuning defaults to the real-time race arm: a wall-clock
        # hedge timer cannot race virtually-injected latency
        scenario = args.scenario or (
            "hedge_race" if knob == "hedge_delay_ms" else "latency_ramp"
        )
        seeds = [args.seed + i for i in range(max(1, args.seeds))]
        return run_sweep(knob, candidates, scenario, seeds, args.write_tuning)

    if args.matrix:
        names = sorted(SCENARIOS)
    elif args.scenario:
        names = [args.scenario]
    else:
        ap.error("pick a mode: --scenario NAME, --matrix, or --sweep")
    return run_matrix(names, args.seed)


if __name__ == "__main__":
    sys.exit(main())
