"""Resumable bench trajectory with per-line regression gates.

The BENCH_rNN.json trajectory stalled at r5 with no tooling to resume
or gate it: every round was a hand-run of `bench.py` pasted into a
file, and nothing failed when a line regressed. This tool is the
missing loop:

1. Run `tools/baseline_configs_bench.py` (``--quick`` by default on
   this container; pass ``--full`` on a chip host) — or consume an
   existing run's output via ``--from-log`` (the chip run prints the
   lines once; gating must not require a rerun).
2. Write the next ``BENCH_rNN.json`` (N = highest existing + 1) in a
   JSON-lines-carrying shape: ``{"n", "cmd", "rc", "label", "lines"}``.
   The label records WHAT the numbers mean — CPU-container lines
   validate schedule shape, not chip throughput, and must say so.
3. Diff every line against the previous round under the per-line
   thresholds below and **exit nonzero on regression** — the perf CI
   gate. Rounds r1–r5 carry a single ``parsed`` metric
   (``bls_batch_verify_sigs_per_sec``); the diff runs over the metric
   intersection, so the old shape chains into the new one.
4. Regenerate the dashboards (`tools/gen_dashboards.py`) so the
   device-launches dashboard's trajectory panel picks up the round.

``--compare PRIOR CURRENT`` runs ONLY the gate over two existing
round files (exit 0 clean / 1 regression) — the mode CI and the
regression-gate tests drive.

The metric names in ``THRESHOLDS`` are statically checked two-way
against what ``baseline_configs_bench.py`` / ``bench.py`` actually
report by the ``bench-wiring`` analysis rule (tools/analysis): a
renamed bench line without a threshold — or a threshold gating a line
nobody emits — fails the tier-1 gate, not the next chip run.

Run from the repo root: python tools/bench_trajectory.py [--quick|--full]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: metric -> max tolerated fractional regression vs the prior round.
#: Throughput lines carry 0.5 (the CPU container's scheduler noise is
#: real; a chip host can tighten these); the launch-budget lines are
#: near-deterministic schedule invariants and carry 0.05 — a fused
#: schedule quietly growing a fourth launch IS the regression this
#: gate exists for.
THRESHOLDS: dict[str, float] = {
    "host_prep_sets_per_sec_single_core": 0.5,
    "device_prep_sets_per_sec": 0.5,
    "prep_launches_per_set": 0.05,
    "prep_launches_per_set_unfused": 0.05,
    # single-launch dispatch budget: 1 program per verified batch vs the
    # 3+verify split reference — a schedule invariant, gated tight (a
    # fused chain quietly growing a second launch IS the regression)
    "e2e_launches_per_batch": 0.05,
    "e2e_launches_per_batch_split": 0.05,
    "single_launch_replay_sigs_per_sec": 0.5,
    "merkle_sha256_pair_hashes_per_sec": 0.5,
    "state_htr_chunks_per_sec": 0.5,
    "epoch_htr_ms_device": 0.75,
    "epoch_htr_ms_cpu": 0.75,
    "backfill_window_e2e_sigs_per_sec_1core_host": 0.5,
    "backfill_window_device_sigs_per_sec": 0.5,
    "gossip_replay_sigs_per_sec": 0.5,
    "gossip_replay_sigs_per_sec_device_prep": 0.5,
    "pipelined_gossip_replay_sigs_per_sec": 0.5,
    "prep_verify_overlap_occupancy_pct": 0.75,
    "sync_committee_fast_aggregate_verifies_per_sec": 0.5,
    "mesh_sigs_per_sec_1dev": 0.5,
    "mesh_sigs_per_sec_2dev": 0.5,
    "mesh_sigs_per_sec_4dev": 0.5,
    "mesh_sigs_per_sec_8dev": 0.5,
    # lower-better with a tiny, noisy prior (3.2 on a 10-point
    # envelope): tolerate up to 3x before gating
    "two_tenant_fairness_share_error_pct": 3.0,
    # bench.py's config-1 headline — the single metric rounds r1–r5
    # carry, kept so the old trajectory chains into this gate
    "bls_batch_verify_sigs_per_sec": 0.5,
    # chaos harness lines (tools/chaos_experiment.py): worst-case
    # degraded-throughput retention across the scenario matrix, and
    # slots-to-recovery after the last heal. Retention regressing past
    # 25% of prior means a fault class started starving the pipeline;
    # recovery_slots has a 0 prior, so the lower-is-better zero-prior
    # branch gates it absolutely (anything past 2 slots fails).
    "chaos_degraded_throughput_retention_pct": 0.25,
    "chaos_recovery_slots": 2.0,
}

#: metrics where a LARGER value is the regression (latency, error pct,
#: launches-per-set); everything else is higher-is-better throughput
LOWER_IS_BETTER: set = {
    "epoch_htr_ms_device",
    "epoch_htr_ms_cpu",
    "two_tenant_fairness_share_error_pct",
    "prep_launches_per_set",
    "prep_launches_per_set_unfused",
    "e2e_launches_per_batch",
    "e2e_launches_per_batch_split",
    "chaos_recovery_slots",
}

#: fallback for a metric a newer bench emits before its threshold
#: lands (the bench-wiring rule flags the gap; the gate stays usable
#: on the chip host in the meantime)
DEFAULT_THRESHOLD = 0.5

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def parse_bench_lines(text: str) -> list[dict]:
    """The JSON lines with a "metric" key out of a bench run's stdout
    (warnings, notes, and compiler chatter interleave freely)."""
    lines = []
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            doc = json.loads(raw)
        except ValueError:
            continue
        if isinstance(doc, dict) and "metric" in doc and "value" in doc:
            lines.append(doc)
    return lines


def round_files(repo: str = REPO) -> list[tuple[int, str]]:
    out = []
    for name in os.listdir(repo):
        m = _ROUND_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(repo, name)))
    return sorted(out)


def load_round_metrics(path: str) -> dict[str, dict]:
    """metric -> line for one round file; understands both the r1–r5
    single-``parsed`` shape and the r6+ ``lines`` shape."""
    with open(path) as f:
        doc = json.load(f)
    out: dict[str, dict] = {}
    for line in doc.get("lines") or []:
        if isinstance(line, dict) and "metric" in line:
            out[line["metric"]] = line
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        out.setdefault(parsed["metric"], parsed)
    return out


def compare_rounds(
    prior: dict[str, dict], current: dict[str, dict]
) -> tuple[list[dict], list[str]]:
    """(regressions, notes) for the metric intersection. A regression
    is a fractional move past the metric's threshold in its bad
    direction; notes record metrics that could not be compared."""
    regressions: list[dict] = []
    notes: list[str] = []
    for metric in sorted(set(prior) & set(current)):
        p = float(prior[metric]["value"])
        c = float(current[metric]["value"])
        threshold = THRESHOLDS.get(metric)
        if threshold is None:
            notes.append(f"{metric}: no threshold (gated at default {DEFAULT_THRESHOLD})")
            threshold = DEFAULT_THRESHOLD
        if p <= 0:
            if metric in LOWER_IS_BETTER and c > threshold:
                # a perfect (0) lower-is-better prior must not disarm the
                # gate: with no denominator to take a fraction of, the
                # threshold is read in the metric's own units (e.g.
                # fairness 0.0 -> anything past 3.0 pct gates)
                regressions.append(
                    {
                        "metric": metric,
                        "prior": p,
                        "current": c,
                        "regression_frac": None,
                        "threshold": threshold,
                        "direction": "lower_is_better (absolute: zero prior)",
                    }
                )
            else:
                notes.append(f"{metric}: prior value {p} not comparable")
            continue
        if metric in LOWER_IS_BETTER:
            frac = (c - p) / p
        else:
            frac = (p - c) / p
        if frac > threshold:
            regressions.append(
                {
                    "metric": metric,
                    "prior": p,
                    "current": c,
                    "regression_frac": round(frac, 4),
                    "threshold": threshold,
                    "direction": "lower_is_better" if metric in LOWER_IS_BETTER else "higher_is_better",
                }
            )
    for metric in sorted(set(prior) - set(current)):
        notes.append(f"{metric}: present in prior round only (not gated)")
    for metric in sorted(set(current) - set(prior)):
        notes.append(f"{metric}: new in this round (baseline recorded)")
    return regressions, notes


def write_round(path: str, n: int, cmd: str, rc: int, label: str, lines: list[dict]) -> None:
    doc = {"n": n, "cmd": cmd, "rc": rc, "label": label, "lines": lines}
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


def regen_dashboards() -> None:
    """Refresh dashboards/ so the device-launches trajectory panel
    includes the round just written (gen_dashboards reads the
    BENCH_r*.json files at generation time)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import gen_dashboards

    gen_dashboards.main(out=os.path.join(REPO, "dashboards"))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench-trajectory",
        description="run the baseline bench, write the next BENCH_rNN.json, "
        "gate each line against the prior round (exit 1 on regression)",
    )
    ap.add_argument(
        "--compare", nargs=2, metavar=("PRIOR", "CURRENT"), default=None,
        help="gate-only mode: diff two existing round files and exit",
    )
    ap.add_argument(
        "--from-log", default=None, metavar="FILE",
        help="parse bench lines from an existing run's output instead of rerunning",
    )
    ap.add_argument(
        "--full", action="store_true",
        help="run the full bench (default passes --quick; use on chip hosts)",
    )
    ap.add_argument(
        "--label",
        default="cpu-container shape-validation (--quick; schedule shape, not chip throughput)",
        help="what this round's numbers mean — recorded in the round file",
    )
    ap.add_argument(
        "--no-write", action="store_true",
        help="gate against the prior round but do not write a round file",
    )
    ap.add_argument(
        "--no-dashboards", action="store_true",
        help="skip regenerating dashboards/ after writing the round",
    )
    args = ap.parse_args(argv)

    if args.compare is not None:
        prior = load_round_metrics(args.compare[0])
        current = load_round_metrics(args.compare[1])
        regressions, notes = compare_rounds(prior, current)
        for note in notes:
            print(f"note: {note}", file=sys.stderr)
        for r in regressions:
            print(json.dumps({"regression": r}), flush=True)
        if regressions:
            print(
                f"FAIL: {len(regressions)} regression(s) past threshold",
                file=sys.stderr,
            )
            return 1
        print(f"ok: {len(set(prior) & set(current))} line(s) within thresholds")
        return 0

    rounds = round_files()
    if not rounds:
        print("error: no BENCH_rNN.json rounds found (run from the repo root)", file=sys.stderr)
        return 2
    prior_n, prior_path = rounds[-1]
    next_n = prior_n + 1

    if args.from_log is not None:
        with open(args.from_log) as f:
            text = f.read()
        cmd = f"(from log) {args.from_log}"
        rc = 0
    else:
        bench_cmd = [sys.executable, os.path.join(REPO, "tools", "baseline_configs_bench.py")]
        if not args.full:
            bench_cmd.append("--quick")
        cmd = " ".join(bench_cmd)
        print(f"running: {cmd}", flush=True)
        proc = subprocess.run(bench_cmd, cwd=REPO, capture_output=True, text=True)
        text = proc.stdout
        rc = proc.returncode
        if rc != 0:
            sys.stderr.write(proc.stderr[-4000:])
            print(f"error: bench exited {rc}; no round written", file=sys.stderr)
            return 2

    lines = parse_bench_lines(text)
    if not lines:
        print("error: bench output carried no metric lines; no round written", file=sys.stderr)
        return 2

    prior = load_round_metrics(prior_path)
    current = {l["metric"]: l for l in lines}
    regressions, notes = compare_rounds(prior, current)
    for note in notes:
        print(f"note: {note}", file=sys.stderr)

    if not args.no_write:
        out_path = os.path.join(REPO, f"BENCH_r{next_n:02d}.json")
        write_round(out_path, next_n, cmd, rc, args.label, lines)
        print(f"wrote {out_path} ({len(lines)} lines)")
        if not args.no_dashboards:
            regen_dashboards()

    for r in regressions:
        print(json.dumps({"regression": r}), flush=True)
    if regressions:
        print(
            f"FAIL: {len(regressions)} regression(s) vs r{prior_n:02d} past threshold",
            file=sys.stderr,
        )
        return 1
    print(f"ok: r{next_n:02d} within thresholds vs r{prior_n:02d}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
