"""Can the MXU run the polynomial conv fast with exact small limbs?

Candidates at the operating batch (221k field elements):
  - current int32 32x12-bit band matmul (baseline, inside mont_mul)
  - bf16 48x8-bit einsum conv ('bi,bj,ijk->bk', f32 accumulation — exact
    for 8-bit limbs: products <= 65025, <=48 terms < 2^24)
  - int8 55x7-bit einsum conv (int32 accumulation — always exact)
  - two-stage: materialized outer product + band dot, bf16
Prints ms/conv; decides whether a 48x8 (or 55x7) fp rewrite can hit the
north star.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from lodestar_tpu.ops import fp
from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54
K = int(sys.argv[2]) if len(sys.argv) > 2 else 64

rng = np.random.default_rng(0)


def band(nl):
    t = np.zeros((nl * nl, 2 * nl), dtype=np.int32)
    for i in range(nl):
        for j in range(nl):
            t[i * nl + j, i + j] = 1
    return t


def band3(nl):
    t = np.zeros((nl, nl, 2 * nl), dtype=np.int32)
    for i in range(nl):
        for j in range(nl):
            t[i, j, i + j] = 1
    return t


def bench(name, fn, a, b, iters=3):
    # feed each conv's output back into the next iteration's operand —
    # K identical pure calls would be common-subexpression-eliminated to
    # ONE conv + K adds, timing the adds instead of the conv
    @jax.jit
    def f(x, y):
        nl = x.shape[1]
        for _ in range(K):
            r = fn(x, y)
            if x.dtype == jnp.int8:
                x = (x + r[:, :nl].astype(jnp.int8)) & 63
            elif x.dtype == jnp.int32:
                x = (x + r[:, :nl].astype(jnp.int32)) & 0xFFF
            else:
                x = jnp.mod(x + r[:, :nl].astype(x.dtype), jnp.asarray(256, x.dtype))
        return x[0, :1].astype(jnp.float32)

    np.asarray(f(a, b))
    t0 = time.perf_counter()
    for _ in range(iters):
        np.asarray(f(a, b))
    dt = (time.perf_counter() - t0) / iters / K
    print(f"{name:44s} {dt*1e3:8.3f} ms/conv", flush=True)


# baseline: current 32x12 int32 band matmul
a32 = jnp.asarray(rng.integers(0, 4096, size=(B, 32), dtype=np.int32))
b32 = jnp.asarray(rng.integers(0, 4096, size=(B, 32), dtype=np.int32))
T32 = jnp.asarray(band(32))


def conv_int32(x, y):
    outer = x[:, :, None] * y[:, None, :]
    return outer.reshape(B, 32 * 32) @ T32


bench("int32 32x12 outer+band (current)", conv_int32, a32, b32)

# bf16 48x8 einsum
a48 = jnp.asarray(rng.integers(0, 256, size=(B, 48), dtype=np.int32)).astype(jnp.bfloat16)
b48 = jnp.asarray(rng.integers(0, 256, size=(B, 48), dtype=np.int32)).astype(jnp.bfloat16)
T48 = jnp.asarray(band3(48)).astype(jnp.bfloat16)


def conv_bf16_einsum(x, y):
    return jnp.einsum("bi,bj,ijk->bk", x, y, T48, preferred_element_type=jnp.float32)


bench("bf16 48x8 einsum bi,bj,ijk->bk", conv_bf16_einsum, a48, b48)


# NOTE: an outer+band variant in bf16 would materialize 16-bit products
# in bf16 (8 significand bits) and is NOT exact — only the einsum form
# (f32 accumulation) preserves exactness, so only it is benchmarked.

# int8 55x7 einsum
a55 = jnp.asarray(rng.integers(0, 128, size=(B, 55), dtype=np.int8))
b55 = jnp.asarray(rng.integers(0, 128, size=(B, 55), dtype=np.int8))
T55 = jnp.asarray(band3(55)).astype(jnp.int8)


def conv_int8_einsum(x, y):
    return jnp.einsum("bi,bj,ijk->bk", x, y, T55, preferred_element_type=jnp.int32)


bench("int8 55x7 einsum bi,bj,ijk->bk", conv_int8_einsum, a55, b55)

# constant-operand conv as a plain matmul in bf16 (the m*P / t*P' halves)
M48 = jnp.asarray(rng.integers(0, 256, size=(48, 96), dtype=np.int32)).astype(jnp.bfloat16)


def const_conv_bf16(x, y):
    return jnp.dot(x, M48, preferred_element_type=jnp.float32)


bench("bf16 48x8 constant band matmul", const_conv_bf16, a48, b48)
print("done", flush=True)
