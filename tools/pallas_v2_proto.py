"""Pallas v2 prototype: fused conv/redc kernels in sublane-major layout.

The r4 Pallas v1 failed because limbs sat on the LANE axis, making every
shifted-window access an expensive lane shift (see bench-perf notes).
v2 transposes in-kernel to (limbs on sublanes, batch on lanes): the
schoolbook convolution becomes 33 sublane ROLLS + broadcasts (VPU-native)
and the whole multiply runs in VMEM, killing both the (B, 1089) HBM
intermediate and the 66x-redundant band matmul of the XLA path.

Run on hardware:  python tools/pallas_v2_proto.py [batch] [chain]
Prints correctness vs ops/fp + per-op times for XLA vs Pallas.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from lodestar_tpu.ops import fp
from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54
K = int(sys.argv[2]) if len(sys.argv) > 2 else 64
BB = 512  # batch block (lanes)

L = fp.LIMBS  # 33
A = fp.ACC_LIMBS  # 66
PPRIME = [int(v) for v in fp.PPRIME_LIMBS]
P_L = [int(v) for v in fp.P_LIMBS]
TWO_RP = np.asarray(fp._TWO_RP, dtype=np.int32)  # (66,)
TWO_P = np.asarray(fp._TWO_P, dtype=np.int32)  # (33,)


def _carry_once_rows(x, drop_top: bool):
    """Signed carry pass along the SUBLANE (row) axis of (rows, BB)."""
    c = x >> 12
    if not drop_top:
        c = jnp.concatenate([c[:-1], jnp.zeros_like(c[:1])], axis=0)
    lo = x - (c << 12)
    return lo + jnp.concatenate([jnp.zeros_like(c[:1]), c[:-1]], axis=0)


def _carry2_rows(x, drop_top: bool = False):
    return _carry_once_rows(_carry_once_rows(x, drop_top), drop_top)


def _conv_var(at, bt, out_rows: int):
    """Variable-variable schoolbook conv on transposed operands:
    at, bt (33, BB) -> (out_rows, BB) via 33 sublane rolls."""
    at_pad = jnp.pad(at, ((0, out_rows - L), (0, 0)))
    acc = jnp.zeros((out_rows, at.shape[1]), jnp.int32)
    for j in range(L):
        rolled = at_pad if j == 0 else jnp.roll(at_pad, j, axis=0)
        acc = acc + rolled * bt[j][None, :]  # zeros wrap in from the pad
    return acc


def _conv_const(xt, coeffs, out_rows: int):
    """Constant-coefficient conv: coeffs are python ints (scalars)."""
    x_pad = jnp.pad(xt, ((0, out_rows - xt.shape[0]), (0, 0)))
    acc = jnp.zeros((out_rows, xt.shape[1]), jnp.int32)
    for j in range(L):
        if coeffs[j] == 0:
            continue
        rolled = x_pad if j == 0 else jnp.roll(x_pad, j, axis=0)
        acc = acc + rolled * np.int32(coeffs[j])
    return acc


def _mul_acc_kernel(a_ref, b_ref, out_ref):
    at = a_ref[...].T  # (33, BB)
    bt = b_ref[...].T
    t = _carry2_rows(_conv_var(at, bt, A))
    out_ref[...] = t.T


def _redc_rows(t, two_rp_col, two_p_col):
    """(66, BB) acc -> (33, BB) relaxed element (ops/fp.redc, transposed)."""
    t = _carry_once_rows(t, False)
    # full-width conv then truncate: position >= 33 coefficients are
    # multiples of R (drop), but sublane ROLL would WRAP them in
    m = _carry2_rows(_conv_const(t[:L], PPRIME, A)[:L], drop_top=True)
    s = _carry2_rows(t + _conv_const(m, P_L, A) + two_rp_col)
    carry = (s[L - 1] >= 2048).astype(jnp.int32)
    hi = s[L:]
    hi = jnp.concatenate([hi[:1] + carry[None, :], hi[1:]], axis=0)
    return _carry_once_rows(hi - two_p_col, False)


def _redc_kernel(t_ref, two_rp_ref, two_p_ref, out_ref):
    out_ref[...] = _redc_rows(
        t_ref[...].T, two_rp_ref[...].T, two_p_ref[...].T
    ).T


def _mont_mul_kernel(a_ref, b_ref, two_rp_ref, two_p_ref, out_ref):
    at = a_ref[...].T
    bt = b_ref[...].T
    t = _carry2_rows(_conv_var(at, bt, A))
    out_ref[...] = _redc_rows(t, two_rp_ref[...].T, two_p_ref[...].T).T


_TWO_RP_IN = TWO_RP[None, :]  # (1, 66)
_TWO_P_IN = TWO_P[None, :]  # (1, 33)


def _call(kernel, out_limbs, *args, consts=()):
    b = args[0].shape[0]
    grid = (b // BB,)
    in_specs = [pl.BlockSpec((BB, x.shape[1]), lambda i: (i, 0)) for x in args]
    in_specs += [
        pl.BlockSpec((1, c.shape[1]), lambda i: (0, 0)) for c in consts
    ]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((BB, out_limbs), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, out_limbs), jnp.int32),
    )(*args, *consts)


def pallas_mul_acc(a, b):
    return _call(_mul_acc_kernel, A, a, b)


def pallas_redc(t):
    return _call(_redc_kernel, L, t, consts=(_TWO_RP_IN, _TWO_P_IN))


def pallas_mont_mul(a, b):
    return _call(_mont_mul_kernel, L, a, b, consts=(_TWO_RP_IN, _TWO_P_IN))


# --- correctness + bench ------------------------------------------------------

rng = np.random.default_rng(0)


def rand_fp(n):
    vals = [int.from_bytes(rng.bytes(47), "big") % fp.P for _ in range(n)]
    return jnp.asarray(fp.limbs_from_ints(vals))


def xla_mul_acc(x, y):
    """Explicit XLA body: fp.mont_mul would route back to Pallas on TPU."""
    return fp._carry2(fp._conv_pair(x, y))


def xla_redc(t):
    t = fp._carry_once(t)
    m = fp._carry2(fp._conv_pprime_low(t[..., : fp.LIMBS]), drop_top=True)
    s = fp._carry2(t + fp._conv_p_full(m) + jnp.asarray(fp._TWO_RP))
    carry = s[..., fp.LIMBS - 1] >= 2048
    hi = s[..., fp.LIMBS :]
    hi0 = hi[..., :1] + carry[..., None].astype(jnp.int32)
    hi = jnp.concatenate([hi0, hi[..., 1:]], axis=-1)
    return fp._carry_once(hi - jnp.asarray(fp._TWO_P))


def xla_mont_mul(x, y):
    return xla_redc(xla_mul_acc(x, y))


def main():
    n = max(BB * 2, (B // BB) * BB)
    a = rand_fp(n)
    b = rand_fp(n)

    # correctness vs the explicit XLA bodies (value-level: canon both)
    got = np.asarray(fp.canon(pallas_mont_mul(a[:BB], b[:BB])))
    want = np.asarray(fp.canon(xla_mont_mul(a[:BB], b[:BB])))
    print("mont_mul correct:", bool((got == want).all()), flush=True)
    got = np.asarray(pallas_mul_acc(a[:BB], b[:BB]))
    want = np.asarray(xla_mul_acc(a[:BB], b[:BB]))
    same_val = [
        fp.int_from_limbs(got[i].astype(np.int64)) == fp.int_from_limbs(want[i].astype(np.int64))
        for i in range(8)
    ]
    print("mul_acc value-correct:", all(same_val), flush=True)

    def chained(op):
        @jax.jit
        def f(x, y):
            for _ in range(K):
                x = op(x, y)
            return x[0, :1]

        return f

    def timeit(name, op, iters=3):
        f = chained(op)
        np.asarray(f(a, b))
        t0 = time.perf_counter()
        for _ in range(iters):
            np.asarray(f(a, b))
        dt = (time.perf_counter() - t0) / iters / K
        print(f"{name:28s} {dt*1e3:9.3f} ms/call", flush=True)
        return dt

    timeit("mont_mul XLA", xla_mont_mul)
    timeit("mont_mul PALLAS", pallas_mont_mul)
    timeit("mul_acc+redc XLA", lambda x, y: xla_redc(xla_mul_acc(x, y)))
    timeit("mul_acc+redc PALLAS", lambda x, y: pallas_redc(pallas_mul_acc(x, y)))


if __name__ == "__main__":
    main()
