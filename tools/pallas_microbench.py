"""Pallas vs XLA mont_mul on the real device (chained, RTT-amortized)."""
import sys, time
import numpy as np
sys.path.insert(0, ".")
import jax, jax.numpy as jnp
from lodestar_tpu.ops import fp, fp_pallas
from lodestar_tpu.utils import enable_compile_cache
enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54
K = int(sys.argv[2]) if len(sys.argv) > 2 else 64
rng = np.random.default_rng(0)
vals = lambda n: [int.from_bytes(rng.bytes(47), "big") % fp.P for _ in range(n)]
a = fp.to_mont(fp.limbs_from_ints(vals(B)))
b = fp.to_mont(fp.limbs_from_ints(vals(B)))

def bench(name, op):
    @jax.jit
    def f(x, y):
        for _ in range(K):
            x = op(x, y)
        return x[0, :1]
    np.asarray(f(a, b))
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        out = np.asarray(f(a, b))
    dt = (time.perf_counter() - t0) / iters / K
    print(f"{name:28s} {dt*1e3:8.3f} ms/call", flush=True)
    return out

o1 = bench("mont_mul XLA", fp.mont_mul)
o2 = bench("mont_mul PALLAS", lambda x, y: fp_pallas.mont_mul(x, y))
print("agree:", bool((o1 == o2).all()), flush=True)
