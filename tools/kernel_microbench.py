"""Microbenchmark fp-kernel primitives on the real device (r5 core).

Measures, at the batch-verify operating shape (~221k field elements),
chained invocations of each primitive (k per launch, so per-call cost is
dispatch-amortized), syncing on a scalar device->host transfer —
block_until_ready does NOT reliably wait through the axon relay. Chains
feed outputs back into inputs (CSE-proof; the r4 lesson).

Run: python tools/kernel_microbench.py [batch] [chain]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from lodestar_tpu.ops import fp
from lodestar_tpu.ops import tower as tw
from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54
K = int(sys.argv[2]) if len(sys.argv) > 2 else 16

rng = np.random.default_rng(0)


def rand_fp(n):
    vals = [int.from_bytes(rng.bytes(47), "big") % fp.P for _ in range(n)]
    return jnp.asarray(fp.limbs_from_ints(vals))


a = rand_fp(B)
b = rand_fp(B)

ARR = B * fp.LIMBS * 4  # one (B, 33) int32 pass


def chained(op):
    @jax.jit
    def f(x, y):
        for _ in range(K):
            x = op(x, y)
        return x[0, :1]  # tiny output: the sync point

    return f


def timeit(name, op, iters=3, passes_per_call=3, x=None, y=None):
    f = chained(op)
    x = a if x is None else x
    y = b if y is None else y
    np.asarray(f(x, y))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = np.asarray(f(x, y))
    dt = (time.perf_counter() - t0) / iters / K
    gbps = passes_per_call * ARR / dt / 1e9
    print(f"{name:34s} {dt*1e3:9.3f} ms/call  {gbps:7.1f} GB/s(min)", flush=True)
    return dt


timeit("mont_mul (relaxed)", fp.mont_mul)
timeit("mont_sq (relaxed)", lambda x, y: fp.mont_sq(x))
timeit("add", fp.add)
timeit("sub", fp.sub)
timeit("mul_acc + redc", lambda x, y: fp.redc(fp.mul_acc(x, y)))
timeit(
    "2 acc sum + 1 redc",
    lambda x, y: fp.redc(fp.acc_add(fp.mul_acc(x, y), fp.sq_acc(x))),
)

# tower shapes: fp2 at B/2, fp12 at B/12 keeps total element count ~B
a2 = a[: (B // 2) * 2].reshape(B // 2, 2, fp.LIMBS)
b2 = b[: (B // 2) * 2].reshape(B // 2, 2, fp.LIMBS)
timeit("fp2_mul (acc domain)", tw.fp2_mul, x=a2, y=b2)
n12 = B // 12
a12 = a[: n12 * 12].reshape(n12, 2, 3, 2, fp.LIMBS)
b12 = b[: n12 * 12].reshape(n12, 2, 3, 2, fp.LIMBS)
timeit("fp12_mul (12 redc)", tw.fp12_mul, x=a12, y=b12)
timeit("fp12_sq (karatsuba)", lambda x, y: tw.fp12_sq(x), x=a12, y=b12)

print("done", flush=True)
