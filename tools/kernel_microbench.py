"""Microbenchmark fp-kernel variants on the real device.

Measures, at the batch-verify operating shape (~221k field elements),
chained invocations of each variant (k per launch, so per-call cost is
dispatch-amortized), syncing on a scalar device->host transfer — 
block_until_ready does NOT reliably wait through the axon relay.

Run: python tools/kernel_microbench.py [batch] [chain]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from lodestar_tpu.ops import fp
from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54
K = int(sys.argv[2]) if len(sys.argv) > 2 else 16

rng = np.random.default_rng(0)


def rand_fp(n):
    vals = [int.from_bytes(rng.bytes(47), "big") % fp.P for _ in range(n)]
    return jnp.asarray(fp.limbs_from_ints(vals))


a = rand_fp(B)
b = rand_fp(B)

ARR = B * 32 * 4  # one (B, 32) int32 pass


def chained(op):
    @jax.jit
    def f(x, y):
        for _ in range(K):
            x = op(x, y)
        return x[0, :1]  # tiny output: the sync point

    return f


def timeit(name, op, iters=3, passes_per_call=3):
    f = chained(op)
    np.asarray(f(a, b))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = np.asarray(f(a, b))
    dt = (time.perf_counter() - t0) / iters / K
    gbps = passes_per_call * ARR / dt / 1e9
    print(f"{name:34s} {dt*1e3:9.3f} ms/call  {gbps:7.1f} GB/s(min)", flush=True)
    return dt


timeit("mont_mul (live)", fp.mont_mul)
timeit("mont_sq (live)", lambda x, y: fp.mont_sq(x))
timeit("add (live)", fp.add)
timeit("_carry_seq", lambda x, y: fp._carry_seq(x + y), passes_per_call=2)
timeit("_cond_sub_p", lambda x, y: fp._cond_sub_p(jnp.clip(x + y, 0, 4095)), passes_per_call=2)
timeit("_carry3(64)", lambda x, y: fp._carry3(jnp.concatenate([x, y], -1))[..., :32], passes_per_call=4)

_T = np.zeros((fp.LIMBS * fp.LIMBS, 2 * fp.LIMBS), dtype=np.int32)
for i in range(fp.LIMBS):
    for j in range(fp.LIMBS):
        _T[i * fp.LIMBS + j, i + j] = 1


def conv_band(x, y):
    outer = x[..., :, None] * y[..., None, :]
    flat = outer.reshape(*outer.shape[:-2], fp.LIMBS * fp.LIMBS)
    return (flat @ jnp.asarray(_T))[..., :32]


def conv_shift(x, y):
    # true 32-term shifted-FMA formulation (fp._conv_pair is now the band
    # matmul; this keeps the alternative measurable)
    total = None
    for j in range(32):
        term = jnp.pad(x * y[:, j : j + 1], [(0, 0), (j, 32 - j)])
        total = term if total is None else total + term
    return total[..., :32]


def conv_stacksum(x, y):
    terms = [
        jnp.pad(x * y[..., j : j + 1], [(0, 0), (j, fp.LIMBS - j)])
        for j in range(fp.LIMBS)
    ]
    return jnp.sum(jnp.stack(terms, 0), 0)[..., :32]


timeit("conv shifted-FMA (live)", conv_shift, passes_per_call=4)
timeit("conv outer+band matmul (old)", conv_band, passes_per_call=4)
timeit("conv stack+sum", conv_stacksum, passes_per_call=4)


def mont_mul_lazy(x, y):
    t = fp._carry_once(fp._carry_once(fp._conv_pair(x, y)))
    m = fp._carry_once(fp._carry_once(fp._conv_pprime_low(t[..., : fp.LIMBS])))
    s = fp._carry_once(fp._carry_once(t + fp._conv_p_full(m)))
    carry = jnp.any(s[..., : fp.LIMBS] != 0, axis=-1)
    hi = s[..., fp.LIMBS :]
    hi0 = hi[..., :1] + carry[..., None].astype(jnp.int32)
    return jnp.concatenate([hi0, hi[..., 1:]], axis=-1)


timeit("mont_mul LAZY (no scans)", mont_mul_lazy)

print("done", flush=True)
