"""Microbenchmark fp-kernel variants on the real device.

Measures, at the batch-verify operating shape (~221k field elements),
the per-call time of:
  * the live mont_mul / add / carry primitives
  * alternative conv formulations (band-matmul, stacked-pad sum)
  * a scan-free "lazy" mont_mul prototype (no exact carry, no cond-sub)
Prints one line per variant: name, ms/call, implied GB/s of array traffic.

Run: python tools/kernel_microbench.py [batch]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from lodestar_tpu.ops import fp
from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54

rng = np.random.default_rng(0)


def rand_fp(n):
    vals = [int.from_bytes(rng.bytes(47), "big") % fp.P for _ in range(n)]
    return jnp.asarray(fp.limbs_from_ints(vals))


a = rand_fp(B)
b = rand_fp(B)


def timeit(name, fn, *args, iters=10, passes_bytes=None):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    gbps = (passes_bytes / dt / 1e9) if passes_bytes else 0.0
    print(f"{name:34s} {dt*1e3:9.3f} ms   {gbps:7.1f} GB/s(min-traffic)", flush=True)
    return dt


ARR = B * 32 * 4  # one (B, 32) int32 pass


# --- live primitives ---------------------------------------------------------

timeit("mont_mul (live)", fp.mont_mul, a, b, passes_bytes=3 * ARR)
timeit("mont_sq (live)", fp.mont_sq, a, passes_bytes=2 * ARR)
timeit("add (live)", fp.add, a, b, passes_bytes=3 * ARR)


@jax.jit
def carry_seq_only(x):
    return fp._carry_seq(x)


@jax.jit
def cond_sub_only(x):
    return fp._cond_sub_p(x)


@jax.jit
def carry3_only(x):
    return fp._carry3(jnp.pad(x, [(0, 0), (0, fp.LIMBS)]))


timeit("_carry_seq alone", carry_seq_only, a, passes_bytes=2 * ARR)
timeit("_cond_sub_p alone", cond_sub_only, a, passes_bytes=2 * ARR)
timeit("_carry3 (64-wide) alone", carry3_only, a, passes_bytes=4 * ARR)


# --- conv variants -----------------------------------------------------------


@jax.jit
def conv_shift(a, b):
    return fp._conv_pair(a, b)


_T = np.zeros((fp.LIMBS * fp.LIMBS, 2 * fp.LIMBS), dtype=np.int32)
for i in range(fp.LIMBS):
    for j in range(fp.LIMBS):
        _T[i * fp.LIMBS + j, i + j] = 1


@jax.jit
def conv_bandmatmul(a, b):
    outer = a[..., :, None] * b[..., None, :]
    flat = outer.reshape(*outer.shape[:-2], fp.LIMBS * fp.LIMBS)
    return flat @ jnp.asarray(_T)


@jax.jit
def conv_stacksum(a, b):
    terms = [
        jnp.pad(a * b[..., j : j + 1], [(0, 0), (j, fp.LIMBS - j)])
        for j in range(fp.LIMBS)
    ]
    return jnp.sum(jnp.stack(terms, 0), 0)


timeit("conv: shifted-FMA chain (live)", conv_shift, a, b, passes_bytes=4 * ARR)
timeit("conv: outer+band matmul (old)", conv_bandmatmul, a, b, passes_bytes=4 * ARR)
timeit("conv: stack+sum", conv_stacksum, a, b, passes_bytes=4 * ARR)


# --- lazy mont_mul prototype (no scans, no cond-sub) -------------------------


@jax.jit
def mont_mul_lazy(a, b):
    t = fp._carry_once(fp._carry_once(fp._conv_pair(a, b)))
    m = fp._carry_once(fp._carry_once(fp._conv_const_low(t[..., : fp.LIMBS], fp.PPRIME_LIMBS)))
    s = fp._carry_once(fp._carry_once(t + fp._conv_const_full(m, fp.P_LIMBS)))
    carry = jnp.any(s[..., : fp.LIMBS] != 0, axis=-1)
    hi = s[..., fp.LIMBS :]
    hi0 = hi[..., :1] + carry[..., None].astype(jnp.int32)
    return jnp.concatenate([hi0, hi[..., 1:]], axis=-1)


timeit("mont_mul LAZY prototype", mont_mul_lazy, a, b, passes_bytes=3 * ARR)


# --- chained composition (amortization check) --------------------------------


@jax.jit
def chain8_live(a, b):
    x = a
    for _ in range(8):
        x = fp.mont_mul(x, b)
    return x


@jax.jit
def chain8_lazy(a, b):
    x = a
    for _ in range(8):
        x = mont_mul_lazy(x, b)
    return x


timeit("8-chain live mont_mul", chain8_live, a, b, iters=5, passes_bytes=24 * ARR)
timeit("8-chain LAZY mont_mul", chain8_lazy, a, b, iters=5, passes_bytes=24 * ARR)

print("done", flush=True)
