"""Generate the Grafana dashboards under dashboards/ (reference ships 16
under /dashboards; these cover the subsystems this framework actually
exports, wired to the repo's metric names so a Grafana + Prometheus pair
scraping the node renders them unmodified).

Run from the repo root: python tools/gen_dashboards.py
"""

import glob
import json
import os

OUT = "dashboards"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def panel(title, exprs, *, unit="short", x=0, y=0, w=12, h=8, pid=1, kind="timeseries"):
    targets = [
        {"expr": e, "legendFormat": leg, "refId": chr(ord("A") + i)}
        for i, (e, leg) in enumerate(exprs)
    ]
    return {
        "id": pid,
        "title": title,
        "type": kind,
        "datasource": {"type": "prometheus", "uid": "${DS_PROMETHEUS}"},
        "gridPos": {"x": x, "y": y, "w": w, "h": h},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": targets,
    }


def text_panel(title, content, *, x=0, y=0, w=24, h=8, pid=1):
    """Markdown panel (no Prometheus targets — static content baked at
    generation time, e.g. the bench trajectory table)."""
    return {
        "id": pid,
        "title": title,
        "type": "text",
        "gridPos": {"x": x, "y": y, "w": w, "h": h},
        "options": {"mode": "markdown", "content": content},
        "targets": [],
    }


def dashboard(uid, title, panels, tags):
    return {
        "uid": uid,
        "title": title,
        "tags": tags,
        "timezone": "utc",
        "schemaVersion": 39,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "templating": {
            "list": [
                {
                    "name": "DS_PROMETHEUS",
                    "type": "datasource",
                    "query": "prometheus",
                    "current": {},
                }
            ]
        },
        "panels": panels,
    }


def bls_pool():
    ps = [
        panel(
            "Signature throughput (sets/s)",
            [
                ("rate(lodestar_bls_thread_pool_sig_sets_started_total[1m])", "started"),
                ("rate(lodestar_bls_thread_pool_batch_sigs_success_total[1m])", "batch success"),
                # prometheus_client suffixes counters with _total even when
                # the reference name already ends in _count
                ("rate(lodestar_bls_thread_pool_success_jobs_signature_sets_count_total[1m])", "success"),
            ],
            unit="ops", x=0, y=0, pid=1,
        ),
        panel(
            "Jobs started / errors",
            [
                ("rate(lodestar_bls_thread_pool_jobs_started_total[1m])", "jobs"),
                ("rate(lodestar_bls_thread_pool_error_jobs_signature_sets_count_total[1m])", "error sets"),
                ("rate(lodestar_bls_thread_pool_batch_retries_total[1m])", "batch retries"),
            ],
            unit="ops", x=12, y=0, pid=2,
        ),
        panel(
            "Queue wait time",
            [
                (
                    "histogram_quantile(0.5, rate(lodestar_bls_thread_pool_queue_job_wait_time_seconds_bucket[5m]))",
                    "p50",
                ),
                (
                    "histogram_quantile(0.95, rate(lodestar_bls_thread_pool_queue_job_wait_time_seconds_bucket[5m]))",
                    "p95",
                ),
            ],
            unit="s", x=0, y=8, pid=3,
        ),
        panel(
            "Device time per signature set",
            [
                (
                    "histogram_quantile(0.5, rate(lodestar_bls_thread_pool_time_per_sig_set_seconds_bucket[5m]))",
                    "p50",
                ),
                (
                    "histogram_quantile(0.95, rate(lodestar_bls_thread_pool_time_per_sig_set_seconds_bucket[5m]))",
                    "p95",
                ),
            ],
            unit="s", x=12, y=8, pid=4,
        ),
        panel(
            "Input prep throughput by layer (device vs host)",
            [
                ("rate(lodestar_bls_prep_sets_total[1m])", "{{layer}}"),
            ],
            unit="ops", x=0, y=16, pid=5,
        ),
        panel(
            "Input prep time by layer",
            [
                (
                    "histogram_quantile(0.95, sum by (le, layer) (rate(lodestar_bls_prep_seconds_bucket[5m])))",
                    "p95 {{layer}}",
                ),
            ],
            unit="s", x=12, y=16, pid=6,
        ),
        panel(
            "Input prep fallbacks / rejected batches",
            [
                ("rate(lodestar_bls_prep_fallback_total[1m])", "device→host fallbacks"),
                (
                    "rate(lodestar_bls_single_launch_fallback_total[1m])",
                    "single-launch→split fallbacks",
                ),
                ("rate(lodestar_bls_prep_rejected_total[1m])", "rejected batches"),
            ],
            unit="ops", x=0, y=24, pid=7,
        ),
        panel(
            # launches-per-set: the fused schedule costs a fixed launch
            # budget per batch, so this quotient falls with batch size
            # and spikes if a regression re-serializes the chains. The
            # numerator is the plain dispatch counter (it counts per-leg
            # and hash-to-G2 dispatches too); the strict per-batch
            # budget invariant lives in the tests. BOTH operands wrapped
            # in sum(): a labeled-vs-aggregated vector match is empty
            # and renders the panel permanently blank (the PR 7 round-5
            # launches/flush lesson). The plain
            # lodestar_bls_prep_launches_total counter counts EVERY
            # dispatch at the seam (single-launch verifies included
            # since round 13), so the split-schedule numerator
            # subtracts the single-launch program's telemetry count —
            # with the `or vector(0)` guard so the subtraction (and the
            # panel) still renders when telemetry is off or no
            # single-launch traffic exists. Known over-reads, both
            # deliberate: with telemetry off + single-launch on the
            # series blends the schedules (no per-program signal to
            # subtract), and during a single-launch fallback storm the
            # FAILED dispatches stay in the numerator (the counter
            # ticks at dispatch, the histogram only on success) — an
            # elevated split series next to a busy fallbacks panel is
            # the storm being visible, not a split-schedule regression.
            # The single-launch
            # series reads the one-program schedule
            # (--bls-single-launch): numerator = the single-launch
            # program's dispatches, denominator the sets staged under
            # the single_launch prep layer — at budget it tracks
            # 1/batch-size while the split series tracks 3/batch-size.
            "Prep launches per set (device layer)",
            [
                (
                    "(sum(rate(lodestar_bls_prep_launches_total[5m])) - "
                    "(sum(rate(lodestar_device_launch_seconds_count{program=\"_single_launch_verify\"}[5m])) or vector(0))) / "
                    "sum(rate(lodestar_bls_prep_sets_total{layer=\"device\"}[5m]))",
                    "split-schedule launches/set",
                ),
                (
                    "sum(rate(lodestar_device_launch_seconds_count{program=\"_single_launch_verify\"}[5m])) / "
                    "sum(rate(lodestar_bls_prep_sets_total{layer=\"single_launch\"}[5m]))",
                    "single-launch launches/set",
                ),
            ],
            unit="ops", x=12, y=24, pid=8,
        ),
        panel(
            # live export of the pool's pipeline_stats(): how much of
            # verify wall time carried a prep stage in flight (the PR 9
            # bench line, now readable during a run) and whether the
            # double buffer engaged at all (0 staged packages = it
            # never did — 1-lane auto, or no stageable lanes)
            "Prep→verify pipeline overlap",
            [
                ("lodestar_bls_pipeline_overlap_occupancy_pct", "overlap % of verify time"),
                ("lodestar_bls_pipeline_staged_packages", "staged packages (cum)"),
            ],
            x=0, y=32, pid=9,
        ),
        panel(
            "Pipeline stage busy time (rate of cumulative seconds)",
            [
                ("rate(lodestar_bls_pipeline_prep_seconds_total[5m])", "prep busy s/s"),
                ("rate(lodestar_bls_pipeline_verify_seconds_total[5m])", "verify busy s/s"),
            ],
            x=12, y=32, pid=10,
        ),
    ]
    return dashboard("lodestar-bls-pool", "Lodestar TPU - BLS verifier pool", ps, ["lodestar", "bls"])


def block_processor():
    ps = [
        panel(
            "Head / finalized",
            [
                ("beacon_head_slot", "head slot"),
                ("beacon_clock_slot", "clock slot"),
                ("beacon_finalized_epoch * 8", "finalized (slots)"),
            ],
            x=0, y=0, pid=1,
        ),
        panel(
            "Block processing time",
            [
                (
                    "histogram_quantile(0.5, rate(lodestar_stfn_process_block_seconds_bucket[5m]))",
                    "p50",
                ),
                (
                    "histogram_quantile(0.95, rate(lodestar_stfn_process_block_seconds_bucket[5m]))",
                    "p95",
                ),
            ],
            unit="s", x=12, y=0, pid=2,
        ),
        panel(
            "Epoch transition / hashTreeRoot",
            [
                (
                    "histogram_quantile(0.95, rate(lodestar_stfn_epoch_transition_seconds_bucket[5m]))",
                    "epoch p95",
                ),
                (
                    "histogram_quantile(0.95, rate(lodestar_stfn_hash_tree_root_seconds_bucket[5m]))",
                    "htr p95",
                ),
            ],
            unit="s", x=0, y=8, pid=3,
        ),
        panel(
            "Gossip queues",
            [
                ("lodestar_gossip_validation_queue_length", "{{topic}}"),
                ("rate(lodestar_gossip_validation_queue_dropped_jobs_total[1m])", "dropped {{topic}}"),
            ],
            x=12, y=8, pid=4,
        ),
        panel(
            "State caches",
            [
                ("rate(lodestar_state_cache_hits_total[1m])", "state hits"),
                ("rate(lodestar_state_cache_misses_total[1m])", "state misses"),
                ("rate(lodestar_cp_state_cache_hits_total[1m])", "checkpoint hits"),
            ],
            unit="ops", x=0, y=16, pid=5,
        ),
        panel(
            "Fork choice",
            [
                ("rate(lodestar_fork_choice_requests_total[1m])", "findHead"),
                ("rate(lodestar_fork_choice_reorg_events_total[1m])", "reorgs"),
                ("rate(lodestar_fork_choice_errors_total[1m])", "errors"),
            ],
            unit="ops", x=12, y=16, pid=6,
        ),
    ]
    return dashboard(
        "lodestar-block-processor", "Lodestar TPU - Block processor", ps, ["lodestar", "chain"]
    )


def networking():
    ps = [
        panel(
            "Peers",
            [
                ("libp2p_peers", "total"),
                ("lodestar_peers_by_direction_count", "{{direction}}"),
            ],
            x=0, y=0, pid=1,
        ),
        panel(
            "Gossip traffic",
            [
                ("rate(lodestar_gossip_peer_received_messages_total[1m])", "received"),
                ("rate(lodestar_gossipsub_seen_cache_duplicates_total[1m])", "duplicates"),
            ],
            unit="ops", x=12, y=0, pid=2,
        ),
        panel(
            "ReqResp",
            [
                ("rate(beacon_reqresp_outgoing_requests_total[1m])", "out {{protocol}}"),
                ("rate(beacon_reqresp_incoming_requests_total[1m])", "in {{protocol}}"),
                ("rate(beacon_reqresp_incoming_errors_total[1m])", "errors {{protocol}}"),
            ],
            unit="ops", x=0, y=8, pid=3,
        ),
        panel(
            "Sync",
            [
                ("rate(lodestar_sync_range_blocks_total[1m])", "range blocks"),
                ("rate(lodestar_sync_range_errors_total[1m])", "range errors"),
                ("rate(lodestar_backfill_sync_blocks_total[1m])", "backfill blocks"),
            ],
            unit="ops", x=12, y=8, pid=4,
        ),
    ]
    return dashboard(
        "lodestar-networking", "Lodestar TPU - Networking & sync", ps, ["lodestar", "network"]
    )


def validator_monitor():
    ps = [
        panel(
            "Local validators",
            [("validator_monitor_validators_total", "registered")],
            x=0, y=0, w=6, pid=1, kind="stat",
        ),
        panel(
            "Proposals",
            [("rate(validator_monitor_beacon_block_total[10m])", "blocks")],
            unit="ops", x=6, y=0, w=6, pid=2,
        ),
        panel(
            "Attestation hits / misses per epoch",
            [
                ("increase(validator_monitor_prev_epoch_attestations_total[10m])", "attested"),
                (
                    "increase(validator_monitor_prev_epoch_attestations_missed_total[10m])",
                    "missed",
                ),
            ],
            x=12, y=0, pid=3,
        ),
        panel(
            "Inclusion distance",
            [
                (
                    "histogram_quantile(0.5, rate(validator_monitor_prev_epoch_attestation_inclusion_distance_bucket[10m]))",
                    "p50",
                ),
                (
                    "histogram_quantile(0.95, rate(validator_monitor_prev_epoch_attestation_inclusion_distance_bucket[10m]))",
                    "p95",
                ),
            ],
            x=0, y=8, pid=4,
        ),
        panel(
            "Gossip-seen local attestations",
            [("rate(validator_monitor_unaggregated_attestation_total[1m])", "seen")],
            unit="ops", x=12, y=8, pid=5,
        ),
    ]
    return dashboard(
        "lodestar-validator-monitor", "Lodestar TPU - Validator monitor", ps,
        ["lodestar", "validator"],
    )


def mesh_serving_dashboard():
    """Multi-chip serving (chain/bls/mesh.py + offload/tenancy.py):
    per-device occupancy/launch/wedge state for the verifier mesh and
    per-tenant served/shed/in-flight for the multi-tenant offload
    front-end. The "is the fleet healthy and is every tenant getting
    its share" dashboard."""
    ps = [
        panel(
            "Per-chip occupancy (‰)",
            [("lodestar_sched_lane_occupancy_permille", "{{device}}")],
            pid=1,
        ),
        panel(
            "Mesh lanes available (non-wedged)",
            [("lodestar_sched_mesh_lanes_available", "lanes")],
            x=12, pid=2,
        ),
        panel(
            "Launch rate by chip and mode",
            [
                (
                    "sum by (device, mode) (rate(lodestar_sched_lane_launches_total[5m]))",
                    "{{device}} {{mode}}",
                ),
            ],
            unit="ops", y=8, pid=3,
        ),
        panel(
            "Per-chip wedge-breaker trips",
            [
                (
                    "sum by (device) (increase(lodestar_sched_lane_wedge_trips_total[1h]))",
                    "{{device}}",
                ),
            ],
            x=12, y=8, pid=4,
        ),
        panel(
            "Tenant served sets rate",
            [
                (
                    "sum by (tenant) (rate(lodestar_offload_tenant_served_sets_total[5m]))",
                    "{{tenant}}",
                ),
            ],
            unit="ops", y=16, pid=5,
        ),
        panel(
            "Tenant sheds by reason",
            [
                (
                    "sum by (tenant, reason) (rate(lodestar_offload_tenant_shed_total[5m]))",
                    "{{tenant}} {{reason}}",
                ),
            ],
            unit="ops", x=12, y=16, pid=6,
        ),
        panel(
            "Tenant in-flight grants vs quota weight",
            [
                ("lodestar_offload_tenant_inflight", "inflight {{tenant}}"),
                ("lodestar_offload_tenant_quota_weight", "weight {{tenant}}"),
            ],
            y=24, pid=7,
        ),
    ]
    return dashboard(
        "lodestar-mesh-serving",
        "Lodestar TPU - Multi-chip serving",
        ps,
        ["lodestar", "mesh", "tenancy"],
    )


def _bench_trajectory_markdown():
    """Markdown table of the BENCH_rNN.json trajectory, baked at
    generation time (tools/bench_trajectory.py regenerates dashboards
    after writing each round, so this panel tracks the trajectory).
    Handles both the r1–r5 single-``parsed`` shape and the r6+
    ``lines`` shape."""
    rows = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        n = doc.get("n", "?")
        label = doc.get("label", "")
        lines = [l for l in doc.get("lines") or [] if isinstance(l, dict) and "metric" in l]
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            lines.append(parsed)
        for line in lines:
            rows.append(
                "| r{n:02d} | `{metric}` | {value} {unit} | {vs} | {label} |".format(
                    n=int(n) if isinstance(n, int) else 0,
                    metric=line.get("metric", "?"),
                    value=line.get("value", "?"),
                    unit=line.get("unit", ""),
                    vs=line.get("vs_baseline", ""),
                    label=label,
                )
            )
    header = (
        "### Bench trajectory (BENCH_rNN.json)\n\n"
        "Written by `tools/bench_trajectory.py` — each round is gated "
        "line-by-line against the prior round (exit nonzero on "
        "regression). CPU-container rounds validate schedule shape, "
        "not chip throughput; read the label column.\n\n"
        "| round | metric | value | vs baseline | label |\n"
        "|---|---|---|---|---|\n"
    )
    return header + "\n".join(rows) + "\n"


def device_launches_dashboard():
    """Device launch telemetry (lodestar_tpu/telemetry.py): per-program
    dispatch latency and rate at the counted launch seams, the
    compile-vs-dispatch decomposition (first-call detection per
    (program, size class)), and the bench trajectory. The "where did
    the chip run's wall time go" dashboard the hardware measurement
    campaign reads."""
    ps = [
        panel(
            "Launch rate by program",
            [
                (
                    "sum by (program) (rate(lodestar_device_launch_seconds_count[5m]))",
                    "{{program}}",
                ),
            ],
            unit="ops", pid=1,
        ),
        panel(
            "Launch wall time p95 by program",
            [
                (
                    "histogram_quantile(0.95, sum by (program, le) "
                    "(rate(lodestar_device_launch_seconds_bucket[5m])))",
                    "{{program}}",
                ),
            ],
            unit="s", x=12, pid=2,
        ),
        panel(
            "Launch wall time p95 by size class",
            [
                (
                    "histogram_quantile(0.95, sum by (size_class, le) "
                    "(rate(lodestar_device_launch_seconds_bucket[5m])))",
                    "class {{size_class}}",
                ),
            ],
            unit="s", y=8, pid=3,
        ),
        panel(
            # compile vs dispatch: misses are first-call-per-(program,
            # size class) dispatches that paid trace+compile (or the
            # persistent-cache load); a miss spike in steady state means
            # a new shape bucket leaked into the hot path
            "Compile hits / misses by program",
            [
                (
                    "sum by (program) (rate(lodestar_device_compile_hits_total[5m]))",
                    "hit {{program}}",
                ),
                (
                    "sum by (program) (rate(lodestar_device_compile_misses_total[5m]))",
                    "MISS {{program}}",
                ),
            ],
            unit="ops", x=12, y=8, pid=4,
        ),
        panel(
            "Compile wall time (first-call dispatches, s/s)",
            [
                ("rate(lodestar_device_compile_seconds_total[5m])", "compile s/s"),
            ],
            y=16, pid=5,
        ),
        panel(
            "Launch time share by program (sum/s)",
            [
                (
                    "sum by (program) (rate(lodestar_device_launch_seconds_sum[5m]))",
                    "{{program}}",
                ),
            ],
            unit="s", x=12, y=16, pid=6,
        ),
        text_panel(
            "Bench trajectory",
            _bench_trajectory_markdown(),
            y=24, pid=7,
        ),
    ]
    return dashboard(
        "lodestar-device-launches",
        "Lodestar TPU - Device launch telemetry",
        ps,
        ["lodestar", "telemetry"],
    )


def slo_dashboard():
    """Slot-deadline SLO (lodestar_tpu/slo): per-class remaining-slack
    distributions at enqueue/dispatch/verdict, deadline-miss rates, the
    good/total SLI availability ratio and its error-budget burn rate
    (the panels behind alerts/lodestar_alerts.yml), and the offload
    host's per-tenant serving slack. The "are verdicts landing inside
    the slot, and if not where did the budget go" dashboard — the
    per-leg wait decomposition lives at GET /eth/v0/debug/slo."""
    ps = [
        panel(
            # p05, not p50: the SLO question is the worst-case tail —
            # "how close to the cliff are the slowest verdicts"
            "Verdict slack p05 by class (s left at the cutoff)",
            [
                (
                    "histogram_quantile(0.05, sum by (class, le) "
                    '(rate(lodestar_slo_slack_seconds_bucket{stage="verdict"}[5m])))',
                    "{{class}}",
                ),
            ],
            unit="s", pid=1,
        ),
        panel(
            # enqueue vs verdict medians: slack lost BETWEEN the stages
            # is spent inside this process (the wait-budget legs);
            # slack already negative at enqueue is upstream lateness
            "Slack p50 by stage (where the budget goes)",
            [
                (
                    "histogram_quantile(0.5, sum by (stage, le) "
                    "(rate(lodestar_slo_slack_seconds_bucket[5m])))",
                    "{{stage}}",
                ),
            ],
            unit="s", x=12, pid=2,
        ),
        panel(
            "Deadline misses by class",
            [
                (
                    "sum by (class) (rate(lodestar_slo_deadline_miss_total[5m]))",
                    "{{class}}",
                ),
            ],
            unit="ops", y=8, pid=3,
        ),
        panel(
            "SLI availability (good/total) by class",
            [
                (
                    "sum by (class) (rate(lodestar_slo_sli_good_total[5m])) / "
                    "sum by (class) (rate(lodestar_slo_sli_total[5m]))",
                    "{{class}}",
                ),
            ],
            unit="percentunit", x=12, y=8, pid=4,
        ),
        panel(
            # burn rate in budget multiples (1.0 = exactly on target,
            # 14.4 = the fast-burn page threshold): the live view of
            # the alert pair in alerts/lodestar_alerts.yml
            "Error-budget burn rate (x budget, 99.9% target)",
            [
                (
                    "(1 - (sum(rate(lodestar_slo_sli_good_total[5m])) / "
                    "sum(rate(lodestar_slo_sli_total[5m])))) / 0.001",
                    "5m window",
                ),
                (
                    "(1 - (sum(rate(lodestar_slo_sli_good_total[1h])) / "
                    "sum(rate(lodestar_slo_sli_total[1h])))) / 0.001",
                    "1h window",
                ),
            ],
            y=16, pid=5,
        ),
        panel(
            "Offload host: per-tenant serving slack p05",
            [
                (
                    "histogram_quantile(0.05, sum by (tenant, le) "
                    "(rate(lodestar_offload_tenant_slack_seconds_bucket[5m])))",
                    "{{tenant}}",
                ),
            ],
            unit="s", x=12, y=16, pid=6,
        ),
    ]
    return dashboard(
        "lodestar-slo",
        "Lodestar TPU - Slot-deadline SLO",
        ps,
        ["lodestar", "slo"],
    )


def all_dashboards():
    return (
        ("lodestar_bls_verifier_pool.json", bls_pool()),
        ("lodestar_block_processor.json", block_processor()),
        ("lodestar_networking.json", networking()),
        ("lodestar_validator_monitor.json", validator_monitor()),
        ("lodestar_sync.json", sync_dashboard()),
        ("lodestar_reqresp_api.json", reqresp_api_dashboard()),
        ("lodestar_db.json", db_dashboard()),
        ("lodestar_block_pipeline_trace.json", trace_dashboard()),
        ("lodestar_sched_occupancy.json", sched_dashboard()),
        ("lodestar_offload_resilience.json", resilience_dashboard()),
        ("lodestar_offload_audit.json", audit_dashboard()),
        ("lodestar_ssz_htr.json", ssz_htr_dashboard()),
        ("lodestar_node_internals.json", node_internals_dashboard()),
        ("lodestar_mesh_serving.json", mesh_serving_dashboard()),
        ("lodestar_device_launches.json", device_launches_dashboard()),
        ("lodestar_slo.json", slo_dashboard()),
    )


def main(out: str = OUT):
    os.makedirs(out, exist_ok=True)
    for name, dash in all_dashboards():
        path = os.path.join(out, name)
        with open(path, "w") as f:
            # sort_keys keeps the output byte-stable across dict-build
            # order changes, so the static-analysis metrics rule (and
            # the regen-is-noop test) can diff dashboards exactly
            json.dump(dash, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {path}")



def sync_dashboard():
    ps = [
        panel("Sync status", [("lodestar_sync_status", "status (0 stalled/1 syncing/2 synced)")], pid=1),
        panel("Head distance (slots behind)", [("lodestar_sync_head_distance_slots", "behind")], x=12, pid=2),
        panel(
            "Range-sync batches",
            [
                ("rate(lodestar_sync_range_batches_total[5m])", "{{status}}"),
                ("rate(lodestar_sync_range_batches_downloaded_total[5m])", "downloaded"),
                ("rate(lodestar_sync_range_download_retries_total[5m])", "retries"),
            ],
            y=8, pid=3,
        ),
        panel(
            "Blocks imported by sync",
            [
                ("rate(lodestar_sync_range_blocks_total[5m])", "range"),
                ("rate(lodestar_backfill_sync_blocks_total[5m])", "backfill"),
            ],
            x=12, y=8, pid=4,
        ),
        panel(
            "Batch latency p95",
            [
                ("histogram_quantile(0.95, rate(lodestar_sync_range_batch_download_seconds_bucket[5m]))", "download"),
                ("histogram_quantile(0.95, rate(lodestar_sync_range_batch_processing_seconds_bucket[5m]))", "processing"),
            ],
            unit="s", y=16, pid=5,
        ),
        panel(
            "Backfill / unknown-block",
            [
                ("lodestar_backfill_earliest_slot", "backfill earliest slot"),
                ("lodestar_sync_unknown_block_pending_count", "unknown-block pending"),
                ("rate(lodestar_sync_unknown_block_requests_total[5m])", "unknown-block requests"),
            ],
            x=12, y=16, pid=6,
        ),
    ]
    return dashboard("lodestar-sync", "Lodestar TPU - Sync", ps, ["lodestar", "sync"])


def reqresp_api_dashboard():
    ps = [
        panel(
            "Req/resp requests",
            [
                ("sum by (protocol) (rate(beacon_reqresp_incoming_requests_total[5m]))", "in {{protocol}}"),
                ("sum by (protocol) (rate(beacon_reqresp_outgoing_requests_total[5m]))", "out {{protocol}}"),
            ],
            pid=1,
        ),
        panel(
            "Req/resp chunks + errors",
            [
                ("sum by (protocol) (rate(beacon_reqresp_outgoing_response_chunks_total[5m]))", "chunks {{protocol}}"),
                ("sum by (protocol) (rate(beacon_reqresp_incoming_errors_total[5m]))", "errors {{protocol}}"),
                ("sum by (protocol) (rate(beacon_reqresp_rate_limited_total[5m]))", "rate-limited {{protocol}}"),
            ],
            x=12, pid=2,
        ),
        panel(
            "REST API requests",
            [
                ("sum by (method, status) (rate(lodestar_api_rest_requests_total[5m]))", "{{method}} {{status}}"),
                ("rate(lodestar_api_rest_errors_total[5m])", "5xx"),
            ],
            y=8, pid=3,
        ),
        panel(
            "REST response time p95",
            [("histogram_quantile(0.95, rate(lodestar_api_rest_response_time_seconds_bucket[5m]))", "p95")],
            unit="s", x=12, y=8, pid=4,
        ),
        panel(
            "Dial health",
            [
                ("rate(beacon_reqresp_dial_timeouts_total[5m])", "dial timeouts"),
                ("rate(beacon_reqresp_streams_reset_total[5m])", "streams reset"),
            ],
            y=16, pid=5,
        ),
    ]
    return dashboard("lodestar-reqresp-api", "Lodestar TPU - ReqResp and REST API", ps, ["lodestar", "api"])


def db_dashboard():
    ps = [
        panel(
            "DB requests",
            [
                ("sum by (bucket) (rate(lodestar_db_read_req_total[5m]))", "read {{bucket}}"),
                ("sum by (bucket) (rate(lodestar_db_write_req_total[5m]))", "write {{bucket}}"),
            ],
            pid=1,
        ),
        panel(
            "DB items",
            [
                ("sum by (bucket) (rate(lodestar_db_read_items_total[5m]))", "read {{bucket}}"),
                ("sum by (bucket) (rate(lodestar_db_write_items_total[5m]))", "write {{bucket}}"),
            ],
            x=12, pid=2,
        ),
        panel(
            "Size",
            [
                ("lodestar_db_size_bytes", "db"),
                ("lodestar_db_wal_size_bytes", "wal"),
            ],
            unit="bytes", y=8, pid=3,
        ),
        panel(
            "Archive / prune",
            [
                ("rate(lodestar_db_archived_states_total[5m])", "states archived"),
                ("rate(lodestar_db_archived_blocks_total[5m])", "blocks archived"),
                ("rate(lodestar_db_pruned_blocks_total[5m])", "blocks pruned"),
            ],
            x=12, y=8, pid=4,
        ),
        panel(
            "Batch write latency p95",
            [("histogram_quantile(0.95, rate(lodestar_db_batch_write_seconds_bucket[5m]))", "p95")],
            unit="s", y=16, pid=5,
        ),
    ]
    return dashboard("lodestar-db", "Lodestar TPU - Database", ps, ["lodestar", "db"])


def trace_dashboard():
    """Per-slot pipeline tracing (lodestar_tpu/tracing): span-duration
    summaries the tracer derives into the registry, plus the slow-slot
    dump rate. Slot-level detail lives at /eth/v0/debug/traces/{slot}."""
    ps = [
        panel(
            "Block pipeline duration",
            [
                (
                    "histogram_quantile(0.5, rate(lodestar_trace_block_pipeline_seconds_bucket[5m]))",
                    "p50",
                ),
                (
                    "histogram_quantile(0.95, rate(lodestar_trace_block_pipeline_seconds_bucket[5m]))",
                    "p95",
                ),
            ],
            unit="s", pid=1,
        ),
        panel(
            "Span p95 by stage",
            [
                (
                    "histogram_quantile(0.95, sum by (span, le) "
                    "(rate(lodestar_trace_span_duration_seconds_bucket[5m])))",
                    "{{span}}",
                ),
            ],
            unit="s", x=12, pid=2,
        ),
        panel(
            "Span time share (sum/s by stage)",
            [
                (
                    "sum by (span) (rate(lodestar_trace_span_duration_seconds_sum[5m]))",
                    "{{span}}",
                ),
            ],
            unit="s", y=8, pid=3,
        ),
        panel(
            "Traces completed / slow-slot dumps",
            [
                ("rate(lodestar_trace_completed_total[5m])", "completed"),
                ("rate(lodestar_trace_slow_slot_total[5m])", "slow slots"),
            ],
            unit="ops", x=12, y=8, pid=4,
        ),
        panel(
            "Span rate by stage",
            [
                (
                    "sum by (span) (rate(lodestar_trace_span_duration_seconds_count[5m]))",
                    "{{span}}",
                ),
            ],
            unit="ops", y=16, pid=5,
        ),
    ]
    return dashboard(
        "lodestar-block-pipeline-trace",
        "Lodestar TPU - Block pipeline trace",
        ps,
        ["lodestar", "tracing"],
    )


def sched_dashboard():
    """Device work scheduler (lodestar_tpu/scheduler): EWMA occupancy +
    graded admission, per-launch-class queue depth/wait/serve rates, and
    the anti-starvation/shed counters. The "can this host absorb another
    beacon node" dashboard."""
    ps = [
        panel(
            "Device occupancy (busy-ns per wall-ns, ‰)",
            [("lodestar_sched_occupancy_permille", "occupancy ‰")],
            pid=1,
        ),
        panel(
            "Admission state",
            [("lodestar_sched_admission_state", "0 accept / 1 shed-bulk / 2 reject")],
            x=12, pid=2,
        ),
        panel(
            "Launch queue depth by class",
            [("lodestar_sched_queue_depth", "{{class}}")],
            y=8, pid=3,
        ),
        panel(
            "Queue wait p95 by class",
            [
                (
                    "histogram_quantile(0.95, sum by (class, le) "
                    "(rate(lodestar_sched_queue_wait_seconds_bucket[5m])))",
                    "{{class}}",
                ),
            ],
            unit="s", x=12, y=8, pid=4,
        ),
        panel(
            "Dequeue rate by class",
            [
                (
                    "sum by (class) (rate(lodestar_sched_jobs_dequeued_total[5m]))",
                    "{{class}}",
                ),
            ],
            unit="ops", y=16, pid=5,
        ),
        panel(
            "Starvation promotions / shed work",
            [
                ("rate(lodestar_sched_starvation_promotions_total[5m])", "aging promotions"),
                ("sum by (class) (rate(lodestar_sched_shed_total[5m]))", "shed {{class}}"),
            ],
            unit="ops", x=12, y=16, pid=6,
        ),
    ]
    return dashboard(
        "lodestar-sched-occupancy",
        "Lodestar TPU - Device work scheduler",
        ps,
        ["lodestar", "scheduler"],
    )


def resilience_dashboard():
    """Offload resilience (offload/resilience.py + chain/bls/fallback.py):
    per-endpoint routing/failover/hedge rates, circuit-breaker states,
    and the degradation chain's fallback activity. The "is the offload
    leg healthy, and what is absorbing its failures" dashboard."""
    ps = [
        panel(
            "Breaker state by endpoint (0 closed / 1 half-open / 2 open)",
            [("lodestar_resilience_breaker_state", "{{endpoint}}")],
            pid=1,
        ),
        panel(
            "Verify RPCs routed by endpoint",
            [
                ("sum by (endpoint) (rate(lodestar_resilience_routed_total[5m]))", "{{endpoint}}"),
            ],
            unit="ops", x=12, pid=2,
        ),
        panel(
            "Failovers / breaker transitions",
            [
                ("sum by (endpoint) (rate(lodestar_resilience_failover_total[5m]))", "failover {{endpoint}}"),
                (
                    "sum by (endpoint, state) (rate(lodestar_resilience_breaker_transitions_total[5m]))",
                    "{{endpoint}} -> {{state}}",
                ),
            ],
            unit="ops", y=8, pid=3,
        ),
        panel(
            "Hedged retries by class",
            [
                ("sum by (class) (rate(lodestar_resilience_hedge_total[5m]))", "hedged {{class}}"),
                ("sum by (class) (rate(lodestar_resilience_hedge_win_total[5m]))", "won {{class}}"),
            ],
            unit="ops", x=12, y=8, pid=4,
        ),
        panel(
            "Degradation chain activity",
            [
                ("lodestar_resilience_fallback_active", "fallback active"),
                ("sum by (layer) (rate(lodestar_resilience_fallback_total[5m]))", "served {{layer}}"),
                (
                    "sum by (layer) (rate(lodestar_resilience_fallback_skipped_total[5m]))",
                    "skipped {{layer}}",
                ),
            ],
            y=16, pid=5,
        ),
        panel(
            "Admission sheds / outage-unscored rejections",
            [
                ("sum by (reason) (rate(lodestar_resilience_shed_total[5m]))", "shed {{reason}}"),
                (
                    "rate(lodestar_resilience_outage_unscored_total[5m])",
                    "outage rejections (peer spared)",
                ),
            ],
            unit="ops", x=12, y=16, pid=6,
        ),
    ]
    return dashboard(
        "lodestar-offload-resilience",
        "Lodestar TPU - Offload resilience",
        ps,
        ["lodestar", "resilience"],
    )


def audit_dashboard():
    """Byzantine offload auditing (offload/audit.py): sampling and
    re-verification rates, per-endpoint trust EWMA, Byzantine events and
    quarantine state, and the audit worker's CPU spend against its duty-
    cycle budget. The "can I trust my offload helpers" dashboard.
    (prometheus_client suffixes counters with _total — every counter
    expr below carries it.)"""
    ps = [
        panel(
            "Trust score by endpoint (EWMA, 1.0 = never contradicted)",
            [("lodestar_offload_audit_trust_score", "{{endpoint}}")],
            pid=1,
        ),
        panel(
            "Quarantined endpoints / Byzantine events",
            [
                ("lodestar_offload_audit_quarantined", "quarantined {{endpoint}}"),
                (
                    "sum by (endpoint) (increase(lodestar_offload_audit_byzantine_total[1h]))",
                    "byzantine {{endpoint}} (1h)",
                ),
            ],
            x=12, pid=2,
        ),
        panel(
            "Audit sampling rate by class",
            [
                (
                    "sum by (class) (rate(lodestar_offload_audit_sampled_total[5m]))",
                    "sampled {{class}}",
                ),
            ],
            unit="ops", y=8, pid=3,
        ),
        panel(
            "Re-verification outcomes",
            [
                (
                    "sum by (outcome) (rate(lodestar_offload_audit_verified_total[5m]))",
                    "{{outcome}}",
                ),
                (
                    "sum by (reason) (rate(lodestar_offload_audit_dropped_total[5m]))",
                    "dropped {{reason}}",
                ),
            ],
            unit="ops", x=12, y=8, pid=4,
        ),
        panel(
            "Audit queue backlog",
            [("lodestar_offload_audit_queue_depth", "backlog")],
            y=16, pid=5,
        ),
        panel(
            "Audit CPU duty cycle (fraction of one core)",
            [
                (
                    "rate(lodestar_offload_audit_cpu_seconds_total[5m])",
                    "audit cpu s/s",
                ),
            ],
            x=12, y=16, pid=6,
        ),
    ]
    return dashboard(
        "lodestar-offload-audit",
        "Lodestar TPU - Offload Byzantine audit",
        ps,
        ["lodestar", "audit"],
    )


def ssz_htr_dashboard():
    """Device hashTreeRoot (ssz/device_htr.py collector +
    state_transition/htr.py tracker): flush rate per backend, dirty
    chunk volume, device dispatch rate (all hash_pairs launches —
    collector flush levels plus shared-hook batch levels; the strict
    one-per-level-per-flush invariant is asserted by tests, which read
    the per-collector counter), flush latency, and degradations by
    leg. (prometheus_client suffixes counters with _total — every
    counter expr below carries it.)"""
    ps = [
        panel(
            "Collector flushes by backend",
            [
                (
                    "sum by (backend) (rate(lodestar_ssz_htr_flushes_total[5m]))",
                    "{{backend}}",
                ),
            ],
            unit="ops", pid=1,
        ),
        panel(
            "Dirty chunks re-hashed",
            [("rate(lodestar_ssz_htr_dirty_chunks_total[5m])", "chunks/s")],
            unit="ops", x=12, pid=2,
        ),
        panel(
            "Device dispatch rate (flush levels + batch-hook levels)",
            [
                (
                    "sum (rate(lodestar_ssz_htr_launches_total[5m]))",
                    "dispatches/s",
                ),
                (
                    'sum (rate(lodestar_ssz_htr_flushes_total{backend="device"}[5m]))',
                    "device flushes/s",
                ),
            ],
            unit="ops", y=8, pid=3,
        ),
        panel(
            "Flush wall time p95 by backend",
            [
                (
                    "histogram_quantile(0.95, sum by (le, backend) "
                    "(rate(lodestar_ssz_htr_seconds_bucket[5m])))",
                    "p95 {{backend}}",
                ),
            ],
            unit="s", x=12, y=8, pid=4,
        ),
        panel(
            "Degradations by leg (flush = device fault, tracker = logic bug)",
            [
                (
                    "sum by (leg) (rate(lodestar_ssz_htr_fallback_total[5m]))",
                    "{{leg}}",
                ),
            ],
            unit="ops", y=16, pid=5,
        ),
        panel(
            "State hashTreeRoot time (state-transition histogram)",
            [
                (
                    "histogram_quantile(0.95, sum by (le) "
                    "(rate(lodestar_stfn_hash_tree_root_seconds_bucket[5m])))",
                    "p95",
                ),
            ],
            unit="s", x=12, y=16, pid=6,
        ),
    ]
    return dashboard(
        "lodestar-ssz-htr",
        "Lodestar TPU - Device hashTreeRoot",
        ps,
        ["lodestar", "ssz"],
    )


def node_internals_dashboard():
    """Node internals (chain/process/peer detail): the registered
    families that belong on a dashboard but fit none of the
    subsystem-specific ones. Kept two-way-consistent with the registry
    by the static-analysis metrics rule (tools/analysis)."""
    ps = [
        panel(
            "Block import / production p95",
            [
                ("histogram_quantile(0.95, rate(lodestar_block_processor_import_seconds_bucket[5m]))", "import p95"),
                ("histogram_quantile(0.95, rate(lodestar_block_production_seconds_bucket[5m]))", "production p95"),
            ],
            unit="s", pid=1,
        ),
        panel(
            "Import outcomes",
            [
                ("sum by (source) (rate(lodestar_blocks_imported_total[5m]))", "imported {{source}}"),
                ("sum by (reason) (rate(lodestar_blocks_rejected_total[5m]))", "rejected {{reason}}"),
                ("rate(lodestar_attestations_imported_total[5m])", "attestations"),
            ],
            unit="ops", x=12, pid=2,
        ),
        panel(
            "Gossip validation verdicts",
            [
                ("sum by (topic) (rate(lodestar_gossip_validation_accept_total[5m]))", "accept {{topic}}"),
                ("sum by (topic) (rate(lodestar_gossip_validation_reject_total[5m]))", "reject {{topic}}"),
            ],
            unit="ops", y=8, pid=3,
        ),
        panel(
            "Event loop lag",
            [
                ("histogram_quantile(0.5, rate(lodestar_event_loop_lag_seconds_bucket[5m]))", "p50"),
                ("histogram_quantile(0.95, rate(lodestar_event_loop_lag_seconds_bucket[5m]))", "p95"),
            ],
            unit="s", x=12, y=8, pid=4,
        ),
        panel(
            "State caches & regen",
            [
                ("lodestar_state_cache_size", "hot states"),
                ("lodestar_cp_state_cache_size", "checkpoint states"),
                ("lodestar_regen_queue_length", "regen queue"),
                ("histogram_quantile(0.95, rate(lodestar_regen_fn_call_duration_seconds_bucket[5m]))", "regen p95 (s)"),
            ],
            y=16, pid=5,
        ),
        panel(
            "Seen caches",
            [
                ("lodestar_seen_cache_attesters_size", "attesters"),
                ("lodestar_seen_cache_aggregators_size", "aggregators"),
            ],
            x=12, y=16, pid=6,
        ),
        panel(
            "Op pool sizes",
            [
                ("lodestar_op_pool_attestation_pool_size", "attestations"),
                ("lodestar_op_pool_aggregated_attestation_pool_size", "aggregated"),
                ("lodestar_op_pool_voluntary_exit_pool_size", "exits"),
                ("lodestar_op_pool_proposer_slashing_pool_size", "proposer slashings"),
                ("lodestar_op_pool_attester_slashing_pool_size", "attester slashings"),
                ("lodestar_op_pool_sync_committee_message_pool_size", "sync messages"),
            ],
            y=24, pid=7,
        ),
        panel(
            "Peers & dials",
            [
                ("lodestar_peers_count", "peers"),
                ("lodestar_peers_by_client_count", "{{client}}"),
                ("sum by (reason) (rate(lodestar_peer_disconnects_total[5m]))", "disconnects {{reason}}"),
                ("rate(lodestar_peers_dial_attempts_total[5m])", "dials"),
                ("rate(lodestar_peers_dial_success_total[5m])", "dials ok"),
            ],
            x=12, y=24, pid=8,
        ),
        panel(
            "Fork choice findHead p95",
            [("histogram_quantile(0.95, rate(lodestar_fork_choice_find_head_seconds_bucket[5m]))", "p95")],
            unit="s", y=32, pid=9,
        ),
        panel(
            "Offload client (process view)",
            [
                ("lodestar_offload_outstanding_jobs", "outstanding"),
                ("lodestar_offload_healthy", "healthy bit"),
            ],
            x=12, y=32, pid=10,
        ),
    ]
    return dashboard(
        "lodestar-node-internals",
        "Lodestar TPU - Node internals",
        ps,
        ["lodestar", "node"],
    )


if __name__ == "__main__":
    main()
