"""Project-invariant static analysis for lodestar-tpu.

Run: ``python -m tools.analysis [--rule NAME ...] [paths...]``
Gate: ``tests/analysis/`` runs every rule over ``lodestar_tpu/`` in
tier-1 and fails on any finding.

See ``tools/analysis/core.py`` for the framework (findings, pragmas,
runner) and ``tools/analysis/rules/`` for the individual checkers.
"""

from .core import Finding, Rule, SourceFile, analyze, iter_py_files

__all__ = ["Finding", "Rule", "SourceFile", "analyze", "iter_py_files"]
