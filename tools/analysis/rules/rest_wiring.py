"""rest-route-wiring: REST route table ↔ router handlers ↔ API impl,
both directions — the cross-file sibling of the cli.py ↔
BeaconNodeOptions rule (same doctrine: a route that parses but reaches
no handler, or an impl method no route can reach, silently does
nothing exactly when a standard beacon client calls it).

Project-scoped over two fixed locations:

1. **ROUTES → _Router**: every handler name in the
   ``lodestar_tpu/api/server.py`` ``ROUTES`` table must be a method of
   ``_Router`` — a typo'd handler name 404s (AttributeError at
   construction) only at runtime.
2. **_Router → ROUTES**: every ``r_*`` method on ``_Router`` must be
   named by some ROUTES entry — an unrouted handler is dead code that
   LOOKS like an exposed endpoint.
3. **server → impl**: every ``self.api.X`` access inside ``_Router``
   must be an attribute ``BeaconApiImpl`` actually defines
   (``lodestar_tpu/api/impl.py``) — the gap class where a handler
   dispatches to a method that was renamed on the impl.
4. **impl → server**: every public method of ``BeaconApiImpl`` must be
   reached by some ``self.api.X`` access in the server, or carry an
   entry in ``UNROUTED_IMPL_ALLOWLIST`` with a reason — an impl method
   no route reaches is API surface that silently fell off the REST
   server. Allowlist entries naming no impl method are flagged stale
   (same doctrine as unused pragmas).
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..core import Finding, Rule

#: BeaconApiImpl public methods intentionally not behind a REST route;
#: every entry carries the reason. (Currently empty — the tree is fully
#: two-way wired; the dict exists so a future internal-consumer method
#: documents itself instead of growing a pragma.)
UNROUTED_IMPL_ALLOWLIST: dict[str, str] = {}

ROUTER_CLASS = "_Router"
IMPL_CLASS = "BeaconApiImpl"
HANDLER_PREFIX = "r_"


def _routes_entries(tree: ast.Module) -> list[tuple[str, int]]:
    """(handler_name, line) per ROUTES tuple entry."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "ROUTES" for t in targets):
            continue
        value = node.value
        if not isinstance(value, (ast.List, ast.Tuple)):
            continue
        for elt in value.elts:
            if not isinstance(elt, (ast.Tuple, ast.List)) or len(elt.elts) < 3:
                continue
            handler = elt.elts[2]
            if isinstance(handler, ast.Constant) and isinstance(handler.value, str):
                out.append((handler.value, elt.lineno))
    return out


def _class_def(tree: ast.Module, name: str) -> ast.ClassDef | None:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _methods(cls: ast.ClassDef) -> dict[str, int]:
    out: dict[str, int] = {}
    for fn in cls.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(fn.name, fn.lineno)
    return out


def _api_accesses(cls: ast.ClassDef) -> dict[str, int]:
    """attr -> first line for every `self.api.attr` / `<x>.api.attr`
    access inside the router class."""
    out: dict[str, int] = {}
    for node in ast.walk(cls):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "api"
        ):
            out.setdefault(node.attr, node.lineno)
    return out


def _allowlist_line(name: str) -> int:
    for i, line in enumerate(Path(__file__).read_text(encoding="utf-8").splitlines(), 1):
        if f'"{name}"' in line:
            return i
    return 1


class RestRouteWiringRule(Rule):
    name = "rest-route-wiring"
    description = (
        "REST route table ↔ router handlers ↔ BeaconApiImpl methods are "
        "wired both ways (routes reach handlers, handlers reach real impl "
        "methods, impl surface is routed or allowlisted)"
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        findings: list[Finding] = []
        server_path = repo_root / "lodestar_tpu" / "api" / "server.py"
        impl_path = repo_root / "lodestar_tpu" / "api" / "impl.py"
        if not (server_path.is_file() and impl_path.is_file()):
            return findings
        server_tree = ast.parse(
            server_path.read_text(encoding="utf-8"), filename=str(server_path)
        )
        impl_tree = ast.parse(
            impl_path.read_text(encoding="utf-8"), filename=str(impl_path)
        )
        router = _class_def(server_tree, ROUTER_CLASS)
        impl = _class_def(impl_tree, IMPL_CLASS)
        if router is None or impl is None:
            # the rule's anchors moved: that is itself a wiring break
            missing = ROUTER_CLASS if router is None else IMPL_CLASS
            where = server_path if router is None else impl_path
            findings.append(
                Finding(
                    self.name, str(where), 1,
                    f"class {missing} not found — the rest-route-wiring "
                    "anchors moved; update the rule",
                )
            )
            return findings

        routes = _routes_entries(server_tree)
        handlers = _methods(router)
        handler_names = {n for n in handlers if n.startswith(HANDLER_PREFIX)}
        routed = {name for name, _ in routes}

        # 1. ROUTES -> _Router
        for name, line in routes:
            if name not in handlers:
                findings.append(
                    Finding(
                        self.name, str(server_path), line,
                        f"ROUTES names handler '{name}' but {ROUTER_CLASS} "
                        "defines no such method — the route 404s at runtime",
                    )
                )
        # 2. _Router -> ROUTES
        for name in sorted(handler_names - routed):
            findings.append(
                Finding(
                    self.name, str(server_path), handlers[name],
                    f"{ROUTER_CLASS}.{name} is defined but no ROUTES entry "
                    "dispatches to it — dead handler or missing route",
                )
            )

        impl_methods = _methods(impl)
        api_calls = _api_accesses(router)

        # 3. server -> impl
        for attr, line in sorted(api_calls.items()):
            if attr not in impl_methods:
                findings.append(
                    Finding(
                        self.name, str(server_path), line,
                        f"router accesses self.api.{attr} but {IMPL_CLASS} "
                        "defines no such method — the handler raises at "
                        "dispatch",
                    )
                )
        # 4. impl -> server
        public = {
            n: line
            for n, line in impl_methods.items()
            if not n.startswith("_")
        }
        for attr in sorted(set(public) - set(api_calls)):
            if attr in UNROUTED_IMPL_ALLOWLIST:
                continue
            findings.append(
                Finding(
                    self.name, str(impl_path), public[attr],
                    f"{IMPL_CLASS}.{attr} is public but no router handler "
                    "reaches it — add a route or an "
                    "UNROUTED_IMPL_ALLOWLIST entry with a reason",
                )
            )
        # allowlist staleness
        for name in sorted(UNROUTED_IMPL_ALLOWLIST):
            if name not in public:
                findings.append(
                    Finding(
                        self.name, __file__, _allowlist_line(name),
                        f"UNROUTED_IMPL_ALLOWLIST entry '{name}' names no "
                        f"public {IMPL_CLASS} method — remove the stale entry",
                    )
                )
        return findings
