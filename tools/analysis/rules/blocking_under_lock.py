"""blocking-under-lock: no blocking waits inside a held lock.

Inside any ``with ...<something>lock...:`` block, flag:

* ``time.sleep(...)`` (module aliases and ``from time import sleep``
  both recognized),
* any ``.wait(...)`` call (``Event.wait``, ``Condition.wait``, thread
  waits — all park the holder while other threads spin on the lock),
* blocking ``.get(...)`` / ``.put(...)`` on queue-named receivers
  (receiver's trailing name contains ``queue`` or ends in ``_q``;
  ``get_nowait``/``put_nowait`` are different attribute names and pass),
* any ``.result(...)`` call (a future's result blocks until another
  worker — possibly one queued behind this very lock — completes),
* zero-argument ``.join()`` (thread/process join; ``", ".join(parts)``
  always takes the iterable, and ``join(timeout=...)`` is caught by
  the ``timeout=`` check below),
* any call carrying a ``timeout=`` keyword — in this codebase that is
  the signature of an RPC or a bounded wait (``ep.verify(frame,
  timeout=...)``), neither of which belongs under a lock.

``str.join(iterable)`` / ``dict.get`` stay unflagged (receiver/arity/
keyword filters above are what make this precise enough to gate on).
A bare positional ``thread.join(5)`` is the one documented gap.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile
from ._locks import WithLockTracker

_QUEUEISH = ("queue",)


def _receiver_name(expr: ast.expr) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _time_sleep_names(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(module aliases of `time`, local names bound to `time.sleep`)."""
    mods: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "sleep":
                    funcs.add(a.asname or "sleep")
    return mods, funcs


class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    description = (
        "no time.sleep / .wait() / blocking queue ops / .result() / "
        "join() / timeout= calls inside a 'with ...lock:' body"
    )

    def check(self, sf: SourceFile):
        findings: list[Finding] = []
        time_mods, sleep_funcs = _time_sleep_names(sf.tree)

        def flag(node: ast.AST, what: str) -> None:
            findings.append(
                Finding(
                    BlockingUnderLockRule.name, sf.path, node.lineno,
                    f"{what} inside a held lock blocks every other "
                    "thread contending for it",
                )
            )

        class _V(WithLockTracker):
            def visit_Call(self, node: ast.Call) -> None:
                if self.held:
                    fn = node.func
                    if (
                        isinstance(fn, ast.Attribute)
                        and fn.attr == "sleep"
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id in time_mods
                    ):
                        flag(node, "time.sleep()")
                    elif isinstance(fn, ast.Name) and fn.id in sleep_funcs:
                        flag(node, "sleep()")
                    elif isinstance(fn, ast.Attribute) and fn.attr == "wait":
                        flag(node, f"{_receiver_name(fn.value)}.wait()")
                    elif isinstance(fn, ast.Attribute) and fn.attr in ("get", "put"):
                        recv = _receiver_name(fn.value).lower()
                        if any(q in recv for q in _QUEUEISH) or recv.endswith("_q"):
                            flag(node, f"blocking queue .{fn.attr}()")
                    elif isinstance(fn, ast.Attribute) and fn.attr == "result":
                        flag(node, f"{_receiver_name(fn.value)}.result()")
                    elif (
                        isinstance(fn, ast.Attribute)
                        and fn.attr == "join"
                        and not node.args
                        and not node.keywords
                    ):
                        # zero-arg join is a thread/process join;
                        # str.join always takes the iterable
                        flag(node, f"{_receiver_name(fn.value)}.join()")
                    elif any(kw.arg == "timeout" for kw in node.keywords):
                        flag(node, "a timeout= call (RPC/bounded wait)")
                self.generic_visit(node)

        _V().visit(sf.tree)
        return findings
