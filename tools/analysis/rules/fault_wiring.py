"""fault-wiring: `FaultKind` registry ↔ delivery code ↔ consumers, both
directions — the cross-file sibling of the REST-route rule (same
doctrine: a fault that schedules but never fires, or a consumer naming
a fault that doesn't exist, silently does nothing exactly when a chaos
test depends on it).

Project-scoped over ``lodestar_tpu/testing/faults.py`` (the registry)
plus every ``FaultKind`` consumer under ``lodestar_tpu/`` and
``tests/`` (``tests/analysis/fixtures`` excluded — those trees are
deliberately broken):

1. **registry → delivery**: every ``FaultKind`` member must be
   referenced by name somewhere in ``faults.py`` OUTSIDE the enum class
   body — the delivery seams (``_pre_call`` / ``wrap_backend`` /
   ``_BACKEND_KINDS``). A member with no delivery branch falls through
   ``_next_fault``'s rule match and then injects NOTHING: the chaos
   test believes it stormed the system and proved an invariant the
   fault never exercised.
2. **consumers → registry**: every ``FaultKind.X`` attribute access and
   every ``FaultKind("...")`` literal construction in the scanned trees
   must name a declared member/value — a typo'd kind is an
   AttributeError/ValueError only at the moment the chaos test runs.
3. **registry hygiene**: two members sharing one string value make
   ``FaultKind("...")`` lookups ambiguous aliases — flagged.
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..core import Finding, Rule, cached_source

REGISTRY_REL = Path("lodestar_tpu") / "testing" / "faults.py"
ENUM_CLASS = "FaultKind"
#: directories scanned for consumers (relative to repo_root); the
#: analysis fixture trees are deliberately-broken code and excluded
SCAN_DIRS = ("lodestar_tpu", "tests")
EXCLUDE_PARTS = {"fixtures", "__pycache__"}


def _enum_members(tree: ast.Module) -> tuple[ast.ClassDef | None, dict[str, tuple[str, int]]]:
    """(class node, name -> (value, line)) for the FaultKind enum."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == ENUM_CLASS:
            members: dict[str, tuple[str, int]] = {}
            for stmt in node.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                target = stmt.targets[0]
                if not isinstance(target, ast.Name) or target.id.startswith("_"):
                    continue
                value = stmt.value
                val = value.value if isinstance(value, ast.Constant) else None
                members[target.id] = (val, stmt.lineno)
            return node, members
    return None, {}


def _kind_refs(tree: ast.Module) -> list[tuple[str, int]]:
    """(member_name, line) for every `FaultKind.X` / `<mod>.FaultKind.X`
    attribute access in the tree."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        base = node.value
        if isinstance(base, ast.Name) and base.id == ENUM_CLASS:
            out.append((node.attr, node.lineno))
        elif isinstance(base, ast.Attribute) and base.attr == ENUM_CLASS:
            out.append((node.attr, node.lineno))
    return out


def _kind_calls(tree: ast.Module) -> list[tuple[str, int]]:
    """(value, line) for every `FaultKind("...")` literal construction."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or len(node.args) != 1:
            continue
        fn = node.func
        named = (isinstance(fn, ast.Name) and fn.id == ENUM_CLASS) or (
            isinstance(fn, ast.Attribute) and fn.attr == ENUM_CLASS
        )
        arg = node.args[0]
        if named and isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
    return out


def _outside_class(refs: list[tuple[str, int]], cls: ast.ClassDef) -> set[str]:
    end = getattr(cls, "end_lineno", cls.lineno)
    return {name for name, line in refs if not (cls.lineno <= line <= end)}


class FaultWiringRule(Rule):
    name = "fault-wiring"
    description = (
        "FaultKind registry ↔ delivery seams ↔ consumers are wired both "
        "ways (every member has a delivery branch; every FaultKind.X / "
        'FaultKind("...") names a real member)'
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        findings: list[Finding] = []
        registry_path = repo_root / REGISTRY_REL
        registry_sf = cached_source(sources, registry_path)
        if registry_sf is None or registry_sf.tree is None:
            return findings
        tree = registry_sf.tree
        cls, members = _enum_members(tree)
        if cls is None or not members:
            findings.append(
                Finding(
                    self.name, str(registry_path), 1,
                    f"class {ENUM_CLASS} not found — the fault-wiring "
                    "anchors moved; update the rule",
                )
            )
            return findings

        # registry hygiene: duplicate string values alias each other
        by_value: dict[str, str] = {}
        for name, (val, line) in sorted(members.items(), key=lambda kv: kv[1][1]):
            if val in by_value:
                findings.append(
                    Finding(
                        self.name, str(registry_path), line,
                        f"{ENUM_CLASS}.{name} reuses value {val!r} of "
                        f"{ENUM_CLASS}.{by_value[val]} — aliased members make "
                        f'{ENUM_CLASS}("{val}") ambiguous',
                    )
                )
            else:
                by_value[val] = name

        # 1. registry -> delivery
        delivered = _outside_class(_kind_refs(tree), cls)
        for name in sorted(set(members) - delivered):
            findings.append(
                Finding(
                    self.name, str(registry_path), members[name][1],
                    f"{ENUM_CLASS}.{name} is declared but never referenced by "
                    "a delivery seam in this module — the fault schedules "
                    "and then injects nothing",
                )
            )

        # 2. consumers -> registry
        values = {val for val, _name in by_value.items()}
        for path in self._consumer_files(repo_root, registry_path):
            sf = cached_source(sources, path)
            if sf is None or sf.tree is None or ENUM_CLASS not in sf.text:
                continue
            consumer = sf.tree
            for name, line in _kind_refs(consumer):
                if name not in members:
                    findings.append(
                        Finding(
                            self.name, str(path), line,
                            f"{ENUM_CLASS}.{name} names no declared member — "
                            "AttributeError the moment this fault is scheduled",
                        )
                    )
            for val, line in _kind_calls(consumer):
                if val not in values:
                    findings.append(
                        Finding(
                            self.name, str(path), line,
                            f'{ENUM_CLASS}("{val}") matches no member value — '
                            "ValueError the moment this fault is scheduled",
                        )
                    )
        return findings

    @staticmethod
    def _consumer_files(repo_root: Path, registry_path: Path):
        for rel in SCAN_DIRS:
            base = repo_root / rel
            if not base.is_dir():
                continue
            for path in sorted(base.rglob("*.py")):
                # exclusion is RELATIVE to the scanned tree: a repo that
                # itself lives under a directory named "fixtures" (this
                # rule's own test fixtures) must still be scanned
                if EXCLUDE_PARTS & set(path.relative_to(base).parts):
                    continue
                if path.resolve() == registry_path.resolve():
                    continue
                yield path
