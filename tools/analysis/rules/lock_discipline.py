"""lock-discipline: annotated shared attributes are only touched under
their declared lock.

Declaration convention — a trailing comment on the attribute's
assignment (normally in ``__init__``)::

    self._outstanding = 0          # guarded by: _lock
    self.healthy = True            # guarded by: _lock [shared] — owning client's
    self._buffered = []            # guarded by: event-loop (single-threaded)

* ``# guarded by: <lock>`` — `<lock>` is a Python identifier naming the
  guarding lock attribute (``_lock``, ``_fs_lock``, ...). Every
  load/store of ``self.<attr>`` in the DECLARING class must sit inside
  a ``with ...<lock>:`` block. Accesses in ``__init__`` are exempt
  (the object is not yet shared).
* ``[shared]`` — the attribute is mutated through non-`self` receivers
  too (e.g. ``_Endpoint`` state owned by the client's lock): the check
  widens to every ``<name>.<attr>`` access in the module. Use only for
  attribute names that are unambiguous within their module.
* A non-identifier guard (``event-loop``, ``advisory``, ``contextvar``,
  ...) is DOCUMENTATION ONLY: it records why the attribute needs no
  lock; nothing is enforced. This keeps the annotation honest for
  loop-confined or racy-benign-by-design state.

Lock identity is lexical (see `_locks`): helper methods that run with
the caller's lock held carry a def-line
``# lint: allow(lock-discipline) — caller holds ...`` pragma.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from ..core import Finding, Rule, SourceFile
from ._locks import WithLockTracker

_GUARD_RE = re.compile(r"#\s*guarded by:\s*(\S+)(.*)$")
_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


@dataclass
class GuardDecl:
    attr: str
    lock: str
    shared: bool
    enforced: bool
    cls: str
    line: int


def collect_decls(sf: SourceFile) -> dict[str, list[GuardDecl]]:
    """``self.X = ...`` assignments whose line carries a guard comment,
    keyed by attribute name (module scope). A list per attribute:
    distinct classes may legitimately declare the same name with
    different guards, and overwriting would silently disable the
    first class's enforcement."""
    decls: dict[str, list[GuardDecl]] = {}
    guards: dict[int, tuple[str, bool, bool]] = {}
    for line, comment in sf.comments.items():
        m = _GUARD_RE.search(comment)
        if m is None:
            continue
        lock, rest = m.group(1), m.group(2) or ""
        shared = "[shared]" in rest
        guards[line] = (lock, shared, bool(_IDENT_RE.match(lock)))

    class _V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.cls: list[str] = []

        def visit_ClassDef(self, node: ast.ClassDef) -> None:
            self.cls.append(node.name)
            self.generic_visit(node)
            self.cls.pop()

        def _decl(self, target: ast.expr, line: int) -> None:
            g = guards.get(line)
            if g is None or not self.cls:
                return
            if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
                if target.value.id == "self":
                    lock, shared, enforced = g
                    decls.setdefault(target.attr, []).append(
                        GuardDecl(target.attr, lock, shared, enforced, self.cls[-1], line)
                    )

        def visit_Assign(self, node: ast.Assign) -> None:
            for t in node.targets:
                self._decl(t, node.lineno)
            self.generic_visit(node)

        def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
            self._decl(node.target, node.lineno)
            self.generic_visit(node)

    if sf.tree is not None:
        _V().visit(sf.tree)
    return decls


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "attributes annotated '# guarded by: <lock>' must only be "
        "read/written inside a 'with ...<lock>:' block"
    )

    def check(self, sf: SourceFile):
        decls = collect_decls(sf)
        if not any(d.enforced for ds in decls.values() for d in ds):
            return []
        findings: list[Finding] = []

        # [shared] widens enforcement module-wide by NAME; if another
        # class declares the same attribute under a different guard,
        # a non-self access cannot be attributed to either declaration
        for attr, ds in decls.items():
            if len(ds) > 1 and any(d.shared for d in ds):
                if len({(d.lock, d.shared, d.enforced) for d in ds}) > 1:
                    sites = ", ".join(f"{d.cls}:{d.line} ({d.lock})" for d in ds)
                    findings.append(
                        Finding(
                            self.name, sf.path, max(d.line for d in ds),
                            f"'{attr}' has conflicting guard declarations "
                            f"[{sites}] — a [shared] guard requires the "
                            "attribute name to be unambiguous in its module",
                        )
                    )

        class _V(WithLockTracker):
            def visit_Attribute(self, node: ast.Attribute) -> None:
                ds = decls.get(node.attr)
                if ds and not self.in_init():
                    is_self = (
                        isinstance(node.value, ast.Name) and node.value.id == "self"
                    )
                    if is_self:
                        # the receiver's own class's declaration wins;
                        # otherwise a [shared] decl from another class
                        # still covers this name
                        own = [d for d in ds if d.cls == self.current_class()]
                        applicable = own or [d for d in ds if d.shared]
                    else:
                        applicable = [d for d in ds if d.shared]
                    for d in applicable:
                        if d.enforced and not self.holds(d.lock):
                            findings.append(
                                Finding(
                                    LockDisciplineRule.name,
                                    sf.path,
                                    node.lineno,
                                    f"'{node.attr}' is guarded by '{d.lock}' "
                                    f"(declared {d.cls}:{d.line}) but accessed "
                                    f"outside 'with ...{d.lock}'",
                                )
                            )
                            break
                self.generic_visit(node)

        _V().visit(sf.tree)
        return findings
