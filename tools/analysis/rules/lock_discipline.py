"""lock-discipline: annotated shared attributes are only touched under
their declared lock (or, for loop-confined guards, only written by
their declared owner context).

Declaration convention — a trailing comment on the attribute's
assignment (normally in ``__init__``)::

    self._outstanding = 0          # guarded by: _lock
    self.healthy = True            # guarded by: _lock [shared] — owning client's
    self._buffered = []            # guarded by: event-loop (single-threaded)
    self.trust = 1.0               # guarded by: audit-thread (single writer)

* ``# guarded by: <lock>`` — `<lock>` is a Python identifier naming the
  guarding lock attribute (``_lock``, ``_fs_lock``, ...). Every
  load/store of ``self.<attr>`` in the DECLARING class must sit inside
  a ``with ...<lock>:`` block. Accesses in ``__init__`` are exempt
  (the object is not yet shared).
* ``[shared]`` — the attribute is mutated through non-`self` receivers
  too (e.g. ``_Endpoint`` state owned by the client's lock): the check
  widens to every ``<name>.<attr>`` access in the module. Use only for
  attribute names that are unambiguous within their module.
* ``event-loop`` / ``audit-thread`` / ``probe-thread`` — loop-confined
  OWNERSHIP guards, enforced as single-writer checks: every WRITE to
  the attribute (assignment, augmented assignment, delete, or an
  in-place mutator call like ``.append``/``.clear``/``.add``) must sit
  in a function owned by the declared context. Reads are deliberately
  unrestricted — these annotations exist precisely because stale reads
  from other threads are benign by design; the invariant worth
  machine-checking is that only the owner mutates. Ownership is
  computed per module as a fixpoint over the intra-module reference
  graph:

  - ``event-loop`` owner roots: ``async def`` functions, plus functions
    and lambdas REGISTERED with the loop (passed to ``call_later`` /
    ``call_soon`` / ``call_at`` / ``call_soon_threadsafe`` /
    ``add_done_callback`` / ``create_task`` / ``ensure_future`` /
    ``run_coroutine_threadsafe``).
  - ``*-thread`` owner roots: functions passed as ``target=`` to a
    ``Thread(...)`` construction in the module.
  - A sync helper is owned when every in-module reference to it comes
    from an owned scope (registration sites don't count as references —
    they are how a root is declared, not an invocation). Like the
    lexical lock tracking, ownership is by NAME within the module and
    loop-confined guards widen to non-``self`` receivers (a probe
    thread mutating ``ep.consecutive_failures`` is the canonical case);
    both are the repo's naming-discipline approximation, not alias
    analysis.

* Any other non-identifier guard (``advisory-only``, ``config-time``,
  ``contextvar``, ...) is DOCUMENTATION ONLY: it records why the
  attribute needs no lock; nothing is enforced.

Lock identity is lexical (see `_locks`): helper methods that run with
the caller's lock held carry a def-line
``# lint: allow(lock-discipline) — caller holds ...`` pragma.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from ..core import Finding, Rule, SourceFile
from ._locks import WithLockTracker, _last_segment

_GUARD_RE = re.compile(r"#\s*guarded by:\s*(\S+)(.*)$")
_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")

#: loop-confined guards enforced as single-writer ownership, mapped to
#: their owner-root kind
_OWNER_GUARDS = {
    "event-loop": "loop",
    "audit-thread": "thread",
    "probe-thread": "thread",
}

#: loop APIs whose function-valued arguments run ON the event loop
_LOOP_SCHEDULERS = {
    "call_later",
    "call_soon",
    "call_at",
    "call_soon_threadsafe",
    "add_done_callback",
    "create_task",
    "ensure_future",
    "run_coroutine_threadsafe",
}

#: method calls that mutate the receiver in place (the write shapes a
#: single-writer guard must own)
_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "clear",
    "discard",
    "extend",
    "insert",
    "pop",
    "popleft",
    "remove",
    "setdefault",
    "update",
}


@dataclass
class GuardDecl:
    attr: str
    lock: str
    shared: bool
    enforced: bool
    cls: str
    line: int
    owner: str | None = None  # "loop" / "thread" for owner-enforced guards


def collect_decls(sf: SourceFile) -> dict[str, list[GuardDecl]]:
    """``self.X = ...`` assignments whose line carries a guard comment,
    keyed by attribute name (module scope). A list per attribute:
    distinct classes may legitimately declare the same name with
    different guards, and overwriting would silently disable the
    first class's enforcement."""
    decls: dict[str, list[GuardDecl]] = {}
    guards: dict[int, tuple[str, bool, bool]] = {}
    for line, comment in sf.comments.items():
        m = _GUARD_RE.search(comment)
        if m is None:
            continue
        lock, rest = m.group(1), m.group(2) or ""
        shared = "[shared]" in rest
        guards[line] = (lock, shared, bool(_IDENT_RE.match(lock)))

    class _V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.cls: list[str] = []

        def visit_ClassDef(self, node: ast.ClassDef) -> None:
            self.cls.append(node.name)
            self.generic_visit(node)
            self.cls.pop()

        def _decl(self, target: ast.expr, line: int) -> None:
            g = guards.get(line)
            if g is None or not self.cls:
                return
            if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
                if target.value.id == "self":
                    lock, shared, enforced = g
                    decls.setdefault(target.attr, []).append(
                        GuardDecl(
                            target.attr,
                            lock,
                            shared,
                            enforced,
                            self.cls[-1],
                            line,
                            owner=_OWNER_GUARDS.get(lock),
                        )
                    )

        def visit_Assign(self, node: ast.Assign) -> None:
            for t in node.targets:
                self._decl(t, node.lineno)
            self.generic_visit(node)

        def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
            self._decl(node.target, node.lineno)
            self.generic_visit(node)

    if sf.tree is not None:
        _V().visit(sf.tree)
    return decls


class _OwnerAnalysis:
    """Module-level ownership fixpoint for the loop-confined guards.

    `scope_owned(node, kind)` answers whether the function/lambda scope
    node is owned by the event loop ("loop") or a module thread
    ("thread")."""

    def __init__(self, tree: ast.AST) -> None:
        self.func_defs: dict[str, list[ast.AST]] = {}
        self.async_names: set[str] = set()
        self.thread_targets: set[str] = set()
        self.loop_registered: set[str] = set()
        self.owned_lambdas: set[int] = set()  # id() of scheduler-arg lambdas
        self.refs: dict[str, list[ast.AST | None]] = {}
        self._registration_nodes: set[int] = set()
        self._collect(tree)
        self.owned_loop = self._fixpoint("loop")
        self.owned_thread = self._fixpoint("thread")

    # -- collection ------------------------------------------------------------

    def _collect(self, tree: ast.AST) -> None:
        defs = self.func_defs
        outer = self

        class _Pre(ast.NodeVisitor):
            """Pass 1: function defs + registration sites (Thread
            targets, loop-scheduled callables)."""

            def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
                defs.setdefault(node.name, []).append(node)
                self.generic_visit(node)

            def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
                defs.setdefault(node.name, []).append(node)
                outer.async_names.add(node.name)
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                fname = _last_segment(node.func)
                if fname == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            seg = _last_segment(kw.value)
                            if seg is not None:
                                outer.thread_targets.add(seg)
                                outer._registration_nodes.add(id(kw.value))
                elif fname in _LOOP_SCHEDULERS:
                    for arg in list(node.args) + [k.value for k in node.keywords]:
                        if isinstance(arg, ast.Lambda):
                            outer.owned_lambdas.add(id(arg))
                        else:
                            seg = _last_segment(arg)
                            if seg is not None:
                                outer.loop_registered.add(seg)
                                outer._registration_nodes.add(id(arg))
                self.generic_visit(node)

        _Pre().visit(tree)

        class _Refs(ast.NodeVisitor):
            """Pass 2: every non-registration reference to a known
            function name, attributed to its innermost scope."""

            def __init__(self) -> None:
                self.scope: list[ast.AST] = []

            def _func(self, node) -> None:
                self.scope.append(node)
                self.generic_visit(node)
                self.scope.pop()

            visit_FunctionDef = _func
            visit_AsyncFunctionDef = _func
            visit_Lambda = _func

            def _ref(self, node: ast.expr, name: str) -> None:
                if name in defs and id(node) not in outer._registration_nodes:
                    outer.refs.setdefault(name, []).append(
                        self.scope[-1] if self.scope else None
                    )

            def visit_Attribute(self, node: ast.Attribute) -> None:
                self._ref(node, node.attr)
                self.generic_visit(node)

            def visit_Name(self, node: ast.Name) -> None:
                self._ref(node, node.id)

        _Refs().visit(tree)

    # -- fixpoint --------------------------------------------------------------

    def _roots(self, kind: str) -> set[str]:
        if kind == "loop":
            return self.async_names | self.loop_registered
        return set(self.thread_targets)

    def _fixpoint(self, kind: str) -> set[str]:
        owned = {n for n in self._roots(kind) if n in self.func_defs}
        changed = True
        while changed:
            changed = False
            for name in self.func_defs:
                if name in owned:
                    continue
                rs = self.refs.get(name)
                if not rs:
                    continue
                if all(self._scope_owned_in(s, owned, kind) for s in rs):
                    owned.add(name)
                    changed = True
        return owned

    def _scope_owned_in(self, scope, owned: set[str], kind: str) -> bool:
        if scope is None:
            return False
        if isinstance(scope, ast.Lambda):
            return kind == "loop" and id(scope) in self.owned_lambdas
        return scope.name in owned

    # -- query -----------------------------------------------------------------

    def scope_owned(self, scope, kind: str) -> bool:
        owned = self.owned_loop if kind == "loop" else self.owned_thread
        return self._scope_owned_in(scope, owned, kind)


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "attributes annotated '# guarded by: <lock>' are only touched "
        "under 'with ...<lock>:'; loop-confined guards (event-loop, "
        "audit-thread, probe-thread) are only WRITTEN by their owner"
    )

    def check(self, sf: SourceFile):
        decls = collect_decls(sf)
        if not any(d.enforced or d.owner for ds in decls.values() for d in ds):
            return []
        findings: list[Finding] = []

        owner_analysis = (
            _OwnerAnalysis(sf.tree)
            if any(d.owner for ds in decls.values() for d in ds)
            else None
        )

        # [shared] widens enforcement module-wide by NAME; if another
        # class declares the same attribute under a different guard,
        # a non-self access cannot be attributed to either declaration
        for attr, ds in decls.items():
            if len(ds) > 1 and any(d.shared for d in ds):
                if len({(d.lock, d.shared, d.enforced) for d in ds}) > 1:
                    sites = ", ".join(f"{d.cls}:{d.line} ({d.lock})" for d in ds)
                    findings.append(
                        Finding(
                            self.name, sf.path, max(d.line for d in ds),
                            f"'{attr}' has conflicting guard declarations "
                            f"[{sites}] — a [shared] guard requires the "
                            "attribute name to be unambiguous in its module",
                        )
                    )

        rule_name = self.name

        class _V(WithLockTracker):
            def __init__(self) -> None:
                super().__init__()
                self.scope_nodes: list[ast.AST] = []

            def _visit_func(self, node) -> None:
                self.scope_nodes.append(node)
                super()._visit_func(node)
                self.scope_nodes.pop()

            def visit_Lambda(self, node: ast.Lambda) -> None:
                self.scope_nodes.append(node)
                super().visit_Lambda(node)
                self.scope_nodes.pop()

            # -- owner (single-writer) enforcement ----------------------------

            def _owner_write(self, node: ast.Attribute) -> None:
                """`node` is a guarded attribute being WRITTEN (store,
                del, augassign target, or in-place mutator receiver)."""
                ds = decls.get(node.attr)
                if not ds or self.in_init():
                    return
                # owner guards follow the attribute through any receiver
                # (single-writer state routinely lives on helper objects)
                for d in ds:
                    if d.owner is None:
                        continue
                    scope = self.scope_nodes[-1] if self.scope_nodes else None
                    if not owner_analysis.scope_owned(scope, d.owner):
                        findings.append(
                            Finding(
                                rule_name,
                                sf.path,
                                node.lineno,
                                f"'{node.attr}' is owned by '{d.lock}' "
                                f"(declared {d.cls}:{d.line}) but written "
                                f"outside a {d.lock}-owned scope",
                            )
                        )
                        break

            def visit_Call(self, node: ast.Call) -> None:
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _MUTATORS
                    and isinstance(f.value, ast.Attribute)
                    and f.value.attr in decls
                ):
                    self._owner_write(f.value)
                self.generic_visit(node)

            def visit_Subscript(self, node: ast.Subscript) -> None:
                # item writes are writes: `self._buffered[0] = x` /
                # `del self._buffered[0]` put Store/Del on the
                # SUBSCRIPT while the guarded Attribute reads as Load —
                # the most common mutation shape must not slip through
                if (
                    isinstance(node.ctx, (ast.Store, ast.Del))
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr in decls
                ):
                    self._owner_write(node.value)
                self.generic_visit(node)

            def visit_Attribute(self, node: ast.Attribute) -> None:
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    self._owner_write(node)
                ds = decls.get(node.attr)
                if ds and not self.in_init():
                    is_self = (
                        isinstance(node.value, ast.Name) and node.value.id == "self"
                    )
                    if is_self:
                        # the receiver's own class's declaration wins;
                        # otherwise a [shared] decl from another class
                        # still covers this name
                        own = [d for d in ds if d.cls == self.current_class()]
                        applicable = own or [d for d in ds if d.shared]
                    else:
                        applicable = [d for d in ds if d.shared]
                    for d in applicable:
                        if d.enforced and not self.holds(d.lock):
                            findings.append(
                                Finding(
                                    LockDisciplineRule.name,
                                    sf.path,
                                    node.lineno,
                                    f"'{node.attr}' is guarded by '{d.lock}' "
                                    f"(declared {d.cls}:{d.line}) but accessed "
                                    f"outside 'with ...{d.lock}'",
                                )
                            )
                            break
                self.generic_visit(node)

        _V().visit(sf.tree)
        return findings
